"""Digital-twin autopilot: the live↔sim control loop (docs/autopilot.md).

The sweep plane answered "what would happen under config X"; the
autopilot asks and ANSWERS the operator's real question — "which
config meets my SLO under what the cluster is going through right
now" — by closing the loop the ROADMAP names:

* :mod:`fit`        — telemetry → a :class:`ConditionEstimate`
  (loss/churn as data axes, pauses as a ``FaultPlan``);
* :mod:`objective`  — ``telemetry/slo.py`` rules → the scalar the
  search minimizes (the same grammar ``POST /sweep`` verdicts use);
* :mod:`search`     — grid seeding + elite-jitter ES, one vmapped
  ``FleetSim`` dispatch per generation, every evaluation counted;
* :mod:`controller` — recommend / replay-verify / apply-gate, the
  ``POST /autopilot/recommend`` + ``GET /api/autopilot.json``
  surfaces, and the ``autopilot.*`` metrics.
"""

from sidecar_tpu.autopilot.controller import (  # noqa: F401
    AutopilotController,
    default_axes,
    replay_check,
)
from sidecar_tpu.autopilot.fit import (  # noqa: F401
    ConditionEstimate,
    fit_from_trace,
    fit_live,
)
from sidecar_tpu.autopilot.objective import Objective  # noqa: F401
from sidecar_tpu.autopilot.search import (  # noqa: F401
    AxisSpec,
    FleetEvaluator,
    SearchResult,
    es_search,
)
