"""Operator SLOs → one scalar over fleet rows (docs/autopilot.md).

The objective is the autopilot's contract with the operator: the SAME
``telemetry/slo.py`` rule grammar humans write for ``POST /sweep``
verdicts and the bench gate ("converge <= 5 s", "agreement >= 0.99",
"p99 <= 12 rounds") is what the optimizer minimizes — there is no
second, private notion of "good" that could diverge from the one the
verdict surface reports.

Scoring (minimized):

* every PASSING rule contributes 0;
* a FAILING rule contributes ``PENALTY · (1 + violation)`` where
  ``violation`` is the relative overshoot (capped — one hopeless rule
  must not flatten the gradient of the others);
* a rule that could not be evaluated contributes the base ``PENALTY``
  and a never-converged row the capped maximum — unevaluable never
  outranks measured-and-failing, and neither ever beats a pass;
* ties among SLO-clean candidates break on a bounded-in-[0,1) blend
  of normalized rounds-to-ε and exchange bytes, so the recommendation
  is the CHEAPEST config meeting the SLO, not merely any config.

The penalty scale dwarfs the tiebreaker by construction: no volume of
saved bytes can buy back a failed SLO.
"""

from __future__ import annotations

from typing import Optional

from sidecar_tpu.telemetry.slo import SloEvaluator

PENALTY = 1000.0      # one failed/unevaluable rule
VIOLATION_CAP = 10.0  # relative-overshoot cap per rule


def _violation(verdict: dict) -> float:
    """Relative overshoot of a failed rule, 0 when unmeasurable."""
    obs, thr = verdict.get("observed"), float(verdict["threshold"])
    if obs is None:
        return VIOLATION_CAP
    if verdict.get("unit") == "ms":
        thr /= 1e3            # observed is in seconds (slo.py contract)
    scale = max(abs(thr), 1e-9)
    if verdict["direction"] == ">=":
        return min(max((thr - obs) / scale, 0.0), VIOLATION_CAP)
    return min(max((obs - thr) / scale, 0.0), VIOLATION_CAP)


class Objective:
    """Scalarize SLO verdicts + cost over one fleet result row."""

    def __init__(self, rules, *, seconds_per_round: Optional[float] = None,
                 bytes_scale: float = 1e8) -> None:
        self.evaluator = rules if isinstance(rules, SloEvaluator) \
            else SloEvaluator(rules)
        self.seconds_per_round = seconds_per_round
        self.bytes_scale = float(bytes_scale)

    @property
    def rules_text(self) -> list:
        return [r.text() for r in self.evaluator.rules]

    def score_row(self, row: dict, lag: Optional[dict] = None,
                  horizon: Optional[int] = None) -> tuple:
        """(score, verdict block) for one ``FleetRun.table`` row —
        lower is better; the verdict block is the ``evaluate_row``
        document the sweep surface returns for the same row."""
        block = self.evaluator.evaluate_row(
            row, lag=lag, seconds_per_round=self.seconds_per_round,
            publish=False)
        score = 0.0
        for v in block["rules"]:
            if v["pass"] is True:
                continue
            if v["pass"] is False:
                score += PENALTY * (1.0 + _violation(v))
            else:                      # unevaluable — never a free pass
                score += PENALTY
        r = row.get("rounds_to_eps")
        hz = max(int(horizon or row.get("rounds_run") or 1), 1)
        rounds_term = min((r if r is not None else hz) / hz, 1.0)
        xb = float(row.get("exchange_bytes") or 0.0)
        bytes_term = xb / (xb + self.bytes_scale)
        return score + 0.45 * rounds_term + 0.45 * bytes_term, block
