"""The autopilot control loop (docs/autopilot.md).

``AutopilotController.recommend`` closes the live↔sim loop in four
moves, each owned by a sibling module:

1. **fit** (autopilot/fit.py) — snapshot the cluster's conditions into
   a :class:`ConditionEstimate` (loss/churn as data-axis base fields,
   pauses as a shared ``FaultPlan``);
2. **objective** (autopilot/objective.py) — the operator's
   ``telemetry/slo.py`` rules become the scalar the search minimizes;
3. **search** (autopilot/search.py) — grid seeding + elite-jitter ES
   over the data-axis knob space, one vmapped ``FleetSim`` dispatch
   per generation, every scenario counted;
4. **verify + gate** — the winning bundle is replayed UNBATCHED
   through the classic sim (``ExactSim`` / ``ChaosExactSim``) and must
   be bit-identical to its fleet lane (:func:`replay_check`) before it
   is recommended; auto-APPLY (rewriting the bridge's live
   ``TimeConfig`` with the winner's clock knobs) additionally requires
   the ``SIDECAR_TPU_AUTOPILOT_APPLY=1`` master gate — a request may
   ask for apply, but only the operator's environment can arm it, and
   a blocked apply is counted (``autopilot.apply_blocked``), never
   silent.

Env contract (docs/env.md):

* ``SIDECAR_TPU_AUTOPILOT_APPLY`` — "1" arms auto-apply; anything
  else leaves every recommendation advisory.
* ``SIDECAR_TPU_AUTOPILOT_RULES`` — comma-separated default SLO rules
  (requests may override per call).
* ``SIDECAR_TPU_AUTOPILOT_ROUNDS`` / ``_GENERATIONS`` /
  ``_POPULATION`` — default search budget knobs.

Every recommendation publishes ``autopilot.*`` metrics
(docs/metrics.md) and stores its report on the catalog state
(``state.autopilot_report``) for ``GET /api/autopilot.json``.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional

import jax
import numpy as np

from sidecar_tpu import metrics
from sidecar_tpu.autopilot.fit import ConditionEstimate, fit_live
from sidecar_tpu.autopilot.objective import Objective
from sidecar_tpu.autopilot.search import (
    AxisSpec,
    EvalResult,
    FleetEvaluator,
    es_search,
)
from sidecar_tpu.fleet import restart_churn_perturb
from sidecar_tpu.fleet.batch import _TIMECFG_FIELDS
from sidecar_tpu.models.exact import ExactSim, SimParams
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import topology as topo_mod

ENV_APPLY = "SIDECAR_TPU_AUTOPILOT_APPLY"
ENV_RULES = "SIDECAR_TPU_AUTOPILOT_RULES"
ENV_ROUNDS = "SIDECAR_TPU_AUTOPILOT_ROUNDS"
ENV_GENERATIONS = "SIDECAR_TPU_AUTOPILOT_GENERATIONS"
ENV_POPULATION = "SIDECAR_TPU_AUTOPILOT_POPULATION"

DEFAULT_AUTOPILOT_RULES = ("converge <= 30 rounds", "agreement >= 0.99")

# The fleet-lane ↔ unbatched-run comparison surface (the exact-family
# lockstep contract, tests/test_fleet.py).
REPLAY_FIELDS = ("known", "sent", "node_alive", "round_idx")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def apply_armed() -> bool:
    """The master auto-apply gate: only ``SIDECAR_TPU_AUTOPILOT_APPLY=1``
    in the operator's environment arms it."""
    return os.environ.get(ENV_APPLY, "0") == "1"


def default_axes(timecfg: TimeConfig,
                 params: Optional[SimParams] = None) -> tuple:
    """The stock searchable knobs, anchored at the status-quo config:
    gossip cadence (log scale — it spans orders of magnitude),
    transmit limit, and the suspicion window."""
    limit = params.resolved_retransmit_limit() if params is not None \
        else 6
    return (
        AxisSpec("push_pull_interval_s", 0.5, 30.0, log=True,
                 base=timecfg.push_pull_interval_s),
        AxisSpec("retransmit_limit", 2, 12, base=limit),
        AxisSpec("suspicion_window_s", 0.0, 8.0,
                 base=timecfg.suspicion_window_s),
    )


def axis_from_wire(doc: dict) -> AxisSpec:
    """An ``AxisSpec`` from the ``POST /autopilot/recommend`` wire form
    (unknown keys rejected loudly — a typoed bound silently defaulting
    would search the wrong space)."""
    if not isinstance(doc, dict):
        raise ValueError(f"axis entries must be objects, got {doc!r}")
    allowed = {"name", "lo", "hi", "integer", "log", "base"}
    bad = set(doc) - allowed
    if bad:
        raise ValueError(
            f"unknown axis field(s) {sorted(bad)}; expected a subset "
            f"of {sorted(allowed)}")
    for req in ("name", "lo", "hi"):
        if req not in doc:
            raise ValueError(f"axis entry missing {req!r}: {doc!r}")
    return AxisSpec(name=str(doc["name"]), lo=float(doc["lo"]),
                    hi=float(doc["hi"]),
                    integer=bool(doc.get("integer", False)),
                    log=bool(doc.get("log", False)),
                    base=None if doc.get("base") is None
                    else float(doc["base"]))


def estimate_from_wire(doc: dict, *, n: int,
                       services_per_node: int) -> ConditionEstimate:
    """A ``ConditionEstimate`` from the request body (operators may
    state conditions directly instead of fitting them)."""
    if not isinstance(doc, dict):
        raise ValueError(
            f"'estimate' must be an object, got {doc!r}")
    allowed = {"loss_rate", "churn_rate", "paused_frac",
               "seconds_per_round"}
    bad = set(doc) - allowed
    if bad:
        raise ValueError(
            f"unknown estimate field(s) {sorted(bad)}; expected a "
            f"subset of {sorted(allowed)}")
    for k in ("loss_rate", "churn_rate", "paused_frac"):
        v = doc.get(k, 0.0)
        if not 0.0 <= float(v) <= 1.0:
            raise ValueError(f"estimate.{k}={v} not in [0, 1]")
    return ConditionEstimate(
        n=n, services_per_node=services_per_node,
        loss_rate=float(doc.get("loss_rate", 0.0)),
        churn_rate=float(doc.get("churn_rate", 0.0)),
        paused_frac=float(doc.get("paused_frac", 0.0)),
        seconds_per_round=doc.get("seconds_per_round"),
        source="request")


def replay_check(result: EvalResult) -> dict:
    """Verify the winner OUTSIDE the batch: rebuild scenario ``lane``'s
    classic unbatched sim (``scenario_params`` / ``scenario_timecfg`` /
    ``scenario_plan`` + the static-prob churn twin) and require its
    final state to be bit-identical to the fleet row on every
    :data:`REPLAY_FIELDS` leaf.  A recommendation whose replay
    diverges is reported with ``identical: false`` — the controller
    refuses to apply it."""
    batch, run, lane = result.batch, result.run, result.lane
    spec = batch.specs[lane]
    params_i = batch.scenario_params(lane)
    perturb = (restart_churn_perturb(params_i, prob=spec.churn_prob)
               if spec.churn_prob > 0 else None)
    topo = (topo_mod.from_name(batch.topology, params_i.n)
            if batch.topology else topo_mod.complete(params_i.n))
    plan_i = batch.scenario_plan(lane)
    if plan_i is not None:
        from sidecar_tpu.chaos import ChaosExactSim
        sim = ChaosExactSim(params_i, topo, batch.scenario_timecfg(lane),
                            plan=plan_i, perturb=perturb)
    else:
        sim = ExactSim(params_i, topo, batch.scenario_timecfg(lane),
                       perturb=perturb)
    rounds = int(run.rounds[lane])       # stop=False → the full horizon
    final, _conv = sim.run(sim.init_state(),
                           jax.random.PRNGKey(spec.seed), rounds)
    fleet_st = run.final_states
    a_src = fleet_st.sim if hasattr(fleet_st, "sim") else fleet_st
    b_src = final.sim if hasattr(final, "sim") else final
    fields = {}
    for name in REPLAY_FIELDS:
        a = np.asarray(getattr(a_src, name))[lane]
        b = np.asarray(getattr(b_src, name))
        fields[name] = bool(np.array_equal(a, b))
    return {"checked": True, "rounds": rounds,
            "identical": all(fields.values()), "fields": fields}


class AutopilotController:
    """One recommendation pass over the knob space.

    ``bridge`` (bridge/sim_bridge.SimBridge) supplies the live catalog
    shape, the protocol clock, and the apply target; either may be
    omitted for library use (then ``n`` and ``estimate`` must be
    given)."""

    def __init__(self, bridge=None, state=None,
                 timecfg: Optional[TimeConfig] = None) -> None:
        self.bridge = bridge
        self.state = state if state is not None \
            else getattr(bridge, "state", None)
        self.timecfg = timecfg if timecfg is not None \
            else getattr(bridge, "t", None) or TimeConfig()

    # -- the loop ----------------------------------------------------------

    def recommend(self, *, rules=None, axes=None, estimate=None,
                  rounds: Optional[int] = None, eps: float = 0.01,
                  n: Optional[int] = None, services_per_node: int = 4,
                  fanout: int = 3, budget: int = 15, seed: int = 0,
                  seed_grid: int = 2, generations: Optional[int] = None,
                  population: Optional[int] = None, elites: int = 2,
                  apply: bool = False, provenance: int = 0,
                  max_batch: Optional[int] = None) -> dict:
        """Run fit → objective → search → verify → gate and return the
        report (also stored as ``state.autopilot_report``).  Raises
        ``ValueError`` on malformed rules/axes/estimate — the bridge
        maps it to a parseable 400."""
        t0 = time.perf_counter()
        if rules is None:
            raw = os.environ.get(ENV_RULES, "")
            rules = [r for r in (p.strip() for p in raw.split(","))
                     if r] or list(DEFAULT_AUTOPILOT_RULES)
        if not isinstance(rules, (list, tuple)) or not rules:
            raise ValueError(
                "'rules' must be a non-empty list of SLO rule strings")
        rounds = int(rounds if rounds is not None
                     else _env_int(ENV_ROUNDS, 120))
        if rounds < 1:
            raise ValueError(f"rounds={rounds} must be >= 1")
        generations = int(generations if generations is not None
                          else _env_int(ENV_GENERATIONS, 2))
        population = int(population if population is not None
                         else _env_int(ENV_POPULATION, 6))
        if n is None:
            if self.state is not None:
                with self.state._lock:
                    n = len(self.state.servers)
                n = max(int(n), 8)
            elif estimate is not None and hasattr(estimate, "n"):
                n = int(estimate.n)
            else:
                raise ValueError(
                    "'n' is required without a live catalog to size "
                    "the twin from")
        n, spn = int(n), int(services_per_node)

        if estimate is None:
            estimate = fit_live(n=n, services_per_node=spn)
        elif isinstance(estimate, dict):
            estimate = estimate_from_wire(estimate, n=n,
                                          services_per_node=spn)

        # Cold-start study clock (the sweep convention): refresh pinned
        # out so rounds-to-ε measures pure epidemic spread.
        cfg = dataclasses.replace(self.timecfg,
                                  refresh_interval_s=10_000.0)
        params = SimParams(n=n, services_per_node=spn,
                           fanout=int(fanout), budget=int(budget))
        if axes is None:
            axes = default_axes(cfg, params)
        else:
            axes = tuple(ax if isinstance(ax, AxisSpec)
                         else axis_from_wire(ax) for ax in axes)

        spr = cfg.round_ticks / cfg.ticks_per_second
        objective = Objective(rules, seconds_per_round=spr)
        base = dict(estimate.base_fields())
        base["seed"] = int(seed)
        plan = estimate.fault_plan(seed=int(seed))
        tracked = ()
        if provenance:
            from sidecar_tpu.ops import provenance as prov_ops
            tracked = prov_ops.default_tracked(params.m,
                                               int(provenance))
        evaluator = FleetEvaluator(
            params, cfg, objective, plan=plan, rounds=rounds,
            eps=float(eps), base=base, tracked=tracked,
            max_batch=max_batch)
        result = es_search(evaluator, axes, seed_grid=int(seed_grid),
                           generations=generations,
                           population=population, elites=int(elites),
                           seed=int(seed))
        replay = replay_check(result.best)

        # -- the apply gate ------------------------------------------------
        armed = apply_armed()
        applied_fields: dict = {}
        applied = False
        if apply and armed and replay["identical"]:
            applied_fields = {k: v for k, v
                              in result.best.candidate.items()
                              if k in _TIMECFG_FIELDS}
            if self.bridge is not None and applied_fields:
                self.bridge.t = dataclasses.replace(self.bridge.t,
                                                    **applied_fields)
            applied = bool(applied_fields)
            metrics.incr("autopilot.applied")
        elif apply:
            metrics.incr("autopilot.apply_blocked")

        wall = time.perf_counter() - t0
        metrics.incr("autopilot.recommendations")
        metrics.incr("autopilot.evaluations", result.evaluations)
        metrics.set_gauge("autopilot.best_score", result.best.score)
        if result.baseline is not None:
            metrics.set_gauge("autopilot.baseline_score",
                              result.baseline.score)
        best_pass = result.best.slo.get("pass")
        metrics.set_gauge("autopilot.slo_pass",
                          1.0 if best_pass else 0.0)
        metrics.set_gauge("autopilot.replay_identical",
                          1.0 if replay["identical"] else 0.0)
        metrics.histogram_since("autopilot.recommend", t0)

        report = {
            "rules": objective.rules_text,
            "estimate": estimate.to_json(),
            "axes": [dataclasses.asdict(ax) for ax in axes],
            "n": n, "services_per_node": spn,
            "fanout": int(fanout), "budget": int(budget),
            "rounds": rounds, "eps": float(eps), "seed": int(seed),
            "fault_plan": (None if plan is None else
                           {"nodes_paused": sum(
                               len(nf.nodes) for nf in plan.nodes),
                            "seed": plan.seed}),
            "baseline": (None if result.baseline is None
                         else result.baseline.to_json()),
            "recommended": result.best.to_json(),
            "evaluations": result.evaluations,
            "dispatches": result.dispatches,
            "generations_run": result.generations_run,
            "grid_points": result.grid_points,
            "candidates": len(result.history),
            "replay": replay,
            "apply": {"requested": bool(apply), "armed": armed,
                      "applied": applied, "fields": applied_fields},
            "wall_seconds": round(wall, 3),
        }
        if self.state is not None:
            self.state.autopilot_report = report
        return report
