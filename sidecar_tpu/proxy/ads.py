"""Envoy v3 ADS control plane — the SotW gRPC stream.

The reference's production Envoy path is a push-based gRPC ADS server
built on go-control-plane: a 1 s looper compares ``state.LastChanged``
to the cached value and publishes a full versioned snapshot on change
(envoy/server.go:61-124, versions are UnixNano, :54-59); the stream
layer replays the xDS state-of-the-world protocol — every
DiscoveryResponse carries a version + nonce, the client ACKs by echoing
both (or NACKs by echoing the nonce with an error_detail), and a new
snapshot triggers a push (envoy/server_test.go:138-205 drives exactly
this with a mock ADS client).

Since the query plane landed, the 1 s ``LastChanged`` poll is gone:
the server subscribes to the catalog's
:class:`~sidecar_tpu.query.hub.QueryHub` and rebuilds its xDS snapshot
the moment a delta arrives (push-on-delta), reading the hub's immutable
catalog snapshot — never ``state._lock``.  Wire versions are the hub's
monotonic snapshot versions, so the SotW contract (versioned full
snapshots, ACK/NACK by version + nonce) is unchanged on the wire while
update latency drops from worst-case 1 s to the hub's fan-out latency.

This implementation serves the protocol with grpcio generic handlers
(no generated service stubs) over the shared resource generation in
proxy/envoy.py.  Ordering on snapshot push follows go-control-plane's
make-before-break: clusters → endpoints → listeners.

Alongside SotW the server speaks **incremental (delta) xDS** on the
same ADS service (``DeltaAggregatedResources``): per-resource version
stamps from ``EnvoyResources.versions`` let each stream diff a
client's acked cache against the new snapshot and ship only changed
resources + removed names per hub delta, with full-set resync as the
fallback on version-gap or NACK — and the snapshot rebuild itself
reuses the previous snapshot's encoded Any objects for
version-unchanged resources (``ads.delta.*`` metrics,
docs/query.md)."""

from __future__ import annotations

import logging
import queue
import threading
from concurrent import futures
from typing import Optional

import grpc

from sidecar_tpu import metrics
from sidecar_tpu.catalog.state import ServicesState
from sidecar_tpu.proxy import xds_proto
from sidecar_tpu.proxy.envoy import (
    TYPE_CLUSTER,
    TYPE_ENDPOINT,
    TYPE_LISTENER,
    resources_from_state,
)

log = logging.getLogger(__name__)

ADS_METHOD = ("/envoy.service.discovery.v3.AggregatedDiscoveryService/"
              "StreamAggregatedResources")
ADS_DELTA_METHOD = ("/envoy.service.discovery.v3."
                    "AggregatedDiscoveryService/DeltaAggregatedResources")

# Make-before-break push order (go-control-plane's ADS ordering).
PUSH_ORDER = (TYPE_CLUSTER, TYPE_ENDPOINT, TYPE_LISTENER)

# EnvoyResources.versions kind key per type_url.
_VERSION_KIND = {TYPE_CLUSTER: "clusters", TYPE_ENDPOINT: "endpoints",
                 TYPE_LISTENER: "listeners"}


class Snapshot:
    """One immutable versioned resource set (server.go:54-59).

    ``by_type`` maps type_url → list of ``(name, Any)`` pairs so the
    stream can scope a response to a request's ``resource_names``
    (go-control-plane's sotw responder filters by name — the semantics
    behind envoy/server.go:61-124).

    ``versions`` maps type_url → ``{name: resource version}`` (the
    per-resource stamps from ``EnvoyResources.versions``) — the delta
    xDS stream diffs a client's acked cache against these to send only
    changed resources + removed names instead of the full set."""

    def __init__(self, version: str, by_type: dict[str, list],
                 versions: Optional[dict[str, dict[str, str]]] = None):
        self.version = version
        self.by_type = by_type
        self.versions = versions if versions is not None \
            else {t: {name: version for name, _ in pairs}
                  for t, pairs in by_type.items()}

    def resources(self, type_url: str, names) -> list:
        """The Any payloads for one response: everything for a wildcard
        subscription (empty ``names``), else only the requested names —
        in sotw, names the snapshot doesn't have are simply omitted."""
        pairs = self.by_type.get(type_url, [])
        if not names:
            return [res for _, res in pairs]
        return [res for name, res in pairs if name in names]

    def pairs(self, type_url: str) -> dict:
        return dict(self.by_type.get(type_url, []))


class AdsServer:
    """Snapshot cache + hub-driven refresh + the ADS stream service."""

    def __init__(self, state: ServicesState, bind_ip: str = "0.0.0.0",
                 use_hostnames: bool = False) -> None:
        self.state = state
        self.bind_ip = bind_ip
        self.use_hostnames = use_hostnames
        self._snapshot = Snapshot("0", {t: [] for t in PUSH_ORDER})
        self._published_version = -1   # hub version of self._snapshot
        self._damping_gen = 0          # forced-rebuild counter (see refresh)
        self._damped_seen: frozenset = frozenset()
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._server: Optional[grpc.Server] = None
        self._delta_thread: Optional[threading.Thread] = None

    # -- snapshot maintenance ----------------------------------------------

    def refresh(self, force: bool = False) -> bool:
        """Rebuild + publish an xDS snapshot if the hub moved past the
        published version (server.go:70-110 recast onto the query
        plane).  Reads the hub's immutable catalog snapshot — no
        ``state._lock`` — and reuses its version as the SotW wire
        version.  True when a new snapshot was set.

        ``force`` rebuilds even at an unchanged hub version — the
        damping readmission path: a suppressed service readmits by
        penalty DECAY, which produces no catalog event, so the delta
        loop forces a rebuild when it notices the damped set moved.
        The wire version gains a ``.d<n>`` suffix then, keeping SotW
        versions unique without faking a catalog change."""
        hub = self.state.query_hub()
        catalog = hub.current()
        if catalog.version == self._published_version and not force:
            return False
        # Flap-damped admission on the snapshot path (catalog/damping.py
        # via the hub): suppressed instances are withheld from the xDS
        # resource set without leaving the catalog.
        res = resources_from_state(catalog, self.bind_ip,
                                   self.use_hostnames, eds_mode="ads",
                                   damper=hub.damper)
        # Incremental rebuild: a resource whose per-name version stamp
        # is unchanged since the previous snapshot keeps its encoded Any
        # object (the stamps are constructed so version-unchanged ⇒
        # content-unchanged, proxy/envoy.py) — per hub delta the proto
        # encoding work is O(changed resources), not O(catalog).
        prev = self.snapshot()
        versions = {t: dict(res.versions[k])
                    for t, k in _VERSION_KIND.items()}
        sources = {
            TYPE_CLUSTER: (res.clusters, "name",
                           xds_proto.cluster_to_any),
            TYPE_ENDPOINT: (res.endpoints, "cluster_name",
                            xds_proto.endpoint_to_any),
            TYPE_LISTENER: (res.listeners, "name",
                            xds_proto.listener_to_any),
        }
        reused = encoded = 0
        by_type: dict[str, list] = {}
        for type_url, (dicts, key, encode) in sources.items():
            prev_pairs = prev.pairs(type_url)
            prev_vers = prev.versions.get(type_url, {})
            pairs = []
            for doc in dicts:
                name = doc[key]
                if name in prev_pairs and \
                        prev_vers.get(name) == versions[type_url][name]:
                    pairs.append((name, prev_pairs[name]))
                    reused += 1
                else:
                    pairs.append((name, encode(doc)))
                    encoded += 1
            by_type[type_url] = pairs
        metrics.incr("ads.delta.reused", reused)
        metrics.incr("ads.delta.encoded", encoded)
        with self._cond:
            version = str(catalog.version)
            if catalog.version == self._published_version:
                # Forced (damping-driven) rebuild at the same catalog
                # version: suffix a generation counter so every pushed
                # SotW version stays distinct.
                self._damping_gen += 1
                version = f"{catalog.version}.d{self._damping_gen}"
            else:
                self._damping_gen = 0
            self._snapshot = Snapshot(version, by_type, versions)
            self._published_version = catalog.version
            self._cond.notify_all()
        log.debug("ads: published snapshot %s", self._snapshot.version)
        return True

    def snapshot(self) -> Snapshot:
        with self._cond:
            return self._snapshot

    def _delta_loop(self) -> None:
        """Push-on-delta: block on the hub subscription, refresh on any
        event.  A tiny buffer is enough — coalescing to snapshot-at-
        latest is exactly right here, since refresh always reads the
        CURRENT catalog snapshot regardless of how many deltas the
        wake-up represents."""
        sub = self.state.query_hub().subscribe("ads", buffer=4,
                                               prime=False)
        try:
            # Close the serve()-time race: a publish that lands after
            # serve()'s initial refresh() but before this subscribe()
            # has no subscriber to wake — catch up once, now that every
            # later publish is guaranteed to land on the queue.  (The
            # old 1 s poll hid this window; no-op when nothing moved.)
            try:
                self.refresh()
            except Exception:
                log.exception("ads: snapshot refresh failed")
            while not self._stop.is_set():
                ev = sub.get(timeout=0.5)
                if ev is None:
                    # Idle tick: damping readmission is driven by
                    # penalty DECAY (no catalog event fires), so check
                    # whether the damped set moved and force a rebuild
                    # when it did.
                    damper = self.state.query_hub().damper
                    if damper is not None:
                        damped = frozenset(damper.damped())
                        if damped != self._damped_seen:
                            try:
                                self.refresh(force=True)
                                # Recorded only AFTER a successful
                                # rebuild, so a transient refresh
                                # failure is retried next tick instead
                                # of leaving Envoys on stale routing.
                                self._damped_seen = damped
                            except Exception:
                                log.exception(
                                    "ads: damping refresh failed")
                    continue
                sub.drain()  # collapse the burst; refresh reads latest
                try:
                    self.refresh()
                    damper = self.state.query_hub().damper
                    if damper is not None:
                        self._damped_seen = frozenset(damper.damped())
                except Exception:
                    log.exception("ads: snapshot refresh failed")
        finally:
            sub.close()

    # -- the stream handler -------------------------------------------------

    def stream_aggregated_resources(self, request_iterator, context):
        """One ADS stream: per-type version/nonce bookkeeping, pushes on
        snapshot change, ACK/NACK handling (the SotW protocol)."""
        requests: queue.Queue = queue.Queue()
        done = threading.Event()

        def reader():
            try:
                for req in request_iterator:
                    requests.put(req)
            except Exception:
                pass
            finally:
                done.set()

        threading.Thread(target=reader, daemon=True,
                         name="ads-stream-reader").start()

        nonce_counter = 0
        # type_url → {"sent_version", "nonce", "names"} — the whole SotW
        # per-stream state.  A NACKed version needs no extra flag: the
        # push loop only re-sends when sent_version differs from the
        # current snapshot, and a NACK leaves sent_version at the
        # rejected (= current) one, so nothing re-fires until a NEW
        # snapshot exists — exactly the protocol's intent.  ``names`` is
        # the type's current resource_names subscription (empty =
        # wildcard): responses are scoped to it (Envoy subscribes to EDS
        # per cluster name; go-control-plane's sotw server honors
        # DiscoveryRequest.ResourceNames, the layer behind
        # envoy/server.go:61-124), and a request that changes it gets an
        # immediate re-response even at an ACKed version.
        subs: dict[str, dict] = {}

        def respond(snap: Snapshot, type_url: str):
            nonlocal nonce_counter
            nonce_counter += 1
            nonce = str(nonce_counter)
            resp = xds_proto.pb().DiscoveryResponse(
                version_info=snap.version, type_url=type_url,
                nonce=nonce)
            resp.resources.extend(
                snap.resources(type_url, subs[type_url]["names"]))
            subs[type_url].update(sent_version=snap.version, nonce=nonce)
            return resp

        while not done.is_set() and not self._stop.is_set():
            try:
                req = requests.get(timeout=0.1)
            except queue.Empty:
                # Push path: a new snapshot goes to every subscribed
                # type that has ACKed (or at least been sent) an older
                # version, in make-before-break order.
                snap = self.snapshot()
                for type_url in PUSH_ORDER:
                    sub = subs.get(type_url)
                    if sub is not None and \
                            sub["sent_version"] != snap.version:
                        yield respond(snap, type_url)
                continue

            type_url = req.type_url
            if not type_url:
                log.warning("ads: request with empty type_url ignored")
                continue
            sub = subs.setdefault(
                type_url, {"sent_version": None, "nonce": None,
                           "names": frozenset()})
            names = frozenset(req.resource_names)

            if req.response_nonce and req.response_nonce != sub["nonce"]:
                # Stale nonce: response to a superseded push — its
                # ACK/NACK meaning is void (the xDS spec's
                # stale-response rule), but a changed resource_names set
                # is still the client's CURRENT subscription and must be
                # served now: a cluster added here would otherwise go
                # without endpoints until the next catalog change.
                if names != sub["names"]:
                    sub["names"] = names
                    yield respond(self.snapshot(), type_url)
                continue
            if req.response_nonce and req.HasField("error_detail"):
                # NACK: the client rejected sent_version; the push loop
                # stays quiet until a NEW snapshot version exists.  A
                # NACK can still legally carry a changed subscription —
                # and that part is not rejected content, so answer it
                # immediately at the current version (mirroring the
                # ACK-with-changed-names branch).
                log.warning("ads: NACK for %s version %s: %s", type_url,
                            req.version_info, req.error_detail.message)
                if names != sub["names"]:
                    sub["names"] = names
                    yield respond(self.snapshot(), type_url)
                continue
            if req.response_nonce:
                # ACK of sent_version.  If the subscription set changed
                # (e.g. Envoy adds an EDS cluster name), answer it at
                # the current version with the re-scoped resource set.
                if names != sub["names"]:
                    sub["names"] = names
                    yield respond(self.snapshot(), type_url)
                continue

            # Initial subscription request for this type.
            sub["names"] = names
            yield respond(self.snapshot(), type_url)

    # -- the incremental (delta) stream handler ------------------------------

    def delta_aggregated_resources(self, request_iterator, context):
        """One incremental ADS stream (delta xDS, docs/query.md).

        Per type the stream keeps the client's acked resource cache
        (``name → version``) and, on every new snapshot, sends ONLY the
        resources whose per-name version moved plus the removed names —
        instead of regenerating and resending the full set per hub
        delta.  Full-set resync stays the fallback:

        * a client that cannot prove its cache (no
          ``initial_resource_versions`` on subscribe — the version-gap
          case) gets the complete set (``ads.delta.full_resync``);
        * a NACK wipes the server's view of the client cache and the
          next response is again the complete set (``ads.delta.nack``).
        """
        requests: queue.Queue = queue.Queue()
        done = threading.Event()

        def reader():
            try:
                for req in request_iterator:
                    requests.put(req)
            except Exception:
                pass
            finally:
                done.set()

        threading.Thread(target=reader, daemon=True,
                         name="ads-delta-stream-reader").start()

        nonce_counter = 0
        # type_url → {"names": frozenset | None (None = wildcard),
        #             "have": {name: version} (client cache, server
        #             view), "nonce", "system_version", "resync"}.
        subs: dict[str, dict] = {}

        def respond(snap: Snapshot, type_url: str, sub: dict,
                    full: bool = False):
            """Build one DeltaDiscoveryResponse, or None when the
            client's cache already matches the snapshot scope."""
            nonlocal nonce_counter
            vers = snap.versions.get(type_url, {})
            pairs = snap.pairs(type_url)
            scope = set(pairs) if sub["names"] is None \
                else set(sub["names"]) & set(pairs)
            have = sub["have"]
            if full:
                changed = sorted(scope)
            else:
                changed = sorted(n for n in scope
                                 if have.get(n) != vers.get(n))
            removed = sorted(set(have) - scope)
            sub["system_version"] = snap.version
            if not changed and not removed and not full:
                return None
            nonce_counter += 1
            nonce = str(nonce_counter)
            x = xds_proto.pb()
            resp = x.DeltaDiscoveryResponse(
                system_version_info=snap.version, type_url=type_url,
                nonce=nonce)
            wrapped = []
            for name in changed:
                r = x.Resource(name=name,
                               version=vers.get(name, snap.version))
                r.resource.CopyFrom(pairs[name])
                wrapped.append(r)
            resp.resources.extend(wrapped)
            resp.removed_resources.extend(removed)
            # Server-side view of the client cache advances at send
            # time; a NACK resets it (full resync), so a rejected
            # update can never strand the client on a diff base the
            # server believes but the client refused.
            for name in changed:
                have[name] = vers.get(name, snap.version)
            for name in removed:
                have.pop(name, None)
            sub["nonce"] = nonce
            metrics.incr("ads.delta.resources_sent", len(changed))
            metrics.incr("ads.delta.removed_sent", len(removed))
            if full:
                metrics.incr("ads.delta.full_resync")
            return resp

        while not done.is_set() and not self._stop.is_set():
            try:
                req = requests.get(timeout=0.1)
            except queue.Empty:
                # Push path: diff every subscribed type against the new
                # snapshot in make-before-break order.  A type whose
                # scope didn't move just advances its system version —
                # no response on the wire (the whole point).
                snap = self.snapshot()
                for type_url in PUSH_ORDER:
                    sub = subs.get(type_url)
                    if sub is None or \
                            sub["system_version"] == snap.version:
                        continue
                    resp = respond(snap, type_url, sub,
                                   full=sub["resync"])
                    if resp is not None:
                        sub["resync"] = False
                        yield resp
                continue

            type_url = req.type_url
            if not type_url:
                log.warning("ads: delta request with empty type_url "
                            "ignored")
                continue
            first = type_url not in subs
            sub = subs.setdefault(
                type_url, {"names": None, "have": {}, "nonce": None,
                           "system_version": None, "resync": False})
            sub_names = list(req.resource_names_subscribe)
            unsub_names = set(req.resource_names_unsubscribe)

            if first:
                # Initial subscription: explicit names, or wildcard
                # when none / "*" are given.  initial_resource_versions
                # is the client's surviving cache (e.g. across a
                # reconnect): only resources whose version moved are
                # resent, stale/unknown names come back as removals.
                # No initial versions = nothing provable = full set.
                if sub_names and sub_names != ["*"]:
                    sub["names"] = frozenset(sub_names)
                sub["have"] = dict(req.initial_resource_versions)
                resp = respond(self.snapshot(), type_url, sub,
                               full=not sub["have"])
                if resp is not None:
                    yield resp
                continue

            if req.response_nonce and req.response_nonce != sub["nonce"]:
                # Stale nonce: ACK/NACK meaning void (xDS stale-response
                # rule); subscription changes below still apply.
                pass
            elif req.response_nonce and req.HasField("error_detail"):
                # NACK: the client rejected the last delta — the
                # server-side cache view is no longer trustworthy, so
                # wipe it and resend the complete scoped set.
                log.warning("ads: delta NACK for %s: %s", type_url,
                            req.error_detail.message)
                metrics.incr("ads.delta.nack")
                sub["have"] = {}
                resp = respond(self.snapshot(), type_url, sub, full=True)
                if resp is not None:
                    yield resp
                continue

            # Subscription maintenance (ACK or spontaneous request):
            # newly subscribed names are served immediately, an
            # unsubscribe drops them from the tracked cache.
            changed_scope = False
            if sub_names and sub["names"] is not None:
                new = frozenset(sub["names"]) | set(sub_names)
                if new != sub["names"]:
                    sub["names"] = new
                    changed_scope = True
            if unsub_names and sub["names"] is not None:
                sub["names"] = frozenset(sub["names"]) - unsub_names
                for name in unsub_names:
                    sub["have"].pop(name, None)
                changed_scope = True
            if changed_scope:
                resp = respond(self.snapshot(), type_url, sub)
                if resp is not None:
                    yield resp

    # -- serving ------------------------------------------------------------

    def _handlers(self):
        x = xds_proto.pb()
        rpc = grpc.stream_stream_rpc_method_handler(
            self.stream_aggregated_resources,
            request_deserializer=x.DiscoveryRequest.FromString,
            response_serializer=x.DiscoveryResponse.SerializeToString)
        delta_rpc = grpc.stream_stream_rpc_method_handler(
            self.delta_aggregated_resources,
            request_deserializer=x.DeltaDiscoveryRequest.FromString,
            response_serializer=(
                x.DeltaDiscoveryResponse.SerializeToString))
        service, method = ADS_METHOD.lstrip("/").split("/")
        delta_method = ADS_DELTA_METHOD.rsplit("/", 1)[1]
        return grpc.method_handlers_generic_handler(
            service, {method: rpc, delta_method: delta_rpc})

    def serve(self, bind: str = "0.0.0.0", port: int = 7776) -> int:
        """Start the gRPC server (reference binds :7776,
        config/config.go:32).  Returns the bound port (0 → ephemeral)."""
        self.refresh()
        # Each open ADS stream occupies one worker for its lifetime;
        # size the pool well past any realistic same-host Envoy count so
        # an extra client never hangs waiting for a slot.
        # so_reuseport off: grpc's default lets several servers silently
        # SHARE a port on Linux — two nodes on one host would each get a
        # random subset of Envoy streams instead of one of them failing
        # loudly (the conflict check below).
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=64,
                                       thread_name_prefix="ads"),
            options=(("grpc.so_reuseport", 0),))
        self._server.add_generic_rpc_handlers((self._handlers(),))
        bound = self._server.add_insecure_port(f"{bind}:{port}")
        if bound == 0 and port != 0:
            # grpc reports a bind conflict by returning port 0 instead
            # of raising; surface it like any other server would so
            # callers can degrade deliberately (main.py logs and runs
            # on without a control plane).
            self._server.stop(grace=0)
            self._server = None
            raise OSError(f"ads: failed to bind {bind}:{port} "
                          "(address in use?)")
        self._server.start()
        self._delta_thread = threading.Thread(
            target=self._delta_loop, name="ads-delta", daemon=True)
        self._delta_thread.start()
        log.info("ads: gRPC control plane on %s:%d", bind, bound)
        return bound

    def shutdown(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.stop(grace=0.5)
        if self._delta_thread is not None:
            self._delta_thread.join(timeout=2.0)
