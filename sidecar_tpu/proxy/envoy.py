"""Envoy control plane: xDS resource generation + serving.

Reference: envoy/adapter/adapter.go:33-390 (resource generation),
envoy/server.go:22-139 (ADS server with a 1 s LastChanged poll), and
sidecarhttp/envoy_api.go:25-438 (legacy V1 REST SDS/CDS/LDS).

The reference builds go-control-plane v2 protobufs and pushes them over
an ADS gRPC stream.  Here resources are generated as **v3 proto-JSON**
dicts — the JSON encoding Envoy itself accepts — and served through
Envoy's REST xDS transport (``api_type: REST`` fetch), which needs no
gRPC stack; the same resource-generation logic (port-collision guard
with oldest-wins via the sorted state walk, EDS-type clusters,
per-ProxyMode filter chains incl. websocket upgrade) is preserved.
A gRPC ADS server can be layered on the same ``resources_from_state``
output when grpcio is available."""

from __future__ import annotations

import dataclasses
import json
import logging
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from sidecar_tpu.catalog.state import ServicesState
from sidecar_tpu.service import Service, ns_to_rfc3339

log = logging.getLogger(__name__)

SERVICE_NAME_SEPARATOR = ":"          # adapter.go:33
PORT_COLLISION_LOGGING_BACKOFF = 60.0  # adapter.go:37
LOOPER_UPDATE_INTERVAL = 1.0          # server.go:25

TYPE_CLUSTER = "type.googleapis.com/envoy.config.cluster.v3.Cluster"
TYPE_ENDPOINT = ("type.googleapis.com/"
                 "envoy.config.endpoint.v3.ClusterLoadAssignment")
TYPE_LISTENER = "type.googleapis.com/envoy.config.listener.v3.Listener"

# Name of the static cluster an Envoy bootstrap must define pointing at
# this control plane; generated REST eds_configs reference it.
XDS_CLUSTER_NAME = "sidecar_xds"


def _eds_config(eds_mode: str) -> dict:
    """EDS source stanza matching the serving transport.  A cluster that
    declares ``{"ads": {}}`` but is served over REST never resolves its
    endpoints (Envoy waits for an ADS stream that doesn't exist), so the
    REST path must emit an api_config_source instead."""
    if eds_mode == "ads":
        return {"ads": {}, "resource_api_version": "V3"}
    if eds_mode == "rest":
        return {
            "resource_api_version": "V3",
            "api_config_source": {
                "api_type": "REST",
                "transport_api_version": "V3",
                "cluster_names": [XDS_CLUSTER_NAME],
                "refresh_delay": "1s",
            },
        }
    raise ValueError(f"unknown eds_mode {eds_mode!r} (want 'ads' or 'rest')")

_last_logged_port_collision = 0.0


def svc_name(name: str, port: int) -> str:
    """adapter.go:52-55."""
    return f"{name}{SERVICE_NAME_SEPARATOR}{port}"


def svc_name_split(name: str) -> tuple[str, int]:
    """adapter.go:57-70; raises ValueError on bad input."""
    parts = name.split(SERVICE_NAME_SEPARATOR)
    if len(parts) < 2:
        raise ValueError("Unable to split service name and port!")
    try:
        return parts[0], int(parts[1])
    except ValueError as exc:
        raise ValueError("Unable to parse port!") from exc


def lookup_host(hostname: str) -> str:
    """adapter.go:73-82 — dev-mode-only DNS resolution."""
    return socket.gethostbyname(hostname)


@dataclasses.dataclass
class EnvoyResources:
    """adapter.go:45-49 — v3 proto-JSON resource dicts.

    ``versions`` maps resource kind (``"endpoints"``/``"clusters"``/
    ``"listeners"``) → ``{envoy_name: version}`` — per-resource version
    stamps derived from the snapshot's frozen per-service ``updated``
    stamps, chosen so a resource's version changes **iff its content
    can have changed** (the incremental-xDS invariant, docs/query.md):

    * endpoints — ``"<max contributing updated>.<endpoint count>"``:
      any address/status/damping admission change bumps a contributor's
      stamp or the count;
    * clusters — constant (content is a pure function of the name and
      the server's fixed eds_mode);
    * listeners — the owning service's proxy mode (content is
      ``f(name, port, proxy_mode, bind_ip)``; name/port are the
      resource name, bind_ip is fixed per server).
    """

    endpoints: list[dict]
    clusters: list[dict]
    listeners: list[dict]
    versions: Optional[dict[str, dict[str, str]]] = None


def _lb_endpoints(svc: Service, svc_port: int,
                  use_hostnames: bool) -> list[dict]:
    """adapter.go:355-390."""
    out = []
    for port in svc.ports:
        if port.service_port != svc_port:
            continue
        address = port.ip
        if use_hostnames:
            try:
                address = lookup_host(svc.hostname)
            except OSError:
                log.warning("Unable to resolve %s, using IP address",
                            svc.hostname)
        out.append({
            "endpoint": {
                "address": {"socket_address": {
                    "address": address, "port_value": port.port}},
            }
        })
    return out


def _http_connection_manager(svc: Service, envoy_name: str,
                             websocket: bool) -> dict:
    """adapter.go:218-296 (v3 shape)."""
    manager = {
        "@type": ("type.googleapis.com/envoy.extensions.filters.network."
                  "http_connection_manager.v3.HttpConnectionManager"),
        "stat_prefix": "ingress_http",
        "http_filters": [{
            "name": "envoy.filters.http.router",
            "typed_config": {
                "@type": ("type.googleapis.com/envoy.extensions.filters."
                          "http.router.v3.Router")},
        }],
        "route_config": {
            "validate_clusters": False,
            "virtual_hosts": [{
                "name": svc.name,
                "domains": ["*"],
                "routes": [{
                    "match": {"prefix": "/"},
                    "route": {"cluster": envoy_name, "timeout": "0s"},
                }],
            }],
        },
    }
    if websocket:
        manager["upgrade_configs"] = [{"upgrade_type": "websocket"}]
    return manager


def _connection_manager(svc: Service, envoy_name: str) -> tuple[str, dict]:
    """adapter.go:216-304; raises ValueError on unknown proxy mode."""
    if svc.proxy_mode == "http":
        return ("envoy.filters.network.http_connection_manager",
                _http_connection_manager(svc, envoy_name, websocket=False))
    if svc.proxy_mode == "tcp":
        return ("envoy.filters.network.tcp_proxy", {
            "@type": ("type.googleapis.com/envoy.extensions.filters."
                      "network.tcp_proxy.v3.TcpProxy"),
            "stat_prefix": "ingress_tcp",
            "cluster": envoy_name,
        })
    if svc.proxy_mode == "ws":
        return ("envoy.filters.network.http_connection_manager",
                _http_connection_manager(svc, envoy_name, websocket=True))
    raise ValueError(f"unrecognised proxy mode: {svc.proxy_mode}")


def _listener_from_service(svc: Service, envoy_name: str, svc_port: int,
                           bind_ip: str) -> dict:
    """adapter.go:320-351."""
    manager_name, manager = _connection_manager(svc, envoy_name)
    return {
        "name": envoy_name,
        "address": {"socket_address": {
            "address": bind_ip, "port_value": svc_port}},
        "filter_chains": [{
            "filters": [{"name": manager_name,
                         "typed_config": manager}],
        }],
    }


def resources_from_state(state: ServicesState, bind_ip: str = "0.0.0.0",
                         use_hostnames: bool = False,
                         eds_mode: str = "rest",
                         damper=None) -> EnvoyResources:
    """Full resource set from the catalog (adapter.go:108-212).

    The port-collision guard gives each ServicePort to the first (oldest,
    via the sorted state walk) service claiming it — multiple listeners
    on one port make Envoy melt down (adapter.go:87-103).

    ``damper`` (catalog/damping.py): flap-damped admission — instances
    the damper currently suppresses are withheld from the resource set
    (no endpoint, no listener) while remaining in the catalog; they
    readmit automatically once their penalty decays below the reuse
    threshold."""
    global _last_logged_port_collision
    endpoint_map: dict[str, dict] = {}
    cluster_map: dict[str, dict] = {}
    listener_map: dict[str, dict] = {}
    ports_map: dict[int, str] = {}
    # Per-resource version inputs (see EnvoyResources.versions):
    # envoy_name → [max contributing svc.updated, lb endpoint count].
    ep_stamp: dict[str, list] = {}
    listener_mode: dict[str, str] = {}

    # ``state`` is either a live ServicesState (walk under its lock,
    # copying out) or an immutable query-plane CatalogSnapshot (no lock
    # to take, nothing can mutate — the ADS path reads snapshots).
    lock = getattr(state, "_lock", None)
    if lock is None:
        walk = list(state.each_service_sorted())
    else:
        with lock:
            walk = [(c, h, svc.copy())
                    for c, h, svc in state.each_service_sorted()]
    for _, _, svc in walk:
        if not svc.is_alive():
            continue
        if damper is not None and not damper.admitted(svc):
            continue
        for port in svc.ports:
            if port.service_port < 1:
                continue
            owner = ports_map.setdefault(port.service_port, svc.name)
            if owner != svc.name:
                now = time.monotonic()
                if now - _last_logged_port_collision > \
                        PORT_COLLISION_LOGGING_BACKOFF:
                    log.warning(
                        "Port collision! %s is attempting to squat on port "
                        "%d owned by %s", svc.name, port.service_port,
                        owner)
                    _last_logged_port_collision = now
                continue

            envoy_name = svc_name(svc.name, port.service_port)
            lbs = _lb_endpoints(svc, port.service_port, use_hostnames)
            stamp = ep_stamp.setdefault(envoy_name, [0, 0])
            stamp[0] = max(stamp[0], svc.updated)
            stamp[1] += len(lbs)
            if envoy_name in endpoint_map:
                endpoint_map[envoy_name]["endpoints"][0][
                    "lb_endpoints"].extend(lbs)
            else:
                endpoint_map[envoy_name] = {
                    "@type": TYPE_ENDPOINT,
                    "cluster_name": envoy_name,
                    "endpoints": [{"lb_endpoints": lbs}],
                }
                cluster_map[envoy_name] = {
                    "@type": TYPE_CLUSTER,
                    "name": envoy_name,
                    "connect_timeout": "0.500s",
                    "type": "EDS",
                    "eds_cluster_config": {
                        "eds_config": _eds_config(eds_mode),
                    },
                }
            if envoy_name not in listener_map:
                try:
                    listener_map[envoy_name] = _listener_from_service(
                        svc, envoy_name, port.service_port, bind_ip)
                    listener_mode[envoy_name] = svc.proxy_mode
                except ValueError as exc:
                    log.error("Failed to create Envoy listener for service "
                              "%r and port %d: %s", svc.name,
                              port.service_port, exc)
                    continue

    return EnvoyResources(
        endpoints=list(endpoint_map.values()),
        clusters=list(cluster_map.values()),
        listeners=list(listener_map.values()),
        versions={
            "endpoints": {n: f"{s[0]}.{s[1]}"
                          for n, s in ep_stamp.items()
                          if n in endpoint_map},
            "clusters": {n: "cfg" for n in cluster_map},
            "listeners": dict(listener_mode),
        },
    )


# -- V1 REST API (deprecated in the reference, kept for parity) ------------

class EnvoyApiV1:
    """sidecarhttp/envoy_api.go:25-438: SDS /v1/registration/{service},
    CDS /v1/clusters, LDS /v1/listeners."""

    def __init__(self, state: ServicesState, bind_ip: str = "0.0.0.0",
                 use_hostnames: bool = False, cluster_name: str = "") -> None:
        self.state = state
        self.bind_ip = bind_ip
        self.use_hostnames = use_hostnames
        self.cluster_name = cluster_name

    def _service_entry(self, svc: Service,
                       svc_port: int) -> Optional[dict]:
        for port in svc.ports:
            if port.service_port != svc_port:
                continue
            address = port.ip
            if self.use_hostnames:
                try:
                    address = lookup_host(svc.hostname)
                except OSError:
                    log.warning("Unable to resolve %s, using IP address",
                                svc.hostname)
            return {
                "ip_address": address,
                "last_check_in": ns_to_rfc3339(svc.updated),
                "port": port.port,
                "revision": svc.version(),
                "service": svc_name(svc.name, svc_port),
                "service_repo_name": svc.image,
                "tags": {},
            }
        return None

    def registration(self, name: str):
        """SDS (envoy_api.go:114-176)."""
        try:
            wanted, port = svc_name_split(name)
        except ValueError as exc:
            return 404, {"status": "error",
                         "message": f"Not Found - {exc}"}
        # Snapshot matches under the lock, build entries after: with
        # use_hostnames the entry builder does DNS lookups, which must
        # not stall catalog writers (the clusters/listeners walks use
        # the same copy-then-process pattern).  Copies, not references:
        # catalog writers mutate Service in place (catalog/state.py
        # AddServiceEntry sets status/updated), so a live reference read
        # after lock release could serve a half-updated record.
        with self.state._lock:
            matched = [svc.copy() for _, _, svc in self.state.each_service()
                       if svc.name == wanted and svc.is_alive()]
        hosts = []
        for svc in matched:
            entry = self._service_entry(svc, port)
            if entry is not None:
                hosts.append(entry)
        return 200, {"env": self.cluster_name, "hosts": hosts,
                     "service": name}

    def clusters(self):
        """CDS (envoy_api.go:180-208, 280-310)."""
        out = []
        seen: dict[int, str] = {}
        with self.state._lock:
            walk = [(c, h, svc.copy())
                    for c, h, svc in self.state.each_service_sorted()]
        for _, _, svc in walk:
            if not svc.is_alive():
                continue
            for port in svc.ports:
                if port.service_port < 1:
                    continue
                if seen.setdefault(port.service_port, svc.name) != svc.name:
                    continue
                name = svc_name(svc.name, port.service_port)
                if any(c["name"] == name for c in out):
                    continue
                out.append({
                    "name": name,
                    "type": "sds",
                    "connect_timeout_ms": 500,
                    "lb_type": "round_robin",
                    "service_name": name,
                })
        return 200, {"clusters": out}

    def listeners(self):
        """LDS (envoy_api.go:212-276, 314-424)."""
        out = []
        seen: dict[int, str] = {}
        with self.state._lock:
            walk = [(c, h, svc.copy())
                    for c, h, svc in self.state.each_service_sorted()]
        for _, _, svc in walk:
            if not svc.is_alive():
                continue
            for port in svc.ports:
                if port.service_port < 1:
                    continue
                if seen.setdefault(port.service_port, svc.name) != svc.name:
                    continue
                name = svc_name(svc.name, port.service_port)
                if any(l["name"] == name for l in out):
                    continue
                address = f"tcp://{self.bind_ip}:{port.service_port}"
                if svc.proxy_mode == "tcp":
                    filters = [{
                        "name": "tcp_proxy",
                        "config": {
                            "stat_prefix": "ingress_tcp",
                            "route_config": {
                                "routes": [{"cluster": name}],
                            },
                        },
                    }]
                else:
                    filters = [{
                        "name": "http_connection_manager",
                        "config": {
                            "codec_type": "auto",
                            "stat_prefix": "ingress_http",
                            "route_config": {
                                "virtual_hosts": [{
                                    "name": svc.name,
                                    "domains": ["*"],
                                    "routes": [{
                                        "timeout_ms": 0,
                                        "prefix": "/",
                                        "host_rewrite": "",
                                        "cluster": name,
                                    }],
                                }],
                            },
                        },
                    }]
                out.append({"name": name, "address": address,
                            "filters": filters})
        return 200, {"listeners": out}


# -- REST xDS v3 server ----------------------------------------------------

class XdsServer:
    """Serves v3 resources over Envoy's REST xDS transport and keeps a
    versioned snapshot refreshed on a LastChanged poll (server.go:61-124;
    versions are UnixNano, server.go:54-59)."""

    def __init__(self, state: ServicesState, bind_ip: str = "0.0.0.0",
                 use_hostnames: bool = False) -> None:
        self.state = state
        self.bind_ip = bind_ip
        self.use_hostnames = use_hostnames
        self._snapshot: Optional[EnvoyResources] = None
        self._version = "0"
        self._last_changed = -1
        self._damped_seen: frozenset = frozenset()
        self._lock = threading.Lock()

    def refresh(self) -> bool:
        """Rebuild the snapshot if the state changed — or if the flap
        damper's suppressed set moved (catalog/damping.py: readmission
        is penalty-DECAY driven and produces no catalog event, so the
        LastChanged poll alone would never serve it); True when
        updated."""
        damper = getattr(self.state, "flap_damper", None)
        damped = frozenset(damper.damped()) if damper is not None \
            else frozenset()
        if self.state.last_changed == self._last_changed \
                and damped == self._damped_seen:
            return False
        resources = resources_from_state(
            self.state, self.bind_ip, self.use_hostnames, eds_mode="rest",
            damper=damper)
        with self._lock:
            self._snapshot = resources
            self._version = str(time.time_ns())
            self._last_changed = self.state.last_changed
            self._damped_seen = damped
        return True

    def discovery_response(self, type_url: str):
        """One REST xDS fetch (DiscoveryRequest → DiscoveryResponse)."""
        self.refresh()
        with self._lock:
            snap = self._snapshot
            version = self._version
        if snap is None:
            return {"version_info": "0", "resources": [],
                    "type_url": type_url}
        resources = {
            TYPE_CLUSTER: snap.clusters,
            TYPE_ENDPOINT: snap.endpoints,
            TYPE_LISTENER: snap.listeners,
        }.get(type_url)
        if resources is None:
            raise KeyError(type_url)
        return {"version_info": version, "resources": resources,
                "type_url": type_url}

    def serve(self, bind: str = "0.0.0.0", port: int = 7776,
              background: bool = True) -> ThreadingHTTPServer:
        """REST xDS endpoints: POST /v3/discovery:{clusters,endpoints,
        listeners} (the reference's gRPC ADS server binds 7776,
        config/config.go:32)."""
        xds = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                log.debug("xds: " + a[0], *a[1:])

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    self.rfile.read(length)
                route = self.path.split("?")[0]
                type_url = {
                    "/v3/discovery:clusters": TYPE_CLUSTER,
                    "/v3/discovery:endpoints": TYPE_ENDPOINT,
                    "/v3/discovery:listeners": TYPE_LISTENER,
                }.get(route)
                if type_url is None:
                    body = b'{"message": "unknown discovery type"}'
                    self.send_response(404)
                else:
                    body = json.dumps(
                        xds.discovery_response(type_url)).encode()
                    self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        server = ThreadingHTTPServer((bind, port), Handler)
        if background:
            threading.Thread(target=server.serve_forever,
                             name="xds-server", daemon=True).start()
        else:
            server.serve_forever()
        return server
