"""Proxy drivers: HAProxy config writer and Envoy xDS control plane
(reference: haproxy/ and envoy/ packages)."""

from sidecar_tpu.proxy.haproxy import HAProxy
from sidecar_tpu.proxy.envoy import EnvoyResources, resources_from_state

__all__ = ["HAProxy", "EnvoyResources", "resources_from_state"]
