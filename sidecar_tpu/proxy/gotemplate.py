"""A minimal Go ``text/template`` interpreter for proxy templates.

The reference renders HAProxy configs through Go's template engine with
a registered FuncMap (haproxy/haproxy.go:140-193), and operators point
``HAPROXY_TEMPLATE_FILE`` at their own template (views/haproxy.cfg is
the stock one).  For those custom templates to keep working against
this implementation, this module interprets the dialect that proxy
templates actually use:

* ``{{ <expr> }}`` — evaluate and write (stringified).
* ``{{ if <expr> }} … {{ else if <expr> }} … {{ else }} … {{ end }}``
  — Go truthiness (empty string/zero/empty collection/None are false).
* ``{{ with <expr> }} … {{ else }} … {{ end }}`` — rebinds dot to the
  expression when truthy, else renders the else branch.
* ``{{ range $v := <expr> }} … {{ else }} … {{ end }}`` and
  ``{{ range $k, $v := <expr> }} … {{ end }}`` — over lists (index,
  item) or maps (key, value; keys iterated in sorted order, matching
  Go's map range in templates); the ``else`` branch renders when the
  collection is empty, as in Go.
* ``{{- … -}}`` trim markers — strip the whitespace (including
  newlines) adjacent to the action, exactly text/template's rule (the
  marker must be followed/preceded by whitespace to count as a
  marker).
* Expressions: ``$var``, ``.Field``, ``$var.Field.Sub``, quoted
  strings, integers, and function calls ``fname arg1 arg2`` resolved
  against the caller's FuncMap (parenthesized sub-calls are not
  supported — not used by proxy templates).
* Field access maps Go's exported names onto this codebase's snake_case
  attributes (``.ServicePort`` → ``service_port``, ``.ID`` → ``id``)
  and falls back to dict keys verbatim.

This is deliberately NOT a full text/template: unsupported constructs
raise ``TemplateError`` at parse time rather than rendering something
silently wrong.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional

_ACTION = re.compile(r"\{\{(.*?)\}\}", re.DOTALL)
_CAMEL = re.compile(r"(?<=[a-z0-9])(?=[A-Z])")


class TemplateError(ValueError):
    pass


def _snake(name: str) -> str:
    return _CAMEL.sub("_", name).lower()


def _truthy(v: Any) -> bool:
    """Go template truth: the zero value of the type is false."""
    if v is None or v is False:
        return False
    if isinstance(v, (str, bytes, list, tuple, dict, set)):
        return len(v) > 0
    if isinstance(v, (int, float)):
        return v != 0
    return True


def _stringify(v: Any) -> str:
    if v is None:
        return "<no value>"
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


class _Env:
    def __init__(self, dot: Any, funcs: dict[str, Callable],
                 parent: Optional["_Env"] = None):
        self.dot = dot
        self.funcs = funcs
        self.vars: dict[str, Any] = {}
        self.parent = parent

    def lookup(self, name: str) -> Any:
        env: Optional[_Env] = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        raise TemplateError(f"undefined variable ${name}")


def _resolve_field(obj: Any, field: str) -> Any:
    if isinstance(obj, dict):
        # Go text/template: a missing map key yields the zero value
        # (templates legitimately probe optional keys with `if`); only
        # a missing struct field is an error.
        return obj.get(field)
    attr = _snake(field)
    if hasattr(obj, attr):
        return getattr(obj, attr)
    raise TemplateError(
        f"{type(obj).__name__} has no field .{field} (looked for "
        f"attribute {attr!r})")


# -- expression evaluation ---------------------------------------------------

def _eval_primary(token: str, env: _Env) -> Any:
    if token.startswith('"') and token.endswith('"') and len(token) >= 2:
        return token[1:-1]
    if re.fullmatch(r"-?\d+", token):
        return int(token)
    if token == ".":
        return env.dot
    if token.startswith("$"):
        parts = token[1:].split(".")
        val = env.lookup(parts[0])
        for field in parts[1:]:
            val = _resolve_field(val, field)
        return val
    if token.startswith("."):
        val = env.dot
        for field in token[1:].split("."):
            val = _resolve_field(val, field)
        return val
    raise TemplateError(f"cannot evaluate {token!r}")


def _eval_expr(tokens: list[str], env: _Env) -> Any:
    if not tokens:
        raise TemplateError("empty action")
    head = tokens[0]
    if head in env.funcs:
        args = [_eval_primary(t, env) for t in tokens[1:]]
        return env.funcs[head](*args)
    if len(tokens) != 1:
        raise TemplateError(
            f"{head!r} is not a registered function but has arguments "
            f"{tokens[1:]}")
    return _eval_primary(head, env)


# -- parsing -----------------------------------------------------------------

class _Text:
    def __init__(self, text: str):
        self.text = text


class _Action:
    def __init__(self, tokens: list[str]):
        self.tokens = tokens


class _If:
    def __init__(self, tokens: list[str], body: list):
        self.tokens = tokens
        self.body = body
        self.else_body: list = []


class _With:
    def __init__(self, tokens: list[str], body: list):
        self.tokens = tokens
        self.body = body
        self.else_body: list = []


class _Range:
    def __init__(self, kvar: Optional[str], vvar: str,
                 tokens: list[str], body: list):
        self.kvar = kvar
        self.vvar = vvar
        self.tokens = tokens
        self.body = body
        self.else_body: list = []


def _tokenize_action(src: str) -> list[str]:
    out = re.findall(r'"[^"]*"|\S+', src)
    return out


class _Frame:
    """One open block while parsing: ``body`` is the list new nodes
    append to (switched by ``else``); ``cur`` is the innermost _If an
    ``else if`` chains onto (all branches share one ``{{ end }}``)."""

    def __init__(self, kind: str, node: Any, body: list):
        self.kind = kind
        self.node = node
        self.cur = node
        self.body = body
        self.saw_else = False


def _parse(text: str) -> list:
    """Template → node tree (one pass with an explicit block stack)."""
    root = _Frame("root", None, [])
    stack: list[_Frame] = [root]
    pos = 0
    trim_left = False          # a preceding action ended with `-}}`
    for m in _ACTION.finditer(text):
        if m.start() > pos:
            seg = text[pos:m.start()]
            if trim_left:
                seg = seg.lstrip()
            if seg:
                stack[-1].body.append(_Text(seg))
        pos = m.end()
        src = m.group(1)
        # text/template trim markers: `{{- ` strips the whitespace
        # before the action, ` -}}` after it; the marker only counts
        # when separated from the action by whitespace (so `{{-3}}` is
        # still the number -3).
        if src.startswith("-") and len(src) > 1 and src[1].isspace():
            src = src[1:]
            last = stack[-1].body[-1] if stack[-1].body else None
            if isinstance(last, _Text):
                last.text = last.text.rstrip()
                if not last.text:
                    stack[-1].body.pop()
        trim_left = src.endswith("-") and len(src) > 1 \
            and src[-2].isspace()
        if trim_left:
            src = src[:-1]
        tokens = _tokenize_action(src.strip())
        if not tokens:
            raise TemplateError("empty {{ }} action")
        head = tokens[0]
        if head == "end":
            frame = stack.pop()
            if frame.kind == "root":
                raise TemplateError("{{ end }} without an open block")
            stack[-1].body.append(frame.node)
        elif head == "if":
            node = _If(tokens[1:], [])
            stack.append(_Frame("if", node, node.body))
        elif head == "with":
            if ":=" in tokens:
                raise TemplateError(
                    "`with $v := expr` is not supported by this "
                    "renderer (use plain `with expr`)")
            node = _With(tokens[1:], [])
            stack.append(_Frame("with", node, node.body))
        elif head == "else":
            frame = stack[-1]
            if frame.kind == "root":
                raise TemplateError("{{ else }} without an open block")
            if frame.saw_else:
                raise TemplateError("duplicate {{ else }} in one block")
            if len(tokens) > 1:
                # `else if <expr>`: chain a nested _If that shares this
                # block's single {{ end }}.  (saw_else already rejected
                # above: nothing may follow a plain else.)
                if frame.kind != "if" or tokens[1] != "if":
                    raise TemplateError(
                        f"unexpected tokens after else: {tokens[1:]}")
                nxt = _If(tokens[2:], [])
                frame.cur.else_body.append(nxt)
                frame.cur = nxt
                frame.body = nxt.body
            else:
                frame.saw_else = True
                frame.body = frame.cur.else_body
        elif head == "range":
            rest = tokens[1:]
            if ":=" in rest:
                idx = rest.index(":=")
                decl, expr = rest[:idx], rest[idx + 1:]
                # `range $k, $v :=` tokenizes as ["$k,", "$v", ":=", …];
                # the expr may itself be a function call's tokens.
                decl = [d.rstrip(",") for d in decl]
                if len(decl) == 1:
                    kvar, vvar = None, decl[0]
                elif len(decl) == 2:
                    kvar, vvar = decl
                else:
                    raise TemplateError(
                        f"range declares {len(decl)} variables")
                if not vvar.startswith("$") or \
                        (kvar is not None and not kvar.startswith("$")):
                    raise TemplateError("range variables must be $names")
                node = _Range(kvar[1:] if kvar else None, vvar[1:],
                              expr, [])
            else:
                raise TemplateError(
                    "only `range $v := expr` / `range $k, $v := expr` "
                    "forms are supported")
            stack.append(_Frame("range", node, node.body))
        elif head in ("template", "block", "define"):
            raise TemplateError(
                f"{{{{ {head} }}}} is not supported by this renderer")
        else:
            stack[-1].body.append(_Action(tokens))
    if len(stack) != 1:
        raise TemplateError(f"unclosed {{{{ {stack[-1].kind} }}}} block")
    if pos < len(text):
        seg = text[pos:]
        if trim_left:
            seg = seg.lstrip()
        if seg:
            root.body.append(_Text(seg))
    return root.body


# -- rendering ---------------------------------------------------------------

def _render_nodes(nodes: list, env: _Env, out: list[str]) -> None:
    for node in nodes:
        if isinstance(node, _Text):
            out.append(node.text)
        elif isinstance(node, _Action):
            out.append(_stringify(_eval_expr(node.tokens, env)))
        elif isinstance(node, _If):
            if _truthy(_eval_expr(node.tokens, env)):
                _render_nodes(node.body, env, out)
            else:
                _render_nodes(node.else_body, env, out)
        elif isinstance(node, _With):
            val = _eval_expr(node.tokens, env)
            if _truthy(val):
                child = _Env(val, env.funcs, parent=env)
                _render_nodes(node.body, child, out)
            else:
                _render_nodes(node.else_body, env, out)
        elif isinstance(node, _Range):
            coll = _eval_expr(node.tokens, env)
            if isinstance(coll, dict):
                items = [(k, coll[k]) for k in sorted(coll)]
            elif isinstance(coll, (list, tuple)):
                items = list(enumerate(coll))
            elif coll is None:
                items = []
            else:
                raise TemplateError(
                    f"cannot range over {type(coll).__name__}")
            if not items:
                _render_nodes(node.else_body, env, out)
            for k, v in items:
                child = _Env(env.dot, env.funcs, parent=env)
                if node.kvar is not None:
                    child.vars[node.kvar] = k
                child.vars[node.vvar] = v
                _render_nodes(node.body, child, out)


class Template:
    """Parse once, execute many (text/template's lifecycle)."""

    def __init__(self, text: str):
        self.nodes = _parse(text)

    def execute(self, data: Any, funcs: dict[str, Callable]) -> str:
        out: list[str] = []
        _render_nodes(self.nodes, _Env(data, funcs), out)
        return "".join(out)


def render(text: str, data: Any, funcs: dict[str, Callable]) -> str:
    return Template(text).execute(data, funcs)
