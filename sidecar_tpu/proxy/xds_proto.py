"""Wire-format xDS resources: protoc-generated messages + converters.

``pb()`` compiles ``xds.proto`` (a field-number-exact subset of the
envoy v3 API, see the comments there) with the system ``protoc`` into a
cached module on first use — the same build-on-demand approach as the
native transport engine — so no generated code is vendored and the
runtime protobuf library always matches its own gencode.

The converters turn the proto-JSON resource dicts produced by
``resources_from_state`` (proxy/envoy.py, the shared generation logic
for REST and gRPC serving) into serialized ``google.protobuf.Any``
wrappers carrying the REAL envoy type URLs — what a production Envoy
receives on the ADS stream (envoy/adapter/adapter.go:108-212 builds the
same resources via go-control-plane)."""

from __future__ import annotations

import hashlib
import importlib.util
import pathlib
import subprocess
import sys
import threading

_HERE = pathlib.Path(__file__).resolve().parent
_PROTO = _HERE / "xds.proto"
_GEN_DIR = _HERE / "_xds_gen"

_lock = threading.Lock()
_pb = None

TYPE_LISTENER_URL = "type.googleapis.com/envoy.config.listener.v3.Listener"
TYPE_ROUTER = ("type.googleapis.com/envoy.extensions.filters.http."
               "router.v3.Router")
TYPE_HCM = ("type.googleapis.com/envoy.extensions.filters.network."
            "http_connection_manager.v3.HttpConnectionManager")
TYPE_TCP_PROXY = ("type.googleapis.com/envoy.extensions.filters.network."
                  "tcp_proxy.v3.TcpProxy")


def pb():
    """The generated ``xds_pb2`` module (compiled + cached on demand)."""
    global _pb
    with _lock:
        if _pb is not None:
            return _pb
        digest = hashlib.sha256(_PROTO.read_bytes()).hexdigest()[:16]
        stamp = _GEN_DIR / "STAMP"
        gen = _GEN_DIR / "xds_pb2.py"
        if not gen.exists() or not stamp.exists() or \
                stamp.read_text().strip() != digest:
            _GEN_DIR.mkdir(exist_ok=True)
            subprocess.run(
                ["protoc", f"--python_out={_GEN_DIR}", f"-I{_HERE}",
                 str(_PROTO)],
                check=True, capture_output=True)
            stamp.write_text(digest)
        spec = importlib.util.spec_from_file_location(
            "sidecar_tpu.proxy._xds_gen.xds_pb2", gen)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _pb = mod
        return _pb


# -- proto-JSON dict → wire-format converters -------------------------------

def _duration(msg, text: str) -> None:
    """Parse a proto-JSON duration string ('0.500s') into msg."""
    seconds = float(text.rstrip("s"))
    msg.seconds = int(seconds)
    msg.nanos = int(round((seconds - int(seconds)) * 1e9))


def _address(msg, d: dict) -> None:
    sa = d["socket_address"]
    msg.socket_address.address = sa["address"]
    msg.socket_address.port_value = sa["port_value"]


def _any(type_url: str, message):
    """Wrap a message in Any under the REAL envoy type URL (manual —
    Any.Pack would stamp this module's private package name)."""
    from google.protobuf import any_pb2

    out = any_pb2.Any()
    out.type_url = type_url
    out.value = message.SerializeToString()
    return out


def _route_config(msg, d: dict) -> None:
    if "validate_clusters" in d:
        msg.validate_clusters.value = bool(d["validate_clusters"])
    for vh in d.get("virtual_hosts", ()):
        vmsg = msg.virtual_hosts.add()
        vmsg.name = vh["name"]
        vmsg.domains.extend(vh["domains"])
        for route in vh.get("routes", ()):
            rmsg = vmsg.routes.add()
            rmsg.match.prefix = route["match"]["prefix"]
            rmsg.route.cluster = route["route"]["cluster"]
            if "timeout" in route["route"]:
                _duration(rmsg.route.timeout, route["route"]["timeout"])


def _filter_any(d: dict):
    """A listener filter's typed_config dict → wire Any (HCM or
    TcpProxy, the two proxy modes of adapter.go:216-304)."""
    x = pb()
    at_type = d["@type"]
    if at_type.endswith("HttpConnectionManager"):
        m = x.HttpConnectionManager()
        m.stat_prefix = d["stat_prefix"]
        _route_config(m.route_config, d["route_config"])
        for hf in d.get("http_filters", ()):
            fmsg = m.http_filters.add()
            fmsg.name = hf["name"]
            router = x.Router()
            fmsg.typed_config.CopyFrom(_any(TYPE_ROUTER, router))
        for up in d.get("upgrade_configs", ()):
            m.upgrade_configs.add().upgrade_type = up["upgrade_type"]
        return _any(TYPE_HCM, m)
    if at_type.endswith("TcpProxy"):
        m = x.TcpProxy()
        m.stat_prefix = d["stat_prefix"]
        m.cluster = d["cluster"]
        return _any(TYPE_TCP_PROXY, m)
    raise ValueError(f"unknown filter config type {at_type!r}")


def cluster_to_any(d: dict):
    """Cluster proto-JSON dict (resources_from_state) → Any."""
    x = pb()
    m = x.Cluster()
    m.name = d["name"]
    m.type = x.Cluster.EDS
    _duration(m.connect_timeout, d["connect_timeout"])
    eds = d["eds_cluster_config"]["eds_config"]
    if "ads" in eds:
        m.eds_cluster_config.eds_config.ads.SetInParent()
    m.eds_cluster_config.eds_config.resource_api_version = x.V3
    return _any(d["@type"], m)


def endpoint_to_any(d: dict):
    x = pb()
    m = x.ClusterLoadAssignment()
    m.cluster_name = d["cluster_name"]
    for locality in d.get("endpoints", ()):
        lmsg = m.endpoints.add()
        for lb in locality.get("lb_endpoints", ()):
            emsg = lmsg.lb_endpoints.add()
            _address(emsg.endpoint.address, lb["endpoint"]["address"])
    return _any(d["@type"], m)


def listener_to_any(d: dict):
    x = pb()
    m = x.Listener()
    m.name = d["name"]
    _address(m.address, d["address"])
    for chain in d.get("filter_chains", ()):
        cmsg = m.filter_chains.add()
        for filt in chain.get("filters", ()):
            fmsg = cmsg.filters.add()
            fmsg.name = filt["name"]
            fmsg.typed_config.CopyFrom(_filter_any(filt["typed_config"]))
    # Listener dicts carry no "@type" (they are never emitted through a
    # REST DiscoveryResponse's Any position).
    return _any(d.get("@type", TYPE_LISTENER_URL), m)
