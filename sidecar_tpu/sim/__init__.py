"""Scenario runners, convergence instrumentation, oracle, checkpointing."""
