"""Checkpoint/resume for simulated cluster state.

The reference needs no checkpointing (state rebuilds from peers on
rejoin, SURVEY.md §5); the simulator does — long convergence studies
should survive preemption.  Chunk-resumability is exact: the scan
derives per-round PRNG keys from the round index, so a resumed run
replays the same randomness as an uninterrupted one."""

from __future__ import annotations

import dataclasses
import json
import pathlib

import jax.numpy as jnp
import numpy as np

from sidecar_tpu.models.exact import SimParams, SimState

FORMAT_VERSION = 1


def save_state(path: str | pathlib.Path, state: SimState,
               params: SimParams) -> None:
    """Write state + params to a compressed npz."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    np.savez_compressed(
        path,
        version=FORMAT_VERSION,
        known=np.asarray(state.known),
        sent=np.asarray(state.sent),
        node_alive=np.asarray(state.node_alive),
        round_idx=np.asarray(state.round_idx),
        params=json.dumps(dataclasses.asdict(params)),
    )


def load_state(path: str | pathlib.Path) -> tuple[SimState, SimParams]:
    """Load a checkpoint; raises ValueError on version/shape mismatch."""
    with np.load(pathlib.Path(path), allow_pickle=False) as data:
        version = int(data["version"])
        if version != FORMAT_VERSION:
            raise ValueError(
                f"checkpoint version {version} unsupported "
                f"(expected {FORMAT_VERSION})")
        params = SimParams(**json.loads(str(data["params"])))
        state = SimState(
            known=jnp.asarray(data["known"]),
            sent=jnp.asarray(data["sent"]),
            node_alive=jnp.asarray(data["node_alive"]),
            round_idx=jnp.asarray(data["round_idx"]),
        )
    if state.known.shape != (params.n, params.m):
        raise ValueError(
            f"checkpoint shape {state.known.shape} does not match params "
            f"({params.n}, {params.m})")
    return state, params
