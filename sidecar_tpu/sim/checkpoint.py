"""Checkpoint/resume for simulated cluster state — both models.

The reference needs no checkpointing (state rebuilds from peers on
rejoin, SURVEY.md §5); the simulator does — long convergence studies
should survive preemption.  Chunk-resumability is exact: the scan
derives per-round PRNG keys from the round index, so a resumed run
replays the same randomness as an uninterrupted one.

Supports the dense ``ExactSim`` state and the compressed large-cluster
``CompressedSim`` state (both single-chip and their sharded twins —
the arrays are gathered to host on save and re-placed by the target
sim's ``init``-style sharding on the next ``run``)."""

from __future__ import annotations

import dataclasses
import json
import pathlib

import jax.numpy as jnp
import numpy as np

from sidecar_tpu.models.compressed import CompressedParams, CompressedState
from sidecar_tpu.models.exact import SimParams, SimState

FORMAT_VERSION = 2

_KINDS = {
    "exact": (SimState, SimParams),
    "compressed": (CompressedState, CompressedParams),
}


def _kind_of(state) -> str:
    for kind, (state_cls, _) in _KINDS.items():
        if isinstance(state, state_cls):
            return kind
    raise TypeError(f"unsupported state type {type(state).__name__}")


def save_state(path: str | pathlib.Path, state, params) -> None:
    """Write state + params to a compressed npz."""
    kind = _kind_of(state)
    _, params_cls = _KINDS[kind]
    if not isinstance(params, params_cls):
        raise TypeError(
            f"{type(state).__name__} must be saved with "
            f"{params_cls.__name__}, got {type(params).__name__}")
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    arrays = {f.name: np.asarray(getattr(state, f.name))
              for f in dataclasses.fields(state)}
    np.savez_compressed(
        path,
        version=FORMAT_VERSION,
        kind=kind,
        params=json.dumps(dataclasses.asdict(params)),
        **arrays,
    )


def load_state(path: str | pathlib.Path):
    """Load a checkpoint → (state, params); raises ValueError on
    version/shape mismatch.  Version-1 files (exact model only, no
    ``kind`` field) load transparently."""
    with np.load(pathlib.Path(path), allow_pickle=False) as data:
        version = int(data["version"])
        if version == 1:
            kind = "exact"
        elif version == FORMAT_VERSION:
            kind = str(data["kind"])
        else:
            raise ValueError(
                f"checkpoint version {version} unsupported "
                f"(expected <= {FORMAT_VERSION})")
        if kind not in _KINDS:
            raise ValueError(f"unknown checkpoint kind {kind!r}")
        state_cls, params_cls = _KINDS[kind]
        params = params_cls(**json.loads(str(data["params"])))
        # Fields ADDED after a format was frozen default to zero scalars
        # so older files stay loadable — but only those exact fields: a
        # file missing anything else (e.g. a truncated npz without
        # round_idx) must still fail loudly, not resume at tick 0.
        added_fields = {"dropped"}
        missing = {f.name for f in dataclasses.fields(state_cls)
                   if f.name not in data} - added_fields
        if missing:
            raise ValueError(
                f"checkpoint is missing state field(s) {sorted(missing)}")
        state = state_cls(**{
            f.name: jnp.asarray(data[f.name]) if f.name in data
            else jnp.zeros((), jnp.int32)
            for f in dataclasses.fields(state_cls)})

    if kind == "exact":
        expect = {"known": (params.n, params.m),
                  "sent": (params.n, params.m),
                  "node_alive": (params.n,)}
    else:
        expect = {
            "own": (params.n, params.services_per_node),
            "cache_slot": (params.n, params.cache_lines),
            "cache_val": (params.n, params.cache_lines),
            "cache_sent": (params.n, params.cache_lines),
            "floor": (params.m,),
            "node_alive": (params.n,),
        }
    for name, shape in expect.items():
        got = getattr(state, name).shape
        if got != shape:
            raise ValueError(
                f"checkpoint shape {name}={got} does not match params "
                f"{shape}")
    if kind == "compressed":
        _validate_cache_placement(state, params)
    return state, params


def _validate_cache_placement(state, params) -> None:
    """Fail loudly on checkpoints whose cache entries sit on lines the
    CURRENT hash no longer assigns them.

    The owner-run cache layout (models/compressed.hash_line, r5) changed
    where slots live; a pre-change v2 checkpoint deserializes cleanly
    with entries on old-hash lines, silently breaking the invariants
    _insert_own_offers (no collision handling) and the fast census rely
    on — duplicate records per slot and an undercounting census after
    resume (ADVICE.md r5 medium).  Placement is cheap to prove on load:
    every occupied line must equal hash_line(slot)."""
    from sidecar_tpu.models.compressed import hash_line

    cache_slot = np.asarray(state.cache_slot)
    occupied = cache_slot >= 0
    if not occupied.any():
        return
    lines = np.broadcast_to(
        np.arange(cache_slot.shape[1], dtype=np.int64)[None, :],
        cache_slot.shape)
    expected = np.asarray(hash_line(
        jnp.asarray(np.where(occupied, cache_slot, 0)),
        params.cache_lines, params.services_per_node))
    bad = occupied & (lines != expected)
    if bad.any():
        n_bad = int(bad.sum())
        node, line = np.argwhere(bad)[0]
        raise ValueError(
            f"checkpoint cache layout mismatch: {n_bad} cache entr"
            f"{'y' if n_bad == 1 else 'ies'} sit on lines the current "
            f"hash_line does not assign them (first: node {node}, line "
            f"{line}, slot {int(cache_slot[node, line])}).  This "
            "checkpoint predates the owner-run cache layout; resuming it "
            "would corrupt the census — re-run the scenario or migrate "
            "the checkpoint")
