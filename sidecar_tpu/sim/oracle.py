"""Straight-line NumPy oracle for the gossip kernels.

This is the test-time ground truth: a sequential, loop-based
re-implementation of the reference's merge/sweep/anti-entropy semantics
(catalog/services_state.go) operating on the same packed representation as
the TPU kernels.  It deliberately mirrors the *Go control flow* — one
record merged at a time, full-state exchanges done pairwise and in order —
so that equivalence tests between the batched kernels and this oracle
carry the same weight as the reference's own two-state merge tests
(services_state_test.go:299-308), plus the convergence-over-rounds
coverage the reference never had (SURVEY.md §4).

Peer/message *sampling* is shared with the kernels (the oracle calls the
same deterministic ``sample_peers`` / ``select_messages`` with the same
PRNG keys); what the oracle re-implements independently is every state
*transition*: announce scheduling, per-record LWW merge with stickiness
and staleness, the lifespan sweep with the +1 s rule, and push-pull.
"""

from __future__ import annotations

import numpy as np
import jax

from sidecar_tpu.models.exact import ExactSim, SimState
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops.status import (
    ALIVE,
    DRAINING,
    STATUS_BITS,
    STATUS_MASK,
    TOMBSTONE,
)


def _ts(p: int) -> int:
    return p >> STATUS_BITS


def _st(p: int) -> int:
    return p & STATUS_MASK


def _pack(ts: int, st: int) -> int:
    return (int(ts) << STATUS_BITS) | int(st)


class OracleSim:
    """Sequential mirror of :class:`ExactSim`. Evolves its own NumPy state
    using the same PRNG keys; `known` should match the kernel bit-for-bit
    in scenarios without same-batch DRAINING races (see ops/merge.py)."""

    def __init__(self, sim: ExactSim, state: SimState):
        self.sim = sim
        self.p = sim.p
        self.t = sim.t
        self.known = np.asarray(state.known).copy()
        self.sent = np.asarray(state.sent).astype(np.int32).copy()
        self.node_alive = np.asarray(state.node_alive).copy()
        self.round_idx = int(state.round_idx)
        self.owner = np.asarray(sim.owner)
        self.limit = sim.p.resolved_retransmit_limit()

    # -- the Go-faithful single-record merge (AddServiceEntry) -------------

    def merge_one(self, node: int, svc: int, incoming: int, now: int) -> None:
        """services_state.go:293-347, one record at a time."""
        its, ist = _ts(incoming), _st(incoming)
        if its == 0:
            return
        if its < now - self.t.stale_ticks:  # IsStale + fudge (:302-308)
            return
        cur = int(self.known[node, svc])
        cts, cst = _ts(cur), _st(cur)
        if cts == 0:  # unknown server/service: accept (:317-320)
            self.known[node, svc] = incoming
            self.sent[node, svc] = 0  # re-enqueue for relay (:377-392)
            return
        if its > cts:  # Invalidates: strictly newer (:321, service.go:64-66)
            if cst == DRAINING and ist == ALIVE:  # sticky (:329-331)
                ist = DRAINING
            new = _pack(its, ist)
            if new != cur:
                self.known[node, svc] = new
                self.sent[node, svc] = 0

    # -- announce (BroadcastServices/SendServices schedule) ----------------

    def announce(self, round_idx: int, now: int) -> None:
        p, t = self.p, self.t
        for m in range(p.m):
            o = int(self.owner[m])
            if not self.node_alive[o]:
                continue
            cur = int(self.known[o, m])
            ts, st = _ts(cur), _st(cur)
            if ts == 0 or st == TOMBSTONE:
                continue
            phase = o % t.refresh_rounds
            if (round_idx % t.refresh_rounds) == phase:
                new = _pack(now, st)
                if new != cur:
                    self.known[o, m] = new
                    self.sent[o, m] = 0

    # -- gossip delivery (sequential, Go-style) ----------------------------

    def deliver(self, dst: np.ndarray, svc_idx: np.ndarray, msg: np.ndarray,
                now: int, drop: np.ndarray | None = None) -> None:
        n, fanout = dst.shape
        budget = svc_idx.shape[1]
        for s in range(n):
            if not self.node_alive[s]:
                continue
            for f in range(fanout):
                tgt = int(dst[s, f])
                if not self.node_alive[tgt]:
                    continue
                for b in range(budget):
                    if drop is not None and drop[s, f, b]:
                        continue
                    self.merge_one(tgt, int(svc_idx[s, b]), int(msg[s, b]), now)

    # -- anti-entropy ------------------------------------------------------

    def push_pull(self, partner: np.ndarray, now: int) -> None:
        """Two-way full-state exchange per initiator (LocalState/
        MergeRemoteState, services_delegate.go:146-167). All exchanged
        payloads are read from the pre-exchange snapshot — in the kernel
        every pull gathers and every push offers pre-round state, so the
        oracle does the same to stay bit-identical."""
        n = self.known.shape[0]
        pre = self.known.copy()
        for i in range(n):
            t = int(partner[i])
            if t == i:
                continue
            for m in range(self.known.shape[1]):
                self.merge_one(i, m, int(pre[t, m]), now)   # pull
            for m in range(self.known.shape[1]):
                self.merge_one(t, m, int(pre[i, m]), now)   # push

    # -- lifespan sweep ----------------------------------------------------

    def sweep(self, now: int) -> None:
        """TombstoneOthersServices per node (services_state.go:635-683)."""
        t = self.t
        n, m_tot = self.known.shape
        for node in range(n):
            for m in range(m_tot):
                cur = int(self.known[node, m])
                ts, st = _ts(cur), _st(cur)
                if ts == 0:
                    continue
                if st == TOMBSTONE:
                    if ts < now - t.tombstone_lifespan:
                        self.known[node, m] = 0  # GC (:645-653)
                        self.sent[node, m] = 0
                    continue
                lifespan = (t.draining_lifespan if st == DRAINING
                            else t.alive_lifespan)
                if ts < now - lifespan:
                    # +1 s rule (:667-675); re-enqueue for the 10× rebroadcast
                    self.known[node, m] = _pack(ts + t.one_second, TOMBSTONE)
                    self.sent[node, m] = 0

    # -- full round, mirroring ExactSim._step ------------------------------

    def step(self, key: jax.Array) -> None:
        p, t = self.p, self.t
        self.round_idx += 1
        now = self.round_idx * t.round_ticks
        _k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)

        self.announce(self.round_idx, now)

        dst = np.asarray(gossip_ops.sample_peers(
            k_peers, p.n, p.fanout,
            nbrs=self.sim._nbrs, deg=self.sim._deg,
            node_alive=jax.numpy.asarray(self.node_alive),
            cut_mask=self.sim._cut,
        ))
        svc_idx, msg = gossip_ops.select_messages(
            jax.numpy.asarray(self.known),
            jax.numpy.asarray(self.sent.astype(np.int8)),
            p.budget, self.limit)
        svc_idx, msg = np.asarray(svc_idx), np.asarray(msg)
        # Transmit accounting (TransmitLimited: fanout sends per offer).
        for node in range(p.n):
            for b in range(p.budget):
                if msg[node, b] > 0:
                    s = int(svc_idx[node, b])
                    self.sent[node, s] = min(self.sent[node, s] + p.fanout,
                                             self.limit)
        drop = None
        if p.drop_prob > 0:
            keep = jax.random.bernoulli(
                k_drop, 1.0 - p.drop_prob, (p.n, p.fanout, p.budget))
            drop = ~np.asarray(keep)
        self.deliver(dst, svc_idx, msg, now, drop)

        if self.round_idx % t.push_pull_rounds == 0:
            partner = np.asarray(gossip_ops.sample_peers(
                k_pp, p.n, 1,
                nbrs=self.sim._nbrs, deg=self.sim._deg,
                node_alive=jax.numpy.asarray(self.node_alive),
                cut_mask=self.sim._cut,
            ))[:, 0]
            alive = self.node_alive
            partner = np.where(alive & alive[partner], partner, np.arange(p.n))
            self.push_pull(partner, now)

        if self.round_idx % t.sweep_rounds == 0:
            self.sweep(now)

    def convergence(self) -> float:
        alive = self.node_alive
        truth = np.max(np.where(alive[:, None], self.known, 0), axis=0)
        agree = (self.known == truth[None, :]).mean(axis=1)
        return float((agree * alive).sum() / max(alive.sum(), 1))
