"""Straight-line NumPy oracle for the gossip kernels.

This is the test-time ground truth: a sequential, loop-based
re-implementation of the reference's merge/sweep/anti-entropy semantics
(catalog/services_state.go) operating on the same packed representation as
the TPU kernels.  It deliberately mirrors the *Go control flow* — one
record merged at a time, full-state exchanges done pairwise and in order —
so that equivalence tests between the batched kernels and this oracle
carry the same weight as the reference's own two-state merge tests
(services_state_test.go:299-308), plus the convergence-over-rounds
coverage the reference never had (SURVEY.md §4).

Peer/message *sampling* is shared with the kernels (the oracle calls the
same deterministic ``sample_peers`` / ``select_messages`` with the same
PRNG keys); what the oracle re-implements independently is every state
*transition*: announce scheduling, per-record LWW merge with stickiness
and staleness, transmit-count accounting, the lifespan sweep with the
+1 s rule, and push-pull.

Batch-resolution note: the reference applies same-round messages
sequentially, so a round where one cell receives both a DRAINING-sticky
and a plain update is order-dependent *in the reference itself*.  The
kernel resolves such races one consistent way (stickiness evaluated
against the pre-round state, then max over adjusted values); the oracle
implements that same resolution — sequentially, record by record, but
with stickiness against its own pre-round snapshot.
"""

from __future__ import annotations

import numpy as np
import jax

from sidecar_tpu.models.exact import ExactSim, SimState
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops.status import (
    ALIVE,
    DRAINING,
    STATUS_BITS,
    STATUS_MASK,
    SUSPECT,
    TOMBSTONE,
)


def _ts(p: int) -> int:
    return p >> STATUS_BITS


def _st(p: int) -> int:
    return p & STATUS_MASK


def _pack(ts: int, st: int) -> int:
    return (int(ts) << STATUS_BITS) | int(st)


class OracleSim:
    """Sequential mirror of :class:`ExactSim`. Evolves its own NumPy state
    using the same PRNG keys; `known`/`sent` should match the kernel
    bit-for-bit."""

    def __init__(self, sim: ExactSim, state: SimState):
        self.sim = sim
        self.p = sim.p
        self.t = sim.t
        self.known = np.asarray(state.known).copy()
        self.sent = np.asarray(state.sent).astype(np.int32).copy()
        self.node_alive = np.asarray(state.node_alive).copy()
        self.round_idx = int(state.round_idx)
        self.owner = np.asarray(sim.owner)
        self.limit = sim.p.resolved_retransmit_limit()
        # ClockFault mirror (chaos/plan.py): a CLOCK-ONLY chaos plan
        # leaves the round structurally identical to ExactSim's, so the
        # oracle can lockstep a ChaosExactSim by reading the per-node
        # skew off the plan (edge/node faults are NOT mirrored here).
        plan = getattr(sim, "plan", None)
        self.clocks = plan if plan is not None and plan.clocks else None
        # Future-admission bound (ops/merge.future_mask): None = off.
        self.future_ticks = sim.t.future_ticks
        # Byzantine mirror (chaos/adversary.py, docs/chaos.md): the
        # compiled plan's ``host_overrides`` replays the PRNG-free
        # corruption formulas on the shared select_messages packet, and
        # the budget/quarantine knobs mirror the kernel's defense gates
        # — so a ChaosExactSim under attack locksteps exactly like a
        # clock-only plan does.
        self.adv = getattr(sim, "_adv", None)
        self.tomb_budget = sim.t.tomb_budget
        self.quarantine_threshold = sim.t.quarantine_threshold
        self.origin_violations = np.zeros(self.p.n, np.int64)

    def _offsets(self) -> np.ndarray | None:
        """Per-node skew ticks for the CURRENT round, or None — the
        NumPy twin of CompiledFaultPlan.clock_offsets (identical
        float32-multiply + floor drift math)."""
        if self.clocks is None:
            return None
        return np.array([self.clocks.clock_offset(i, self.round_idx)
                         for i in range(self.p.n)], dtype=np.int64)

    def _too_future(self, ts: int, now_r: int) -> bool:
        """Receiver-side future-admission bound (ops/merge.future_mask)
        against the RECEIVER's clock ``now_r``; False when disabled."""
        return self.future_ticks is not None and \
            ts > now_r + self.future_ticks

    # -- one delivered/announced value, vs the pre-round snapshot ----------

    def apply_one(self, node: int, svc: int, incoming: int,
                  pre: np.ndarray) -> None:
        """One update through the merge semantics
        (services_state.go:293-347 recast to the kernel's batch
        resolution): staleness was already gated at prepare time; accept
        iff the packed key advances the cell; DRAINING stickiness is
        evaluated against the pre-round snapshot ``pre``."""
        if incoming == 0:
            return
        pre_val = int(pre[node, svc])
        if incoming > pre_val:
            ist = _st(incoming)
            if (pre_val >> STATUS_BITS) > 0 and _st(pre_val) == DRAINING \
                    and ist == ALIVE:
                incoming = _pack(_ts(incoming), DRAINING)
            if incoming > int(self.known[node, svc]):
                self.known[node, svc] = incoming
            # Any advancing update re-enqueues the cell for relay
            # (services_state.go:377-392): transmit count back to zero.
            self.sent[node, svc] = 0

    # -- full round, mirroring ExactSim._step ------------------------------

    def step(self, key: jax.Array) -> None:
        p, t = self.p, self.t
        self.round_idx += 1
        now = self.round_idx * t.round_ticks
        _k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)

        pre = self.known.copy()

        # 1. select + deliveries (sampling shared with the kernel).
        # ``_gate_kw`` mirrors the sim's stagger/cadence delivery gates
        # (ops/gossip.cadence_gate): off nodes self-send — and still
        # select and charge ``sent`` below, the PR 13 semantics.
        dst = np.asarray(gossip_ops.sample_peers(
            k_peers, p.n, p.fanout,
            nbrs=self.sim._nbrs, deg=self.sim._deg,
            node_alive=jax.numpy.asarray(self.node_alive),
            cut_mask=self.sim._cut,
            **self.sim._gate_kw(self.round_idx),
        ))
        svc_idx, msg = gossip_ops.select_messages(
            jax.numpy.asarray(self.known),
            jax.numpy.asarray(self.sent.astype(np.int8)),
            p.budget, self.limit)
        svc_idx, msg = np.asarray(svc_idx), np.asarray(msg)

        # Per-node clocks (ClockFault): senders already stamped with
        # their own skewed clocks; every RECEIVER gates admission,
        # refresh, and expiry by its own.
        offs = self._offsets()

        def clock(node: int) -> int:
            # Epoch floor, mirroring the sim's jnp.maximum(now+off, 0).
            return now if offs is None else max(0, now + int(offs[node]))

        # Adversary corruption lands between selection and transmit
        # accounting (the kernel order): attackers replace the leading
        # columns of their packets with forged records, lying relative
        # to their OWN skewed clocks, and pay transmit counts for the
        # forged sends.
        if self.adv is not None:
            now_vec = np.array([clock(i) for i in range(p.n)], np.int64)
            fmask, fslots, fvals = self.adv.host_overrides(
                self.round_idx, now_vec)
            svc_idx = np.where(fmask, fslots, svc_idx)
            msg = np.where(fmask, fvals, msg)

        # Byzantine defenses (docs/chaos.md "the defense ladder"): the
        # quarantine gate reads the ROUND-START evidence, exactly like
        # the kernel (chaos/sim_inject.py).
        tb = self.tomb_budget
        qt = self.quarantine_threshold
        quar = (np.zeros(p.n, bool) if qt is None
                else self.origin_violations >= qt)

        # Transmit accounting (TransmitLimited: fanout sends per offer).
        # Unclamped, mirroring ops/gossip.record_transmissions: counts
        # stop growing the round a record crosses the limit (it is never
        # offered again), so the value is bounded by limit + fanout - 1.
        budget = msg.shape[1]
        for node in range(p.n):
            for b in range(budget):
                if msg[node, b] > 0:
                    s = int(svc_idx[node, b])
                    self.sent[node, s] += p.fanout

        drop = None
        if p.drop_prob > 0:
            keep = jax.random.bernoulli(
                k_drop, 1.0 - p.drop_prob, (p.n, p.fanout, budget))
            drop = ~np.asarray(keep)

        # Quarantine evidence accrual, mirroring the kernel's raw
        # candidate tally (before the loss/liveness gates): a FRESH
        # third-party claim — a record for a slot the sender doesn't
        # own, stamped at-or-ahead of the receiver's clock — beyond the
        # budget rank charges the SENDING origin, per packet copy.
        if tb is not None:
            for s in range(p.n):
                for f in range(p.fanout):
                    now_r = clock(int(dst[s, f]))
                    rank = 0
                    for b in range(budget):
                        val = int(msg[s, b])
                        ts = val >> STATUS_BITS
                        if ts <= 0 or ts < now_r - t.stale_ticks:
                            continue  # staleness-zeroed candidates
                        sv = int(svc_idx[s, b])
                        own = int(self.owner[min(sv, p.m - 1)]) == s
                        if (not own) and ts >= now_r:
                            rank += 1
                            if rank > tb:
                                self.origin_violations[s] += 1

        for s in range(p.n):
            # A quarantined origin loses its send channel outright (the
            # kernel's edge_keep fold); the budget rank below is still
            # computed per packet regardless of the unrelated loss/
            # liveness gates, exactly like admit_gate's candidate-set
            # cumsum.
            send_ok = bool(self.node_alive[s]) and not quar[s]
            for f in range(p.fanout):
                tgt = int(dst[s, f])
                now_r = clock(tgt)
                stale_floor = now_r - t.stale_ticks
                rank = 0
                for b in range(budget):
                    val = int(msg[s, b])
                    ts = val >> STATUS_BITS
                    if ts > 0 and ts < stale_floor:  # staleness gate
                        continue
                    if self._too_future(ts, now_r):  # future bound
                        continue
                    sv = int(svc_idx[s, b])
                    if tb is not None and ts > 0:
                        # Per-origin budget (ops/merge.budget_mask):
                        # the first ``tb`` suspicious third-party
                        # records of a packet pass, the rest drop.
                        own = int(self.owner[min(sv, p.m - 1)]) == s
                        suspicious = (not own) and (
                            _st(val) == TOMBSTONE or ts > now_r)
                        if suspicious:
                            rank += 1
                            if rank > tb:
                                continue
                    if not send_ok or not self.node_alive[tgt]:
                        continue
                    if drop is not None and drop[s, f, b]:
                        continue
                    self.apply_one(tgt, sv, val, pre)

        # 2. announce re-stamps (end of round, same scatter in the
        # kernel).  Independent sequential mirror of the kernel's
        # refresh stagger (ops/gossip.refresh_due): hash-spread per-slot
        # phase + per-record elapsed-time guard — the reference refreshes
        # on each service's own elapsed time (services_state.go:547-549).
        guard = (t.refresh_rounds * t.round_ticks) // 4
        for m in range(p.m):
            o = int(self.owner[m])
            if not self.node_alive[o]:
                continue
            cur = int(pre[o, m])
            ts, st = _ts(cur), _st(cur)
            if ts == 0 or st == TOMBSTONE:
                continue
            now_o = clock(o)   # the OWNER's clock stamps its refresh
            phase = ((m * 2654435761) & 0xFFFFFFFF) % t.refresh_rounds
            due = (self.round_idx % t.refresh_rounds) == phase \
                and (now_o - ts) >= guard
            if t.suspicion_window > 0 and st == SUSPECT:
                # Lifeguard self-refutation (ops/suspicion.py): an
                # alive owner whose own record is quarantined announces
                # a refuting ALIVE immediately, phase regardless.
                due, st = True, ALIVE
            if due:
                self.apply_one(o, m, _pack(now_o, st), pre)

        # 3. anti-entropy push-pull.
        if self.round_idx % t.push_pull_rounds == 0:
            partner = np.asarray(gossip_ops.sample_peers(
                k_pp, p.n, 1,
                nbrs=self.sim._nbrs, deg=self.sim._deg,
                node_alive=jax.numpy.asarray(self.node_alive),
                cut_mask=self.sim._cut,
            ))[:, 0]
            alive = self.node_alive
            partner = np.where(alive & alive[partner], partner,
                               np.arange(p.n))
            if qt is not None:
                # A quarantined origin neither pushes nor is pulled
                # from: any exchange touching one remaps to the self
                # no-op (the kernel's pp_partner remap).
                partner = np.where(quar | quar[partner],
                                   np.arange(p.n), partner)
            self.push_pull(partner, now, offs)

        # 4. lifespan sweep.
        if self.round_idx % t.sweep_rounds == 0:
            self.sweep(now, offs)

    # -- anti-entropy ------------------------------------------------------

    def push_pull(self, partner: np.ndarray, now: int,
                  offs: np.ndarray | None = None) -> None:
        """Two-way full-state exchange per initiator (LocalState/
        MergeRemoteState, services_delegate.go:146-167). All exchanged
        payloads are read from the pre-exchange snapshot — in the kernel
        every pull gathers and every push offers pre-round state, so the
        oracle does the same to stay bit-identical.  Each leg admits at
        the RECEIVING node's clock (``offs`` per-node skew)."""
        n = self.known.shape[0]
        t = self.t
        tb = self.tomb_budget
        pre = self.known.copy()
        for i in range(n):
            tgt = int(partner[i])
            if tgt == i:
                continue
            # Two legs per initiator, each a full-row packet admitted at
            # the RECEIVER's clock: pull merges the partner's row into
            # ``i``; push merges ``i``'s row into the partner.  The
            # per-origin budget ranks suspicious records across the
            # exchanged row (ops/gossip.push_pull's contract), with the
            # sender's own slots exempt.  Legs resolve against the
            # pre-exchange snapshot, so leg order is immaterial.
            for node, sender in ((i, tgt), (tgt, i)):
                now_r = now if offs is None \
                    else max(0, now + int(offs[node]))
                rank = 0
                for m in range(self.known.shape[1]):
                    val = int(pre[sender, m])
                    ts = val >> STATUS_BITS
                    if ts == 0 or ts < now_r - t.stale_ticks:
                        continue
                    if self._too_future(ts, now_r):
                        continue
                    if tb is not None:
                        own = int(self.owner[m]) == sender
                        suspicious = (not own) and (
                            _st(val) == TOMBSTONE or ts > now_r)
                        if suspicious:
                            rank += 1
                            if rank > tb:
                                continue
                    self.apply_one(node, m, val, pre)

    # -- lifespan sweep ----------------------------------------------------

    def sweep(self, now: int, offs: np.ndarray | None = None) -> None:
        """TombstoneOthersServices per node (services_state.go:635-683),
        plus the SWIM suspicion quarantine when the window is enabled
        (ops/ttl.py suspicion_window, docs/chaos.md).  Each node expires
        by its OWN clock (``offs`` per-node skew) — a slow node sees
        everyone else as early-stale, the FP-tombstone workload."""
        t = self.t
        window = t.suspicion_window
        n, m_tot = self.known.shape
        now_g = now
        for node in range(n):
            now = now_g if offs is None \
                else max(0, now_g + int(offs[node]))
            for m in range(m_tot):
                cur = int(self.known[node, m])
                ts, st = _ts(cur), _st(cur)
                if ts == 0:
                    continue
                if st == TOMBSTONE:
                    if ts < now - t.tombstone_lifespan:
                        self.known[node, m] = 0  # GC (:645-653)
                        self.sent[node, m] = 0
                    continue
                if window > 0:
                    # Quarantine-before-tombstone: non-DRAINING expiry
                    # re-packs SUSPECT at the ORIGINAL ts; only an
                    # unrefuted suspicion past the window tombstones
                    # (still at ts + 1 s — the +1 s rule holds).
                    if st == SUSPECT:
                        if ts < now - t.alive_lifespan - window:
                            self.known[node, m] = _pack(
                                ts + t.one_second, TOMBSTONE)
                            self.sent[node, m] = 0
                        continue
                    if st == DRAINING:
                        if ts < now - t.draining_lifespan:
                            self.known[node, m] = _pack(
                                ts + t.one_second, TOMBSTONE)
                            self.sent[node, m] = 0
                        continue
                    if ts < now - t.alive_lifespan:
                        self.known[node, m] = _pack(ts, SUSPECT)
                        self.sent[node, m] = 0
                    continue
                lifespan = (t.draining_lifespan if st == DRAINING
                            else t.alive_lifespan)
                if ts < now - lifespan:
                    # +1 s rule (:667-675); re-enqueue for the 10×
                    # rebroadcast.
                    self.known[node, m] = _pack(ts + t.one_second, TOMBSTONE)
                    self.sent[node, m] = 0

    def convergence(self) -> float:
        alive = self.node_alive
        truth = np.max(np.where(alive[:, None], self.known, 0), axis=0)
        agree = (self.known == truth[None, :]).mean(axis=1)
        return float((agree * alive).sum() / max(alive.sum(), 1))

    def quarantined_origins(self) -> tuple:
        """Origins at/over the quarantine threshold — the host twin of
        ``ChaosExactSim.quarantined_origins`` (empty when the knob is
        off)."""
        qt = self.quarantine_threshold
        if qt is None:
            return ()
        return tuple(int(i) for i in
                     np.where(self.origin_violations >= qt)[0])


class PipelinedOracleSim(OracleSim):
    """Sequential mirror of :meth:`ExactSim._step_pipelined`
    (docs/pipeline.md): the ``(state, inflight)`` carry with the honest
    one-round-stale publish.  Call :meth:`prime` once with the chain's
    base key (the prologue — mirrors ``ExactSim.prime_pipeline``), then
    :meth:`step` per tick with the SAME base key; per-round now/next
    keys are folded in here exactly as the scan drivers fold them.

    Scope mirrors the kernel's: plain ``ExactSim`` rounds only — the
    chaos planes (clock skew, adversary, quarantine) declare
    ``supports_pipeline = False`` on the sim and are rejected here too.
    """

    def __init__(self, sim: ExactSim, state: SimState):
        super().__init__(sim, state)
        if self.clocks is not None or self.adv is not None \
                or self.quarantine_threshold is not None:
            raise ValueError(
                "the pipelined oracle mirrors the plain ExactSim round; "
                "chaos planes (clocks/adversary/quarantine) are "
                "lockstep-only (supports_pipeline=False)")
        self.inflight = None

    # -- the hoisted publish (ExactSim._select_inflight's mirror) ---------

    def _select(self, k_round: jax.Array, round_sel: int):
        """Select round ``round_sel``'s publish from the CURRENT belief
        (sampling shared with the kernel), charge ``sent``, and return
        the in-flight triple.  The charge lands pre-apply, so a version
        advance folding in the same tick resets it — the kernel's
        bump-then-reset ordering."""
        p = self.p
        _kp, k_peers, _kd, _kpp = jax.random.split(k_round, 4)
        dst = np.asarray(gossip_ops.sample_peers(
            k_peers, p.n, p.fanout,
            nbrs=self.sim._nbrs, deg=self.sim._deg,
            node_alive=jax.numpy.asarray(self.node_alive),
            cut_mask=self.sim._cut,
            **self.sim._gate_kw(round_sel),
        ))
        svc_idx, msg = gossip_ops.select_messages(
            jax.numpy.asarray(self.known),
            jax.numpy.asarray(self.sent.astype(np.int8)),
            p.budget, self.limit)
        svc_idx, msg = np.asarray(svc_idx), np.asarray(msg)
        for node in range(p.n):
            for b in range(msg.shape[1]):
                if msg[node, b] > 0:
                    self.sent[node, int(svc_idx[node, b])] += p.fanout
        return dst, svc_idx, msg

    def prime(self, key: jax.Array) -> None:
        """The pipeline prologue: select round ``round_idx + 1``'s
        publish from the current state."""
        self.inflight = self._select(
            jax.random.fold_in(key, self.round_idx), self.round_idx + 1)

    # -- one pipelined tick ----------------------------------------------

    def step(self, key: jax.Array) -> None:
        """Fold the carried in-flight publish, select the next round's
        from the pre-fold belief, then run the lockstep push-pull/sweep
        tail.  ``key`` is the chain's BASE key."""
        if self.inflight is None:
            raise ValueError("pipelined oracle not primed — call "
                             "prime(key) first")
        p, t = self.p, self.t
        k_now = jax.random.fold_in(key, self.round_idx)
        k_next = jax.random.fold_in(key, self.round_idx + 1)
        self.round_idx += 1
        now = self.round_idx * t.round_ticks
        _k_perturb, _k_peers, k_drop, k_pp = jax.random.split(k_now, 4)

        pre = self.known.copy()
        dst, svc_idx, msg = self.inflight
        budget = msg.shape[1]

        # Round r+1's publish, from the pre-fold belief — BEFORE the
        # deliveries mutate known/sent (its transmit charge may then be
        # reset by an advancing delivery below, exactly the kernel's
        # combined-scatter resolution).
        self.inflight = self._select(k_next, self.round_idx + 1)

        drop = None
        if p.drop_prob > 0:
            keep = jax.random.bernoulli(
                k_drop, 1.0 - p.drop_prob, (p.n, p.fanout, budget))
            drop = ~np.asarray(keep)

        tb = self.tomb_budget
        for s in range(p.n):
            # The in-flight targets were gated with LAST round's
            # liveness (the stale-by-one selection), but the fold drops
            # packets from senders dead NOW — expand_deliveries' sender
            # gate reads the current round's liveness in both modes.
            send_ok = bool(self.node_alive[s])
            for f in range(p.fanout):
                tgt = int(dst[s, f])
                stale_floor = now - t.stale_ticks
                rank = 0
                for b in range(budget):
                    val = int(msg[s, b])
                    ts = val >> STATUS_BITS
                    if ts > 0 and ts < stale_floor:   # staleness gate
                        continue
                    if self._too_future(ts, now):     # future bound
                        continue
                    sv = int(svc_idx[s, b])
                    if tb is not None and ts > 0:
                        own = int(self.owner[min(sv, p.m - 1)]) == s
                        suspicious = (not own) and (
                            _st(val) == TOMBSTONE or ts > now)
                        if suspicious:
                            rank += 1
                            if rank > tb:
                                continue
                    if not send_ok or not self.node_alive[tgt]:
                        continue
                    if drop is not None and drop[s, f, b]:
                        continue
                    self.apply_one(tgt, sv, val, pre)

        # Announce re-stamps vs the pre-fold belief (same combined
        # scatter in the kernel).
        guard = (t.refresh_rounds * t.round_ticks) // 4
        for m in range(p.m):
            o = int(self.owner[m])
            if not self.node_alive[o]:
                continue
            cur = int(pre[o, m])
            ts, st = _ts(cur), _st(cur)
            if ts == 0 or st == TOMBSTONE:
                continue
            phase = ((m * 2654435761) & 0xFFFFFFFF) % t.refresh_rounds
            due = (self.round_idx % t.refresh_rounds) == phase \
                and (now - ts) >= guard
            if t.suspicion_window > 0 and st == SUSPECT:
                due, st = True, ALIVE
            if due:
                self.apply_one(o, m, _pack(now, st), pre)

        # Lockstep tail: anti-entropy push-pull, then the sweep.
        if self.round_idx % t.push_pull_rounds == 0:
            partner = np.asarray(gossip_ops.sample_peers(
                k_pp, p.n, 1,
                nbrs=self.sim._nbrs, deg=self.sim._deg,
                node_alive=jax.numpy.asarray(self.node_alive),
                cut_mask=self.sim._cut,
            ))[:, 0]
            alive = self.node_alive
            partner = np.where(alive & alive[partner], partner,
                               np.arange(p.n))
            self.push_pull(partner, now, None)

        if self.round_idx % t.sweep_rounds == 0:
            self.sweep(now, None)


class ProvenanceOracle:
    """Sequential NumPy mirror of ops/provenance (docs/telemetry.md):
    the same version-ref holder test and the same minimal-(hops, node
    id) attribution rule, evolved receiver by receiver with plain
    loops instead of the kernel's packed-score scatter-min.  Feed it
    the SAME holder matrices and channel lists the kernel consumes
    (``sim._prov_belief`` / ``sim._prov_channels``) and ``first_seen``
    / ``parent`` / ``hops`` / ``coverage`` must match element-for-
    element."""

    # pack(tick=1, status=0): the floor of ops/provenance._MIN_KNOWN.
    MIN_KNOWN = 8

    def __init__(self, belief0: np.ndarray, round0: int):
        belief0 = np.asarray(belief0)                  # packed [N, T]
        self.n, self.t_n = belief0.shape
        self.ref = np.maximum(belief0.max(axis=0).astype(np.int64),
                              self.MIN_KNOWN)
        self.first_seen = np.full((self.t_n, self.n), -1, np.int64)
        self.parent = np.full((self.t_n, self.n), -1, np.int64)
        self.hops = np.full((self.t_n, self.n), -1, np.int64)
        self.coverage: list = []
        hold = self.holders(belief0)
        for ti in range(self.t_n):
            for node in range(self.n):
                if hold[node, ti]:
                    self.first_seen[ti, node] = int(round0)
                    self.hops[ti, node] = 0   # parent stays ORIGIN (-1)

    def holders(self, belief) -> np.ndarray:
        """Bool [N, T]: beliefs that reached the traced version."""
        return np.asarray(belief) >= self.ref[None, :]

    def observe(self, prev_hold, nxt_hold, round_idx: int,
                pushes=(), pulls=()) -> None:
        """Fold one round: for every node newly holding a record, scan
        every sampled channel whose sender already held it and charge
        the minimal-(hops, sender id) candidate; no open candidate ⇒
        PARENT_UNATTRIBUTED (−2) at hop 0."""
        prev_hold = np.asarray(prev_hold)
        nxt_hold = np.asarray(nxt_hold)
        pushes = [(np.asarray(i),
                   None if m is None
                   else np.broadcast_to(np.asarray(m), np.shape(i)))
                  for i, m in pushes]
        pulls = [(np.asarray(i),
                  None if m is None
                  else np.broadcast_to(np.asarray(m), np.shape(i)))
                 for i, m in pulls]
        for ti in range(self.t_n):
            for node in range(self.n):
                if not nxt_hold[node, ti] \
                        or self.first_seen[ti, node] >= 0:
                    continue
                best = None                            # (hops, sender)
                for idx, mask in pushes:
                    for s in range(idx.shape[0]):
                        if not prev_hold[s, ti]:
                            continue
                        for k in range(idx.shape[1]):
                            if int(idx[s, k]) != node:
                                continue
                            if mask is not None and not mask[s, k]:
                                continue
                            cand = (max(int(self.hops[ti, s]), 0), s)
                            if best is None or cand < best:
                                best = cand
                for idx, mask in pulls:
                    for k in range(idx.shape[1]):
                        if mask is not None and not mask[node, k]:
                            continue
                        src = int(idx[node, k])
                        if not prev_hold[src, ti]:
                            continue
                        cand = (max(int(self.hops[ti, src]), 0), src)
                        if best is None or cand < best:
                            best = cand
                self.first_seen[ti, node] = int(round_idx)
                if best is None:
                    self.parent[ti, node] = -2
                    self.hops[ti, node] = 0
                else:
                    self.parent[ti, node] = best[1]
                    self.hops[ti, node] = best[0] + 1
        self.coverage.append(
            nxt_hold.sum(axis=0).astype(np.int64).tolist())
