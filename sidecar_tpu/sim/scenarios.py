"""The BASELINE.json validation scenarios, runnable end to end.

Five configs (BASELINE.md):
  1. static single node, 3 services — CPU-grade merge reference
  2. 32-node ring, fanout 3, 10 services/node — convergence vs oracle
  3. 4,096-node Erdős–Rényi with 5% service churn + tombstone propagation
  4. 65,536-node Barabási–Albert with periodic anti-entropy
  5. 1M-node partitioned mesh, 2-way split + heal (compressed model)

Each scenario returns a :class:`ScenarioResult` with the convergence
curve, ε-convergence round/wall-clock, and rounds/sec.

Configs 1-3 run the dense exact model from a cold start (the dense row
is O(N²·spn), fine to 4,096 nodes).  Configs 4 and 5 run at their
DECLARED scale (65,536 / 1,000,000 nodes) on the compressed
large-cluster model (``models/compressed.py``), which starts converged
and measures how injected churn — the steady-state workload — drains
back to full convergence; cold-start full-catalog sync at that scale is
the push-pull regime the model's floor absorbs by construction (see the
module docstring there).  ``scale`` shrinks any config proportionally
for quick runs/tests; at scale=1 configs 4/5 report ``scaled_from=None``.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable, Optional

import jax
import numpy as np

from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
from sidecar_tpu.models.exact import ExactSim, SimParams
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import topology as topo_mod


@dataclasses.dataclass
class ScenarioResult:
    name: str
    n: int
    services_per_node: int
    rounds_run: int
    convergence: np.ndarray          # sampled convergence curve
    eps_round: Optional[int]         # first round with conv >= 1 - eps
    eps_seconds_simulated: Optional[float]
    wall_seconds: float
    rounds_per_sec: float
    scaled_from: Optional[int] = None  # declared full-scale N, if reduced
    conv_every: int = 1              # rounds between convergence samples
    notes: str = ""

    def summary(self) -> dict:
        return {
            "scenario": self.name,
            "n": self.n,
            "rounds": self.rounds_run,
            "final_convergence": float(self.convergence[-1])
            if len(self.convergence) else None,
            "eps_round": self.eps_round,
            "eps_seconds_simulated": self.eps_seconds_simulated,
            "wall_seconds": round(self.wall_seconds, 3),
            "rounds_per_sec": round(self.rounds_per_sec, 2),
            "scaled_from": self.scaled_from,
            "conv_every": self.conv_every,
            "notes": self.notes,
        }


def _eps_round(conv: np.ndarray, eps: float,
               conv_every: int = 1) -> Optional[int]:
    hits = np.nonzero(conv >= 1.0 - eps)[0]
    return (int(hits[0]) + 1) * conv_every if hits.size else None


# Longest single device program, in node-rounds: the per-round PRNG
# folds round_idx into the key, so host-side chunking is bit-identical
# to one long scan (the tested checkpoint/resume contract) — and
# multi-minute XLA programs have been observed to trip the TPU worker's
# watchdog (a 7-minute 1M-node program crashed it; ~2-minute programs
# run reliably).
MAX_CHUNK_NODE_ROUNDS = 50_000_000
MAX_CHUNK_ROUNDS = 400


def _chunk_rounds(n: int, conv_every: int) -> int:
    chunk = min(MAX_CHUNK_ROUNDS, max(1, MAX_CHUNK_NODE_ROUNDS // n))
    chunk = max(conv_every, chunk - chunk % conv_every)
    return chunk


def _run_chunked(sim, state, key, rounds: int, conv_every: int):
    """sim.run in watchdog-safe chunks; returns (state, conv array)."""
    if rounds <= 0:
        return state, np.zeros((0,), np.float32)
    chunk = _chunk_rounds(sim.p.n, conv_every)
    parts = []
    done = 0
    while done < rounds:
        step = min(chunk, rounds - done)
        if conv_every > 1:
            state, conv = sim.run(state, key, step, conv_every)
        else:
            state, conv = sim.run(state, key, step)
        parts.append(np.asarray(jax.device_get(conv)))
        done += step
    return state, np.concatenate(parts)


def _run(sim, state, rounds: int, seed: int,
         name: str, eps: float, scaled_from: Optional[int] = None,
         conv_every: int = 1, notes: str = "") -> ScenarioResult:
    """Drive any sim exposing run(state, key, rounds) -> (state, conv)
    (ExactSim and CompressedSim share the driver contract).
    ``conv_every`` samples the metric on a cadence (compressed sims
    only) — the census is scatter-bound at large N."""
    key = jax.random.PRNGKey(seed)
    t0 = time.perf_counter()
    state, conv = _run_chunked(sim, state, key, rounds, conv_every)
    wall = time.perf_counter() - t0
    er = _eps_round(conv, eps, conv_every)
    return ScenarioResult(
        name=name, n=sim.p.n, services_per_node=sim.p.services_per_node,
        rounds_run=rounds, convergence=conv, eps_round=er,
        eps_seconds_simulated=(er * sim.t.round_ticks /
                               sim.t.ticks_per_second
                               if er is not None else None),
        wall_seconds=wall, rounds_per_sec=rounds / wall,
        scaled_from=scaled_from, conv_every=conv_every, notes=notes)


# Cold-start studies pin the refresh far out so convergence measures pure
# epidemic spread, not the refresh chase.
_STUDY_CFG = TimeConfig(refresh_interval_s=10_000.0)


def config1_static_merge(eps: float = 0.0) -> ScenarioResult:
    """Single node, 3 services: the merge-kernel sanity config."""
    sim = ExactSim(SimParams(n=1, services_per_node=3, fanout=1, budget=3),
                   topo_mod.complete(1), _STUDY_CFG)
    return _run(sim, sim.init_state(), rounds=10, seed=1,
                name="config1-static", eps=eps,
                notes="single node: converged by construction")


def config2_ring(eps: float = 0.0, rounds: int = 120) -> ScenarioResult:
    """32-node ring, fanout 3, 10 services/node."""
    sim = ExactSim(SimParams(n=32, services_per_node=10, fanout=3,
                             budget=15),
                   topo_mod.ring(32), _STUDY_CFG)
    return _run(sim, sim.init_state(), rounds=rounds, seed=2,
                name="config2-ring32", eps=eps)


def _churn_perturb(params: SimParams, timecfg: TimeConfig,
                   churn_prob_per_round: float):
    """Service churn: each round a Bernoulli subset of slots restarts —
    old instance tombstoned by its owner, a successor announced with a
    fresh timestamp (the owner-side analog of Docker die/start events).

    ONE implementation shared with the fleet plane
    (``fleet/batch.restart_churn_perturb`` — lazy import, the fleet
    package imports this module's validators at load time): the fleet
    runs it knob-driven per scenario, the scenarios run it at a static
    probability."""
    del timecfg  # cadence-free: the probability is already per round
    from sidecar_tpu.fleet.batch import restart_churn_perturb

    return restart_churn_perturb(params, prob=churn_prob_per_round)


def config3_er_churn(eps: float = 0.01, rounds: int = 1200,
                     scale: float = 1.0) -> ScenarioResult:
    """4,096-node Erdős–Rényi, 5% churn over the run, tombstones
    propagating.

    1,200 rounds: the full-scale cold start is push-pull-bound — each
    node must acquire all 40,960 records, and the 20 s anti-entropy
    (every 100 rounds) does the bulk syncing, so ε lands around round
    ~1,000 (measured trajectory: 0.26 @ 400 → 0.92 @ 800 → 0.9999 @
    1,200, hovering just under 1.0 as the churn keeps injecting)."""
    n = max(64, int(4096 * scale))
    params = SimParams(n=n, services_per_node=10, fanout=3, budget=15)
    # 5% of services churn across the run.
    churn_per_round = 0.05 / rounds
    sim = ExactSim(params, topo_mod.erdos_renyi(n, avg_degree=8, seed=3),
                   _STUDY_CFG,
                   perturb=_churn_perturb(params, _STUDY_CFG,
                                          churn_per_round))
    return _run(sim, sim.init_state(), rounds=rounds, seed=3,
                name="config3-er4096-churn", eps=eps,
                scaled_from=4096 if n != 4096 else None,
                notes="5% service churn across the run; convergence "
                      "chases a moving target")


def _mint_churn(sim: CompressedSim, state, frac: float, tick: int,
                seed: int, owner_mask: Optional[np.ndarray] = None):
    """Mint a random ``frac`` of all service slots at ``tick`` — the
    churn burst whose drain-to-convergence the large configs measure."""
    rng = np.random.default_rng(seed)
    count = max(1, int(sim.p.m * frac))
    if owner_mask is None:
        slots = rng.choice(sim.p.m, size=count, replace=False)
    else:
        pool = np.nonzero(np.repeat(owner_mask,
                                    sim.p.services_per_node))[0]
        slots = rng.choice(pool, size=min(count, pool.size), replace=False)
    return sim.mint(state, np.sort(slots).astype(np.int32), tick)


def _compressed_sim(params, topo, cfg, sharded: bool, **kw):
    """CompressedSim, or its multi-device twin when ``sharded`` (the
    8-device virtual mesh in tests / a real TPU mesh in production)."""
    if sharded:
        from sidecar_tpu.parallel.sharded_compressed import (
            ShardedCompressedSim,
        )
        return ShardedCompressedSim(params, topo, cfg, **kw)
    return CompressedSim(params, topo, cfg, **kw)


def config4_ba_antientropy(eps: float = 2e-4, rounds: int = 400,
                           scale: float = 1.0,
                           churn_frac: float = 0.002,
                           sharded: bool = False) -> ScenarioResult:
    """65,536-node Barabási–Albert with periodic anti-entropy, at the
    DECLARED scale on the compressed large-cluster model: the cluster
    boots converged, ``churn_frac`` of all services churn at once, and
    the scenario measures drain back to ε-convergence through gossip +
    the 4 s anti-entropy cadence.  ``eps`` is scaled to the churn
    magnitude (the burst itself only unsettles ~``churn_frac`` of
    beliefs).

    Default burst 0.2% (~1,310 records at full scale): the protocol's
    own packet budget (15 records × fanout 3 per 200 ms) bounds drain
    bandwidth, so a 1% burst at this N needs thousands of simulated
    rounds — true of the reference wire protocol too, not a simulator
    artifact; pass churn_frac=0.01 explicitly to study that regime."""
    n = max(128, int(65_536 * scale))
    if sharded:  # the node axis must divide the device mesh
        d = jax.device_count()
        n = -(-n // d) * d
    cfg = dataclasses.replace(_STUDY_CFG, push_pull_interval_s=4.0)
    params = CompressedParams(n=n, services_per_node=10, fanout=3,
                              budget=15, cache_lines=256,
                              deep_sweep_every=5)
    sim = _compressed_sim(params, topo_mod.barabasi_albert(n, m=3, seed=4),
                          cfg, sharded)
    conv_every = 5 if n >= 16_384 else 1
    rounds = -(-rounds // conv_every) * conv_every
    state = _mint_churn(sim, sim.init_state(), churn_frac, tick=10, seed=4)
    return _run(sim, state, rounds=rounds, seed=4,
                name="config4-ba-antientropy", eps=eps,
                conv_every=conv_every,
                scaled_from=65_536 if n != 65_536 else None,
                notes=f"compressed model; {churn_frac:.2%} service churn "
                      "burst; anti-entropy every 4 s simulated"
                      + ("; node-axis sharded" if sharded else ""))


def config5_split_heal(eps: float = 1e-5, split_rounds: int = 150,
                       heal_rounds: int = 450,
                       scale: float = 1.0,
                       churn_frac: float = 1e-4,
                       sharded: bool = False) -> ScenarioResult:
    """Partitioned 2-D mesh at the DECLARED 1M nodes (compressed model):
    churn is injected on ONE side of the split, convergence stalls while
    the partition holds (cross-side gossip AND stride anti-entropy are
    severed), then the cut is removed and the backlog drains to ε.

    Burst sizing at full scale: the bounded cache (K=64 lines/node —
    larger K at 1M nodes exhausts single-chip HBM) drains collision
    chains serially per line at a measured ~40 rounds per fold cycle,
    so the default 0.01% burst (~400 records, ~6 per line) is what a
    450-round heal genuinely completes; larger bursts at this scale
    are capacity-bound in the model exactly as they would be
    memory-bound on real 1M-node hardware."""
    side = max(8, int(1000 * math.sqrt(scale)))
    if sharded:  # the node axis must divide the device mesh
        d = jax.device_count()
        while (side * side) % d:
            side += 1
    n = side * side
    topo = topo_mod.mesh2d(side, side)
    halves = (np.arange(n) % side >= side // 2).astype(np.int32)
    cut = topo_mod.partition_mask(topo, halves)

    params = CompressedParams(n=n, services_per_node=4, fanout=3,
                              budget=15, cache_lines=64,
                              deep_sweep_every=5)
    # Frequent anti-entropy: healing a partition is seeded by push-pull
    # at the boundary, then drained by gossip relay.
    cfg = dataclasses.replace(_STUDY_CFG, push_pull_interval_s=2.0)

    conv_every = 5 if n >= 16_384 else 1
    split_rounds = -(-split_rounds // conv_every) * conv_every
    heal_rounds = -(-heal_rounds // conv_every) * conv_every
    split_sim = _compressed_sim(params, topo, cfg, sharded, cut_mask=cut,
                                node_side=halves)
    key = jax.random.PRNGKey(5)
    t0 = time.perf_counter()
    state = _mint_churn(split_sim, split_sim.init_state(), churn_frac,
                        tick=10, seed=5, owner_mask=halves == 0)
    state, conv_split = _run_chunked(split_sim, state, key, split_rounds,
                                     conv_every)

    heal_sim = _compressed_sim(params, topo, cfg, sharded)  # cut removed
    state, conv_heal = _run_chunked(heal_sim, state, key, heal_rounds,
                                    conv_every)
    wall = time.perf_counter() - t0

    conv = np.concatenate([conv_split, conv_heal])
    rounds = split_rounds + heal_rounds
    er = _eps_round(conv, eps, conv_every)
    split_peak = float(conv_split.max()) if conv_split.size else \
        float("nan")
    return ScenarioResult(
        name="config5-split-heal", n=n,
        services_per_node=params.services_per_node, rounds_run=rounds,
        convergence=conv, eps_round=er,
        eps_seconds_simulated=(er * cfg.round_ticks / cfg.ticks_per_second
                               if er is not None else None),
        wall_seconds=wall, rounds_per_sec=rounds / wall,
        scaled_from=1_000_000 if n != 1_000_000 else None,
        conv_every=conv_every,
        notes=f"compressed model; churn on one side of the split; "
              f"convergence while split peaked at {split_peak:.4f} "
              "(must stay < 1); heal completes it")


def config6_chaos(eps: float = 1e-3, scale: float = 1.0,
                  seed: int = 6) -> ScenarioResult:
    """Partition → churn → heal under 20% asymmetric loss — the chaos
    cross-validation scenario (sidecar_tpu/chaos/), exact model.

    One seeded FaultPlan drives everything: rounds [20, 80) split the
    cluster in half (full cut both ways) while the A→B direction
    additionally suffers 20% packet loss for the whole run (asymmetric
    loss persists after the heal — the recovery must beat it); churn
    lands on side A only, DURING the partition, so side B converges on
    the backlog exclusively through the heal.  The live in-process
    twin of this scenario runs in tests/test_chaos.py from the same
    plan; rerunning this function with the same seed reproduces the
    identical convergence trace (the chaos determinism contract)."""
    from sidecar_tpu.chaos import ChaosExactSim, EdgeFault, FaultPlan

    n = max(32, int(256 * scale))
    n -= n % 2
    spn = 4
    side_a = tuple(range(n // 2))
    side_b = tuple(range(n // 2, n))
    plan = FaultPlan(
        seed=seed,
        edges=(EdgeFault(src=side_a, dst=side_b, drop_prob=0.2),),
    ).with_edges(*FaultPlan.partition(side_a, side_b, 20, 80))

    params = SimParams(n=n, services_per_node=spn, fanout=3, budget=15)

    # Churn on side A only, rounds 30-60 (mid-partition): a Bernoulli
    # subset of side-A slots restarts each round, exactly like
    # config3's churn but windowed and one-sided.
    def perturb(state, key, now):
        import dataclasses as _dc

        import jax.numpy as jnp

        from sidecar_tpu.ops.status import (ALIVE as _ALIVE,
                                            TOMBSTONE as _TOMB)
        from sidecar_tpu.ops.status import pack as _pack
        from sidecar_tpu.ops.status import unpack_status as _ust
        from sidecar_tpu.ops.status import unpack_ts as _uts

        round_idx = now // _STUDY_CFG.round_ticks
        active = (round_idx >= 30) & (round_idx < 60)
        owner = jnp.arange(params.m, dtype=jnp.int32) // spn
        cols = jnp.arange(params.m, dtype=jnp.int32)
        on_side_a = owner < (n // 2)
        churn = jax.random.bernoulli(key, 0.02 / spn, (params.m,))
        own = state.known[owner, cols]
        flip = churn & active & on_side_a & (_uts(own) > 0) & \
            state.node_alive[owner]
        st = _ust(own)
        new_status = jnp.where(st == _ALIVE, _TOMB, _ALIVE)
        new_val = jnp.where(flip, _pack(now, new_status), own)
        known = state.known.at[owner, cols].set(new_val)
        reset_rows = jnp.where(flip, owner, params.n)
        sent = state.sent.at[reset_rows, cols].set(jnp.int8(0),
                                                   mode="drop")
        return _dc.replace(state, known=known, sent=sent)

    cfg = dataclasses.replace(_STUDY_CFG, push_pull_interval_s=2.0)
    sim = ChaosExactSim(params, topo_mod.complete(n), cfg, plan=plan,
                        perturb=perturb)
    return _run(sim, sim.init_state(), rounds=200, seed=seed,
                name="config6-chaos-partition", eps=eps,
                scaled_from=256 if n != 256 else None,
                notes="FaultPlan-driven: 2-way split rounds 20-80, "
                      "one-sided churn rounds 30-60, 20% A->B loss "
                      "throughout; heal drains the backlog")


# -- registration + validation ----------------------------------------------
# Scenario configs are validated at REGISTRATION time: a bad fanout or
# transmit limit must fail here with a named error, not 400 rounds into
# a compiled scan as an inscrutable shape/int8 failure.  The fleet
# plane (sidecar_tpu/fleet/batch.py) routes every grid point through
# :func:`validate_protocol_config` for the same reason.

ALL_SCENARIOS: dict[str, Callable[..., ScenarioResult]] = {}


def register_scenario(name: str, fn: Callable[..., ScenarioResult],
                      *, replace: bool = False) -> None:
    """Register a runnable scenario under ``name``.

    Duplicate names are rejected (two scenarios silently shadowing each
    other is how a sweep reports the wrong config's numbers); pass
    ``replace=True`` to overwrite deliberately."""
    if not callable(fn):
        raise TypeError(f"scenario {name!r}: fn must be callable, got "
                        f"{type(fn).__name__}")
    if not replace and name in ALL_SCENARIOS:
        raise ValueError(
            f"scenario {name!r} is already registered "
            f"(to {ALL_SCENARIOS[name].__name__}); pick a distinct name "
            "or pass replace=True")
    ALL_SCENARIOS[name] = fn


def validate_protocol_config(n: int, *, fanout: int, budget: int,
                             retransmit_limit: int = 0,
                             services_per_node: int = 1,
                             name: str = "scenario") -> None:
    """Range-check the protocol knobs a scenario/grid point declares.

    Raises ``ValueError`` naming the offending knob and its bound —
    the registration-time twin of the mid-scan failures these values
    would otherwise cause (fanout shapes the sampled-peer tensor;
    the transmit limit must keep the int8 ``sent`` counters
    representable, ops/gossip.record_transmissions)."""
    label = f"{name}: " if name else ""
    if n < 1:
        raise ValueError(f"{label}n={n} must be >= 1")
    if services_per_node < 1:
        raise ValueError(
            f"{label}services_per_node={services_per_node} must be >= 1")
    if not 1 <= fanout:
        raise ValueError(f"{label}fanout={fanout} must be >= 1")
    if n > 1 and fanout >= n:
        raise ValueError(
            f"{label}fanout={fanout} must be < n={n} (a node cannot "
            "gossip to more distinct peers than exist)")
    if budget < 1:
        raise ValueError(f"{label}budget={budget} must be >= 1")
    if retransmit_limit < 0:
        raise ValueError(
            f"{label}retransmit_limit={retransmit_limit} must be >= 0 "
            "(0 = auto: RetransmitMult x ceil(log10(n+1)))")
    resolved = retransmit_limit if retransmit_limit > 0 else \
        4 * math.ceil(math.log10(n + 1))
    if resolved + fanout - 1 > 127:
        raise ValueError(
            f"{label}retransmit_limit={resolved} + fanout={fanout} - 1 "
            "exceeds the int8 transmit counter range (127)")


for _name, _fn in (
        ("config1", config1_static_merge),
        ("config2", config2_ring),
        ("config3", config3_er_churn),
        ("config4", config4_ba_antientropy),
        ("config5", config5_split_heal),
        ("config6", config6_chaos)):
    register_scenario(_name, _fn)

_SCALED = ("config3", "config4", "config5", "config6")


def run_all(scale: float = 1.0) -> list[ScenarioResult]:
    out = []
    for name, fn in ALL_SCENARIOS.items():
        if name in _SCALED:
            out.append(fn(scale=scale))
        else:
            out.append(fn())
    return out


if __name__ == "__main__":
    import argparse
    import json
    import os

    # The environment's sitecustomize pins jax to the default platform at
    # interpreter start; re-assert an explicit JAX_PLATFORMS choice so
    # `JAX_PLATFORMS=cpu python -m sidecar_tpu.sim.scenarios` works.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    parser = argparse.ArgumentParser("scenarios")
    parser.add_argument("--scale", type=float, default=1.0,
                        help="scale factor for the large configs "
                             "(1.0 = the declared BASELINE sizes: "
                             "config3 4,096 dense / config4 65,536 "
                             "compressed / config5 1M compressed)")
    parser.add_argument("--only", default=None,
                        help="run a single config (config1..config5)")
    args = parser.parse_args()
    if args.only:
        fn = ALL_SCENARIOS[args.only]
        results = [fn(scale=args.scale)
                   if args.only in _SCALED
                   else fn()]
    else:
        results = run_all(scale=args.scale)
    for result in results:
        print(json.dumps(result.summary()))
