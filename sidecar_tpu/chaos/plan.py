"""The FaultPlan: one seeded, declarative description of every fault a
chaos run injects — shared by the TPU simulator and the live in-process
cluster.

Design requirements (the reason this is its own schema rather than ad
hoc knobs on each component):

* **Deterministic.**  Every probabilistic decision in a plan derives
  from ``plan.seed`` plus stable coordinates (round index, edge, entry
  index) — never from wall-clock entropy — so a failure found in CI can
  be reproduced exactly from its seed (see docs/chaos.md).  The sim
  path draws through the JAX threefry PRNG keyed on the seed; the live
  path draws through :func:`coin`, a counter-based blake2b hash of the
  same seed.  Each path is bit-reproducible against itself.
* **Structured, not i.i.d.**  The "Robust and Tuneable Family of
  Gossiping Algorithms" analysis (PAPERS.md) shows uniform loss is the
  *easy* regime for epidemic protocols; the plan therefore expresses
  per-EDGE schedules (source set × destination set × round window),
  asymmetric partitions, and correlated node windows — the adversarial
  structure a single ``drop_prob`` scalar cannot.
* **Round-indexed.**  All windows are in gossip rounds (one round = one
  GossipInterval).  The sim's round index is exact; the live injector
  maps wall clock onto rounds via its configured round duration.

Time windows are half-open ``[start_round, end_round)``.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import hashlib
import json
from typing import Iterable, Union

# "all" or an explicit tuple of node indices.  Tuples (not lists) so
# plans stay hashable — the sim closes over them as jit-static state.
NodeSel = Union[str, tuple]

FOREVER = 1 << 30


def _as_sel(nodes) -> NodeSel:
    if isinstance(nodes, str):
        if nodes != "all":
            raise ValueError(f"node selector string must be 'all', got "
                             f"{nodes!r}")
        return nodes
    return tuple(int(i) for i in nodes)


def resolve_nodes(sel: NodeSel, n: int) -> tuple:
    """Selector → concrete node-index tuple for an ``n``-node cluster."""
    if sel == "all":
        return tuple(range(n))
    bad = [i for i in sel if not 0 <= i < n]
    if bad:
        raise ValueError(f"node selector {bad} out of range for n={n}")
    return tuple(sel)


@dataclasses.dataclass(frozen=True)
class EdgeFault:
    """Per-edge message faults on the (src → dst) direction.

    ``drop_prob`` loses the packet entirely; ``delay_prob`` diverts it
    to arrive ``delay_rounds`` later; ``duplicate_prob`` delivers it now
    AND again after ``max(delay_rounds, 1)`` rounds.  A full partition
    in one direction is ``drop_prob=1.0``; an asymmetric 20% loss is
    ``drop_prob=0.2`` with src/dst covering one direction only.
    """

    src: NodeSel = "all"
    dst: NodeSel = "all"
    start_round: int = 0
    end_round: int = FOREVER
    drop_prob: float = 0.0
    delay_rounds: int = 0
    delay_prob: float = 0.0
    duplicate_prob: float = 0.0

    def __post_init__(self):
        object.__setattr__(self, "src", _as_sel(self.src))
        object.__setattr__(self, "dst", _as_sel(self.dst))
        for name in ("drop_prob", "delay_prob", "duplicate_prob"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name}={v} not in [0, 1]")
        if self.delay_rounds < 0:
            raise ValueError("delay_rounds must be >= 0")
        if self.delay_prob > 0.0 and self.delay_rounds == 0:
            raise ValueError("delay_prob > 0 requires delay_rounds >= 1")
        if self.start_round >= self.end_round:
            raise ValueError(
                f"empty window [{self.start_round}, {self.end_round})")

    @property
    def needs_ring(self) -> bool:
        """True when the sim must carry a delay ring for this entry."""
        return self.delay_prob > 0.0 or self.duplicate_prob > 0.0

    @property
    def ring_rounds(self) -> int:
        """Depth of the delay ring (duplicates without an explicit delay
        re-arrive the next round)."""
        return max(self.delay_rounds, 1)

    @property
    def full_cut(self) -> bool:
        """A deterministic total cut — severs TCP push-pull too (UDP
        loss below 1.0 does not: TCP rides retransmission)."""
        return self.drop_prob >= 1.0


@dataclasses.dataclass(frozen=True)
class NodeFault:
    """A correlated node window: ``pause`` (the process stalls — sends
    and accepts nothing, state retained) or ``crash`` (same, but at
    ``end_round`` the node restarts COLD: its belief row is wiped to a
    fresh re-announce of its own records — the rejoin workload)."""

    nodes: NodeSel
    start_round: int
    end_round: int
    kind: str = "pause"

    def __post_init__(self):
        object.__setattr__(self, "nodes", _as_sel(self.nodes))
        if self.kind not in ("pause", "crash"):
            raise ValueError(f"kind must be pause|crash, got {self.kind!r}")
        if self.start_round >= self.end_round:
            raise ValueError(
                f"empty window [{self.start_round}, {self.end_round})")


@dataclasses.dataclass(frozen=True)
class HealthFault:
    """Slow/failing health-check injection: checks whose id matches
    ``id_pattern`` (fnmatch) gain ``extra_latency_s`` of synthetic IO
    time inside the window; ``fail`` additionally makes them report
    UNKNOWN.  This is the workload that exposes check-pool starvation
    (ADVICE.md medium, health/monitor.py)."""

    id_pattern: str = "*"
    start_round: int = 0
    end_round: int = FOREVER
    extra_latency_s: float = 0.0
    fail: bool = False

    def matches(self, check_id: str) -> bool:
        return fnmatch.fnmatch(check_id, self.id_pattern)


@dataclasses.dataclass(frozen=True)
class ClockFault:
    """Per-node clock skew: inside ``[start_round, end_round)`` the
    selected nodes STAMP records with their own skewed clock — a static
    ``offset_ticks``, plus ``drift_ticks_per_round`` accumulating from
    the window start, plus an optional one-shot ``step_ticks`` jump
    from ``step_round`` on (an operator fat-fingering ``date``, a leap
    smear gone wrong).  Receivers keep judging admission and TTL expiry
    by their OWN clocks, so a rushing node (+offset) mints records the
    rest of the cluster sees as from the future — LWW poison the
    future-admission bound (ops/merge.future_mask) exists to reject —
    and a slow node (−offset) looks stale early, the false-positive
    tombstone workload.  Offsets of overlapping entries add.

    Drift is computed as ``floor(float32(drift) * float32(r - start))``
    — float32 multiply then floor — identically in the XLA and NumPy
    compilers so the oracle lockstep holds tick for tick.
    """

    nodes: NodeSel = "all"
    start_round: int = 0
    end_round: int = FOREVER
    offset_ticks: int = 0
    drift_ticks_per_round: float = 0.0
    step_ticks: int = 0
    step_round: int = 0

    def __post_init__(self):
        object.__setattr__(self, "nodes", _as_sel(self.nodes))
        if self.start_round < 0:
            raise ValueError(
                f"negative window start {self.start_round}")
        if self.start_round >= self.end_round:
            raise ValueError(
                f"empty window [{self.start_round}, {self.end_round})")
        if self.drift_ticks_per_round != 0.0 and \
                self.end_round >= FOREVER:
            raise ValueError(
                "drift requires a bounded window (end_round < FOREVER): "
                "unbounded drift overflows the int32 tick clock")

    def offset_at(self, round_idx: int) -> int:
        """This entry's skew (ticks) at a round — 0 outside the window.
        The host/NumPy twin of the compiled offset math (float32
        multiply + floor, see class docstring)."""
        if not self.start_round <= round_idx < self.end_round:
            return 0
        import numpy as np
        off = self.offset_ticks
        if self.drift_ticks_per_round != 0.0:
            off += int(np.floor(
                np.float32(self.drift_ticks_per_round)
                * np.float32(round_idx - self.start_round)))
        if self.step_ticks and round_idx >= self.step_round:
            off += self.step_ticks
        return off

    @property
    def max_offset(self) -> int:
        """Largest positive skew this entry can inject over its window
        — the horizon-guard contribution (models/timecfg.validate_horizon).
        The offset is monotone in |drift|, so the max over the window is
        attained at one of the candidate rounds checked here."""
        cands = [self.start_round, min(self.end_round, FOREVER) - 1]
        if self.step_ticks:
            # Each monotone piece of the offset attains its max at a
            # piece endpoint: the step boundary adds two candidates.
            cands += [max(self.step_round, self.start_round),
                      max(self.step_round - 1, self.start_round)]
        return max(0, max(self.offset_at(r) for r in cands
                          if r >= self.start_round))


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """The whole chaos schedule, rooted at one seed."""

    seed: int
    edges: tuple = ()
    nodes: tuple = ()
    health: tuple = ()
    clocks: tuple = ()

    def __post_init__(self):
        object.__setattr__(self, "edges", tuple(self.edges))
        object.__setattr__(self, "nodes", tuple(self.nodes))
        object.__setattr__(self, "health", tuple(self.health))
        object.__setattr__(self, "clocks", tuple(self.clocks))
        for e in self.edges:
            if not isinstance(e, EdgeFault):
                raise TypeError(f"edges entries must be EdgeFault, "
                                f"got {type(e).__name__}")
        for e in self.nodes:
            if not isinstance(e, NodeFault):
                raise TypeError(f"nodes entries must be NodeFault, "
                                f"got {type(e).__name__}")
        for e in self.health:
            if not isinstance(e, HealthFault):
                raise TypeError(f"health entries must be HealthFault, "
                                f"got {type(e).__name__}")
        for e in self.clocks:
            if not isinstance(e, ClockFault):
                raise TypeError(f"clocks entries must be ClockFault, "
                                f"got {type(e).__name__}")

    # -- builders ----------------------------------------------------------

    @staticmethod
    def partition(side_a: Iterable[int], side_b: Iterable[int],
                  start_round: int, end_round: int,
                  direction: str = "both",
                  loss_prob: float = 1.0) -> tuple:
        """Edge entries for a (possibly asymmetric, possibly lossy
        rather than total) partition between two node sets.

        ``direction``: ``both`` | ``a_to_b`` | ``b_to_a`` — which
        traffic direction is affected.  ``loss_prob < 1.0`` models a
        degraded link instead of a clean split.
        """
        a, b = tuple(side_a), tuple(side_b)
        if set(a) & set(b):
            raise ValueError("partition sides overlap")
        out = []
        if direction in ("both", "a_to_b"):
            out.append(EdgeFault(src=a, dst=b, start_round=start_round,
                                 end_round=end_round, drop_prob=loss_prob))
        if direction in ("both", "b_to_a"):
            out.append(EdgeFault(src=b, dst=a, start_round=start_round,
                                 end_round=end_round, drop_prob=loss_prob))
        if not out:
            raise ValueError(
                f"direction must be both|a_to_b|b_to_a, got {direction!r}")
        return tuple(out)

    def with_edges(self, *entries: EdgeFault) -> "FaultPlan":
        flat: list = []
        for e in entries:
            flat.extend(e) if isinstance(e, tuple) else flat.append(e)
        return dataclasses.replace(self, edges=self.edges + tuple(flat))

    # -- live-path helpers -------------------------------------------------

    def health_fault_at(self, check_id: str,
                        round_idx: int) -> tuple[float, bool]:
        """(extra latency seconds, fail?) for a check at a round —
        latencies of overlapping entries add, fail ORs."""
        delay, fail = 0.0, False
        for h in self.health:
            if h.start_round <= round_idx < h.end_round and \
                    h.matches(check_id):
                delay += h.extra_latency_s
                fail = fail or h.fail
        return delay, fail

    def node_down(self, node: int, round_idx: int) -> bool:
        for f in self.nodes:
            if f.start_round <= round_idx < f.end_round and \
                    (f.nodes == "all" or node in f.nodes):
                return True
        return False

    def clock_offset(self, node: int, round_idx: int) -> int:
        """Net clock skew (ticks) node ``node`` stamps with at a round
        — overlapping entries add (the live injector's shim and the
        NumPy oracle both read this)."""
        off = 0
        for f in self.clocks:
            if f.nodes == "all" or node in f.nodes:
                off += f.offset_at(round_idx)
        return off

    @property
    def max_clock_offset(self) -> int:
        """Largest positive skew any node can stamp with under this
        plan — folded into the packed-key overflow guard
        (models/timecfg.validate_horizon)."""
        return sum(f.max_offset for f in self.clocks)

    # -- serialization (reproduction recipes, docs/chaos.md) ---------------

    def to_json(self) -> dict:
        def enc(entry):
            return dataclasses.asdict(entry)
        return {"seed": self.seed,
                "edges": [enc(e) for e in self.edges],
                "nodes": [enc(e) for e in self.nodes],
                "health": [enc(e) for e in self.health],
                "clocks": [enc(e) for e in self.clocks]}

    @classmethod
    def from_json(cls, d: dict) -> "FaultPlan":
        return cls(seed=int(d["seed"]),
                   edges=tuple(EdgeFault(**e) for e in d.get("edges", [])),
                   nodes=tuple(NodeFault(**e) for e in d.get("nodes", [])),
                   health=tuple(HealthFault(**e)
                                for e in d.get("health", [])),
                   clocks=tuple(ClockFault(**e)
                                for e in d.get("clocks", [])))

    def dumps(self) -> str:
        return json.dumps(self.to_json(), sort_keys=True)

    @classmethod
    def loads(cls, s: str) -> "FaultPlan":
        return cls.from_json(json.loads(s))


def coin(seed: int, *coords) -> float:
    """The live path's deterministic uniform draw in [0, 1): a blake2b
    hash of (seed, coords).  Stable across processes and platforms, so
    a live chaos run's fault schedule is a pure function of the plan
    seed and the decision coordinates (edge, per-edge counter)."""
    payload = repr((int(seed),) + tuple(coords)).encode()
    h = hashlib.blake2b(payload, digest_size=8).digest()
    return int.from_bytes(h, "big") / float(1 << 64)
