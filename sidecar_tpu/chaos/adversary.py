"""The AdversaryPlan: declarative Byzantine-peer attack programs,
beside :mod:`sidecar_tpu.chaos.plan`'s honest-fault FaultPlan.

A FaultPlan breaks the *transport* (loss, partitions, pauses, skewed
clocks); an AdversaryPlan breaks the *content*: selected nodes LIE in
the records they gossip.  Each :class:`Attack` entry names who lies
(``nodes``), about whom (``victims``), when (a half-open
``[start_round, end_round)`` window, the FaultPlan convention), how
much of each packet is corrupted (``rate`` of the message budget), and
how (``kind``):

* ``tombstone_bomb`` — forge TOMBSTONE records for the victims' slots
  at the attacker's current tick: LWW poison that kills live services.
* ``future_flood`` — forge ALIVE records stamped ``magnitude_ticks``
  into the future (beyond any admission fudge): unrefreshable poison
  that only ``ops/merge.future_mask`` or the origin budget can stop.
* ``sybil_flood`` — the same forged-ALIVE flood but *within* a small
  magnitude: an identity flood of plausible fresh records that slips
  under the future gate, caught only by the per-origin budget.
* ``past_flood`` / ``replay`` — old-stamped ALIVE floods (a replayed
  stale catalog): mostly harmless to LWW but a bytes-amplification
  attack on the transport and the admission gates.
* ``flap`` — the attacker oscillates its OWN records ALIVE/DRAINING
  with fresh stamps every round: the proxy-churn attack the
  FlapDamper (PR 7) gates on the live path.

Design requirements, shared with FaultPlan:

* **Deterministic, PRNG-free.**  An attack corrupts the first
  ``floor(rate * budget)`` columns of an attacker's packet and targets
  victim slots by pure rotation (``(round * ncorrupt + col) % V``) —
  no random draws at all, so the NumPy oracle and the live injector
  mirror the compiled path tick for tick with plain arithmetic.
* **Round-indexed, window-scoped.**  Windows are gossip rounds;
  overlapping windows of the same kind on the same attacker are a
  validation error (named, tested) rather than an ambiguous overlay.
* **Horizon-guarded.**  Future-stamped forgeries count toward the
  packed-key overflow guard exactly like positive clock skew
  (``max_future_ticks`` → ``models/timecfg.validate_horizon``).

See docs/chaos.md ("Adversarial gossip & the defense ladder") for the
defense stack this plan is measured against.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Union

import numpy as np

from sidecar_tpu.chaos.plan import FOREVER, NodeSel, _as_sel, resolve_nodes
from sidecar_tpu.ops import status as svc_status

ATTACK_KINDS = ("tombstone_bomb", "future_flood", "sybil_flood",
                "past_flood", "replay", "flap")

# Kinds whose forged timestamps sit magnitude_ticks in the future and
# therefore contribute to the packed-key horizon guard.
_FUTURE_KINDS = ("future_flood", "sybil_flood")
# Kinds that need a nonzero timestamp displacement to mean anything.
_NEEDS_MAGNITUDE = ("future_flood", "sybil_flood", "past_flood", "replay")


@dataclasses.dataclass(frozen=True)
class Attack:
    """One attack program: WHO lies about WHOM, WHEN, HOW, and HOW MUCH.

    ``rate`` is the corrupted fraction of the per-packet message budget
    — ``floor(rate * budget)`` columns of every packet the attacker
    sends inside the window carry forged records instead of (or on top
    of) its honest payload.  ``magnitude_ticks`` is the forged-stamp
    displacement for the flood kinds (future for ``future_flood`` /
    ``sybil_flood``, past for ``past_flood`` / ``replay``); it is
    ignored by ``tombstone_bomb`` and ``flap``, which stamp at the
    attacker's current tick.
    """

    kind: str
    nodes: NodeSel
    victims: NodeSel = "all"
    start_round: int = 0
    end_round: int = FOREVER
    rate: float = 1.0
    magnitude_ticks: int = 0

    def __post_init__(self):
        if self.kind not in ATTACK_KINDS:
            raise ValueError(
                f"unknown attack kind {self.kind!r} (expected one of "
                f"{', '.join(ATTACK_KINDS)})")
        object.__setattr__(self, "nodes", _as_sel(self.nodes))
        object.__setattr__(self, "victims", _as_sel(self.victims))
        if not 0.0 < self.rate <= 1.0:
            raise ValueError(f"rate={self.rate} not in (0, 1]")
        if self.start_round < 0:
            raise ValueError(f"negative window start {self.start_round}")
        if self.start_round >= self.end_round:
            raise ValueError(
                f"empty window [{self.start_round}, {self.end_round})")
        if self.magnitude_ticks < 0:
            raise ValueError(
                f"magnitude_ticks must be >= 0, got {self.magnitude_ticks}")
        if self.kind in _NEEDS_MAGNITUDE and self.magnitude_ticks == 0:
            raise ValueError(
                f"{self.kind} requires magnitude_ticks >= 1")

    @property
    def max_future_ticks(self) -> int:
        """Largest future displacement this entry can stamp — the
        horizon-guard contribution (models/timecfg.validate_horizon)."""
        return self.magnitude_ticks if self.kind in _FUTURE_KINDS else 0

    def active_at(self, round_idx: int) -> bool:
        return self.start_round <= round_idx < self.end_round


def _overlap(a: Attack, b: Attack) -> bool:
    if a.kind != b.kind:
        return False
    if a.start_round >= b.end_round or b.start_round >= a.end_round:
        return False
    sa = "all" if a.nodes == "all" else set(a.nodes)
    sb = "all" if b.nodes == "all" else set(b.nodes)
    if sa == "all" or sb == "all":
        return True
    return bool(sa & sb)


@dataclasses.dataclass(frozen=True)
class AdversaryPlan:
    """The whole Byzantine schedule, rooted at one seed.

    The seed exists for schema parity with FaultPlan (one reproduction
    recipe names both plans) and for future randomized attack kinds;
    every current kind is deliberately PRNG-free (module docstring).
    """

    seed: int
    attacks: tuple = ()

    def __post_init__(self):
        object.__setattr__(self, "attacks", tuple(self.attacks))
        for a in self.attacks:
            if not isinstance(a, Attack):
                raise TypeError(f"attacks entries must be Attack, "
                                f"got {type(a).__name__}")
        for i, a in enumerate(self.attacks):
            for b in self.attacks[i + 1:]:
                if _overlap(a, b):
                    raise ValueError(
                        f"overlapping {a.kind} windows "
                        f"[{a.start_round}, {a.end_round}) and "
                        f"[{b.start_round}, {b.end_round}) on shared "
                        f"attacker(s)")

    @property
    def max_future_ticks(self) -> int:
        """Largest future stamp any attacker can mint — folded into the
        packed-key overflow guard beside the plan's clock skew."""
        return max((a.max_future_ticks for a in self.attacks), default=0)

    def attackers(self, n: int) -> tuple:
        """Sorted union of every entry's attacker set for an ``n``-node
        cluster (the live injector's roster and the quarantine tests'
        expected origin set)."""
        out: set = set()
        for a in self.attacks:
            out.update(resolve_nodes(a.nodes, n))
        return tuple(sorted(out))

    def active_attackers(self, n: int, round_idx: int) -> tuple:
        out: set = set()
        for a in self.attacks:
            if a.active_at(round_idx):
                out.update(resolve_nodes(a.nodes, n))
        return tuple(sorted(out))

    # -- serialization (reproduction recipes, docs/chaos.md) ---------------

    def to_json(self) -> dict:
        return {"seed": self.seed,
                "attacks": [dataclasses.asdict(a) for a in self.attacks]}

    @classmethod
    def from_json(cls, d: dict) -> "AdversaryPlan":
        return cls(seed=int(d["seed"]),
                   attacks=tuple(Attack(**a) for a in d.get("attacks", [])))

    def dumps(self) -> str:
        return json.dumps(self.to_json(), sort_keys=True)

    @classmethod
    def loads(cls, s: str) -> "AdversaryPlan":
        return cls.from_json(json.loads(s))


@dataclasses.dataclass(frozen=True)
class _CompiledAttack:
    """One attack resolved against a concrete cluster: static masks and
    slot tables the traced corrupt step closes over."""

    kind: str
    start_round: int
    end_round: int
    ncorrupt: int           # corrupted columns per packet
    magnitude_ticks: int
    attacker_mask: tuple    # length-n bool tuple (hashable static)
    victim_slots: tuple     # sorted victim-owned slot ids ("" for flap)
    own_slots: tuple        # [n][s] per-node owned slots (flap only)


class CompiledAdversaryPlan:
    """An AdversaryPlan resolved against one cluster shape.

    ``corrupt`` is the traced path (jnp, called between
    ``select_messages`` and ``record_transmissions`` in the chaos sim);
    ``host_overrides`` is the NumPy compiler of the SAME formulas for
    the oracle and the live injector.  Both are pure functions of
    (round, per-node now) — no PRNG, so they agree exactly.
    """

    def __init__(self, plan: AdversaryPlan, *, n: int, owner,
                 budget: int):
        self.plan = plan
        self.n = int(n)
        self.budget = int(budget)
        owner = np.asarray(owner, np.int64)
        self.num_slots = int(owner.shape[0])
        entries = []
        for a in plan.attacks:
            attackers = resolve_nodes(a.nodes, n)
            amask = np.zeros(n, bool)
            amask[list(attackers)] = True
            ncorrupt = int(np.floor(a.rate * budget))
            if ncorrupt == 0:
                ncorrupt = 1  # rate > 0 always corrupts at least a column
            own_slots: tuple = ()
            victim_slots: tuple = ()
            if a.kind == "flap":
                per_node = [tuple(np.where(owner == i)[0])
                            for i in range(n)]
                widths = {len(p) for p in per_node}
                if len(widths) != 1:
                    raise ValueError(
                        "flap attack requires a uniform services-per-node "
                        f"layout, got widths {sorted(widths)}")
                own_slots = tuple(per_node)
            else:
                victims = resolve_nodes(a.victims, n)
                vmask = np.isin(owner, np.asarray(victims, np.int64))
                victim_slots = tuple(np.where(vmask)[0])
                if not victim_slots:
                    raise ValueError(
                        f"{a.kind} attack has no victim-owned slots "
                        f"(victims={a.victims!r})")
            entries.append(_CompiledAttack(
                kind=a.kind, start_round=a.start_round,
                end_round=a.end_round, ncorrupt=ncorrupt,
                magnitude_ticks=a.magnitude_ticks,
                attacker_mask=tuple(bool(x) for x in amask),
                victim_slots=victim_slots, own_slots=own_slots))
        self._entries = tuple(entries)
        amask_any = np.zeros(n, bool)
        for e in self._entries:
            amask_any |= np.asarray(e.attacker_mask, bool)
        self.attacker_mask = amask_any

    # -- traced path (jnp) -------------------------------------------------

    def corrupt(self, round_idx, now_n, svc_idx, msg):
        """Overwrite the leading ``ncorrupt`` columns of every active
        attacker's packet with forged records.

        ``round_idx`` is the (possibly traced) round index, ``now_n``
        the per-node stamping clock ``[n]`` (ClockFault offsets already
        applied — liars lie relative to their OWN skewed clocks),
        ``svc_idx``/``msg`` the ``[n, budget]`` packet from
        ``select_messages``.  Returns ``(svc_idx, msg, nforged)`` where
        ``nforged`` is the int32 count of forged columns this round
        (the ``adversary.sim.forgedRecords`` accounting).  Forged
        columns replace honest payload AND padding, so a high-rate
        attacker also amplifies bytes on the wire.
        """
        import jax.numpy as jnp

        if not self._entries:
            return svc_idx, msg, jnp.zeros((), jnp.int32)
        round_idx = jnp.asarray(round_idx, jnp.int32)
        now_col = jnp.asarray(now_n, jnp.int32)[:, None]
        col = jnp.arange(self.budget, dtype=jnp.int32)
        any_mask = jnp.zeros((self.n, self.budget), bool)
        for e in self._entries:
            act = (round_idx >= e.start_round) & (round_idx < e.end_round)
            amask = (jnp.asarray(e.attacker_mask)[:, None]
                     & (col < e.ncorrupt)[None, :] & act)
            if e.kind == "flap":
                own = jnp.asarray(e.own_slots, jnp.int32)
                s = own.shape[1]
                slots = own[:, (round_idx + col) % s]
                stat = jnp.where(round_idx % 2 == 0,
                                 svc_status.ALIVE, svc_status.DRAINING)
                val = svc_status.pack(jnp.maximum(now_col, 1), stat)
            else:
                vslots = jnp.asarray(e.victim_slots, jnp.int32)
                idx = (round_idx * e.ncorrupt + col) % vslots.shape[0]
                slots = jnp.broadcast_to(vslots[idx][None, :],
                                         (self.n, self.budget))
                if e.kind == "tombstone_bomb":
                    ts = jnp.maximum(now_col, 1)
                    stat = svc_status.TOMBSTONE
                elif e.kind in _FUTURE_KINDS:
                    ts = now_col + e.magnitude_ticks
                    stat = svc_status.ALIVE
                else:  # past_flood / replay
                    ts = jnp.maximum(now_col - e.magnitude_ticks, 1)
                    stat = svc_status.ALIVE
                val = svc_status.pack(ts, stat)
            svc_idx = jnp.where(amask, slots,
                                jnp.asarray(svc_idx, jnp.int32))
            msg = jnp.where(amask, val, jnp.asarray(msg, jnp.int32))
            any_mask = any_mask | amask
        return svc_idx, msg, jnp.sum(any_mask.astype(jnp.int32))

    # -- host mirror (NumPy) -----------------------------------------------

    def host_overrides(self, round_idx: int, now_n):
        """The NumPy compiler of :meth:`corrupt`'s formulas: returns
        ``(mask, slots, vals)``, each ``[n, budget]``, where ``mask``
        is True on forged columns.  The oracle applies these on top of
        its shared ``select_messages`` packet; the live injector reads
        per-attacker rows to forge catalog pushes."""
        mask = np.zeros((self.n, self.budget), bool)
        slots = np.zeros((self.n, self.budget), np.int64)
        vals = np.zeros((self.n, self.budget), np.int64)
        if not self._entries:
            return mask, slots, vals
        now_n = np.asarray(now_n, np.int64)
        col = np.arange(self.budget)
        bits = svc_status.STATUS_BITS
        for e in self._entries:
            if not e.start_round <= round_idx < e.end_round:
                continue
            amask = (np.asarray(e.attacker_mask, bool)[:, None]
                     & (col < e.ncorrupt)[None, :])
            if e.kind == "flap":
                own = np.asarray(e.own_slots, np.int64)
                s = own.shape[1]
                eslots = own[:, (round_idx + col) % s]
                stat = (svc_status.ALIVE if round_idx % 2 == 0
                        else svc_status.DRAINING)
                ets = np.maximum(now_n, 1)[:, None]
                ets = np.broadcast_to(ets, (self.n, self.budget))
            else:
                vslots = np.asarray(e.victim_slots, np.int64)
                idx = (round_idx * e.ncorrupt + col) % vslots.shape[0]
                eslots = np.broadcast_to(vslots[idx][None, :],
                                         (self.n, self.budget))
                if e.kind == "tombstone_bomb":
                    ets = np.maximum(now_n, 1)[:, None]
                    stat = svc_status.TOMBSTONE
                elif e.kind in _FUTURE_KINDS:
                    ets = now_n[:, None] + e.magnitude_ticks
                    stat = svc_status.ALIVE
                else:
                    ets = np.maximum(now_n[:, None] - e.magnitude_ticks, 1)
                    stat = svc_status.ALIVE
                ets = np.broadcast_to(ets, (self.n, self.budget))
            evals = (ets.astype(np.int64) << bits) | stat
            mask = np.where(amask, True, mask)
            slots = np.where(amask, eslots, slots)
            vals = np.where(amask, evals, vals)
        return mask, slots, vals
