"""FaultPlan → the live in-process cluster.

The injection shim sits at the Python boundaries the issue names:

* ``transport/gossip.py`` — every GossipTransport accepts a
  ``fault_injector``; the bridge loop consults it on each decoded
  inbound record (drop / delay / duplicate, per plan edge) and on each
  outbound broadcast batch (node pause/crash windows silence the node).
  Inbound edges are attributed by RECORD ORIGIN (``svc.hostname``) —
  the gossip wire doesn't expose the relaying hop to Python, and
  origin-edge attribution is the failure mode that actually matters
  for catalog convergence (who can't hear about whom);
* full partitions additionally use the native engine's receive-side
  packet-drop hook (``st_test_drop_types``) through
  :meth:`LiveChaosController.tick`, so SWIM probes and TCP push-pull
  are cut exactly like user gossip;
* ``health/checks.py`` — :class:`ChaosChecker` wraps any Checker and
  injects the plan's slow/failing health-check windows;
* ``catalog/state.py`` — :meth:`LiveInjector.install_clock` shims the
  catalog's injectable clock (``ServicesState.set_clock``) with the
  plan's ClockFault skew, so a node stamps/admits/expires by its own
  skewed clock — the live twin of the sim's per-node ``now`` threading.

Determinism: every probabilistic decision is :func:`plan.coin` — a
blake2b hash of (seed, src, dst, per-edge counter) — so the DECISION
SEQUENCE per edge is a pure function of the plan seed.  (Live wall
clock still schedules when packets exist at all; the sim path is the
bit-reproducible twin.)

All injections are counted in the process metrics registry
(``chaos.live.*``) — degradation is observable, never silent.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Optional

from sidecar_tpu import metrics
from sidecar_tpu.chaos.plan import FaultPlan, coin
from sidecar_tpu.transport.gossip import DROP_ALL_UDP, DROP_PUSH_PULL


class LiveInjector:
    """One node's view of the plan: decides the fate of that node's
    inbound records and outbound broadcasts.

    ``node_names`` maps cluster node names → plan node indices (the
    same indices a ChaosExactSim of this cluster would use); ``node``
    is this node's name.  ``round_s`` maps wall clock onto plan rounds
    — use the cluster's gossip interval so plan windows mean the same
    thing on both paths.
    """

    def __init__(self, plan: FaultPlan, node_names: list[str], node: str,
                 round_s: float, tick_s: float = 0.001) -> None:
        if round_s <= 0:
            raise ValueError("round_s must be positive")
        if tick_s <= 0:
            raise ValueError("tick_s must be positive")
        self.plan = plan
        self.tick_s = tick_s
        self.index = {name: i for i, name in enumerate(node_names)}
        if node not in self.index:
            raise ValueError(f"node {node!r} not in {node_names}")
        self.node = node
        self.me = self.index[node]
        self.round_s = round_s
        # INERT until start(): the scenario builds and converges its
        # cluster first, then anchors every node's injector (and the
        # controller) to one shared t0 — plan windows mean the same
        # round on every node, and setup traffic is never injected.
        self._t0: Optional[float] = None
        self._lock = threading.Lock()
        self._counters: dict[int, int] = {}     # src index → decision seq
        self._delayed: list = []                # (release, seq, svc)
        self._seq = itertools.count()

    # -- clock -------------------------------------------------------------

    def start(self, t0: Optional[float] = None) -> None:
        """(Re)anchor round 1 at ``t0`` (default: now).  Call when the
        scenario actually begins so plan windows line up across nodes —
        pass one shared stamp to every node's injector."""
        self._t0 = time.monotonic() if t0 is None else t0

    @property
    def active(self) -> bool:
        return self._t0 is not None

    def round_now(self) -> int:
        """Wall clock → plan round (1-based, like the simulator);
        0 before :meth:`start` anchors the clock."""
        if self._t0 is None:
            return 0
        return int((time.monotonic() - self._t0) / self.round_s) + 1

    # -- clock shim --------------------------------------------------------

    def skew_ns(self) -> int:
        """This node's net ClockFault offset right now, in nanoseconds
        (plan offsets are logical ticks at ``tick_s`` seconds per tick
        — the sim's default 1 ms resolution, models/timecfg.py).  0
        before :meth:`start` anchors the clock or when the plan has no
        clock entries."""
        if not self.active or not self.plan.clocks:
            return 0
        off = self.plan.clock_offset(self.me, self.round_now())
        return int(off * self.tick_s * 1e9)

    def install_clock(self, state) -> None:
        """Shim the catalog's injectable clock
        (:meth:`ServicesState.set_clock`) so THIS node stamps records,
        admits merges, and expires lifespans by its skewed plan clock —
        the live twin of the sim's per-node ``now`` threading
        (chaos/sim_inject.py).  Receivers keep their own (possibly
        unskewed) clocks, so a rushing node's records arrive
        future-stamped exactly as in the sim."""
        base = state._now

        def skewed() -> int:
            return int(base()) + self.skew_ns()

        state.set_clock(skewed)

    # -- transport shim: inbound -------------------------------------------

    def _edge_decision(self, src: int, round_idx: int):
        """(drop, delay_rounds, dup_delay_rounds) for the next record on
        the (src → me) edge — dup_delay_rounds 0 means no duplicate.
        One counter tick per record; each active plan entry draws its
        own coin at stable coordinates."""
        with self._lock:
            seq = self._counters.get(src, 0)
            self._counters[src] = seq + 1
        drop = False
        delay = 0
        dup_delay = 0
        for i, e in enumerate(self.plan.edges):
            if not (e.start_round <= round_idx < e.end_round):
                continue
            src_set = e.src == "all" or src in e.src
            dst_set = e.dst == "all" or self.me in e.dst
            if not (src_set and dst_set):
                continue
            if e.drop_prob > 0.0 and \
                    coin(self.plan.seed, "drop", i, src, self.me,
                         seq) < e.drop_prob:
                drop = True
            if e.delay_prob > 0.0 and \
                    coin(self.plan.seed, "delay", i, src, self.me,
                         seq) < e.delay_prob:
                delay = max(delay, e.delay_rounds)
            if e.duplicate_prob > 0.0 and \
                    coin(self.plan.seed, "dup", i, src, self.me,
                         seq) < e.duplicate_prob:
                dup_delay = max(dup_delay, e.ring_rounds)
        return drop, delay, dup_delay

    def on_recv(self, svc) -> list:
        """The inbound boundary: returns the list of records to merge
        NOW (possibly empty, possibly with a duplicate).  Delayed
        records surface later through :meth:`due_records`."""
        if not self.active:
            return [svc]
        r = self.round_now()
        # Paused/crashed nodes accept nothing; the paused node's own
        # bridge loop consults its own injector, so this models the
        # stalled process from the inside.
        if self.plan.node_down(self.me, r):
            metrics.incr("chaos.live.droppedRecords")
            return []
        src = self.index.get(svc.hostname)
        if src is None or src == self.me:
            return [svc]
        drop, delay, dup_delay = self._edge_decision(src, r)
        if drop:
            metrics.incr("chaos.live.droppedRecords")
            return []
        out = [svc]
        if dup_delay:
            # Mirror the sim ring: the duplicate re-arrives LATER (an
            # immediate second copy would be a certain LWW no-op) — it
            # is the late copy of a record the catalog may have moved
            # past that exercises the idempotence/staleness path.
            metrics.incr("chaos.live.duplicatedRecords")
            release = time.monotonic() + dup_delay * self.round_s
            with self._lock:
                heapq.heappush(self._delayed,
                               (release, next(self._seq), svc.copy()))
        if delay:
            metrics.incr("chaos.live.delayedRecords")
            release = time.monotonic() + delay * self.round_s
            with self._lock:
                heapq.heappush(self._delayed,
                               (release, next(self._seq), out.pop(0)))
        return out

    def due_records(self) -> list:
        """Delayed records whose release time has passed — the bridge
        loop drains this every cycle."""
        now = time.monotonic()
        out = []
        with self._lock:
            while self._delayed and self._delayed[0][0] <= now:
                out.append(heapq.heappop(self._delayed)[2])
        return out

    def pending_delayed(self) -> int:
        with self._lock:
            return len(self._delayed)

    def accept_push_pull(self) -> bool:
        """False while this node is inside a pause/crash window: the
        stalled process merges nothing, INCLUDING full-state TCP
        push-pull payloads (the bridge's st_poll_state path, which
        bypasses the per-record :meth:`on_recv` shim).  Without this
        gate a 'paused' node would keep absorbing the whole remote
        catalog every anti-entropy interval and converge through the
        pause — the opposite of what the sim twin models."""
        if not self.active:
            return True
        if self.plan.node_down(self.me, self.round_now()):
            metrics.incr("chaos.live.droppedStateMerges")
            return False
        return True

    # -- transport shim: outbound ------------------------------------------

    def filter_send(self, prepared: list) -> list:
        """The outbound boundary: a node inside a pause/crash window
        broadcasts nothing (the process is stalled)."""
        if not self.active:
            return prepared
        if prepared and self.plan.node_down(self.me, self.round_now()):
            metrics.incr("chaos.live.droppedBroadcasts", len(prepared))
            return []
        return prepared

    # -- health shim -------------------------------------------------------

    def check_fault(self, check_id: str) -> tuple[float, bool]:
        """(extra latency seconds, fail?) for a health check right now —
        consumed by health.checks.ChaosChecker."""
        if not self.active:
            return 0.0, False
        return self.plan.health_fault_at(check_id, self.round_now())


class AdversaryInjector:
    """AdversaryPlan → the live catalog machinery.

    The sim corrupts packets between ``select_messages`` and
    ``record_transmissions`` (chaos/sim_inject.py); the live twin
    forges the equivalent catalog pushes.  Per active attacker per
    round, :meth:`CompiledAdversaryPlan.host_overrides`' forged
    ``(slot, packed val)`` columns become :class:`Service` records —
    hostname is the SLOT OWNER's name (the forger writes any hostname
    it likes), while ``gossip_origin`` carries the attacker's transport
    identity, exactly the annotation ``catalog/state.merge`` stamps on
    push-pull records.  Driving these packets through a
    :class:`~sidecar_tpu.ops.suspicion.QuarantineScorer`-gated
    ``ServicesState`` exercises the same defense rung the sim's origin
    gate models; tests/test_adversary.py pins that both planes
    quarantine the same origin set.

    Deterministic and PRNG-free like every chaos shim: one forged
    packet per (round, attacker) is a pure function of the plan.  Tick
    → ns mapping anchors plan tick 0 at ``base_ns`` on the catalog's
    injected clock (``tick_s`` seconds per tick, the sim's 1 ms
    default).
    """

    def __init__(self, plan, node_names: list[str], *,
                 services_per_node: int, budget: int,
                 tick_s: float = 0.001, base_ns: int = 0) -> None:
        import numpy as np

        from sidecar_tpu.chaos.adversary import CompiledAdversaryPlan

        if services_per_node <= 0:
            raise ValueError("services_per_node must be positive")
        self.names = list(node_names)
        n = len(self.names)
        owner = np.arange(n * services_per_node) // services_per_node
        self.compiled = CompiledAdversaryPlan(plan, n=n, owner=owner,
                                              budget=budget)
        self.services_per_node = int(services_per_node)
        self.tick_s = float(tick_s)
        self.base_ns = int(base_ns)

    def ticks_to_ns(self, ticks: int) -> int:
        return self.base_ns + int(round(ticks * self.tick_s * 1e9))

    def _record(self, slot: int, val: int):
        """One forged column → a live Service record.  Status codes are
        numerically identical across planes (service/service.go:17-23 ↔
        ops/status.py), so the packed status carries over unchanged."""
        from sidecar_tpu import service as svc_mod
        from sidecar_tpu.ops import status as svc_status

        hostname = self.names[slot // self.services_per_node]
        ts = int(val) >> svc_status.STATUS_BITS
        stat = int(val) & ((1 << svc_status.STATUS_BITS) - 1)
        return svc_mod.Service(
            id=f"slot{slot}", name=f"svc{slot % self.services_per_node}",
            hostname=hostname, updated=self.ticks_to_ns(ts), status=stat)

    def forged_packets(self, round_idx: int, now_ticks) -> list:
        """The round's forged pushes: ``[(origin_name, [Service, ...])]``
        — one entry per active attacker, one Service per forged column.
        ``now_ticks`` is the per-node stamping clock ``[n]`` in plan
        ticks (apply any ClockFault offsets first, as the sim does)."""
        import numpy as np

        mask, slots, vals = self.compiled.host_overrides(
            round_idx, np.asarray(now_ticks, np.int64))
        out = []
        for i in np.where(mask.any(axis=1))[0]:
            cols = np.where(mask[i])[0]
            out.append((self.names[int(i)],
                        [self._record(int(slots[i, c]), int(vals[i, c]))
                         for c in cols]))
        return out

    def push_into(self, state, round_idx: int, now_ticks) -> int:
        """Deliver the round's forged pushes into a live catalog the way
        the transport's push-pull merge path would: score each packet
        against the attached origin gate (one packet = one push body),
        annotate every record with its transport origin, and hand it to
        the writer.  Returns the number of records enqueued (records
        from already-quarantined origins are rejected by the writer,
        not here — rejection accounting stays in one place)."""
        delivered = 0
        for origin, records in self.forged_packets(round_idx, now_ticks):
            gate = state.origin_gate
            if gate is not None:
                over = gate.observe(
                    origin,
                    [(svc.hostname == origin, svc.updated)
                     for svc in records],
                    state._now())
                if over:
                    metrics.incr("defense.live.originViolations", over)
            for svc in records:
                svc.gossip_origin = origin
                state.add_service_entry(svc)
                delivered += 1
        return delivered


class LiveChaosController:
    """Cluster-side plan application: drives the faults that live
    OUTSIDE a single node's record stream — full partitions (via the
    native engine's receive-side packet drops, so SWIM and push-pull
    are cut too) and node pause isolation.  Call :meth:`tick`
    periodically (e.g. once per gossip interval) from the scenario
    driver, or :meth:`run` on a thread."""

    def __init__(self, plan: FaultPlan, transports: dict,
                 round_s: float) -> None:
        """``transports``: node name → GossipTransport, in PLAN ORDER
        (dict insertion order defines the plan node indices — keep it
        identical to the injectors' ``node_names``)."""
        self.plan = plan
        self.transports = transports
        self.names = list(transports)
        self.round_s = round_s
        self._t0 = time.monotonic()
        self._applied: dict[tuple[str, str], int] = {}
        self._quit = threading.Event()

    def start(self, t0: Optional[float] = None) -> None:
        self._t0 = time.monotonic() if t0 is None else t0

    def round_now(self) -> int:
        return int((time.monotonic() - self._t0) / self.round_s) + 1

    def _full_cut(self, src: int, dst: int, round_idx: int) -> bool:
        for e in self.plan.edges:
            if not e.full_cut:
                continue
            if not (e.start_round <= round_idx < e.end_round):
                continue
            if (e.src == "all" or src in e.src) and \
                    (e.dst == "all" or dst in e.dst):
                return True
        return False

    def tick(self) -> None:
        """Reconcile the native receive-drop masks with the plan at the
        current round.  UDP is cut per DIRECTION (a src→dst cut drops
        every UDP type from src on dst's engine — asymmetric partitions
        stay asymmetric); TCP push-pull is refused when EITHER direction
        is fully cut (a one-way network cut kills TCP both ways), on
        both engines, matching the sim's severing rule.  A node inside a
        pause/crash window is isolated entirely."""
        r = self.round_now()
        for di, dname in enumerate(self.names):
            dt = self.transports[dname]
            for si, sname in enumerate(self.names):
                if si == di:
                    continue
                down = self.plan.node_down(si, r) or \
                    self.plan.node_down(di, r)
                udp_cut = down or self._full_cut(si, di, r)
                pp_cut = down or udp_cut or self._full_cut(di, si, r)
                mask = (DROP_ALL_UDP if udp_cut else 0) | \
                    (DROP_PUSH_PULL if pp_cut else 0)
                key = (sname, dname)
                if self._applied.get(key, 0) != mask:
                    dt.test_drop_types(sname, mask)
                    self._applied[key] = mask
                    if mask:
                        metrics.incr("chaos.live.partitionEdgesCut")

    def run(self, poll_s: Optional[float] = None) -> threading.Thread:
        """Apply the plan continuously on a daemon thread until
        :meth:`stop`."""
        poll = poll_s if poll_s is not None else self.round_s

        def loop() -> None:
            while not self._quit.is_set():
                self.tick()
                self._quit.wait(poll)

        t = threading.Thread(target=loop, name="chaos-controller",
                             daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._quit.set()
