"""Deterministic chaos/fault-injection framework.

One seeded :class:`FaultPlan` (sidecar_tpu/chaos/plan.py) drives BOTH
execution paths:

* the TPU simulator — :class:`ChaosExactSim` (sim_inject.py) threads
  per-edge drop/delay/duplicate schedules, asymmetric partitions, and
  node crash/pause/restart windows through ``lax.scan``;
* the live in-process cluster — :class:`LiveInjector` (live_inject.py)
  shims the ``transport/gossip.py`` send/recv boundary and
  ``health/checks.py``.

See docs/chaos.md for the schema and the reproduce-from-seed workflow.
"""

from sidecar_tpu.chaos.plan import (
    ClockFault,
    EdgeFault,
    FaultPlan,
    HealthFault,
    NodeFault,
    coin,
    resolve_nodes,
)
from sidecar_tpu.chaos.sim_inject import (
    ChaosExactSim,
    ChaosSimState,
    CompiledFaultPlan,
)

__all__ = [
    "ChaosExactSim",
    "ChaosSimState",
    "ClockFault",
    "CompiledFaultPlan",
    "EdgeFault",
    "FaultPlan",
    "HealthFault",
    "NodeFault",
    "coin",
    "resolve_nodes",
]
