"""FaultPlan → TPU simulator: ChaosExactSim.

Generalizes the exact model's single uniform ``drop_prob`` scalar and
static ``cut_mask`` to the full FaultPlan vocabulary, threaded through
``lax.scan``:

* **per-edge packet faults** — each plan edge entry compiles to static
  (src_mask, dst_mask, window) arrays; every round the sampled gossip
  targets are evaluated against them and packets are dropped, delayed,
  or duplicated at PACKET granularity (a lost UDP datagram loses every
  record it carries — unlike the legacy per-record ``drop_prob``, which
  still composes underneath);
* **delay rings** — each entry with ``delay_rounds``/``duplicate_prob``
  owns a ring buffer of depth ``d`` carried through the scan; diverted
  packets are re-resolved at ARRIVAL time (staleness gate, receiver
  liveness, pre-round stickiness), so an in-flight message that went
  stale or whose receiver crashed behaves exactly as it would on a real
  network;
* **asymmetric partitions** — directional ``drop_prob=1.0`` entries.
  TCP push-pull is severed only by a FULL cut in either direction
  (TCP rides retransmission; probabilistic UDP loss doesn't break it),
  evaluated per sampled anti-entropy partner;
* **node windows** — pause (state retained) and crash (belief row
  wiped to a fresh re-announce of its own records at the restart round
  — the cold-rejoin workload).  Down nodes stay in the convergence
  denominator: a paused node's staleness is degradation the metric
  must show, not hide;
* **in-scan observability** — injected drop/delay/duplicate counts
  accumulate in the carried state; :meth:`ChaosExactSim.run` publishes
  the deltas to the process metrics registry
  (``chaos.sim.droppedPackets`` etc.) so fault pressure is never
  silent.

Every fault draw derives from ``fold_in(PRNGKey(plan.seed), round)`` —
independent of the *driver* seed — so the fault schedule is a pure
function of the plan, and two runs of the same plan produce
bit-identical schedules (tests/test_chaos.py pins this).

Round indices are the simulator's ``round_idx`` values: the first
executed round is 1.

An EMPTY plan is bit-identical to plain ExactSim (also pinned) — the
chaos path adds zero semantic drift when no faults are active.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sidecar_tpu import metrics
from sidecar_tpu.chaos.adversary import AdversaryPlan, CompiledAdversaryPlan
from sidecar_tpu.chaos.plan import FaultPlan, resolve_nodes
from sidecar_tpu.models.exact import ExactSim, SimParams, SimState
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops.knobs import _static
from sidecar_tpu.ops.merge import budget_mask, future_mask, staleness_mask
from sidecar_tpu.ops.status import TOMBSTONE, pack, unpack_status, unpack_ts
from sidecar_tpu.ops.topology import Topology


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ChaosSimState:
    """The exact-model state plus the chaos carry (delay rings and
    injection counters), scanned together."""

    sim: SimState
    rings: tuple            # per delay entry: (rows[d,L], cols[d,L], vals[d,L])
    injected_drops: jax.Array    # int32 — fault-dropped non-empty packets
    injected_delays: jax.Array   # int32 — packets diverted to a delay ring
    injected_dups: jax.Array     # int32 — packets copied for re-delivery
    rejected_future: jax.Array   # int32 — record copies the receiver's
                                 # future-admission bound rejected
    forged_records: jax.Array    # int32 — AdversaryPlan-forged columns
    rejected_budget: jax.Array   # int32 — record copies the per-origin
                                 # budget (ops/merge.budget_mask) rejected
    origin_violations: jax.Array  # int32 [N] — per-SENDER cumulative
                                  # budget violations (quarantine evidence)

    # The ExactSim drivers address state through these two names; the
    # properties make a ChaosSimState drop into the inherited scan
    # machinery unchanged.
    @property
    def round_idx(self):
        return self.sim.round_idx

    @property
    def node_alive(self):
        return self.sim.node_alive

    @property
    def known(self):
        return self.sim.known


class CompiledFaultPlan:
    """A FaultPlan resolved against a concrete cluster size: node
    selectors → bool masks, entries split by capability.  All members
    are static w.r.t. jit (masks are device constants); the per-round
    evaluation methods trace cleanly inside ``lax.scan``."""

    def __init__(self, plan: FaultPlan, n: int):
        self.plan = plan
        self.n = n
        self.edge_entries = []      # (src_mask, dst_mask, entry, ring_idx)
        ring_specs = []
        for e in plan.edges:
            src = np.zeros(n, bool)
            src[list(resolve_nodes(e.src, n))] = True
            dst = np.zeros(n, bool)
            dst[list(resolve_nodes(e.dst, n))] = True
            ring_idx = None
            if e.needs_ring:
                ring_idx = len(ring_specs)
                ring_specs.append(e.ring_rounds)
            self.edge_entries.append(
                (jnp.asarray(src), jnp.asarray(dst), e, ring_idx))
        self.ring_specs = tuple(ring_specs)
        self.node_entries = []
        for f in plan.nodes:
            mask = np.zeros(n, bool)
            mask[list(resolve_nodes(f.nodes, n))] = True
            self.node_entries.append((jnp.asarray(mask), f))
        self.has_drop = any(e.drop_prob > 0 for e in plan.edges)
        self.has_full_cut = any(e.full_cut for e in plan.edges)
        self.has_crash = any(f.kind == "crash" for f in plan.nodes)
        self.clock_entries = []
        for f in plan.clocks:
            mask = np.zeros(n, bool)
            mask[list(resolve_nodes(f.nodes, n))] = True
            self.clock_entries.append((jnp.asarray(mask), f))

    # -- per-round fault evaluation (traced) -------------------------------

    def _fault_key(self, round_idx, seed=None):
        """All fault randomness roots here: fault seed + round — NEVER
        the driver's key, so the schedule is a pure function of the
        plan.  ``seed`` overrides ``plan.seed`` (may be TRACED — the
        fleet's per-scenario FaultPlan-seed knob, ops/knobs.py); the
        default compiles the plan's own seed as before."""
        if seed is None:
            seed = self.plan.seed
        return jax.random.fold_in(jax.random.PRNGKey(seed), round_idx)

    @staticmethod
    def _active(e, round_idx):
        return (round_idx >= e.start_round) & (round_idx < e.end_round)

    def edge_masks(self, dst, round_idx, fault_seed=None):
        """Evaluate edge faults against this round's sampled targets.

        Returns (keep, diverts): ``keep`` is bool [N, F] (False = packet
        dropped) or None when the plan has no drop entries; ``diverts``
        is a list of (ring_idx, delay_sel, dup_sel) with bool [N, F]
        masks (either may be None).  Deterministic given (plan, dst,
        round_idx); ``fault_seed`` re-roots the draws for a fleet
        scenario (same schedule when it equals ``plan.seed``)."""
        n, fanout = dst.shape
        kbase = self._fault_key(round_idx, fault_seed)

        drop_p = None
        for src_m, dst_m, e, _ in self.edge_entries:
            if e.drop_prob <= 0.0:
                continue
            m = src_m[:, None] & dst_m[dst] & self._active(e, round_idx)
            p_e = jnp.where(m, jnp.float32(e.drop_prob), jnp.float32(0.0))
            drop_p = p_e if drop_p is None else \
                1.0 - (1.0 - drop_p) * (1.0 - p_e)
        keep = None
        if drop_p is not None:
            keep = ~jax.random.bernoulli(jax.random.fold_in(kbase, 0),
                                         drop_p)

        diverts = []
        for i, (src_m, dst_m, e, ring_idx) in enumerate(self.edge_entries):
            if ring_idx is None:
                continue
            m = src_m[:, None] & dst_m[dst] & self._active(e, round_idx)
            if keep is not None:
                m = m & keep            # a dropped packet can't be diverted
            delay_sel = dup_sel = None
            if e.delay_prob > 0.0:
                delay_sel = jax.random.bernoulli(
                    jax.random.fold_in(kbase, 100 + i), e.delay_prob,
                    (n, fanout)) & m
            if e.duplicate_prob > 0.0:
                dup_sel = jax.random.bernoulli(
                    jax.random.fold_in(kbase, 200 + i), e.duplicate_prob,
                    (n, fanout)) & m
            diverts.append((ring_idx, delay_sel, dup_sel))
        return keep, diverts

    def pp_severed(self, partner, round_idx):
        """bool [N]: anti-entropy with ``partner`` is severed (a FULL
        directional cut in either direction kills the TCP exchange) —
        or None when the plan has no full cuts."""
        if not self.has_full_cut:
            return None
        idx = jnp.arange(self.n, dtype=jnp.int32)
        sev = jnp.zeros((self.n,), bool)
        for src_m, dst_m, e, _ in self.edge_entries:
            if not e.full_cut:
                continue
            act = self._active(e, round_idx)
            sev = sev | (act & ((src_m[idx] & dst_m[partner])
                                | (src_m[partner] & dst_m[idx])))
        return sev

    def down_mask(self, round_idx):
        """bool [N]: node is inside a pause/crash window — or None."""
        if not self.node_entries:
            return None
        down = jnp.zeros((self.n,), bool)
        for mask, f in self.node_entries:
            down = down | (mask & self._active(f, round_idx))
        return down

    def restart_mask(self, round_idx):
        """bool [N]: a crash window closed THIS round (the node restarts
        cold) — or None when the plan has no crash entries."""
        if not self.has_crash:
            return None
        wipe = jnp.zeros((self.n,), bool)
        for mask, f in self.node_entries:
            if f.kind == "crash":
                wipe = wipe | (mask & (round_idx == f.end_round))
        return wipe

    def clock_offsets(self, round_idx):
        """int32 [N]: each node's net clock skew this round (overlapping
        entries add) — or None when the plan has no clock entries, so a
        clock-free plan compiles the global-clock round bit for bit.
        Drift is float32 multiply + floor, matching
        :meth:`ClockFault.offset_at` (the NumPy/oracle twin) tick for
        tick."""
        if not self.clock_entries:
            return None
        off = jnp.zeros((self.n,), jnp.int32)
        for mask, e in self.clock_entries:
            act = self._active(e, round_idx)
            o = jnp.int32(e.offset_ticks)
            if e.drift_ticks_per_round != 0.0:
                o = o + jnp.floor(
                    jnp.float32(e.drift_ticks_per_round)
                    * jnp.asarray(round_idx - e.start_round
                                  ).astype(jnp.float32)
                ).astype(jnp.int32)
            if e.step_ticks:
                o = jnp.where(round_idx >= e.step_round,
                              o + jnp.int32(e.step_ticks), o)
            off = off + jnp.where(mask & act, o, 0)
        return off


class ChaosExactSim(ExactSim):
    """ExactSim under a FaultPlan.  Drivers (``run``/``run_fast``/
    ``step``), checkpoint chunking, and the convergence metric all work
    unchanged on the wrapped state; scenario ``perturb`` hooks receive
    the inner SimState exactly as before (they must not mutate
    ``node_alive`` — fault windows own it for the round)."""

    # The fault-gated round stays dense: its delay rings and packet
    # masks are already bounded structures, and chaos runs are not the
    # convergence-tail regime the sparse path attacks (docs/sparse.md).
    # FaultPlan-driven *node liveness* composes with the sparse path on
    # the plain sims instead (tests/test_sparse.py).
    supports_sparse = False
    # The chaos round interleaves delay rings and adversary forgery
    # between select and fold — the one-round-stale pipelined carry
    # (docs/pipeline.md) has no slot for those structures, so chaos runs
    # stay lockstep.  SIDECAR_TPU_PIPELINE=1 degrades here (auto-OFF
    # contract in ops/pipeline.py); pipeline=True raises.
    supports_pipeline = False

    def __init__(self, params: SimParams, topo: Topology,
                 timecfg: TimeConfig = TimeConfig(),
                 plan: FaultPlan = FaultPlan(seed=0),
                 perturb=None, cut_mask: Optional[np.ndarray] = None,
                 adversary: Optional[AdversaryPlan] = None):
        super().__init__(params, topo, timecfg, perturb=perturb,
                         cut_mask=cut_mask)
        self.plan = plan
        # Re-root the static knob bundle with the plan's fault seed so
        # the knobbed round (ops/knobs.py) reproduces the plan schedule
        # bit for bit; the fleet overrides the seed per scenario.
        self._knobs = dataclasses.replace(self._knobs,
                                          fault_seed=plan.seed)
        self._prog = CompiledFaultPlan(plan, params.n)
        # Byzantine attack programs (chaos/adversary.py): compiled
        # against this cluster's slot-ownership layout; None (the
        # default) compiles the honest round bit for bit.
        self.adversary = adversary
        self._adv = None
        if adversary is not None and adversary.attacks:
            self._adv = CompiledAdversaryPlan(
                adversary, n=params.n, owner=np.asarray(self.owner),
                budget=min(params.budget, params.m))
        # The horizon guard (models/timecfg.validate_horizon) must
        # cover the highest tick any SKEWED stamp can reach, not just
        # the global clock — checked at every driver dispatch.  Forged
        # future stamps count exactly like positive clock skew.
        self._skew_ticks = plan.max_clock_offset + (
            adversary.max_future_ticks if adversary is not None else 0)
        # owner_row[i, m] — slot m belongs to node i (the crash-restart
        # wipe's "keep only my own records" mask).
        self._owner_row = None
        if self._prog.has_crash:
            self._owner_row = (
                self.owner[None, :]
                == jnp.arange(params.n, dtype=jnp.int32)[:, None])

    # -- state construction ------------------------------------------------

    def init_state(self, live_fraction: float = 1.0,
                   seed: int = 0) -> ChaosSimState:
        base = super().init_state(live_fraction, seed)
        p = self.p
        flat = p.n * p.fanout * min(p.budget, p.m)
        rings = tuple(
            (jnp.full((d, flat), p.n, jnp.int32),   # rows: OOB sentinel
             jnp.zeros((d, flat), jnp.int32),
             jnp.zeros((d, flat), jnp.int32))
            for d in self._prog.ring_specs)
        # DISTINCT zero buffers: the run drivers donate the whole
        # state pytree, and XLA rejects donating one buffer twice.
        return ChaosSimState(sim=base, rings=rings,
                             injected_drops=jnp.zeros((), jnp.int32),
                             injected_delays=jnp.zeros((), jnp.int32),
                             injected_dups=jnp.zeros((), jnp.int32),
                             rejected_future=jnp.zeros((), jnp.int32),
                             forged_records=jnp.zeros((), jnp.int32),
                             rejected_budget=jnp.zeros((), jnp.int32),
                             origin_violations=jnp.zeros((p.n,),
                                                         jnp.int32))

    # -- the chaos round ---------------------------------------------------

    def _step(self, cst: ChaosSimState, key: jax.Array,
              kn=None) -> ChaosSimState:
        p, t, prog = self.p, self.t, self._prog
        kn = self._knobs if kn is None else kn
        limit = kn.limit
        state = cst.sim
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)

        # Node fault windows: the BASE liveness is preserved in the
        # carried state (a pause ends and the node is simply back); the
        # faulted mask governs this round's mechanics only.
        base_alive = state.node_alive
        down = prog.down_mask(round_idx)
        alive = base_alive if down is None else base_alive & ~down

        # Per-node clocks (ClockFault): a skewed node STAMPS with its
        # own clock — mint, refresh re-stamp, crash re-announce — while
        # every RECEIVER keeps admitting, anti-entropying, and sweeping
        # by its own.  ``off is None`` (no clock entries) leaves every
        # scalar-``now`` path below untouched, so a clock-free plan
        # compiles the pre-skew round bit for bit.
        off = prog.clock_offsets(round_idx)
        # Epoch floor: a slow clock cannot read before tick 0 — an
        # unclamped negative would mint a sign-corrupted packed key
        # (ts=0 is the unknown sentinel, so a floored mint is simply
        # an empty cell until the clock recovers).
        now_n = None if off is None else jnp.maximum(now + off, 0)  # [N]
        ft = kn.future_arg()
        rej = cst.rejected_future

        # Byzantine defenses (docs/chaos.md "the defense ladder"): the
        # per-origin suspicious-record budget and the origin-quarantine
        # threshold.  Both carry the future-bound contract — a static
        # "off" knob compiles the pre-defense round bit for bit.  The
        # quarantine gate reads the ROUND-START evidence so the NumPy
        # oracle can mirror it without intra-round ordering ambiguity.
        tb = kn.budget_arg()
        qt = kn.quarantine_arg()
        forged = cst.forged_records
        brej = cst.rejected_budget
        viol = cst.origin_violations
        quar = None if qt is None else (viol >= qt)

        # Crash restarts: wipe the row to a cold re-announce of own
        # records the round the window closes.
        wipe = prog.restart_mask(round_idx)
        known, sent = state.known, state.sent
        if wipe is not None:
            st_codes = unpack_status(known)
            cold = jnp.where(
                self._owner_row & (unpack_ts(known) > 0)
                & (st_codes != TOMBSTONE),
                pack(now if off is None else now_n[:, None],
                     st_codes), 0)
            known = jnp.where(wipe[:, None], cold, known)
            sent = jnp.where(wipe[:, None], jnp.int8(0), sent)
        state = dataclasses.replace(state, known=known, sent=sent,
                                    node_alive=alive)

        if self.perturb is not None:
            # Knob-aware hooks (the fleet's per-scenario churn) opt in
            # via ``wants_knobs`` — same dispatch as ExactSim._step.
            if getattr(self.perturb, "wants_knobs", False):
                state = self.perturb(state, k_perturb, now, kn)
            else:
                state = self.perturb(state, k_perturb, now)
        known, sent = state.known, state.sent

        # 1. select + gossip deliveries, fault-gated.
        dst = gossip_ops.sample_peers(
            k_peers, p.n, p.fanout, nbrs=self._nbrs, deg=self._deg,
            node_alive=alive, cut_mask=self._cut)
        svc_idx, msg = gossip_ops.select_messages(known, sent, p.budget,
                                                  limit)
        # Adversary corruption lands between selection and transmit
        # accounting: attackers REPLACE the leading columns of their
        # own packets with forged records (chaos/adversary.py), lying
        # relative to their OWN — possibly skewed — clocks, and their
        # transmit counters pay for the forged sends.
        if self._adv is not None:
            adv_now = (jnp.broadcast_to(jnp.asarray(now, jnp.int32),
                                        (p.n,))
                       if off is None else now_n)
            svc_idx, msg, nforged = self._adv.corrupt(
                round_idx, adv_now, svc_idx, msg)
            forged = forged + nforged
        sent = gossip_ops.record_transmissions(sent, svc_idx, msg,
                                               p.fanout, limit)

        keep, diverts = prog.edge_masks(dst, round_idx,
                                        fault_seed=kn.fault_seed)
        n, fanout = dst.shape
        budget = svc_idx.shape[1]
        nonempty = jnp.broadcast_to(jnp.any(msg > 0, axis=1)[:, None],
                                    (n, fanout))

        def count(mask):
            return jnp.sum((mask & nonempty).astype(jnp.int32))

        drops = cst.injected_drops
        if keep is not None:
            drops = drops + count(~keep)

        # Raw triples: every gate applied (incl. fault drops), stickiness
        # deferred to arrival.  The uniform-loss keep mask is drawn
        # here (same key/prob/shape as the in-call draw — bit-identical)
        # so a traced per-scenario keep_prob works; static keep_prob 1
        # compiles no draw, as before.
        record_keep = None
        if kn.needs_drop_draw:
            record_keep = jax.random.bernoulli(
                k_drop, kn.keep_prob, (n, fanout, budget))
        recv_now = now if off is None else now_n[dst][:, :, None]
        if ft is not None:
            # Count the wire copies the receiver-side bound rejects —
            # tallied on the raw candidate set, before the unrelated
            # loss/liveness gates, because that is what the bound sees.
            cand = jnp.broadcast_to(msg[:, None, :], (n, fanout, budget))
            rej = rej + jnp.sum(
                (future_mask(cand, recv_now, ft)
                 & (cand > 0)).astype(jnp.int32))
        own_sel = None
        if tb is not None:
            # First-party exemption mask + budget accounting, tallied
            # per SENDER on the raw candidate set (the rejected-future
            # precedent above): exactly what the in-kernel budget gate
            # sees after its staleness/future predecessors, before the
            # unrelated loss/liveness gates.
            own_sel = (self.owner[jnp.minimum(svc_idx, p.m - 1)]
                       == jnp.arange(p.n, dtype=jnp.int32)[:, None])
            own3 = own_sel[:, None, :]
            bcand = jnp.broadcast_to(msg[:, None, :], (n, fanout, budget))
            bcand = jnp.where(
                staleness_mask(bcand, recv_now, kn.stale_ticks), 0, bcand)
            # Quarantine EVIDENCE is narrower than the budget gate: a
            # FRESH third-party claim — a record for a slot the sender
            # doesn't own, stamped at-or-ahead of the receiver's clock.
            # An honest relayer cannot produce one (anything it relays
            # was admitted at least a round earlier, so its stamp
            # trails the receiver clock by ≥ round_ticks), while every
            # first-hop forgery of the bomb/flood/sybil kinds is one —
            # so honest nodes relaying admitted poison never accrue
            # evidence (the smoking-gun rule; the caveat is honest
            # clock skew beyond one round_ticks, where the future
            # bound, not quarantine, is the intended defense —
            # docs/chaos.md).  Counted BEFORE the future gate — a
            # beyond-fudge flood is the most damning evidence of all —
            # with beyond-budget fresh claims charged, per packet copy,
            # to the sending origin.
            bts = unpack_ts(bcand)
            fresh = ((bts > 0) & ~own3
                     & (bts >= jnp.asarray(recv_now, jnp.int32)))
            erank = jnp.cumsum(fresh.astype(jnp.int32), axis=-1)
            ev = fresh & (erank > jnp.asarray(tb, jnp.int32))
            viol = viol + jnp.sum(ev.astype(jnp.int32), axis=(1, 2))
            if ft is not None:
                bcand = jnp.where(future_mask(bcand, recv_now, ft),
                                  0, bcand)
            bm = budget_mask(bcand, recv_now, tb, own3)
            brej = brej + jnp.sum(bm.astype(jnp.int32))
        # Quarantined origins lose their send channel outright (the
        # packet-level fault-drop mechanism, reused as a defense).
        ekeep = keep
        if quar is not None:
            qkeep = ~quar[:, None]
            ekeep = qkeep if ekeep is None else ekeep & qkeep
        rows, cols, vals = gossip_ops.expand_deliveries(
            dst, svc_idx, msg, now_tick=recv_now,
            stale_ticks=kn.stale_ticks,
            node_alive=alive, record_keep=record_keep,
            edge_keep=ekeep, future_ticks=ft,
            tomb_budget=tb, sender_own=own_sel)

        def flat(mask):
            return jnp.broadcast_to(mask[:, :, None],
                                    (n, fanout, budget)).reshape(-1)

        delays, dups = cst.injected_delays, cst.injected_dups
        delay_any = None
        for _, delay_sel, dup_sel in diverts:
            if delay_sel is not None:
                delays = delays + count(delay_sel)
                delay_any = delay_sel if delay_any is None else \
                    delay_any | delay_sel
            if dup_sel is not None:
                dups = dups + count(dup_sel)
        vals_imm = vals if delay_any is None else \
            jnp.where(flat(delay_any), 0, vals)

        # Delay rings: pop the batch that matured (written ring-depth
        # rounds ago lands in this round's slot), push this round's
        # diverted packets into the freed slot.
        new_rings = list(cst.rings)
        all_rows, all_cols, all_vals = [rows], [cols], [vals_imm]
        for ring_idx, delay_sel, dup_sel in diverts:
            divert = delay_sel if dup_sel is None else (
                dup_sel if delay_sel is None else delay_sel | dup_sel)
            r_rows, r_cols, r_vals = new_rings[ring_idx]
            depth = r_rows.shape[0]
            slot = round_idx % depth
            m_rows, m_cols, m_vals = r_rows[slot], r_cols[slot], r_vals[slot]
            # Re-resolve the matured batch at ARRIVAL: staleness and
            # receiver liveness are re-evaluated against *now* (the
            # pre-round stickiness resolution happens with the combined
            # batch below).
            m_idx = jnp.minimum(m_rows, p.n - 1)
            m_now = now if off is None else now_n[m_idx]
            m_vals = jnp.where(staleness_mask(m_vals, m_now,
                                              kn.stale_ticks),
                               0, m_vals)
            if ft is not None:
                fm = future_mask(m_vals, m_now, ft)
                rej = rej + jnp.sum(
                    (fm & (m_vals > 0)).astype(jnp.int32))
                m_vals = jnp.where(fm, 0, m_vals)
            ok = (m_rows < p.n) & alive[m_idx]
            m_vals = jnp.where(ok, m_vals, 0)
            all_rows.append(m_rows)
            all_cols.append(m_cols)
            all_vals.append(m_vals)
            fm = flat(divert)
            new_rings[ring_idx] = (
                r_rows.at[slot].set(jnp.where(fm, rows, p.n)),
                r_cols.at[slot].set(cols),
                r_vals.at[slot].set(jnp.where(fm, vals, 0)))

        if len(all_rows) > 1:
            rows = jnp.concatenate(all_rows)
            cols = jnp.concatenate(all_cols)
            vals = jnp.concatenate(all_vals)
        else:
            vals = vals_imm
        d_vals, d_adv = gossip_ops.finalize_deliveries(known, rows, cols,
                                                       vals)

        # 2. announce re-stamps, folded into the same scatter.
        a_rows, a_cols, a_vals, a_due = self._announce_updates(
            known, alive, round_idx,
            now if off is None else now_n[self.owner], kn=kn)
        rows = jnp.concatenate([rows, a_rows])
        cols = jnp.concatenate([cols, a_cols])
        vals = jnp.concatenate([d_vals, a_vals])
        advanced = jnp.concatenate([d_adv, a_due])
        known, sent = gossip_ops.apply_updates(known, sent, rows, cols,
                                               vals, advanced)

        # 3. anti-entropy — severed where the plan fully cuts the pair.
        pp_partner = gossip_ops.sample_peers(
            k_pp, p.n, 1, nbrs=self._nbrs, deg=self._deg,
            node_alive=alive, cut_mask=self._cut)[:, 0]
        sever = prog.pp_severed(pp_partner, round_idx)
        if sever is not None:
            pp_partner = jnp.where(
                sever, jnp.arange(p.n, dtype=jnp.int32), pp_partner)
        if quar is not None:
            # A quarantined origin neither pushes nor is pulled from:
            # any exchange touching one remaps to the self no-op.
            pp_partner = jnp.where(
                quar | quar[pp_partner],
                jnp.arange(p.n, dtype=jnp.int32), pp_partner)

        # Each push-pull leg admits at the RECEIVER's clock: the pull
        # leg lands on me (my clock), the push leg lands on my partner
        # (theirs).  Self-exchanges (severed/remapped partners) are
        # merge no-ops under any clock, so pre-remap indexing is safe.
        pp_now = now if off is None else now_n[:, None]
        pp_push = None if off is None else now_n[pp_partner][:, None]

        pp_owner = self.owner if tb is not None else None
        if ft is None:
            def do_push_pull(kn_se):
                kn_, se = kn_se
                merged = gossip_ops.push_pull(
                    kn_, pp_partner, now_tick=pp_now,
                    stale_ticks=kn.stale_ticks, node_alive=alive,
                    now_push=pp_push, tomb_budget=tb, owner=pp_owner)
                se = jnp.where(merged != kn_, jnp.int8(0), se)
                return merged, se

            known, sent = lax.cond(
                round_idx % kn.push_pull_rounds == 0,
                do_push_pull, lambda kn_se: kn_se, (known, sent))
        else:
            def do_push_pull(kn_se):
                kn_, se = kn_se
                merged = gossip_ops.push_pull(
                    kn_, pp_partner, now_tick=pp_now,
                    stale_ticks=kn.stale_ticks, node_alive=alive,
                    future_ticks=ft, now_push=pp_push,
                    tomb_budget=tb, owner=pp_owner)
                se = jnp.where(merged != kn_, jnp.int8(0), se)
                pulled = kn_[pp_partner]
                r = jnp.sum((future_mask(pulled, pp_now, ft)
                             & (pulled > 0)).astype(jnp.int32))
                push_now = pp_now if pp_push is None else pp_push
                r = r + jnp.sum((future_mask(kn_, push_now, ft)
                                 & (kn_ > 0)).astype(jnp.int32))
                return merged, se, r

            known, sent, pp_rej = lax.cond(
                round_idx % kn.push_pull_rounds == 0,
                do_push_pull,
                lambda kn_se: (kn_se[0], kn_se[1],
                               jnp.zeros((), jnp.int32)),
                (known, sent))
            rej = rej + pp_rej

        # 4. lifespan sweep.
        def do_sweep(kn_se):
            from sidecar_tpu.ops.ttl import ttl_sweep
            kn_, se = kn_se
            swept, _ = ttl_sweep(
                kn_, now if off is None else now_n[:, None],
                alive_lifespan=kn.alive_lifespan,
                draining_lifespan=kn.draining_lifespan,
                tombstone_lifespan=kn.tombstone_lifespan,
                one_second=t.one_second,
                suspicion_window=kn.suspicion_window)
            se = jnp.where(swept != kn_, jnp.int8(0), se)
            return swept, se

        known, sent = lax.cond(
            round_idx % kn.sweep_rounds == 0,
            do_sweep, lambda kn_se: kn_se, (known, sent))

        return ChaosSimState(
            sim=SimState(known=known, sent=sent, node_alive=base_alive,
                         round_idx=round_idx),
            rings=tuple(new_rings), injected_drops=drops,
            injected_delays=delays, injected_dups=dups,
            rejected_future=rej, forged_records=forged,
            rejected_budget=brej, origin_violations=viol)

    # -- provenance hooks (ops/provenance.py) ------------------------------

    def _prov_belief(self, cst: ChaosSimState,
                     tracked: jax.Array) -> jax.Array:
        return cst.sim.known[:, tracked]

    def _prov_channels(self, cst: ChaosSimState, key: jax.Array,
                       kn=None):
        """The chaos round's OPEN channels: gossip pushes surviving the
        plan's edge drops minus the delayed edges (a delayed packet is
        not delivered this round — its eventual ring maturity arrives
        with no live channel and surfaces as ``PARENT_UNATTRIBUTED``),
        plus the push-pull edge where the plan hasn't severed it.
        Node-fault windows gate sampling exactly as the step does
        (faulted senders self-remap); the perturb hook is NOT re-run —
        the chaos contract forbids it from touching ``node_alive``."""
        p, prog = self.p, self._prog
        kn = self._knobs if kn is None else kn
        state = cst.sim
        round_idx = state.round_idx + 1
        _k_perturb, k_peers, _k_drop, k_pp = jax.random.split(key, 4)

        down = prog.down_mask(round_idx)
        alive = state.node_alive if down is None else \
            state.node_alive & ~down

        dst = gossip_ops.sample_peers(
            k_peers, p.n, p.fanout, nbrs=self._nbrs, deg=self._deg,
            node_alive=alive, cut_mask=self._cut)
        keep, diverts = prog.edge_masks(dst, round_idx,
                                        fault_seed=kn.fault_seed)
        delay_any = None
        for _, delay_sel, _dup_sel in diverts:
            if delay_sel is not None:
                delay_any = delay_sel if delay_any is None else \
                    delay_any | delay_sel
        push_mask = keep
        if delay_any is not None:
            push_mask = ~delay_any if push_mask is None else \
                push_mask & ~delay_any

        pp_partner = gossip_ops.sample_peers(
            k_pp, p.n, 1, nbrs=self._nbrs, deg=self._deg,
            node_alive=alive, cut_mask=self._cut)[:, 0]
        sever = prog.pp_severed(pp_partner, round_idx)
        if sever is not None:
            pp_partner = jnp.where(
                sever, jnp.arange(p.n, dtype=jnp.int32), pp_partner)
        partner = pp_partner[:, None]
        pp_on = jnp.broadcast_to(round_idx % kn.push_pull_rounds == 0,
                                 (p.n, 1))
        pushes = [(dst, push_mask), (partner, pp_on)]
        pulls = [(partner, pp_on)]
        return pushes, pulls

    # -- metric + drivers --------------------------------------------------

    def convergence(self, cst: ChaosSimState) -> jax.Array:
        return super().convergence(cst.sim)

    def _trace_record(self, prev: ChaosSimState, nxt: ChaosSimState,
                      stats):
        """Flight-recorder record off the wrapped SimStates — the chaos
        state carries rings/counters the extractor has no columns for,
        so the record summarizes the protocol state exactly like
        ExactSim's (this is what makes ``run_with_trace`` — and with it
        the false-positive-tombstone robustness measurement,
        benchmarks/robustness.py — work under a FaultPlan)."""
        from sidecar_tpu.ops import trace as trace_ops

        return trace_ops.exact_record(
            prev.sim, nxt.sim, budget=min(self.p.budget, self.p.m),
            fanout=self.p.fanout,
            limit=self.p.resolved_retransmit_limit(), stats=stats,
            rejected_future=nxt.rejected_future - prev.rejected_future,
            tick_period=self._knobs.tick_period,
            tick_phase=self._knobs.tick_phase)

    def injection_counts(self, cst: ChaosSimState) -> dict:
        return {"dropped": int(cst.injected_drops),
                "delayed": int(cst.injected_delays),
                "duplicated": int(cst.injected_dups),
                "rejected_future": int(cst.rejected_future),
                "forged": int(cst.forged_records),
                "rejected_budget": int(cst.rejected_budget),
                "quarantined": len(self.quarantined_origins(cst))}

    def quarantined_origins(self, cst: ChaosSimState) -> tuple:
        """Node ids whose cumulative budget violations crossed the
        quarantine threshold — the sim side of the sim↔live
        cross-validation (tests/test_adversary.py).  Empty when the
        threshold knob is off or traced (the fleet reads the stacked
        counters itself)."""
        qt = self._knobs.quarantine_threshold
        if not _static(qt) or qt < 0:
            return ()
        viol = np.asarray(cst.origin_violations)
        return tuple(int(i) for i in np.where(viol >= qt)[0])

    @staticmethod
    def _counter_snapshot(cst: ChaosSimState) -> dict:
        out = {f: int(getattr(cst, f))
               for f in ("injected_drops", "injected_delays",
                         "injected_dups", "rejected_future",
                         "forged_records", "rejected_budget")}
        out["origin_violations"] = int(np.sum(
            np.asarray(cst.origin_violations)))
        return out

    def _publish_injection_metrics(self, before: dict,
                                   after: ChaosSimState) -> None:
        """Fault pressure must be observable, not silent: push the run's
        injection deltas into the process metrics registry."""
        for name, field in (("chaos.sim.droppedPackets", "injected_drops"),
                            ("chaos.sim.delayedPackets", "injected_delays"),
                            ("chaos.sim.duplicatedPackets",
                             "injected_dups"),
                            ("clock.sim.rejectedFuture",
                             "rejected_future"),
                            ("adversary.sim.forgedRecords",
                             "forged_records"),
                            ("defense.sim.rejectedBudget",
                             "rejected_budget")):
            delta = int(getattr(after, field)) - before[field]
            if delta:
                metrics.incr(name, delta)
        vdelta = int(np.sum(np.asarray(after.origin_violations))) \
            - before["origin_violations"]
        if vdelta:
            metrics.incr("defense.sim.originViolations", vdelta)
        quarantined = len(self.quarantined_origins(after))
        if quarantined:
            metrics.incr("defense.sim.quarantinedOrigins", quarantined)

    def run(self, state, key, num_rounds: int, donate: bool = True,
            start_round=None, sparse=None, pipeline=None):
        # Snapshot the injection counters BEFORE dispatch: the donating
        # run deletes the input state's buffers (models/exact.py).
        # (The snapshot reads device scalars, so a chaos sim pays one
        # sync per chunk even when start_round is supplied.)
        before = self._counter_snapshot(state)
        final, conv = super().run(state, key, num_rounds, donate=donate,
                                  start_round=start_round, sparse=sparse,
                                  pipeline=pipeline)
        self._publish_injection_metrics(before, final)
        return final, conv

    def run_fast(self, state, key, num_rounds: int, donate: bool = True,
                 sparse=None, pipeline=None):
        before = self._counter_snapshot(state)
        final = super().run_fast(state, key, num_rounds, donate=donate,
                                 sparse=sparse, pipeline=pipeline)
        self._publish_injection_metrics(before, final)
        return final

    def run_with_trace(self, state, key, num_rounds: int, cap: int = 0,
                       donate: bool = True, start_round=None,
                       sparse=None):
        before = self._counter_snapshot(state)
        final, tr, conv = super().run_with_trace(
            state, key, num_rounds, cap=cap, donate=donate,
            start_round=start_round, sparse=sparse)
        self._publish_injection_metrics(before, final)
        return final, tr, conv

    def run_with_digest(self, state, key, num_rounds: int, cap: int = 0,
                        buckets: int = 64, idents=None,
                        donate: bool = True, start_round=None,
                        sparse=None):
        before = self._counter_snapshot(state)
        final, dt, conv = super().run_with_digest(
            state, key, num_rounds, cap=cap, buckets=buckets,
            idents=idents, donate=donate, start_round=start_round,
            sparse=sparse)
        self._publish_injection_metrics(before, final)
        return final, dt, conv

    def run_with_provenance(self, state, key, num_rounds: int, tracked,
                            cap: int = 0, prov=None, donate: bool = True,
                            start_round=None, sparse=None):
        before = self._counter_snapshot(state)
        final, pv, conv = super().run_with_provenance(
            state, key, num_rounds, tracked, cap=cap, prov=prov,
            donate=donate, start_round=start_round, sparse=sparse)
        self._publish_injection_metrics(before, final)
        return final, pv, conv
