"""Layered configuration: env vars with defaults, overridden by CLI
flags (reference: config/config.go:11-110 ← cli.go:25-41, wired in
main.go:44-60; effective config printed at boot à la rubberneck,
main.go:305-306).

Env prefixes match the reference exactly (SIDECAR_, DOCKER_, STATIC_,
K8S_, SERVICES_, HAPROXY_, ENVOY_, LISTENERS_) so existing deployments
carry over unchanged.  Durations accept Go syntax ("200ms", "20s",
"1m"), lists are comma-separated."""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Optional

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(h|ms|us|µs|ns|m|s)")
_UNITS = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6,
          "µs": 1e-6, "ns": 1e-9}


def parse_duration(text: str) -> float:
    """Go duration string → seconds."""
    text = text.strip()
    if not text:
        return 0.0
    try:
        return float(text)  # bare number = seconds
    except ValueError:
        pass
    total = 0.0
    pos = 0
    for match in _DURATION_RE.finditer(text):
        if match.start() != pos:
            raise ValueError(f"invalid duration: {text!r}")
        total += float(match.group(1)) * _UNITS[match.group(2)]
        pos = match.end()
    if pos != len(text):
        raise ValueError(f"invalid duration: {text!r}")
    return total


def _env(prefix: str, name: str, default, cast=None):
    raw = os.environ.get(f"{prefix}_{name}")
    if raw is None:
        return default
    if cast is not None:
        return cast(raw)
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return parse_duration(raw)
    if isinstance(default, list):
        return [s for s in raw.split(",") if s]
    return raw


@dataclasses.dataclass
class ListenerUrlsConfig:
    """LISTENERS_ (config.go:11-13)."""

    urls: list[str] = dataclasses.field(default_factory=list)

    @classmethod
    def from_env(cls) -> "ListenerUrlsConfig":
        return cls(urls=_env("LISTENERS", "URLS", []))


@dataclasses.dataclass
class HAproxyConfig:
    """HAPROXY_ (config.go:15-26)."""

    reload_cmd: str = ""
    verify_cmd: str = ""
    bind_ip: str = "192.168.168.168"
    template_file: str = "views/haproxy.cfg"
    config_file: str = "/etc/haproxy.cfg"
    pid_file: str = "/var/run/haproxy.pid"
    disable: bool = False
    user: str = "haproxy"
    group: str = ""
    use_hostnames: bool = False

    @classmethod
    def from_env(cls) -> "HAproxyConfig":
        d = cls()
        return cls(
            reload_cmd=_env("HAPROXY", "RELOAD_COMMAND", d.reload_cmd),
            verify_cmd=_env("HAPROXY", "VERIFY_COMMAND", d.verify_cmd),
            bind_ip=_env("HAPROXY", "BIND_IP", d.bind_ip),
            template_file=_env("HAPROXY", "TEMPLATE_FILE", d.template_file),
            config_file=_env("HAPROXY", "CONFIG_FILE", d.config_file),
            pid_file=_env("HAPROXY", "PID_FILE", d.pid_file),
            disable=_env("HAPROXY", "DISABLE", d.disable),
            user=_env("HAPROXY", "USER", d.user),
            group=_env("HAPROXY", "GROUP", d.group),
            use_hostnames=_env("HAPROXY", "USE_HOSTNAMES", d.use_hostnames),
        )


@dataclasses.dataclass
class EnvoyConfig:
    """ENVOY_ (config.go:28-33)."""

    use_grpc_api: bool = True
    bind_ip: str = "192.168.168.168"
    use_hostnames: bool = False
    grpc_port: str = "7776"

    @classmethod
    def from_env(cls) -> "EnvoyConfig":
        d = cls()
        return cls(
            use_grpc_api=_env("ENVOY", "USE_GRPC_API", d.use_grpc_api),
            bind_ip=_env("ENVOY", "BIND_IP", d.bind_ip),
            use_hostnames=_env("ENVOY", "USE_HOSTNAMES", d.use_hostnames),
            grpc_port=_env("ENVOY", "GRPC_PORT", d.grpc_port),
        )


@dataclasses.dataclass
class ServicesConfig:
    """SERVICES_ (config.go:35-39)."""

    name_match: str = ""
    service_namer: str = "docker_label"
    name_label: str = "ServiceName"

    @classmethod
    def from_env(cls) -> "ServicesConfig":
        d = cls()
        return cls(
            name_match=_env("SERVICES", "NAME_MATCH", d.name_match),
            service_namer=_env("SERVICES", "NAMER", d.service_namer),
            name_label=_env("SERVICES", "NAME_LABEL", d.name_label),
        )


# Spelled out in full for the docs/env.md catalog scanner
# (tools/check_env_docs.py named-constant form): the knob deliberately
# lives in the simulator's SIDECAR_TPU_* namespace — it is the live
# twin of the sim's ops/merge.future_mask bound
# (TimeConfig.future_fudge_s) and one value should drive both planes.
FUTURE_FUDGE_ENV = "SIDECAR_TPU_FUTURE_FUDGE"

# Defense-ladder knobs (ops/merge.budget_mask + ops/suspicion.
# QuarantineScorer, docs/chaos.md): same SIDECAR_TPU_* convention and
# for the same reason — the live twins of TimeConfig.origin_budget /
# origin_quarantine, one value driving both planes.
ORIGIN_BUDGET_ENV = "SIDECAR_TPU_ORIGIN_BUDGET"
ORIGIN_QUARANTINE_ENV = "SIDECAR_TPU_ORIGIN_QUARANTINE"


@dataclasses.dataclass
class SidecarConfig:
    """SIDECAR_ (config.go:41-59)."""

    exclude_ips: list[str] = dataclasses.field(
        default_factory=lambda: ["192.168.168.168"])
    discovery: list[str] = dataclasses.field(
        default_factory=lambda: ["docker"])
    stats_addr: str = ""
    push_pull_interval: float = 20.0
    gossip_messages: int = 15
    gossip_interval: float = 0.2
    handoff_queue_depth: int = 1024
    logging_format: str = ""
    logging_level: str = "info"
    default_check_endpoint: str = "/version"
    seeds: list[str] = dataclasses.field(default_factory=list)
    cluster_name: str = "default"
    advertise_ip: str = ""
    bind_port: int = 7946
    debug: bool = False
    discovery_sleep_interval: float = 1.0
    # Suspicion & flap damping (ops/suspicion.py, catalog/damping.py,
    # docs/chaos.md): one knob bundle shared with the simulator so a
    # `POST /simulate` what-if runs the settings the live node uses.
    suspicion_window: float = 0.0     # SWIM quarantine window (0 = off)
    damping_half_life: float = 60.0   # flap-penalty decay half-life
    damping_threshold: float = 0.0    # suppress at penalty >= (0 = off)
    # Future-admission bound (ops/merge.future_mask, docs/chaos.md):
    # reject records stamped beyond now + this many seconds at every
    # merge/catalog-add site.  Negative (default) disables the gate.
    future_fudge: float = -1.0
    # Defense ladder (ops/merge.budget_mask, ops/suspicion.
    # QuarantineScorer): per-packet cap on third-party suspicious
    # records, and the violation count that quarantines an origin.
    # Negative (default) leaves each rung off.
    origin_budget: int = -1
    origin_quarantine: int = -1

    @classmethod
    def from_env(cls) -> "SidecarConfig":
        d = cls()
        return cls(
            exclude_ips=_env("SIDECAR", "EXCLUDE_IPS", d.exclude_ips),
            discovery=_env("SIDECAR", "DISCOVERY", d.discovery),
            stats_addr=_env("SIDECAR", "STATS_ADDR", d.stats_addr),
            push_pull_interval=_env("SIDECAR", "PUSH_PULL_INTERVAL",
                                    d.push_pull_interval),
            gossip_messages=_env("SIDECAR", "GOSSIP_MESSAGES",
                                 d.gossip_messages),
            gossip_interval=_env("SIDECAR", "GOSSIP_INTERVAL",
                                 d.gossip_interval),
            handoff_queue_depth=_env("SIDECAR", "HANDOFF_QUEUE_DEPTH",
                                     d.handoff_queue_depth),
            logging_format=_env("SIDECAR", "LOGGING_FORMAT",
                                d.logging_format),
            logging_level=_env("SIDECAR", "LOGGING_LEVEL", d.logging_level),
            default_check_endpoint=_env("SIDECAR", "DEFAULT_CHECK_ENDPOINT",
                                        d.default_check_endpoint),
            seeds=_env("SIDECAR", "SEEDS", d.seeds),
            cluster_name=_env("SIDECAR", "CLUSTER_NAME", d.cluster_name),
            advertise_ip=_env("SIDECAR", "ADVERTISE_IP", d.advertise_ip),
            bind_port=_env("SIDECAR", "BIND_PORT", d.bind_port),
            debug=_env("SIDECAR", "DEBUG", d.debug),
            discovery_sleep_interval=_env(
                "SIDECAR", "DISCOVERY_SLEEP_INTERVAL",
                d.discovery_sleep_interval),
            suspicion_window=_env("SIDECAR", "SUSPICION_WINDOW",
                                  d.suspicion_window),
            damping_half_life=_env("SIDECAR", "DAMPING_HALF_LIFE",
                                   d.damping_half_life),
            damping_threshold=_env("SIDECAR", "DAMPING_THRESHOLD",
                                   d.damping_threshold, cast=float),
            future_fudge=_env(*FUTURE_FUDGE_ENV.split("_", 1),
                              d.future_fudge),
            origin_budget=_env(*ORIGIN_BUDGET_ENV.split("_", 1),
                               d.origin_budget, cast=int),
            origin_quarantine=_env(*ORIGIN_QUARANTINE_ENV.split("_", 1),
                                   d.origin_quarantine, cast=int),
        )


@dataclasses.dataclass
class DockerConfig:
    """DOCKER_ (config.go:61-63)."""

    docker_url: str = "unix:///var/run/docker.sock"

    @classmethod
    def from_env(cls) -> "DockerConfig":
        return cls(docker_url=_env("DOCKER", "URL", cls().docker_url))


@dataclasses.dataclass
class StaticConfig:
    """STATIC_ (config.go:65-67)."""

    config_file: str = "static.json"

    @classmethod
    def from_env(cls) -> "StaticConfig":
        return cls(config_file=_env("STATIC", "CONFIG_FILE",
                                    cls().config_file))


@dataclasses.dataclass
class K8sAPIConfig:
    """K8S_ (config.go:69-76)."""

    kube_api_ip: str = "127.0.0.1"
    kube_api_port: int = 8080
    namespace: str = "default"
    kube_timeout: float = 3.0
    creds_path: str = "/var/run/secrets/kubernetes.io/serviceaccount"
    announce_all_nodes: bool = False

    @classmethod
    def from_env(cls) -> "K8sAPIConfig":
        d = cls()
        return cls(
            kube_api_ip=_env("K8S", "KUBE_API_IP", d.kube_api_ip),
            kube_api_port=_env("K8S", "KUBE_API_PORT", d.kube_api_port),
            namespace=_env("K8S", "NAMESPACE", d.namespace),
            kube_timeout=_env("K8S", "KUBE_TIMEOUT", d.kube_timeout),
            creds_path=_env("K8S", "CREDS_PATH", d.creds_path),
            announce_all_nodes=_env("K8S", "ANNOUNCE_ALL_NODES",
                                    d.announce_all_nodes),
        )


@dataclasses.dataclass
class Config:
    """config.go:78-87."""

    sidecar: SidecarConfig
    docker_discovery: DockerConfig
    static_discovery: StaticConfig
    k8s_api_discovery: K8sAPIConfig
    services: ServicesConfig
    haproxy: HAproxyConfig
    envoy: EnvoyConfig
    listeners: ListenerUrlsConfig


def parse_config() -> Config:
    """config.go:88-110."""
    return Config(
        sidecar=SidecarConfig.from_env(),
        docker_discovery=DockerConfig.from_env(),
        static_discovery=StaticConfig.from_env(),
        k8s_api_discovery=K8sAPIConfig.from_env(),
        services=ServicesConfig.from_env(),
        haproxy=HAproxyConfig.from_env(),
        envoy=EnvoyConfig.from_env(),
        listeners=ListenerUrlsConfig.from_env(),
    )


def format_config(config: Config) -> str:
    """Effective-config dump at boot (rubberneck, main.go:305-306)."""
    lines = ["Settings -----------------------------------------"]
    for field in dataclasses.fields(config):
        section = getattr(config, field.name)
        lines.append(f"  * {field.name}:")
        for sub in dataclasses.fields(section):
            lines.append(f"      {sub.name}: {getattr(section, sub.name)}")
    lines.append("--------------------------------------------------")
    return "\n".join(lines)
