"""Docker container discovery
(reference: discovery/docker_discovery.go:16-404).

Polls the container list every second, subscribes to the Docker event
stream ("die"/"stop" delete services immediately), names services with a
pluggable ServiceNamer, and keeps an inspect-result cache with periodic
drain + prune.  The Docker daemon is reached through a ``DockerClient``
protocol; the default implementation is a dependency-free stdlib HTTP
client speaking the Docker Engine API over a Unix socket or TCP
(the reference uses go-dockerclient; the five-method interface it
isolates for testing — docker_discovery.go:20-26 — is preserved here).
"""

from __future__ import annotations

import http.client
import json
import logging
import queue
import socket
import threading
import time
from typing import Callable, Optional

from sidecar_tpu.discovery.base import (
    ChangeListener,
    DEFAULT_SLEEP_INTERVAL,
    Discoverer,
)
from sidecar_tpu.discovery.namer import ServiceNamer
from sidecar_tpu.runtime.looper import Looper
from sidecar_tpu.service import Service, to_service

log = logging.getLogger(__name__)

CACHE_DRAIN_INTERVAL = 600.0  # docker_discovery.go:17


class DockerClient:
    """The five-method client interface (docker_discovery.go:20-26)."""

    def inspect_container(self, container_id: str) -> dict:
        raise NotImplementedError

    def list_containers(self, all: bool = False) -> list[dict]:
        raise NotImplementedError

    def add_event_listener(self, listener: "queue.Queue") -> None:
        raise NotImplementedError

    def remove_event_listener(self, listener: "queue.Queue") -> None:
        raise NotImplementedError

    def ping(self) -> None:
        """Raises on failure."""
        raise NotImplementedError


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: float = 10.0) -> None:
        super().__init__("localhost", timeout=timeout)
        self._path = path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._path)
        self.sock = sock


class EngineAPIClient(DockerClient):
    """Minimal Docker Engine API client (stdlib only).

    ``endpoint`` accepts ``unix:///var/run/docker.sock`` or
    ``tcp://host:port``; empty uses the conventional Unix socket.
    """

    def __init__(self, endpoint: str = "") -> None:
        self.endpoint = endpoint or "unix:///var/run/docker.sock"
        self._event_threads: dict[int, threading.Event] = {}

    def _conn(self, timeout: float = 10.0) -> http.client.HTTPConnection:
        ep = self.endpoint
        if ep.startswith("unix://"):
            return _UnixHTTPConnection(ep[len("unix://"):], timeout)
        if ep.startswith("tcp://"):
            hostport = ep[len("tcp://"):]
            return http.client.HTTPConnection(hostport, timeout=timeout)
        raise ValueError(f"unsupported Docker endpoint: {ep}")

    def _get_json(self, path: str):
        conn = self._conn()
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status >= 400:
                raise OSError(f"docker API {path}: HTTP {resp.status}")
            return json.loads(body)
        finally:
            conn.close()

    def inspect_container(self, container_id: str) -> dict:
        return self._get_json(f"/containers/{container_id}/json")

    def list_containers(self, all: bool = False) -> list[dict]:
        flag = "1" if all else "0"
        return self._get_json(f"/containers/json?all={flag}")

    def ping(self) -> None:
        conn = self._conn(timeout=3.0)
        try:
            conn.request("GET", "/_ping")
            resp = conn.getresponse()
            resp.read()
            if resp.status != 200:
                raise OSError(f"docker ping: HTTP {resp.status}")
        finally:
            conn.close()

    def add_event_listener(self, listener: "queue.Queue") -> None:
        stop = threading.Event()
        self._event_threads[id(listener)] = stop

        def stream() -> None:
            try:
                conn = self._conn(timeout=None)  # long-lived stream
                conn.request("GET", "/events")
                resp = conn.getresponse()
                while not stop.is_set():
                    # Read through the HTTPResponse so chunked
                    # transfer-encoding is decoded — reading resp.fp raw
                    # would hand chunk-size lines to the JSON parser,
                    # and an all-hex-digit size ("22") parses as an int
                    # that would crash the event handler downstream.
                    line = resp.readline()
                    if not line:
                        break
                    try:
                        event = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(event, dict):
                        listener.put(event)
            except OSError as exc:
                log.debug("Docker event stream ended: %s", exc)
            finally:
                listener.put(None)  # signals disconnect, like a closed chan

        threading.Thread(target=stream, name="docker-events",
                         daemon=True).start()

    def remove_event_listener(self, listener: "queue.Queue") -> None:
        stop = self._event_threads.pop(id(listener), None)
        if stop is not None:
            stop.set()


class ContainerCache:
    """Inspect-result cache with drain + prune
    (docker_discovery.go:349-404)."""

    def __init__(self) -> None:
        self._cache: dict[str, dict] = {}
        self._lock = threading.RLock()

    def get(self, svc_id: str) -> Optional[dict]:
        with self._lock:
            return self._cache.get(svc_id)

    def set(self, svc_id: str, container: dict) -> None:
        with self._lock:
            self._cache[svc_id] = container

    def drain(self) -> None:
        with self._lock:
            self._cache = {}

    def prune(self, live_ids: set[str]) -> None:
        with self._lock:
            for cid in list(self._cache):
                if cid not in live_ids:
                    del self._cache[cid]

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)


class DockerDiscovery(Discoverer):
    def __init__(self, endpoint: str, namer: ServiceNamer,
                 advertise_ip: str,
                 client_provider: Optional[
                     Callable[[], DockerClient]] = None,
                 hostname: Optional[str] = None) -> None:
        self.endpoint = endpoint
        self.namer = namer
        self.advertise_ip = advertise_ip
        self.hostname = hostname
        self.client_provider = client_provider or (
            lambda: EngineAPIClient(endpoint))
        self.events: "queue.Queue[Optional[dict]]" = queue.Queue()
        self.container_cache = ContainerCache()
        self.sleep_interval = DEFAULT_SLEEP_INTERVAL
        self._services: list[Service] = []
        self._lock = threading.RLock()
        self._quit = threading.Event()

    # -- Discoverer --------------------------------------------------------

    def services(self) -> list[Service]:
        with self._lock:
            return [svc.copy() for svc in self._services]

    def health_check(self, svc: Service) -> tuple[str, str]:
        """Check type/args from container labels
        (docker_discovery.go:75-83)."""
        container = self._inspect(svc)
        if container is None:
            return "", ""
        labels = (container.get("Config") or {}).get("Labels") or {}
        return labels.get("HealthCheck", ""), labels.get("HealthCheckArgs", "")

    def listeners(self) -> list[ChangeListener]:
        """Containers with a SidecarListener=<ServicePort> label subscribe
        to change events (docker_discovery.go:157-223)."""
        out = []
        with self._lock:
            svcs = list(self._services)
        for svc in svcs:
            container = self._inspect(svc)
            if container is None:
                continue
            listener = self._listener_for(svc, container)
            if listener is not None:
                out.append(listener)
        return out

    def _listener_for(self, svc: Service,
                      container: dict) -> Optional[ChangeListener]:
        labels = (container.get("Config") or {}).get("Labels") or {}
        port_str = labels.get("SidecarListener")
        if port_str is None:
            return None
        try:
            svc_port = int(port_str)
        except ValueError:
            log.warning("SidecarListener label found on %s, can't decode "
                        "port '%s'", svc.id, port_str)
            return None
        for port in svc.ports:
            if port.service_port == svc_port and port.type == "tcp":
                return ChangeListener(
                    name=svc.listener_name(),
                    url=f"http://{port.ip}:{port.port}/sidecar/update")
        log.warning("SidecarListener label found on %s, but no matching "
                    "ServicePort! '%s'", svc.id, port_str)
        return None

    def run(self, looper: Looper) -> None:
        threading.Thread(target=self._manage_connection,
                         name="docker-conn", daemon=True).start()

        def one() -> None:
            # Event-or-poll multiplexing (docker_discovery.go:117-137):
            # handle any queued events, then refresh the full listing.
            deadline = time.monotonic() + self.sleep_interval
            try:
                event = self.events.get(timeout=self.sleep_interval)
                if event is not None:
                    self._handle_event(event)
                    # Drain any burst before re-polling.
                    while True:
                        try:
                            more = self.events.get_nowait()
                        except queue.Empty:
                            break
                        if more is not None:
                            self._handle_event(more)
            except queue.Empty:
                pass
            remaining = deadline - time.monotonic()
            if remaining > 0:
                time.sleep(remaining)
            self.get_containers()
            if time.monotonic() - self._last_drain > CACHE_DRAIN_INTERVAL:
                self.container_cache.drain()
                self._last_drain = time.monotonic()

        self._last_drain = time.monotonic()

        def drive() -> None:
            looper.loop(one)
            self._quit.set()

        threading.Thread(target=drive, name="docker-discovery",
                         daemon=True).start()

    # -- internals ---------------------------------------------------------

    def _inspect(self, svc: Service) -> Optional[dict]:
        cached = self.container_cache.get(svc.id)
        if cached is not None:
            return cached
        try:
            client = self.client_provider()
            container = client.inspect_container(svc.id)
        except OSError as exc:
            log.error("Error inspecting container %s: %s", svc.id, exc)
            return None
        self.container_cache.set(svc.id, container)
        return container

    def get_containers(self) -> None:
        """Refresh the service list from a full container listing
        (docker_discovery.go:248-283)."""
        try:
            client = self.client_provider()
            containers = client.list_containers(all=False)
        except OSError as exc:
            log.error("Error listing containers: %s", exc)
            return
        live_ids: set[str] = set()
        services: list[Service] = []
        for container in containers:
            labels = container.get("Labels") or {}
            if labels.get("SidecarDiscover") == "false":
                continue
            svc = to_service(container, self.advertise_ip,
                             hostname=self.hostname)
            svc.name = self.namer.service_name(container)
            services.append(svc)
            live_ids.add(svc.id)
        with self._lock:
            self._services = services
        self.container_cache.prune(live_ids)

    def _handle_event(self, event: dict) -> None:
        """'die'/'stop' events delete the service immediately
        (docker_discovery.go:327-347)."""
        status = event.get("status") or event.get("Status") or ""
        if status not in ("die", "stop"):
            return
        cid = (event.get("id") or event.get("ID") or "")[:12]
        if len(cid) < 12:
            return
        with self._lock:
            for i, svc in enumerate(self._services):
                if svc.id == cid:
                    log.info("Deleting %s based on Docker '%s' event",
                             svc.id, status)
                    del self._services[i]
                    return

    def _manage_connection(self) -> None:
        """Self-healing event-stream connection
        (docker_discovery.go:299-325)."""
        client: Optional[DockerClient] = self._connect()
        while not self._quit.is_set():
            try:
                if client is None:
                    raise OSError("no client")
                client.ping()
            except OSError:
                log.warning("Lost connection to Docker, re-connecting")
                if client is not None:
                    try:
                        client.remove_event_listener(self.events)
                    except OSError:
                        pass
                client = self._connect()
            self._quit.wait(self.sleep_interval)

    def _connect(self) -> Optional[DockerClient]:
        try:
            client = self.client_provider()
            client.add_event_listener(self.events)
            return client
        except OSError as exc:
            log.error("Error creating Docker client: %s", exc)
            return None
