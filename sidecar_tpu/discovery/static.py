"""File-based static discovery
(reference: discovery/static_discovery.go:18-159).

Parses a ``static.json`` array of Targets once at ``run``; each target's
service gets a random 6-byte-hex ID and is re-stamped ``updated=now`` on
every ``services()`` call so the records stay alive in the catalog."""

from __future__ import annotations

import dataclasses
import json
import logging
import secrets
import socket
from typing import Optional

from sidecar_tpu.discovery.base import ChangeListener, Discoverer
from sidecar_tpu.runtime.looper import Looper
from sidecar_tpu.service import Port, Service, now_ns

log = logging.getLogger(__name__)


@dataclasses.dataclass
class StaticCheck:
    """static_discovery.go:33-36."""

    type: str = ""
    args: str = ""


@dataclasses.dataclass
class Target:
    """static_discovery.go:18-22."""

    service: Service
    check: StaticCheck
    listen_port: int = 0


def random_hex(count: int = 6) -> str:
    """static_discovery.go:148-159."""
    return secrets.token_hex(count)


class StaticDiscovery(Discoverer):
    def __init__(self, config_file: str, default_ip: str,
                 hostname: Optional[str] = None) -> None:
        self.config_file = config_file
        self.default_ip = default_ip
        self.hostname = hostname if hostname is not None \
            else socket.gethostname()
        self.targets: list[Target] = []

    # -- Discoverer --------------------------------------------------------

    def services(self) -> list[Service]:
        now = now_ns()
        out = []
        for target in self.targets:
            target.service.updated = now  # keep-alive re-stamp (:62-69)
            out.append(target.service.copy())
        return out

    def health_check(self, svc: Service) -> tuple[str, str]:
        for target in self.targets:
            if svc.id == target.service.id:
                return target.check.type, target.check.args
        return "", ""

    def listeners(self) -> list[ChangeListener]:
        """Targets with a ListenPort subscribe to change events
        (:72-85)."""
        out = []
        for target in self.targets:
            if target.listen_port > 0:
                out.append(ChangeListener(
                    name=target.service.listener_name(),
                    url=(f"http://{self.hostname}:{target.listen_port}"
                         "/sidecar/update")))
        return out

    def run(self, looper: Looper) -> None:
        try:
            self.targets = self.parse_config(self.config_file)
        except (OSError, ValueError) as exc:
            log.error("StaticDiscovery cannot parse: %s", exc)
            looper.quit()

    # -- config ------------------------------------------------------------

    def parse_config(self, filename: str) -> list[Target]:
        """static_discovery.go:102-145."""
        with open(filename, "rb") as fh:
            raw = json.load(fh)
        if not isinstance(raw, list):
            raise ValueError("static config must be a JSON array of Targets")
        targets = []
        now = now_ns()
        for entry in raw:
            svc = Service.from_json(entry.get("Service") or {})
            svc.id = random_hex(6)
            svc.created = now
            # Services may be exported for a 3rd party; an empty hostname
            # means "this host" (:122-126).
            if not svc.hostname:
                svc.hostname = self.hostname
            for port in svc.ports:
                if not port.ip:
                    port.ip = self.default_ip
            check_raw = entry.get("Check") or {}
            targets.append(Target(
                service=svc,
                check=StaticCheck(type=check_raw.get("Type", ""),
                                  args=check_raw.get("Args", "")),
                listen_port=int(entry.get("ListenPort", 0) or 0),
            ))
            log.info("Discovered service: %s, ID: %s", svc.name, svc.id)
        return targets
