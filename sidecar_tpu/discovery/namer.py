"""Service naming strategies for container discovery
(reference: discovery/service_namer.go:11-85)."""

from __future__ import annotations

import logging
import re
from typing import Optional

log = logging.getLogger(__name__)


class ServiceNamer:
    """service_namer.go:11-13 — container dict → service name."""

    def service_name(self, container: Optional[dict]) -> str:
        raise NotImplementedError


class RegexpNamer(ServiceNamer):
    """First capture group of a regex over the container name, falling
    back to the image (service_namer.go:17-57)."""

    def __init__(self, expression: str) -> None:
        self.service_name_match = expression
        try:
            self.expression = re.compile(expression)
        except re.error as exc:
            raise ValueError(
                f"Invalid regex, can't compile: {expression}") from exc

    def service_name(self, container: Optional[dict]) -> str:
        if container is None:
            log.warning("service_name() called with nil container!")
            return ""
        name = (container.get("Names") or [""])[0]
        match = self.expression.search(name)
        if match is None or match.lastindex is None:
            return container.get("Image", "")
        return match.group(1)


class DockerLabelNamer(ServiceNamer):
    """Value of a Docker label, falling back to the image
    (service_namer.go:61-85)."""

    def __init__(self, label: str = "ServiceName") -> None:
        self.label = label

    def service_name(self, container: Optional[dict]) -> str:
        if container is None:
            log.warning("service_name() called with nil container!")
            return ""
        labels = container.get("Labels") or {}
        if self.label in labels:
            return labels[self.label]
        log.debug("Found container with no '%s' label: %s, returning '%s'",
                  self.label, container.get("Id", ""),
                  container.get("Image", ""))
        return container.get("Image", "")
