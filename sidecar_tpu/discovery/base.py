"""The Discoverer interface and fan-in aggregation
(reference: discovery/discovery.go:16-102)."""

from __future__ import annotations

import dataclasses
from typing import Optional

from sidecar_tpu.runtime.looper import Looper
from sidecar_tpu.service import Service

DEFAULT_SLEEP_INTERVAL = 1.0  # discovery.go:11


@dataclasses.dataclass
class ChangeListener:
    """A co-located service that wants ChangeEvents over HTTP
    (discovery.go:16-20)."""

    name: str
    url: str


class Discoverer:
    """discovery.go:26-37."""

    def services(self) -> list[Service]:
        raise NotImplementedError

    def health_check(self, svc: Service) -> tuple[str, str]:
        """(check type, check args) for a service; ("", "") if unknown."""
        raise NotImplementedError

    def listeners(self) -> list[ChangeListener]:
        raise NotImplementedError

    def run(self, looper: Looper) -> None:
        """Non-blocking: start the discovery loop."""
        raise NotImplementedError


class MultiDiscovery(Discoverer):
    """Fan-in over N discoverers; first non-empty health check wins
    (discovery.go:41-102)."""

    def __init__(self, discoverers: list[Discoverer]) -> None:
        self.discoverers = discoverers
        self._sub_loopers: list[Looper] = []

    def health_check(self, svc: Service) -> tuple[str, str]:
        for disco in self.discoverers:
            check, args = disco.health_check(svc)
            if check:
                return check, args
        return "", ""

    def services(self) -> list[Service]:
        out: list[Service] = []
        for disco in self.discoverers:
            out.extend(disco.services())
        return out

    def listeners(self) -> list[ChangeListener]:
        out: list[ChangeListener] = []
        for disco in self.discoverers:
            out.extend(disco.listeners())
        return out

    def run(self, looper: Looper) -> None:
        from sidecar_tpu.runtime.looper import TimedLooper

        for disco in self.discoverers:
            sub = TimedLooper(DEFAULT_SLEEP_INTERVAL)
            self._sub_loopers.append(sub)
            disco.run(sub)
        # Propagate the controlling looper's quit to the plugins when
        # the owner stops (discovery.go:86-102) — callback-based, so no
        # idle watcher thread exists just to wait on an Event.  The
        # controlling looper has no loop of its own anymore, so quit IS
        # completion: mark it done so ``looper.wait()`` keeps its
        # "block until finished" contract.
        def on_quit() -> None:
            self._stop_plugins()
            looper._done.set()

        looper.add_quit_callback(on_quit)

    def _stop_plugins(self) -> None:
        for sub in self._sub_loopers:
            sub.quit()
