"""Kubernetes API discovery
(reference: discovery/kubernetes_api_discovery.go:17-183,
kubernetes_support.go:96-203).

Announces K8s Services carrying a ``ServiceName`` label, with NodePort
port mappings, either for this node only or for every node
(``announce_all_nodes``).  Health checks are always ``AlwaysSuccessful``
— the fronting load balancer is assumed to have done the health
checking.  The K8s REST API call is isolated behind a
``K8sDiscoveryAdapter`` so tests can inject canned payloads."""

from __future__ import annotations

import json
import logging
import ssl
import threading
import urllib.request
from typing import Optional

from sidecar_tpu.discovery.base import ChangeListener, Discoverer
from sidecar_tpu.runtime.looper import Looper, run_in_thread
from sidecar_tpu.service import ALIVE, Port, Service, now_ns, rfc3339_to_ns

log = logging.getLogger(__name__)


class K8sDiscoveryAdapter:
    """kubernetes_support.go:96-99 — the mockable API-call seam."""

    def get_services(self) -> bytes:
        raise NotImplementedError

    def get_nodes(self) -> bytes:
        raise NotImplementedError


class KubeAPIDiscoveryCommand(K8sDiscoveryAdapter):
    """Direct K8s REST API caller with bearer-token + CA from the
    serviceaccount path (kubernetes_support.go:102-203)."""

    def __init__(self, kube_host: str, kube_port: int, namespace: str,
                 timeout: float, creds_path: str) -> None:
        self.kube_host = kube_host
        self.kube_port = kube_port
        self.namespace = namespace
        self.timeout = timeout
        self.token = ""
        self._ssl_context: Optional[ssl.SSLContext] = None
        try:
            with open(f"{creds_path}/token") as fh:
                self.token = fh.read().replace("\n", "")
        except OSError as exc:
            log.error("Failed to read serviceaccount token: %s", exc)
        try:
            ctx = ssl.create_default_context()
            ctx.load_verify_locations(f"{creds_path}/ca.crt")
            self._ssl_context = ctx
        except (OSError, ssl.SSLError) as exc:
            log.warning("Failed to load CA cert file: %s", exc)

    def _make_request(self, path: str) -> bytes:
        scheme = "https" if self.kube_port == 443 else "http"
        url = f"{scheme}://{self.kube_host}:{self.kube_port}{path}"
        req = urllib.request.Request(
            url, headers={"Authorization": f"Bearer {self.token}"})
        kwargs = {}
        if scheme == "https" and self._ssl_context is not None:
            kwargs["context"] = self._ssl_context
        with urllib.request.urlopen(req, timeout=self.timeout,
                                    **kwargs) as resp:
            if not (200 <= resp.status < 300):
                raise OSError(
                    f"got unexpected response code from {path}: "
                    f"{resp.status}")
            return resp.read()

    def get_services(self) -> bytes:
        return self._make_request("/api/v1/services/")

    def get_nodes(self) -> bytes:
        return self._make_request("/api/v1/nodes/")


def _node_ip_host(node: dict) -> tuple[str, str]:
    """kubernetes_api_discovery.go:117-128."""
    hostname = ip = ""
    for addr in ((node.get("status") or {}).get("addresses") or []):
        if addr.get("type") == "InternalIP":
            ip = addr.get("address", "")
        elif addr.get("type") == "Hostname":
            hostname = addr.get("address", "")
    return hostname, ip


class K8sAPIDiscoverer(Discoverer):
    def __init__(self, command: K8sDiscoveryAdapter, namespace: str = "",
                 announce_all_nodes: bool = False,
                 hostname: str = "") -> None:
        self.command = command
        self.namespace = namespace
        self.announce_all_nodes = announce_all_nodes
        self.hostname = hostname
        self._svcs: dict = {}
        self._nodes: dict = {}
        self._lock = threading.RLock()

    # -- Discoverer --------------------------------------------------------

    def services(self) -> list[Service]:
        with self._lock:
            out: list[Service] = []
            for node in (self._nodes.get("items") or []):
                hostname, ip = _node_ip_host(node)
                if self.announce_all_nodes:
                    out.extend(self._services_for_node(hostname, ip))
                    continue
                if hostname == self.hostname:
                    out = self._services_for_node(hostname, ip)
                    break
            return out

    def _services_for_node(self, hostname: str, ip: str) -> list[Service]:
        """kubernetes_api_discovery.go:48-86 — only items labeled
        ServiceName, only NodePort ports."""
        services = []
        now = now_ns()
        for item in (self._svcs.get("items") or []):
            meta = item.get("metadata") or {}
            labels = meta.get("labels") or {}
            name = labels.get("ServiceName", "")
            if not name:
                continue
            created_raw = meta.get("creationTimestamp")
            svc = Service(
                id=meta.get("uid", ""),
                name=name,
                image=f"{name}:kubernetes-hosted",
                created=(rfc3339_to_ns(created_raw) if created_raw else 0),
                hostname=hostname,
                proxy_mode="http",
                status=ALIVE,
                updated=now,
            )
            for port in ((item.get("spec") or {}).get("ports") or []):
                node_port = int(port.get("nodePort", 0) or 0)
                if node_port < 1:
                    continue
                svc.ports.append(Port(type="tcp", port=node_port,
                                      service_port=int(port.get("port", 0)),
                                      ip=ip))
            services.append(svc)
        return services

    def health_check(self, svc: Service) -> tuple[str, str]:
        """Always AlwaysSuccessful (kubernetes_api_discovery.go:133-135)."""
        return "AlwaysSuccessful", ""

    def listeners(self) -> list[ChangeListener]:
        return []

    def run(self, looper: Looper) -> None:
        def one() -> None:
            try:
                data = self.command.get_services()
                parsed = json.loads(data)
                with self._lock:
                    self._svcs = parsed
            except (OSError, json.JSONDecodeError) as exc:
                log.error("Failed K8s services discovery: %s", exc)
            try:
                data = self.command.get_nodes()
                parsed = json.loads(data)
                with self._lock:
                    self._nodes = parsed
            except (OSError, json.JSONDecodeError) as exc:
                log.error("Failed K8s nodes discovery: %s", exc)

        run_in_thread(looper, one, name="k8s-discovery")
