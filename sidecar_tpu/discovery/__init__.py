"""Service discovery: plugins that find local services and their health
checks (reference: discovery/ package)."""

from sidecar_tpu.discovery.base import (
    ChangeListener,
    Discoverer,
    MultiDiscovery,
)
from sidecar_tpu.discovery.static import StaticDiscovery
from sidecar_tpu.discovery.namer import DockerLabelNamer, RegexpNamer

__all__ = [
    "ChangeListener", "Discoverer", "MultiDiscovery", "StaticDiscovery",
    "RegexpNamer", "DockerLabelNamer",
]
