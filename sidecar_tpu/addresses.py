"""Advertise-IP selection: first private IPv4 not excluded
(reference: addresses.go:10-99)."""

from __future__ import annotations

import ipaddress
import socket
from typing import Optional

PRIVATE_BLOCKS = [
    ipaddress.ip_network("10.0.0.0/8"),
    ipaddress.ip_network("172.16.0.0/12"),
    ipaddress.ip_network("192.168.0.0/16"),
]


def is_private_ip(ip_str: str) -> bool:
    try:
        ip = ipaddress.ip_address(ip_str)
    except ValueError:
        return False
    return any(ip in block for block in PRIVATE_BLOCKS)


def find_private_addresses() -> list[str]:
    """All private IPv4 addresses on this host (addresses.go:36-78)."""
    found: list[str] = []
    seen: set[str] = set()
    hostname = socket.gethostname()
    candidates: list[str] = []
    try:
        for info in socket.getaddrinfo(hostname, None,
                                       family=socket.AF_INET):
            candidates.append(info[4][0])
    except socket.gaierror:
        pass
    # Route-based discovery: a UDP "connection" picks the egress IP
    # without sending anything.
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as probe:
            probe.connect(("10.255.255.255", 1))
            candidates.append(probe.getsockname()[0])
    except OSError:
        pass
    for addr in candidates:
        if addr not in seen and is_private_ip(addr):
            seen.add(addr)
            found.append(addr)
    return found


def get_published_ip(excluded: list[str], advertise: str = "") -> str:
    """ADVERTISE_IP wins; else first non-excluded private IPv4
    (addresses.go:81-99).  Raises RuntimeError when nothing is found."""
    if advertise:
        return advertise
    for addr in find_private_addresses():
        if addr not in excluded:
            return addr
    raise RuntimeError("Can't find address!")
