"""One scheduler thread for every periodic loop in the live node.

The reference multiplexes its periodic duties over a few goroutines on
the Go runtime's thread pool and advertises "a few execution threads"
(its README:54-56).  A thread-per-TimedLooper translation loses that
row (~50 threads/node measured in round 4, benchmarks/live_node.py);
this scheduler restores it: a single thread drives any number of
periodic tasks from a deadline heap.

Contract notes:

* Tasks run ON the scheduler thread, serially.  A slow tick delays its
  siblings — the same property a single-threaded event loop has.  Long
  blocking work (the state-writer queue drain, blocking-IO loops, the
  health-check tick that waits on its worker pool) stays on dedicated
  threads; everything whose tick is quick belongs here.
* ``drive(looper, fn)`` adopts a TimedLooper's contract: honors its
  interval / ``immediate`` / ``quit()``, records a raising tick into
  ``looper.error`` and stops that task (Looper.loop semantics), and
  sets the looper's done event so ``looper.wait()`` keeps working.
* Re-registration cadence is ``fn-end + interval`` (TimedLooper sleeps
  the interval BETWEEN runs, so body time drifts the cadence — matched
  here deliberately).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
from typing import Callable, Optional

from sidecar_tpu.runtime.looper import TimedLooper

log = logging.getLogger(__name__)


class Scheduler:
    def __init__(self, name: str = "scheduler",
                 join_timeout: float = 5.0) -> None:
        self._name = name
        self._join_timeout = join_timeout
        self._heap: list = []       # (deadline, seq, task)
        self._seq = itertools.count()
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- registration -------------------------------------------------------

    def drive(self, looper: TimedLooper, fn: Callable[[], None],
              name: str = "task") -> None:
        """Drive ``fn`` per ``looper``'s interval until ``looper.quit()``
        (or ``fn`` raises).  Starts the scheduler thread on first use."""
        first = time.monotonic() + \
            (0.0 if looper.immediate else looper.interval)
        task = _Task(looper, fn, name)
        # quit() must take effect promptly (TimedLooper honors it within
        # one interruptible wait): wake the scheduler and retire quit
        # tasks immediately instead of at their next heap deadline.
        looper.add_quit_callback(self._reap_quit)
        with self._cond:
            if self._thread is not None and not self._thread.is_alive():
                # Finished thread (a completed stop(), or one whose
                # timed-out join has since drained): safe to replace.
                self._thread = None
            if self._thread is None:
                # Restart after stop(): reset the flag so the lifecycle
                # is well-defined (stop → drive → running again).
                self._stop = False
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True)
                self._thread.start()
            elif self._stop:
                # stop() timed out on a slow tick and the old thread is
                # STILL running: starting a second scheduler here would
                # double-run every task.  Refuse loudly.
                raise RuntimeError(
                    f"scheduler {self._name!r} is still stopping (a slow "
                    "tick outlived the stop timeout); retry drive() after "
                    "the previous thread exits")
            heapq.heappush(self._heap, (first, next(self._seq), task))
            self._cond.notify()

    def _reap_quit(self) -> None:
        with self._cond:
            alive = []
            for entry in self._heap:
                task = entry[2]
                if task.looper._quit.is_set():
                    task.looper._done.set()
                else:
                    alive.append(entry)
            if len(alive) != len(self._heap):
                self._heap[:] = alive
                heapq.heapify(self._heap)
            self._cond.notify()

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=self._join_timeout)
            if thread.is_alive():
                # A slow tick outlived the join: KEEP the handle so a
                # later drive() can tell the thread is still running and
                # refuse to start a duplicate (double task execution,
                # ADVICE.md r5 low).  The thread will still exit at its
                # next loop turn; drive() clears the handle then.
                log.warning(
                    "scheduler %r thread did not stop within %.1f s (slow "
                    "tick still running); keeping the handle to prevent "
                    "a duplicate scheduler", self._name, self._join_timeout)
            else:
                self._thread = None

    # -- the loop -----------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._stop and \
                        (not self._heap or
                         self._heap[0][0] > time.monotonic()):
                    delay = None if not self._heap else \
                        max(0.0, self._heap[0][0] - time.monotonic())
                    self._cond.wait(timeout=delay)
                if self._stop:
                    for _, _, task in self._heap:
                        task.looper._done.set()
                    self._heap.clear()
                    return
                _, _, task = heapq.heappop(self._heap)
            if task.looper._quit.is_set():
                task.looper._done.set()
                continue
            try:
                task.fn()
            except BaseException as exc:  # noqa: BLE001 — Looper.loop parity
                task.looper.error = exc
                task.looper._done.set()
                log.exception("scheduled task %s failed; stopped",
                              task.name)
                continue
            if task.looper._quit.is_set():
                task.looper._done.set()
                continue
            nxt = time.monotonic() + task.looper.interval
            with self._cond:
                heapq.heappush(self._heap, (nxt, next(self._seq), task))


class _Task:
    __slots__ = ("looper", "fn", "name")

    def __init__(self, looper: TimedLooper, fn: Callable[[], None],
                 name: str) -> None:
        self.looper = looper
        self.fn = fn
        self.name = name
