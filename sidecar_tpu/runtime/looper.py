"""Deterministic loop drivers — the analog of ``go-director``.

Every background loop in the live framework takes a ``Looper`` so tests
can substitute ``FreeLooper(n)`` and run exactly *n* iterations
synchronously, the technique the reference uses everywhere
(services_state_test.go:344-351; SURVEY.md §4).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class Looper:
    """Drives ``fn`` repeatedly until quit or error.

    ``loop(fn)`` blocks until the loop ends; run it under
    :func:`run_in_thread` for background behavior.  ``fn`` returning
    normally continues the loop; raising stops it and records the error.
    """

    def __init__(self) -> None:
        self._quit = threading.Event()
        self._done = threading.Event()
        self.error: Optional[BaseException] = None
        self._quit_callbacks: list[Callable[[], None]] = []

    def add_quit_callback(self, cb: Callable[[], None]) -> None:
        """Run ``cb`` when this looper is quit — lets an owner propagate
        shutdown without dedicating a thread to waiting on the event."""
        self._quit_callbacks.append(cb)

    def quit(self) -> None:
        self._quit.set()
        for cb in self._quit_callbacks:
            cb()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the loop finishes; True if it did."""
        return self._done.wait(timeout)

    # -- subclass hooks ----------------------------------------------------

    def _iterations(self):
        raise NotImplementedError

    def loop(self, fn: Callable[[], None]) -> None:
        try:
            for _ in self._iterations():
                if self._quit.is_set():
                    break
                fn()
        except BaseException as exc:  # noqa: BLE001 — loop errors are data
            self.error = exc
        finally:
            self._done.set()


class FreeLooper(Looper):
    """Run exactly ``count`` iterations, as fast as possible (tests)."""

    def __init__(self, count: int) -> None:
        super().__init__()
        self.count = count

    def _iterations(self):
        return range(self.count)


class TimedLooper(Looper):
    """Run every ``interval`` seconds; ``count`` ≤ 0 means forever."""

    def __init__(self, interval: float, count: int = -1,
                 immediate: bool = True) -> None:
        super().__init__()
        self.interval = interval
        self.count = count
        self.immediate = immediate

    def _iterations(self):
        i = 0
        first = True
        while self.count <= 0 or i < self.count:
            if not (first and self.immediate):
                # Interruptible sleep so quit() takes effect promptly.
                if self._quit.wait(self.interval):
                    return
            first = False
            yield i
            i += 1


def run_in_thread(looper: Looper, fn: Callable[[], None],
                  name: str = "looper") -> threading.Thread:
    """Start ``looper.loop(fn)`` on a daemon thread and return it."""
    t = threading.Thread(target=looper.loop, args=(fn,), name=name,
                         daemon=True)
    t.start()
    return t


def monotonic_ms() -> int:
    return int(time.monotonic() * 1000)
