"""Host-side runtime primitives for the live framework.

The reference coordinates its background work with goroutines driven by
``go-director`` loopers (seven created in main.go:318-338), which is also
what makes its async behavior deterministically testable: tests inject a
``FreeLooper(N)`` to run a loop exactly N times (SURVEY.md §4).  This
package provides the same pattern for Python threads.
"""

from sidecar_tpu.runtime.looper import (
    FreeLooper,
    Looper,
    TimedLooper,
    run_in_thread,
)

__all__ = ["Looper", "FreeLooper", "TimedLooper", "run_in_thread"]
