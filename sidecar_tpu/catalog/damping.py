"""Flap damping — churn-gated proxy admission for flapping services.

Under chaos (asymmetric loss, GC pauses), bare TTL expiry makes healthy
services flap alive→tombstone→alive; every flap churns the whole read
path — snapshots, watch deltas, ADS pushes, proxy reloads.  The device
side of the fix is SWIM suspicion (ops/suspicion.py); this module is
the host side: a per-service-instance penalty counter with exponential
decay, the BGP route-flap-damping / Envoy-outlier-detection shape,
gating PROXY ADMISSION only.  A damped service stays fully present in
the catalog and every catalog view (the record is real state; damping
is a routing decision) — it is withheld from HAProxy/Envoy resource
generation until its penalty decays below the reuse threshold.

Mechanics (RFC 2439 recast):

* every observed liveness flap (ALIVE ↔ not-ALIVE status transition on
  the catalog's writer path, ``ServicesState.service_changed``) adds
  ``flap_penalty`` to the instance's penalty;
* the penalty decays continuously with half-life ``half_life_s``;
* an instance whose penalty reaches ``threshold`` is SUPPRESSED;
* it is REINSTATED once the penalty decays below ``reuse_threshold``
  (default threshold/2 — the hysteresis band keeps a service hovering
  at the threshold from thrashing in and out of routing).

``threshold == 0`` disables suppression entirely (observation still
counts flaps, so the metrics stay useful).  The same knobs ride
:class:`~sidecar_tpu.ops.suspicion.ProtocolParams` through config.py
(SIDECAR_DAMPING_*) and ``POST /simulate``, so the simulator predicts
exactly what the live damper would do (tests/test_damping.py
cross-validates the two paths under one FaultPlan).

Metrics: ``damping.flaps`` / ``damping.suppressed`` /
``damping.reinstated`` counters, ``damping.damped_services`` gauge
(docs/metrics.md).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Optional

from sidecar_tpu import metrics
from sidecar_tpu.service import ALIVE, UNKNOWN

# Entries whose penalty decayed below this are garbage-collected on the
# next observation — the table stays bounded by the actively-flapping
# population, not by catalog size.
_GC_FLOOR = 0.01

NS_PER_SECOND = 1_000_000_000


class _SimRecord:
    """Minimal record shim for :class:`TransitionReplay` — simulated
    transitions have no live ``Service`` object behind them."""

    __slots__ = ("hostname", "id", "status")

    def __init__(self, hostname: str, sid: str, status: int) -> None:
        self.hostname = hostname
        self.id = sid
        self.status = status


class TransitionReplay:
    """Replay SIMULATED status observations through a damper with the
    SAME rules the live writer path applies — the ONE definition shared
    by the bench robustness harness (benchmarks/robustness.py), the
    bridge's damping prediction (``SimBridge._predict_damping``), and
    the cross-validation tests; a rule change here changes all of them
    together:

    * SUSPECT (status code 5, ops/status.py) is quarantine, not
      routing-visible liveness: a SUSPECT observation neither flaps nor
      updates the tracked status (the live catalog never materializes
      SUSPECT, so a refuted suspicion is replay-invisible);
    * first sight of a record is discovery, not a flap;
    * only liveness changes (ALIVE ↔ not-ALIVE) flap — exactly
      :meth:`FlapDamper.observe`'s rule, which does the actual
      penalty accounting.
    """

    def __init__(self, damper: FlapDamper) -> None:
        self.damper = damper
        self._last: dict[str, int] = {}
        self.flaps: dict[str, int] = {}

    def prime(self, sid: str, status: int) -> None:
        """Seed the tracked status from an initial catalog view (so the
        first simulated observation is a transition, not discovery)."""
        self._last[sid] = status

    def see(self, hostname: str, sid: str, status: int,
            now_ns: int) -> None:
        """One observed (service, status) sample from the simulated
        stream."""
        from sidecar_tpu.service import SUSPECT as _SUS

        if status == _SUS or status < 0:
            return
        prev = self._last.get(sid)
        self._last[sid] = status
        if prev is None or prev == status:
            return
        if (prev == ALIVE) != (status == ALIVE):
            self.flaps[sid] = self.flaps.get(sid, 0) + 1
        self.damper.observe(_SimRecord(hostname, sid, status), prev,
                            now_ns=now_ns)


class FlapDamper:
    """Per-instance flap penalty with exponential decay and
    suppress/reuse hysteresis.  Thread-safe; observation sites call it
    under the catalog writer's lock, admission sites from reader
    threads."""

    def __init__(self, half_life_s: float = 60.0,
                 threshold: float = 0.0,
                 reuse_threshold: float = 0.0,
                 flap_penalty: float = 1.0,
                 now_fn: Optional[Callable[[], int]] = None) -> None:
        if half_life_s <= 0:
            raise ValueError("half_life_s must be > 0")
        if reuse_threshold > threshold:
            raise ValueError("reuse_threshold cannot exceed threshold")
        self.half_life_s = half_life_s
        self.threshold = threshold
        self.reuse_threshold = reuse_threshold if reuse_threshold > 0 \
            else threshold / 2.0
        self.flap_penalty = flap_penalty
        # Injectable clock (ns) — tests and the sim cross-validation
        # drive a logical clock; the live node uses wall time.
        self._now = now_fn if now_fn is not None else time.time_ns
        self._lock = threading.Lock()
        # key → [penalty, last_ns, suppressed]
        self._entries: dict[tuple[str, str], list] = {}

    @classmethod
    def from_protocol(cls, params,
                      now_fn: Optional[Callable[[], int]] = None
                      ) -> "FlapDamper":
        """Build from an :class:`ops.suspicion.ProtocolParams` bundle —
        the sim↔live shared-knob path."""
        return cls(half_life_s=params.damping_half_life_s,
                   threshold=params.damping_threshold,
                   reuse_threshold=params.resolved_reuse_threshold
                   if params.damping_threshold > 0 else 0.0,
                   flap_penalty=params.damping_flap_penalty,
                   now_fn=now_fn)

    @staticmethod
    def key_of(svc) -> tuple[str, str]:
        return (svc.hostname, svc.id)

    # -- internal ----------------------------------------------------------

    def _decayed(self, entry: list, now_ns: int) -> float:
        penalty, last_ns, _ = entry
        dt_s = max(0, now_ns - last_ns) / NS_PER_SECOND
        return penalty * math.exp(-math.log(2.0) * dt_s / self.half_life_s)

    def _update_suppression(self, key, entry: list, penalty: float) -> None:
        suppressed = entry[2]
        if not suppressed and self.threshold > 0 \
                and penalty >= self.threshold:
            entry[2] = True
            metrics.incr("damping.suppressed")
        elif suppressed and penalty < self.reuse_threshold:
            entry[2] = False
            metrics.incr("damping.reinstated")

    def _gauge(self) -> None:
        metrics.set_gauge("damping.damped_services",
                          sum(1 for e in self._entries.values() if e[2]))

    # -- observation (writer path) -----------------------------------------

    def observe(self, svc, previous_status: int,
                now_ns: Optional[int] = None) -> None:
        """Record one catalog status transition.  A FLAP is a liveness
        change — ALIVE ↔ anything-not-ALIVE — on a record we had seen
        before (the first sighting of a service, previous UNKNOWN, is
        discovery, not a flap)."""
        if previous_status == UNKNOWN:
            return
        was_alive = previous_status == ALIVE
        is_alive = svc.status == ALIVE
        if was_alive == is_alive:
            return
        now = now_ns if now_ns is not None else self._now()
        key = self.key_of(svc)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = [0.0, now, False]
                self._entries[key] = entry
            penalty = self._decayed(entry, now) + self.flap_penalty
            entry[0], entry[1] = penalty, now
            metrics.incr("damping.flaps")
            self._update_suppression(key, entry, penalty)
            self._gc(now)
            self._gauge()

    def _gc(self, now_ns: int) -> None:
        dead = [k for k, e in self._entries.items()
                if not e[2] and self._decayed(e, now_ns) < _GC_FLOOR]
        for k in dead:
            del self._entries[k]

    # -- admission (reader paths) ------------------------------------------

    def suppressed(self, key: tuple[str, str],
                   now_ns: Optional[int] = None) -> bool:
        """Is this instance currently damped out of routing?  Re-checks
        the decayed penalty against the hysteresis band, so a quiet
        service readmits by pure time passage — no new event needed."""
        if self.threshold <= 0:
            return False
        now = now_ns if now_ns is not None else self._now()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return False
            self._update_suppression(key, entry, self._decayed(entry, now))
            result = entry[2]
            self._gauge()
            return result

    def admitted(self, svc, now_ns: Optional[int] = None) -> bool:
        """The proxy-admission gate (HAProxy/Envoy resource
        generation): False while the instance is damped."""
        return not self.suppressed(self.key_of(svc), now_ns)

    def penalty(self, key: tuple[str, str],
                now_ns: Optional[int] = None) -> float:
        now = now_ns if now_ns is not None else self._now()
        with self._lock:
            entry = self._entries.get(key)
            return 0.0 if entry is None else self._decayed(entry, now)

    def damped(self, now_ns: Optional[int] = None) -> set[tuple[str, str]]:
        """The currently-suppressed instance set (hysteresis applied at
        read time)."""
        if self.threshold <= 0:
            return set()
        now = now_ns if now_ns is not None else self._now()
        with self._lock:
            for key, entry in self._entries.items():
                self._update_suppression(key, entry,
                                         self._decayed(entry, now))
            self._gauge()
            return {k for k, e in self._entries.items() if e[2]}

    def snapshot(self, now_ns: Optional[int] = None) -> dict:
        """JSON-able view for the web API (`/api/damping`)."""
        now = now_ns if now_ns is not None else self._now()
        with self._lock:
            return {
                "half_life_s": self.half_life_s,
                "threshold": self.threshold,
                "reuse_threshold": self.reuse_threshold,
                "entries": {
                    f"{host}/{sid}": {
                        "penalty": round(self._decayed(e, now), 4),
                        "suppressed": bool(e[2]),
                    }
                    for (host, sid), e in sorted(self._entries.items())
                },
            }
