"""The replicated service catalog — eventual-consistency state core.

Capability mirror of the reference's ``catalog.ServicesState``
(catalog/services_state.go): a two-level map ``servers[hostname] →
services[id] → Service`` with latest-timestamp-wins merge semantics,
change-event fan-out to listeners, and the broadcast/tombstone lifecycle
loops.  Wire format (JSON field names, RFC3339-ns timestamps) matches the
Go implementation so mixed clusters and existing downstream consumers
keep working.

Concurrency model: one re-entrant lock around the state (the reference
uses one RWMutex, services_state.go:79), a single-writer message queue
(``service_msgs``; services_state.go:127-140), and bounded per-listener
queues with non-blocking delivery (services_state.go:217-240).  All
background loops take a ``Looper`` so tests drive them deterministically.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional

from sidecar_tpu import metrics
from sidecar_tpu import service as svc_mod
from sidecar_tpu.ops import digest as digest_ops
from sidecar_tpu.output import time_ago
from sidecar_tpu.telemetry.span import span as _span
from sidecar_tpu.telemetry import coherence as _coherence
from sidecar_tpu.telemetry import propagation as _propagation
from sidecar_tpu.runtime.looper import Looper, TimedLooper
from sidecar_tpu.service import (
    ALIVE_LIFESPAN,
    DRAINING_LIFESPAN,
    NS_PER_SECOND,
    Service,
    TOMBSTONE,
    TOMBSTONE_LIFESPAN,
    UNKNOWN,
    _as_int,
    _as_str,
    _parse_ts,
    ns_to_rfc3339,
    rfc3339_to_ns,
)

log = logging.getLogger(__name__)

# Lifecycle constants (catalog/services_state.go:26-37).
TOMBSTONE_COUNT = 10           # tombstone announce repetitions @ 1 Hz
ALIVE_COUNT = 5                # new-service announce repetitions @ 1 Hz
TOMBSTONE_SLEEP_INTERVAL = 2.0
TOMBSTONE_RETRANSMIT = 1.0
ALIVE_SLEEP_INTERVAL = 1.0
ALIVE_BROADCAST_INTERVAL = 60.0
LISTENER_EVENT_BUFFER_SIZE = 20
SERVICE_MSGS_BUFFER = 25       # NewServicesState (services_state.go:95)


def _digest_buckets() -> int:
    """Bucket count for the live coherence digest
    (``SIDECAR_TPU_DIGEST_BUCKETS``, a power of two; default
    ops/digest.DEFAULT_BUCKETS).  Read at state construction — one
    process hosts one digest geometry, matching the sim scan's static
    ``buckets`` argument.  A malformed value falls back to the default
    with a warning rather than failing catalog construction."""
    import os

    raw = os.environ.get("SIDECAR_TPU_DIGEST_BUCKETS", "")
    if not raw:
        return digest_ops.DEFAULT_BUCKETS
    try:
        buckets = int(raw)
        digest_ops.IncrementalDigest(buckets)  # validates power-of-two
        return buckets
    except (ValueError, TypeError):
        log.warning("Bad SIDECAR_TPU_DIGEST_BUCKETS=%r; using default %d",
                    raw, digest_ops.DEFAULT_BUCKETS)
        return digest_ops.DEFAULT_BUCKETS


def _ladder_depth() -> int:
    """Merkle-ladder depth for the live digest
    (``SIDECAR_TPU_ANTIENTROPY_DEPTH``, >= 1; default
    ops/digest.DEFAULT_LADDER_DEPTH).  Depth 1 degenerates to the flat
    PR 15 digest — reconciliation then narrows in one step."""
    import os

    raw = os.environ.get("SIDECAR_TPU_ANTIENTROPY_DEPTH", "")
    if not raw:
        return digest_ops.DEFAULT_LADDER_DEPTH
    try:
        depth = int(raw)
        if depth < 1:
            raise ValueError(raw)
        return depth
    except (ValueError, TypeError):
        log.warning("Bad SIDECAR_TPU_ANTIENTROPY_DEPTH=%r; using "
                    "default %d", raw, digest_ops.DEFAULT_LADDER_DEPTH)
        return digest_ops.DEFAULT_LADDER_DEPTH


@dataclasses.dataclass
class ChangeEvent:
    """A major state transition (catalog/services_state.go:42-46)."""

    service: Service
    previous_status: int
    time: int  # ns since epoch

    def to_json(self) -> dict:
        return {"Service": self.service.to_json(),
                "PreviousStatus": self.previous_status,
                "Time": ns_to_rfc3339(self.time)}

    @classmethod
    def from_json(cls, d: dict) -> "ChangeEvent":
        return cls(service=Service.from_json(d.get("Service") or {}),
                   previous_status=_as_int(d.get("PreviousStatus"),
                                           UNKNOWN),
                   time=_ts(d.get("Time")))


# One wire-timestamp rule for both decoders (service.py owns it).
_ts = _parse_ts


class Listener:
    """Receives ChangeEvents on a bounded queue
    (catalog.Listener interface, services_state.go:83-87)."""

    def chan(self) -> "queue.Queue[ChangeEvent]":
        raise NotImplementedError

    def name(self) -> str:
        raise NotImplementedError

    def managed(self) -> bool:
        """Auto-added/removed by discovery (SidecarListener labels)?"""
        return False


class QueueListener(Listener):
    """Trivial listener backed by a queue — test and building-block use."""

    def __init__(self, name: str,
                 buffer: int = LISTENER_EVENT_BUFFER_SIZE,
                 managed: bool = False) -> None:
        self._name = name
        self._chan: "queue.Queue[ChangeEvent]" = queue.Queue(maxsize=buffer)
        self._managed = managed

    def chan(self) -> "queue.Queue[ChangeEvent]":
        return self._chan

    def name(self) -> str:
        return self._name

    def managed(self) -> bool:
        return self._managed


class Server:
    """State about one host in the cluster (services_state.go:50-56)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.services: dict[str, Service] = {}
        self.last_updated: int = 0
        self.last_changed: int = 0

    def has_service(self, service_id: str) -> bool:
        return service_id in self.services

    def to_json(self) -> dict:
        return {
            "Name": self.name,
            "Services": {sid: s.to_json() for sid, s in self.services.items()},
            "LastUpdated": ns_to_rfc3339(self.last_updated),
            "LastChanged": ns_to_rfc3339(self.last_changed),
        }

    @classmethod
    def from_json(cls, d: dict) -> "Server":
        server = cls(_as_str(d.get("Name", ""), ""))
        for sid, sd in (d.get("Services") or {}).items():
            server.services[sid] = Service.from_json(sd)
        server.last_updated = _ts(d.get("LastUpdated"))
        server.last_changed = _ts(d.get("LastChanged"))
        return server


class ServicesState:
    """The cluster-wide replicated catalog (services_state.go:70-110)."""

    def __init__(self, hostname: Optional[str] = None,
                 cluster_name: str = "") -> None:
        import socket

        self.servers: dict[str, Server] = {}
        self.last_changed: int = 0
        self.cluster_name = cluster_name
        self.hostname = hostname if hostname is not None else socket.gethostname()
        # Encoded outbound gossip payloads (lists of encoded records);
        # the transport drains this (services_state.go:75 Broadcasts chan).
        self.broadcasts: "queue.Queue[Optional[list[bytes]]]" = queue.Queue()
        # Single-writer mutation queue (services_state.go:127-140).
        self.service_msgs: "queue.Queue[Service]" = queue.Queue(
            maxsize=SERVICE_MSGS_BUFFER)
        self._listeners: dict[str, Listener] = {}
        self.tombstone_retransmit = TOMBSTONE_RETRANSMIT
        self._lock = threading.RLock()
        self._now: Callable[[], int] = svc_mod.now_ns
        # The versioned snapshot/delta query plane (sidecar_tpu/query/),
        # lazily attached on first read-path use so bare states stay
        # cheap.  Once attached, every change event ALSO publishes a
        # copy-on-write snapshot + delta through the hub.
        self._query_hub = None
        # Flap damper (catalog/damping.py): when attached, every status
        # transition through service_changed feeds it, and the proxy
        # resource generators consult it for admission.  None = the
        # subprotocol is off (SIDECAR_DAMPING_THRESHOLD unset).
        self.flap_damper = None
        # Future-admission bound (SIDECAR_TPU_FUTURE_FUDGE, the live
        # twin of ops/merge.future_mask): a record stamped beyond
        # now + this many seconds is REJECTED at the writer — the
        # symmetric counterpart of the is_stale staleness fudge, the
        # defense against a rushing peer clock poisoning LWW.
        # Negative = disabled (the reference behavior).
        self.future_fudge_s: float = -1.0
        # Origin-admission gate (ops/suspicion.QuarantineScorer, the
        # live twin of the sim's per-origin violation counter): when
        # attached, every push-pull body is scored against the sender
        # and records from quarantined origins are rejected at the
        # writer.  None = the defense rung is off
        # (SIDECAR_TPU_ORIGIN_BUDGET / _ORIGIN_QUARANTINE unset).
        self.origin_gate = None
        # The live coherence digest (ops/digest.py — the ONE definition
        # shared with the sim's run_with_digest scan): maintained
        # incrementally by the writer under the state lock (every
        # add/replace/tombstone/expire is an O(depth) lane update) and
        # PUBLISHED as an immutable snapshot tuple so readers — the
        # push-pull annotation, /api/digest.json, the coherence
        # monitor — never take the lock (atomic reference read).
        # A LadderDigest's level 0 is byte-identical to the former
        # IncrementalDigest, so every existing consumer is unchanged;
        # the deeper levels feed anti-entropy reconciliation
        # (transport/antientropy.py).
        self._digest = digest_ops.LadderDigest(_digest_buckets(),
                                               _ladder_depth())
        self.digest_snapshot: tuple = (0, self._digest.value())
        # Peer digest annotation captured by decode() from a push-pull
        # body's "Digest" key — None on states built directly.
        self.wire_digest: Optional[dict] = None

    # -- time injection (tests) -------------------------------------------

    def set_clock(self, now_fn: Callable[[], int]) -> None:
        self._now = now_fn

    # -- the query plane ---------------------------------------------------

    def query_hub(self):
        """The attached :class:`sidecar_tpu.query.QueryHub`, created on
        first use — the read-path consumers' single entry point (web
        /watch, UrlListener, ADS)."""
        with self._lock:
            if self._query_hub is None:
                from sidecar_tpu.query import QueryHub

                hub = QueryHub(self)
                hub.attach()
                self._query_hub = hub
            return self._query_hub

    # -- basic accessors ---------------------------------------------------

    def has_server(self, hostname: str) -> bool:
        return hostname in self.servers

    def get_local_service_by_id(self, service_id: str) -> Service:
        """services_state.go:349-363; raises KeyError when absent."""
        with self._lock:
            server = self.servers.get(self.hostname)
            if server and service_id in server.services:
                return server.services[service_id].copy()
        raise KeyError(
            f"service with ID {service_id!r} not found on host "
            f"{self.hostname!r}")

    # -- encode / decode ---------------------------------------------------

    def to_json(self) -> dict:
        return {
            "Servers": {h: s.to_json() for h, s in self.servers.items()},
            "LastChanged": ns_to_rfc3339(self.last_changed),
            "ClusterName": self.cluster_name,
            "Hostname": self.hostname,
        }

    def encode(self) -> bytes:
        with self._lock:
            return json.dumps(self.to_json(), separators=(",", ":")).encode()

    def encode_annotated(self) -> bytes:
        """The push-pull body: :meth:`encode`'s Go-wire document plus
        the coherence-digest annotation under a ``"Digest"`` key.  Kept
        OFF :meth:`encode` so decode→encode stays byte-identical to the
        Go fixtures (tests/test_go_wire.py); Go peers ignore the extra
        key (encoding/json skips unknown fields), sidecar-tpu peers
        harvest it in :meth:`merge` via :func:`decode`."""
        with self._lock:
            doc = self.to_json()
            doc["Digest"] = self.digest_doc()
            return json.dumps(doc, separators=(",", ":")).encode()

    # -- the coherence digest (ops/digest.py live twin) --------------------

    def digest_doc(self) -> dict:
        """Wire/JSON view of the published digest snapshot — read
        WITHOUT the state lock (one immutable-tuple reference read;
        ``buckets`` is fixed at construction).  This is the coherence
        plane's read-path contract: /api/digest.json and the push-pull
        annotation never contend with the writer."""
        count, value = self.digest_snapshot
        return {"Buckets": self._digest.buckets, "Records": count,
                "Hex": digest_ops.digest_to_hex(value),
                # The anti-entropy version gate: advertising a ladder
                # geometry declares this peer speaks digest-directed
                # reconciliation (transport/antientropy.py).  Plain-wire
                # peers (and Go's encoding/json) ignore the extra key;
                # absence of it routes a session straight to the
                # full-body fallback.
                "Ladder": {"Depth": self._digest.depth,
                           "Leaf": self._digest.leaf_buckets}}

    def digest_level(self, level: int) -> tuple:
        """One ladder level's canonical digest, read under the state
        lock (levels deeper than the published snapshot are maintained
        by the writer but not snapshotted — reconciliation sessions are
        rare next to mutations, so they pay the lock, not the writer)."""
        with self._lock:
            return self._digest.level(level)

    def ladder_geometry(self) -> tuple:
        """(base buckets, depth) — fixed at construction."""
        return self._digest.base, self._digest.depth

    def services_in_buckets(self, buckets, leaf_buckets: int) -> list:
        """Copies of every record whose identity hashes into one of
        ``buckets`` at the ``leaf_buckets`` ladder level — the
        digest-directed session body (ships divergence, not catalogs).
        Tombstones are records too: a reconciling peer must learn of
        deaths it missed."""
        want = set(buckets)
        out = []
        with self._lock:
            for _, _, svc in self.each_service_sorted():
                ident = digest_ops.ident_of(svc.hostname, svc.id)
                if digest_ops.bucket_of(ident, leaf_buckets) in want:
                    out.append(svc.copy())
        return out

    def _digest_remove(self, svc: Service) -> None:
        """Writer-side capture: MUST run BEFORE a record is replaced,
        deleted, or mutated in place — the digest key includes
        ``(updated, status)``, so the old pair has to be subtracted
        while it is still observable."""
        self._digest.remove(digest_ops.ident_of(svc.hostname, svc.id),
                            digest_ops.live_key(svc.updated, svc.status))

    def _digest_add(self, svc: Service) -> None:
        self._digest.add(digest_ops.ident_of(svc.hostname, svc.id),
                         digest_ops.live_key(svc.updated, svc.status))

    def _digest_publish(self) -> None:
        """Swap in a fresh immutable snapshot (atomic reference
        assignment — the lock-free read path) and feed the local view
        of the coherence monitor, anchored to the query-plane version
        so time-to-coherence is attributable to a specific publish."""
        snap = (self._digest.count, self._digest.value())
        self.digest_snapshot = snap
        hub = self._query_hub
        cur = getattr(hub, "_current", None) if hub is not None else None
        _coherence.observe(self.hostname, snap[1],
                           buckets=self._digest.buckets,
                           records=snap[0], local=True,
                           version=cur.version if cur is not None else 0,
                           now_ns=self._now())

    # -- mutation: the merge kernel ---------------------------------------

    def update_service(self, svc: Service) -> None:
        """Enqueue a state update (services_state.go:137-140).  Blocks if
        the single-writer queue is full, like an unbuffered-over-capacity
        Go channel send."""
        self.service_msgs.put(svc)

    def offer_service(self, svc: Service, timeout: float = 0.0) -> bool:
        """Non-wedging variant of :meth:`update_service`: returns False
        instead of blocking past ``timeout`` when the single-writer
        queue is full.  The transport bridge loop uses this so a stalled
        writer cannot wedge the shared outbound/inbound thread — shed
        records are re-delivered by anti-entropy."""
        try:
            if timeout > 0.0:
                self.service_msgs.put(svc, timeout=timeout)
            else:
                self.service_msgs.put_nowait(svc)
            return True
        except queue.Full:
            return False

    def process_service_msgs(self, looper: Looper) -> None:
        """Single-writer loop draining ``service_msgs``
        (services_state.go:129-135)."""
        def one() -> None:
            svc = self.service_msgs.get()
            if svc is None:  # shutdown sentinel
                raise StopIteration
            self.add_service_entry(svc)

        try:
            looper.loop(one)
        except StopIteration:
            pass

    def stop_processing(self) -> None:
        self.service_msgs.put(None)  # type: ignore[arg-type]

    def add_service_entry(self, new_svc: Service) -> None:
        """THE merge kernel — latest-timestamp-wins with DRAINING
        stickiness and staleness rejection (services_state.go:293-347).
        This is the host-side scalar twin of ops/merge.py's vectorized
        kernel.  Timed like the reference (services_state.go:294)."""
        t0 = time.perf_counter()
        # Span: the merge hop of the live propagation path — the root
        # of the writer-thread chain (snapshot publish nests under it;
        # gossip.receive traces separately across the inbound queue —
        # docs/telemetry.md).
        with _span("catalog.merge"):
            try:
                self._add_service_entry(new_svc)
            finally:
                metrics.measure_since("addServiceEntry", t0)

    def _add_service_entry(self, new_svc: Service) -> None:
        with self._lock:
            now = self._now()
            gate = self.origin_gate
            if gate is not None:
                # Transport-origin annotation, NOT the record's hostname
                # — a forger writes any hostname it likes, the transport
                # knows who actually pushed.  Un-annotated records (the
                # per-record UDP path carries no sender) pass: the gate
                # covers the push-pull plane, exactly where a flood can
                # carry a whole board in one body.
                origin = getattr(new_svc, "gossip_origin", None)
                if origin is not None and gate.is_quarantined(origin):
                    metrics.incr("defense.live.rejectedQuarantine")
                    log.warning(
                        "Dropping record %s:%s (%s) from quarantined "
                        "origin %s", new_svc.hostname, new_svc.name,
                        new_svc.id, origin)
                    return
            if new_svc.is_stale(TOMBSTONE_LIFESPAN, now=now):
                log.warning("Dropping stale service received on gossip: "
                            "%s:%s (%s)", new_svc.hostname, new_svc.name,
                            new_svc.id)
                return
            if self.future_fudge_s >= 0 and new_svc.updated > \
                    now + int(self.future_fudge_s * svc_mod.NS_PER_SECOND):
                # Reject — never clamp: a clamped stamp would still win
                # LWW against honest peers and freeze the record.
                log.warning(
                    "Dropping future-stamped service received on "
                    "gossip: %s:%s (%s) is %.3fs ahead",
                    new_svc.hostname, new_svc.name, new_svc.id,
                    (new_svc.updated - now) / svc_mod.NS_PER_SECOND)
                metrics.incr("clock.live.rejectedFuture")
                return

            if not self.has_server(new_svc.hostname):
                self.servers[new_svc.hostname] = Server(new_svc.hostname)
            server = self.servers[new_svc.hostname]

            if not server.has_service(new_svc.id):
                server.services[new_svc.id] = new_svc
                self._digest_add(new_svc)
                self._digest_publish()
                self.service_changed(new_svc, UNKNOWN, new_svc.updated)
                self.retransmit(new_svc)
                self._observe_propagation(new_svc, now)
            elif new_svc.invalidates(server.services[new_svc.id]):
                server.last_updated = new_svc.updated
                old = server.services[new_svc.id]
                # DRAINING stickiness (services_state.go:329-331).
                if old.status == svc_mod.DRAINING and \
                        new_svc.status == svc_mod.ALIVE:
                    new_svc.status = old.status
                self._digest_remove(old)
                server.services[new_svc.id] = new_svc
                self._digest_add(new_svc)
                self._digest_publish()
                if old.status != new_svc.status:
                    self.service_changed(new_svc, old.status, new_svc.updated)
                self.retransmit(new_svc)
                self._observe_propagation(new_svc, now)

    def _observe_propagation(self, svc: Service, now: int) -> None:
        """Admission-time propagation lag — the live twin of the sim's
        record-level provenance plane (telemetry/propagation.py,
        docs/telemetry.md): merge time minus the record's origin stamp,
        accounted per origin host.  Own records show ~0 lag (they are
        stamped on this node's clock moments before the writer drains
        them), which keeps the per-origin table an honest baseline."""
        _propagation.observe("catalog", svc.hostname,
                             (now - svc.updated) / 1e6)

    def merge(self, other: "ServicesState") -> None:
        """Full-state anti-entropy merge (services_state.go:367-373).

        When the origin gate is attached, one push-pull body is "one
        packet" in the defense ladder's sense: the whole body is scored
        against the sender (``other.hostname`` — the transport origin
        the peer authenticated as, not any record's claimed hostname)
        before a single record is enqueued, and every record is
        annotated with that origin so the writer can reject the push
        once the origin crosses the quarantine threshold."""
        origin = other.hostname
        # Coherence harvest: one push-pull body carries the peer's
        # catalog digest ("Digest" annotation captured by decode(), or
        # the live snapshot when merging an in-process state) — the
        # monitor learns how far the peer's view diverges from ours
        # before a single record lands (telemetry/coherence.py).
        if origin and origin != self.hostname:
            peer_doc = getattr(other, "wire_digest", None)
            if peer_doc is None and \
                    getattr(other, "digest_snapshot", (0,))[0]:
                peer_doc = other.digest_doc()
            if peer_doc is not None:
                _coherence.observe_doc(origin, peer_doc,
                                       now_ns=self._now())
        gate = self.origin_gate
        if gate is not None and origin:
            over = gate.observe(
                origin,
                [(svc.hostname == origin, svc.updated)
                 for server in other.servers.values()
                 for svc in server.services.values()],
                self._now())
            if over:
                metrics.incr("defense.live.originViolations", over)
        for server in other.servers.values():
            for svc in server.services.values():
                c = svc.copy()
                if gate is not None and origin:
                    c.gossip_origin = origin
                self.update_service(c)

    def retransmit(self, svc: Service) -> None:
        """Epidemic relay of non-local changes (services_state.go:377-392);
        bounded by the invalidates() check in add_service_entry."""
        if svc.hostname == self.hostname:
            return
        try:
            self.broadcasts.put_nowait([svc.encode()])
        except queue.Full:  # pragma: no cover — unbounded by default
            log.warning("Broadcast queue full; dropping retransmit")

    # -- change accounting + listener fan-out ------------------------------

    def attach_damper(self, damper) -> None:
        """Attach a :class:`~sidecar_tpu.catalog.damping.FlapDamper`:
        from here on every status transition is observed, and the proxy
        resource generators (which read it through :meth:`query_hub` or
        directly) gate admission on it."""
        with self._lock:
            self.flap_damper = damper

    def attach_origin_gate(self, scorer) -> None:
        """Attach an :class:`~sidecar_tpu.ops.suspicion.QuarantineScorer`
        (same attach pattern as :meth:`attach_damper`): push-pull bodies
        are scored in :meth:`merge` and quarantined origins' records
        rejected in the writer."""
        with self._lock:
            self.origin_gate = scorer

    def service_changed(self, svc: Service, previous_status: int,
                        updated: int) -> None:
        """services_state.go:195-201."""
        self._server_changed(svc.hostname, updated)
        # Flap observation sits on the writer funnel — EVERY status
        # transition passes through here, so the damper sees the full
        # flap history regardless of which consumers are subscribed.
        damper = self.flap_damper
        if damper is not None:
            damper.observe(svc, previous_status)
        self.notify_listeners(svc, previous_status, self.last_changed)

    def _server_changed(self, hostname: str, updated: int) -> None:
        if not self.has_server(hostname):
            log.error("Attempt to change a server we don't have! (%s)",
                      hostname)
            return
        self.servers[hostname].last_updated = updated
        self.servers[hostname].last_changed = updated
        self.last_changed = updated

    def notify_listeners(self, svc: Service, previous_status: int,
                         changed_time: int) -> None:
        """Non-blocking fan-out (services_state.go:217-240)."""
        event = ChangeEvent(service=svc.copy(),
                            previous_status=previous_status,
                            time=changed_time)
        # Query-plane publish rides the same writer path: versions are
        # totally ordered because every change funnels through here
        # (under the state lock), and publish itself never blocks —
        # slow subscribers coalesce on their own bounded queues.
        hub = self._query_hub
        if hub is not None:
            hub.publish(event)
        for listener in list(self._listeners.values()):
            ch = listener.chan()
            if ch is None:
                continue  # hub-driven: fed by the query plane above
            try:
                ch.put_nowait(event)
            except queue.Full:
                log.warning("Can't notify listener (%s). May not be ready "
                            "yet.", listener.name())

    def add_listener(self, listener: Listener) -> None:
        """services_state.go:245-261 — queues must be bounded (≥1).

        Hub-driven listeners (``hub_driven = True``, e.g. UrlListener)
        carry no queue: they register here only for the managed-listener
        lifecycle (track_local_listeners) and receive their events
        through a query-hub subscription instead."""
        ch = listener.chan()
        if ch is None:
            if getattr(listener, "hub_driven", False):
                with self._lock:
                    self._listeners[listener.name()] = listener
                return
            log.error("Refusing to add listener %s with nil channel!",
                      listener.name())
            return
        if ch.maxsize < 1:
            log.error("Refusing to add blocking channel as listener: %s",
                      listener.name())
            return
        with self._lock:
            self._listeners[listener.name()] = listener

    def remove_listener(self, name: str) -> None:
        with self._lock:
            if name not in self._listeners:
                raise KeyError(f"no listener found with the name {name!r}")
            del self._listeners[name]

    def get_listeners(self) -> list[Listener]:
        with self._lock:
            return list(self._listeners.values())

    # -- server expiry (SWIM NotifyLeave path) -----------------------------

    def expire_server(self, hostname: str) -> None:
        """Tombstone all of a dead node's records and announce them
        TOMBSTONE_COUNT× (services_state.go:150-192)."""
        with self._lock:
            server = self.servers.get(hostname)
            if not server or not server.services:
                log.info("No records to expire for %s", hostname)
                return
            if all(svc.is_tombstone() for svc in server.services.values()):
                log.info("No records to expire for %s (no live services)",
                         hostname)
                return
            log.info("Expiring %s", hostname)
            tombstones = []
            now = self._now()
            for svc in server.services.values():
                previous = svc.status
                # tombstone() mutates (status, updated) IN PLACE — the
                # digest key covers both, so subtract the old pair
                # first (capture-before-mutate).
                self._digest_remove(svc)
                svc.tombstone(now=now)
                self._digest_add(svc)
                self.service_changed(svc, previous, svc.updated)
                tombstones.append(svc.copy())
            self._digest_publish()
        self.send_services(
            tombstones,
            TimedLooper(self.tombstone_retransmit, TOMBSTONE_COUNT))

    # -- broadcast lifecycle loops -----------------------------------------

    def is_new_service(self, svc: Service) -> bool:
        """services_state.go:505-517."""
        found = None
        if self.has_server(svc.hostname):
            found = self.servers[svc.hostname].services.get(svc.id)
        return found is None or (not svc.is_tombstone()
                                 and svc.status != found.status)

    def broadcast_services_step(
            self, fn: Callable[[], list[Service]]) -> Callable[[], None]:
        """One tick of :meth:`broadcast_services` — exposed so the node
        scheduler can drive it without a dedicated thread."""
        last_time = 0

        def one() -> None:
            nonlocal last_time
            services = []
            have_new = False
            service_list = fn()
            with self._lock:
                now = self._now()
                for svc in service_list:
                    if self.is_new_service(svc):
                        have_new = True
                        services.append(svc)
                    elif now - int(ALIVE_BROADCAST_INTERVAL *
                                   NS_PER_SECOND) > last_time:
                        services.append(svc)
            if services:
                run_count = ALIVE_COUNT if have_new else 1
                last_time = self._now()
                self.send_services(
                    services,
                    TimedLooper(self.tombstone_retransmit, run_count))
            else:
                self.broadcasts.put(None)

        return one

    def broadcast_services(self, fn: Callable[[], list[Service]],
                           looper: Looper) -> None:
        """Announce local services: new ⇒ ALIVE_COUNT× @ 1 Hz, else
        re-announce on the 1-minute refresh window
        (services_state.go:525-574)."""
        looper.loop(self.broadcast_services_step(fn))

    def send_services(self, services: list[Service], looper: Looper,
                      background: bool = True) -> Optional[threading.Thread]:
        """Re-enqueue each record every second, bumping Updated +50 ns per
        round so peers retransmit (services_state.go:579-604)."""
        services = [svc.copy() for svc in services]
        base_updated = [svc.updated for svc in services]

        def run() -> None:
            additional = 0

            def one() -> None:
                nonlocal additional
                prepared = []
                for svc, base in zip(services, base_updated):
                    # Linear +50 ns per round from the ORIGINAL stamp so
                    # peers see each round as strictly newer
                    # (services_state.go:585-599 copies the struct per
                    # iteration; re-adding to the mutated copy would
                    # compound the skew).
                    svc.updated = base + additional
                    prepared.append(svc.encode())
                additional += 50
                self.broadcasts.put(prepared)

            looper.loop(one)

        if background:
            t = threading.Thread(target=run, name="send-services", daemon=True)
            t.start()
            return t
        run()
        return None

    def broadcast_tombstones(self, fn: Callable[[], list[Service]],
                             looper: Looper) -> None:
        """Tombstone vanished local services + expire remote state
        (services_state.go:606-633)."""
        looper.loop(self.broadcast_tombstones_step(fn))

    def broadcast_tombstones_step(
            self, fn: Callable[[], list[Service]]) -> Callable[[], None]:
        """One tick of :meth:`broadcast_tombstones` (scheduler form)."""
        def one() -> None:
            with self._lock:
                container_list = fn()
                other = self.tombstone_others_services()
                mine = self.tombstone_services(self.hostname, container_list)
                tombstones = mine + other
            if tombstones:
                self.send_services(
                    tombstones,
                    TimedLooper(self.tombstone_retransmit, TOMBSTONE_COUNT))
            else:
                self.broadcasts.put(None)

        return one

    def tombstone_others_services(self) -> list[Service]:
        """Lifespan sweep over the whole view: GC 3h-old tombstones, and
        tombstone expired records at original-timestamp+1s so unseen newer
        records still win (services_state.go:635-683)."""
        result = []
        now = self._now()
        with self._lock:
            changed = False
            for hostname in list(self.servers):
                server = self.servers[hostname]
                for sid in list(server.services):
                    svc = server.services[sid]
                    if svc.is_tombstone() and svc.updated < now - int(
                            TOMBSTONE_LIFESPAN * NS_PER_SECOND):
                        self._digest_remove(svc)
                        changed = True
                        del server.services[sid]
                        if not server.services:
                            del self.servers[hostname]
                        continue
                    lifespan = (DRAINING_LIFESPAN if svc.is_draining()
                                else ALIVE_LIFESPAN)
                    if not svc.is_tombstone() and svc.updated < now - int(
                            lifespan * NS_PER_SECOND):
                        log.warning(
                            "Found expired service %s ID %s from %s, "
                            "tombstoning", svc.name, svc.id, svc.hostname)
                        previous = svc.status
                        # Original timestamp + 1 s, NOT now — the "+1 s
                        # rule" (services_state.go:667-675).  In-place
                        # restamp: subtract the old digest key first.
                        self._digest_remove(svc)
                        svc.status = TOMBSTONE
                        svc.updated = svc.updated + NS_PER_SECOND
                        self._digest_add(svc)
                        changed = True
                        self.service_changed(svc, previous, svc.updated)
                        result.append(svc.copy())
            if changed:
                self._digest_publish()
        return result

    def tombstone_services(self, hostname: str,
                           container_list: list[Service]) -> list[Service]:
        """Tombstone local services that vanished from discovery; each
        record twice for receipt (services_state.go:685-715)."""
        if not self.has_server(hostname):
            return []
        mapping = {svc.id for svc in container_list}
        result = []
        now = self._now()
        with self._lock:
            for svc in self.servers[hostname].services.values():
                if svc.id not in mapping and not svc.is_tombstone():
                    log.warning("Tombstoning %s", svc.id)
                    previous = svc.status
                    self._digest_remove(svc)
                    svc.tombstone(now=now)
                    self._digest_add(svc)
                    self.service_changed(svc, previous, svc.updated)
                    result.extend([svc.copy(), svc.copy()])
            if result:
                self._digest_publish()
        return result

    # -- tracking loops ----------------------------------------------------

    def track_new_services(self, fn: Callable[[], list[Service]],
                           looper: Looper) -> None:
        """services_state.go:444-452."""
        looper.loop(self.track_new_services_step(fn))

    def track_new_services_step(
            self, fn: Callable[[], list[Service]]) -> Callable[[], None]:
        """One tick of :meth:`track_new_services` (scheduler form)."""
        def one() -> None:
            for svc in fn():
                self.update_service(svc)
        return one

    def track_local_listeners(self, fn: Callable[[], list[Listener]],
                              looper: Looper) -> None:
        """Sync managed listeners with discovery
        (services_state.go:454-497)."""
        looper.loop(self.track_local_listeners_step(fn))

    def track_local_listeners_step(
            self, fn: Callable[[], list[Listener]]) -> Callable[[], None]:
        """One tick of :meth:`track_local_listeners` (scheduler form)."""
        def one() -> None:
            discovered = fn()
            names = {listener.name() for listener in discovered}
            for listener in discovered:
                with self._lock:
                    have = listener.name() in self._listeners
                if not have:
                    log.info("Adding listener %s because it was just "
                             "discovered", listener.name())
                    watch = getattr(listener, "watch", None)
                    if callable(watch):
                        watch(self)
                    else:
                        self.add_listener(listener)
            for listener in self.get_listeners():
                if listener.managed() and listener.name() not in names:
                    log.info("Removing listener %s because the service "
                             "appears to be gone", listener.name())
                    stop = getattr(listener, "stop", None)
                    if callable(stop):
                        stop()
                    try:
                        self.remove_listener(listener.name())
                    except KeyError as exc:
                        log.warning("Failed to remove listener: %s", exc)
        return one

    # -- iteration / views -------------------------------------------------

    def each_server(self) -> Iterator[tuple[str, Server]]:
        yield from list(self.servers.items())

    def each_service(self) -> Iterator[tuple[str, str, Service]]:
        for hostname, server in self.each_server():
            for sid, svc in list(server.services.items()):
                yield hostname, sid, svc

    def each_service_sorted(self) -> Iterator[tuple[str, str, Service]]:
        """Deterministic order — hostname then service ID (view.go:14-33);
        the Envoy adapter's oldest-wins collision guard relies on it."""
        for hostname in sorted(self.servers):
            server = self.servers[hostname]
            for sid in sorted(server.services):
                yield hostname, sid, server.services[sid]

    def by_service(self) -> dict[str, list[Service]]:
        """Group instances by service name (services_state.go:752-764)."""
        out: dict[str, list[Service]] = {}
        with self._lock:
            for _, _, svc in self.each_service_sorted():
                out.setdefault(svc.name, []).append(svc.copy())
        return out

    # -- display -----------------------------------------------------------

    def format(self, members: Optional[Iterable[str]] = None) -> str:
        """Human-readable dump (services_state.go:396-436)."""
        now = self._now()
        out = "Services ------------------------------\n"
        with self._lock:
            for name in sorted(self.servers):
                server = self.servers[name]
                out += f"  {name}: ({time_ago(server.last_updated, now)})\n"
                for svc in sorted(server.services.values(),
                                  key=lambda s: s.name):
                    out += svc_mod.format_service(svc, now)
                out += "\n"
        if members is None:
            return out
        out += "\nCluster Hosts -------------------------\n"
        for host in members:
            out += f"    {host}\n"
        out += "---------------------------------------"
        return out


def decode(data: bytes | str) -> ServicesState:
    """Rebuild a state from its JSON wire form (services_state.go:774-782).

    Raises ValueError on ANY malformed payload — push-pull bodies come
    from (same-cluster but untrusted) peers, and a TypeError or
    AttributeError leaking from a shape surprise would kill the caller's
    merge loop, silently ending anti-entropy."""
    try:
        d = json.loads(data)
        if not isinstance(d, dict):
            raise ValueError("state JSON: not an object")
        state = ServicesState(
            hostname=_as_str(d.get("Hostname"), "") or "")
        state.cluster_name = _as_str(d.get("ClusterName"), "") or ""
        state.last_changed = _ts(d.get("LastChanged"))
        for hostname, sd in (d.get("Servers") or {}).items():
            state.servers[hostname] = Server.from_json(sd)
        # Coherence annotation (encode_annotated): captured verbatim for
        # merge() to harvest — never merged into the decoded state's own
        # (empty) incremental digest, which only the writer maintains.
        dig = d.get("Digest")
        if isinstance(dig, dict):
            state.wire_digest = dig
        return state
    except (json.JSONDecodeError, UnicodeDecodeError, TypeError,
            AttributeError, KeyError, OverflowError) as exc:
        raise ValueError(
            f"failed to decode state JSON: {exc}") from exc


def decode_stream(stream, callback) -> None:
    """Newline-delimited JSON of by-service maps
    (services_state.go:766-772): calls ``callback(mapping, error)`` per
    document.

    Stop-on-first-error is DELIBERATE reference parity: the Go
    DecodeStream returns on its first Decode error
    (services_state.go:766-772), ending the stream.  The alternative
    (skip the bad document and continue) would hide a desynced or
    corrupted stream from a long-lived consumer; matching the
    reference, the callback sees the error once and the reader stops —
    reconnecting is the consumer's decision (the receiver library's
    retry loop does exactly that)."""
    for line in stream:
        if not line.strip():
            continue
        try:
            doc = json.loads(line)
            mapping = {name: [Service.from_json(s) for s in svcs]
                       for name, svcs in doc.items()}
        except (json.JSONDecodeError, AttributeError, TypeError,
                ValueError, KeyError, OverflowError) as exc:
            # Same wire-boundary rule as decode(): any malformed document
            # becomes the callback's error, never an exception that
            # kills the reader of a long-lived /watch stream.  Only the
            # parse sits inside the try — a consumer callback's own
            # exceptions must propagate, not masquerade as wire errors.
            callback(None, exc)
            return
        callback(mapping, None)
