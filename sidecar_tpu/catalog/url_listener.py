"""UrlListener: pushes StateChangedEvents to subscriber URLs over HTTP
POST (reference: catalog/url_listener.go:22-161)."""

from __future__ import annotations

import json
import logging
import queue
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from sidecar_tpu.catalog.state import (
    ChangeEvent,
    LISTENER_EVENT_BUFFER_SIZE,
    Listener,
    ServicesState,
)

log = logging.getLogger(__name__)

CLIENT_TIMEOUT = 3.0   # url_listener.go:18
DEFAULT_RETRIES = 5    # url_listener.go:19


def with_retries(count: int, fn) -> Optional[Exception]:
    """url_listener.go:81-94 — linear backoff, first try immediate."""
    last: Optional[Exception] = None
    for i in range(-1, count):
        try:
            fn()
            return None
        except Exception as exc:  # noqa: BLE001 — retry any failure
            last = exc
            if i + 1 < count:
                time.sleep(max(0.1 * (i + 1), 0))
    log.warning("Failed after %d retries", count)
    return last


def state_changed_event_json(state: ServicesState,
                             event: ChangeEvent) -> bytes:
    """Wire shape of StateChangedEvent (url_listener.go:36-39)."""
    with state._lock:
        doc = {"State": state.to_json(), "ChangeEvent": event.to_json()}
    return json.dumps(doc, separators=(",", ":")).encode()


class UrlListener(Listener):
    def __init__(self, url: str, managed: bool = False,
                 retries: int = DEFAULT_RETRIES,
                 timeout: float = CLIENT_TIMEOUT) -> None:
        self.url = url
        self.retries = retries
        self.timeout = timeout
        self._managed = managed
        self._name = f"UrlListener({url})"
        self._chan: "queue.Queue[ChangeEvent]" = queue.Queue(
            maxsize=LISTENER_EVENT_BUFFER_SIZE)
        self._quit = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Session-affinity cookie for LB stickiness
        # (url_listener.go:40-60).
        self._cookie = ("sidecar-session-host="
                        f"{socket.gethostname()}-{time.time()}")

    # -- Listener ----------------------------------------------------------

    def chan(self):
        return self._chan

    def name(self) -> str:
        return self._name

    def set_name(self, name: str) -> None:
        self._name = name

    def managed(self) -> bool:
        return self._managed

    def stop(self) -> None:
        self._quit.set()
        try:
            self._chan.put_nowait(None)  # type: ignore[arg-type]
        except queue.Full:
            pass  # drain thread re-checks _quit after its current POST

    # -- the POST loop -----------------------------------------------------

    def _post(self, data: bytes) -> None:
        req = urllib.request.Request(
            self.url, data=data,
            headers={"Content-Type": "application/json",
                     "Cookie": self._cookie},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            if not (200 <= resp.status < 300):
                raise OSError(f"Bad status code returned ({resp.status})")

    def watch(self, state: ServicesState) -> None:
        """Register and start draining events in a background thread
        (url_listener.go:116-161)."""
        state.add_listener(self)

        def drain() -> None:
            while not self._quit.is_set():
                event = self._chan.get()
                if event is None or self._quit.is_set():
                    return
                data = state_changed_event_json(state, event)
                err = with_retries(self.retries, lambda: self._post(data))
                if err is not None:
                    log.warning("Failed posting state to '%s' %s: %s",
                                self.url, self.name(), err)

        self._thread = threading.Thread(target=drain, name=self._name,
                                        daemon=True)
        self._thread.start()
