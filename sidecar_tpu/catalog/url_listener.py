"""UrlListener: pushes catalog change events to subscriber URLs over
HTTP POST (reference: catalog/url_listener.go:22-161).

Since the query plane landed this is a subscription-hub consumer: the
drain thread reads versioned delta events from a
:class:`sidecar_tpu.query.hub.Subscription` and POSTs the **delta wire
shape** (docs/query.md) — ``{"Version": V, "ChangeEvent": {...}}`` per
change, collapsing to ``{"Version": V, "State": {...}}`` when the hub
coalesced a backlog (the subscriber fell behind; the full state is the
resync).  The old shape — the full catalog dump re-serialized under
``state._lock`` on EVERY event — survives only as
:func:`state_changed_event_json` for legacy consumers, and even that
now serves from the hub's cached snapshot encoding when one is
attached.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

from sidecar_tpu.catalog.state import (
    ChangeEvent,
    LISTENER_EVENT_BUFFER_SIZE,
    Listener,
    ServicesState,
)

log = logging.getLogger(__name__)

CLIENT_TIMEOUT = 3.0   # url_listener.go:18
DEFAULT_RETRIES = 5    # url_listener.go:19
RETRY_INTERVAL = 0.1   # linear backoff unit (url_listener.go:88)


def with_retries(count: int, fn,
                 sleep: Callable[[float], None] = time.sleep
                 ) -> Optional[Exception]:
    """url_listener.go:81-94 — first try immediate, then ``count``
    retries with linear backoff: 1×, 2×, … ``RETRY_INTERVAL`` BEFORE
    each retry (the old schedule slept ``0.1 * 0 = 0`` before the first
    retry, so the documented backoff never backed off where it matters
    most — the immediate-retry hammer).  ``sleep`` is injectable so
    tests assert the schedule against a fake clock."""
    last: Optional[Exception] = None
    for attempt in range(count + 1):
        try:
            fn()
            return None
        except Exception as exc:  # noqa: BLE001 — retry any failure
            last = exc
            if attempt < count:
                sleep(RETRY_INTERVAL * (attempt + 1))
    log.warning("Failed after %d retries", count)
    return last


def state_changed_event_json(state: ServicesState,
                             event: ChangeEvent) -> bytes:
    """LEGACY wire shape of StateChangedEvent (url_listener.go:36-39):
    the full catalog plus the event.  With a query hub attached the
    state document comes from the immutable current snapshot — no
    ``state._lock``, serialization cached per version; the lock path
    survives only for bare states."""
    hub = getattr(state, "_query_hub", None)
    if hub is not None:
        state_doc = hub.current().to_json()
    else:
        with state._lock:
            state_doc = state.to_json()
    doc = {"State": state_doc, "ChangeEvent": event.to_json()}
    return json.dumps(doc, separators=(",", ":")).encode()


def delta_event_json(version: int, event: ChangeEvent) -> bytes:
    """Delta wire shape (docs/query.md): one versioned change.  The
    drain loop serves this same document from the QueryEvent's cached
    buffer (``QueryEvent.delta_doc_bytes`` — byte-identical); this
    builder survives for consumers holding a bare ChangeEvent."""
    return json.dumps({"Version": version,
                       "ChangeEvent": event.to_json()},
                      separators=(",", ":")).encode()


def resync_event_json(snapshot) -> bytes:
    """Resync wire shape (docs/query.md): the subscriber fell behind and
    the hub collapsed its backlog — the full state at the latest
    version replaces every missed delta.  Served from the snapshot's
    shared per-version buffer when it carries one (every listener
    resyncing at a version POSTs the same object)."""
    cached = getattr(snapshot, "resync_doc_bytes", None)
    if cached is not None:
        return cached()
    return json.dumps({"Version": snapshot.version,
                       "State": snapshot.to_json()},
                      separators=(",", ":")).encode()


class UrlListener(Listener):
    # Registered in the state's listener registry for the managed-
    # listener lifecycle, but fed through a hub subscription — see
    # ServicesState.add_listener.
    hub_driven = True

    def __init__(self, url: str, managed: bool = False,
                 retries: int = DEFAULT_RETRIES,
                 timeout: float = CLIENT_TIMEOUT) -> None:
        self.url = url
        self.retries = retries
        self.timeout = timeout
        self._managed = managed
        self._name = f"UrlListener({url})"
        self._sub = None
        self._quit = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Session-affinity cookie for LB stickiness
        # (url_listener.go:40-60).
        self._cookie = ("sidecar-session-host="
                        f"{socket.gethostname()}-{time.time()}")

    # -- Listener ----------------------------------------------------------

    def chan(self):
        # Hub-driven: no listener queue.  Kept returning None so the
        # old add_listener path refuses it loudly rather than silently
        # double-subscribing (watch() is the only supported entry).
        return None

    def name(self) -> str:
        return self._name

    def set_name(self, name: str) -> None:
        self._name = name

    def managed(self) -> bool:
        return self._managed

    def stop(self) -> None:
        self._quit.set()
        if self._sub is not None:
            self._sub.close()  # wakes the drain thread's blocking get

    # -- the POST loop -----------------------------------------------------

    def _post(self, data: bytes) -> None:
        req = urllib.request.Request(
            self.url, data=data,
            headers={"Content-Type": "application/json",
                     "Cookie": self._cookie},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            if not (200 <= resp.status < 300):
                raise OSError(f"Bad status code returned ({resp.status})")

    def watch(self, state: ServicesState) -> None:
        """Subscribe to the state's query hub and start posting delta
        events in a background thread (url_listener.go:116-161 recast
        onto the hub)."""
        self._sub = state.query_hub().subscribe(
            self._name, buffer=LISTENER_EVENT_BUFFER_SIZE, prime=False)
        state.add_listener(self)  # lifecycle registry only (no queue)

        def drain() -> None:
            while not self._quit.is_set():
                ev = self._sub.get(timeout=1.0)
                if self._quit.is_set() or self._sub.closed:
                    return
                if ev is None:
                    continue
                # Shared per-version wire buffers (zero-copy fan-out):
                # every listener POSTing this version sends the SAME
                # bytes object; serialization happened at most once,
                # whoever got there first.
                if ev.kind == "snapshot":
                    data = ev.snapshot.resync_doc_bytes()
                else:
                    data = ev.delta_doc_bytes()
                err = with_retries(self.retries,
                                   lambda: self._post(data))
                if err is not None:
                    log.warning("Failed posting state to '%s' %s: %s",
                                self.url, self.name(), err)

        self._thread = threading.Thread(target=drain, name=self._name,
                                        daemon=True)
        self._thread.start()
