"""The replicated-state core: the live analog of the reference's
``catalog`` package (ServicesState, catalog/services_state.go)."""

from sidecar_tpu.catalog.state import (
    ALIVE_BROADCAST_INTERVAL,
    ALIVE_COUNT,
    ChangeEvent,
    LISTENER_EVENT_BUFFER_SIZE,
    Listener,
    QueueListener,
    Server,
    ServicesState,
    TOMBSTONE_COUNT,
    decode,
    decode_stream,
)

__all__ = [
    "ChangeEvent", "Server", "ServicesState", "Listener", "QueueListener",
    "decode", "decode_stream", "ALIVE_COUNT", "TOMBSTONE_COUNT",
    "ALIVE_BROADCAST_INTERVAL", "LISTENER_EVENT_BUFFER_SIZE",
]
