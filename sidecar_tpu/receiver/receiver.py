"""Receiver: accepts POSTed StateChangedEvents, filters them through the
state-transition table, and batches bursts before invoking the consumer
callback (reference: receiver/receiver.go:17-202, receiver/http.go:17-63)."""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
import urllib.request
from typing import Callable, Optional

from sidecar_tpu import service as svc_mod
from sidecar_tpu.catalog import ServicesState, decode
from sidecar_tpu.catalog.state import ChangeEvent, Server
from sidecar_tpu.runtime.looper import Looper, TimedLooper
from sidecar_tpu.service import Service

log = logging.getLogger(__name__)

RELOAD_HOLD_DOWN = 5.0  # receiver.go:18 — reload at worst every 5 s


def should_notify(old_status: int, new_status: int) -> bool:
    """The significant-transition table (receiver.go:41-69): ALIVE,
    TOMBSTONE and DRAINING always notify; UNKNOWN/UNHEALTHY only when the
    service was ALIVE."""
    if new_status in (svc_mod.ALIVE, svc_mod.TOMBSTONE, svc_mod.DRAINING):
        return True
    if new_status in (svc_mod.UNKNOWN, svc_mod.UNHEALTHY):
        return old_status == svc_mod.ALIVE
    log.error("Got unknown service change status: %d", new_status)
    return False


def fetch_state(url: str, timeout: float = 5.0) -> ServicesState:
    """Fetch a full state dump from a Sidecar /state.json endpoint
    (receiver.go:73-95)."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        if not (200 <= resp.status < 300):
            raise OSError(f"Bad status code on state fetch: {resp.status}")
        return decode(resp.read())


class Receiver:
    """receiver.go:21-37."""

    def __init__(self, capacity: int = 10,
                 on_update: Optional[Callable[[ServicesState],
                                              None]] = None,
                 looper: Optional[Looper] = None) -> None:
        self.state_lock = threading.Lock()
        self.reload_chan: "queue.Queue[float]" = queue.Queue(
            maxsize=capacity)
        self.current_state: Optional[ServicesState] = None
        self.last_svc_changed: Optional[Service] = None
        self.on_update = on_update
        self.looper = looper if looper is not None else TimedLooper(
            RELOAD_HOLD_DOWN)
        self.subscriptions: list[str] = []
        # Version cursor of the sender's query plane (docs/query.md);
        # 0 = no versioned document seen yet.
        self.last_version = 0

    # -- subscriptions -----------------------------------------------------

    def is_subscribed(self, svc_name: str) -> bool:
        """No subscriptions means everything (receiver.go:98-111)."""
        return not self.subscriptions or svc_name in self.subscriptions

    def subscribe(self, svc_name: str) -> None:
        if svc_name not in self.subscriptions:
            self.subscriptions.append(svc_name)

    # -- update intake -----------------------------------------------------

    def enqueue_update(self) -> None:
        try:
            self.reload_chan.put_nowait(time.time())
        except queue.Full:
            pass  # already saturated; the pending flush covers us

    def handle_update(self, payload: bytes | str) -> None:
        """Accept one POSTed catalog document (receiver/http.go:17-63
        extended for the query plane, docs/query.md):

        * delta — ``{"Version", "ChangeEvent"}``: merge the one record
          into the local mirror (LWW, so gaps and duplicates are safe —
          every delta carries the full record);
        * resync/legacy — any document with ``"State"``: replace the
          mirror when newer by LastChanged (the pre-query-plane
          StateChangedEvent shape decodes through the same path).

        Both filter via should_notify + subscriptions, then enqueue a
        batched reload."""
        if isinstance(payload, (memoryview, bytearray)):
            # Zero-copy handoff from the query plane: the sender POSTs
            # the hub's shared per-version buffer (possibly as a view);
            # json.loads only takes str/bytes/bytearray.
            payload = bytes(payload)
        evt = json.loads(payload)
        if not isinstance(evt, dict):
            raise ValueError("StateChangedEvent: not an object")
        if "State" not in evt:
            if "ChangeEvent" in evt:
                self._handle_delta(evt)
                return
            # Neither shape: malformed untrusted input, not an "empty
            # resync" — installing an empty mirror from {} would wipe
            # downstream config.
            raise ValueError("catalog document: neither State nor "
                             "ChangeEvent present")
        state_doc = evt.get("State") or {}
        change_doc = evt.get("ChangeEvent") or {}
        if not isinstance(state_doc, dict) \
                or not isinstance(change_doc, dict):
            raise ValueError("StateChangedEvent: State/ChangeEvent "
                             "not objects")
        state = decode(json.dumps(state_doc))
        change = (ChangeEvent.from_json(change_doc)
                  if change_doc else None)
        version = evt.get("Version") or state_doc.get("Version")

        with self.state_lock:
            if self.current_state is not None and \
                    self.current_state.last_changed >= state.last_changed:
                return
            self.current_state = state
            if isinstance(version, int):
                self.last_version = version
            if change is None:
                # Resync document (no event rode along): the full
                # replacement is itself the significant change.
                if self.on_update is None:
                    log.error("No on_update() callback registered!")
                    return
            else:
                self.last_svc_changed = change.service
                if not should_notify(change.previous_status,
                                     change.service.status):
                    return
                if not self.is_subscribed(change.service.name):
                    return
                if self.on_update is None:
                    log.error("No on_update() callback registered!")
                    return
        self.enqueue_update()

    def _handle_delta(self, evt: dict) -> None:
        """One versioned delta: upsert the record into the local mirror
        iff it invalidates the held copy.  The sender's hub already ran
        the full merge semantics (staleness gate, DRAINING stickiness);
        the mirror records the published outcome, so no re-gating
        here — re-running the staleness gate against the receiver's
        clock would wrongly drop replayed-but-valid history."""
        change_doc = evt.get("ChangeEvent")
        if not isinstance(change_doc, dict):
            raise ValueError("delta event: ChangeEvent not an object")
        version = evt.get("Version")
        if not isinstance(version, int):
            raise ValueError("delta event: missing integer Version")
        change = ChangeEvent.from_json(change_doc)
        svc = change.service

        with self.state_lock:
            # The version cursor is bookkeeping only, NEVER a gate: a
            # restarted sender's hub restarts at version 1, and a
            # cursor-gated receiver would silently drop every delta
            # until the new epoch caught up.  Record-level LWW below is
            # what keeps the mirror correct — duplicates and replays
            # are idempotent no-ops there.
            if version > self.last_version + 1 and self.last_version:
                log.info("delta version gap: %d -> %d (LWW merge keeps "
                         "the mirror consistent)",
                         self.last_version, version)
            elif version < self.last_version:
                log.info("delta version went backwards: %d -> %d "
                         "(sender restart?); continuing on record LWW",
                         self.last_version, version)
            self.last_version = max(self.last_version, version)
            if self.current_state is None:
                self.current_state = ServicesState(hostname="")
            state = self.current_state
            server = state.servers.get(svc.hostname)
            if server is None:
                server = state.servers[svc.hostname] = Server(svc.hostname)
            held = server.services.get(svc.id)
            advanced = held is None or svc.invalidates(held)
            if advanced:
                server.services[svc.id] = svc.copy()
                # max(), not assignment: a valid-but-older record for a
                # DIFFERENT service must not move the server's change
                # stamps backwards.
                server.last_updated = max(server.last_updated,
                                          svc.updated)
                server.last_changed = max(server.last_changed,
                                          svc.updated)
                state.last_changed = max(state.last_changed, svc.updated)
            self.last_svc_changed = svc

            if not advanced:
                return  # duplicate/replay: mirror unchanged, no reload
            if not should_notify(change.previous_status, svc.status):
                return
            if not self.is_subscribed(svc.name):
                return
            if self.on_update is None:
                log.error("No on_update() callback registered!")
                return
        self.enqueue_update()

    # -- the reload loop ---------------------------------------------------

    def process_updates(self) -> None:
        """Batch bursts into single reloads with the 5 s hold-down
        (receiver.go:130-174)."""
        if self.looper is None:
            log.error("Unable to process_updates(), looper is nil!")
            return

        def one() -> None:
            first = self.reload_chan.get()
            if first is None:
                raise StopIteration
            pending = self.reload_chan.qsize()
            if self.on_update is None:
                log.error("on_update() callback not defined!")
            else:
                with self.state_lock:
                    # Deep-copy so the callback can't race the handler
                    # (receiver.go:147-152).
                    snapshot = (decode(self.current_state.encode())
                                if self.current_state is not None else None)
                if snapshot is not None:
                    self.on_update(snapshot)
            for _ in range(pending):
                try:
                    self.reload_chan.get_nowait()
                except queue.Empty:
                    break
            if pending > 0:
                log.info("Skipped %d grouped updates", pending)

        try:
            self.looper.loop(one)
        except StopIteration:
            pass

    def stop(self) -> None:
        self.looper.quit()
        # Non-blocking sentinel delivery: a full queue means process_updates
        # has work pending (or already stopped) — drain one entry and retry
        # so stop() can never hang on the bounded channel.
        while True:
            try:
                self.reload_chan.put_nowait(None)  # type: ignore[arg-type]
                return
            except queue.Full:
                try:
                    self.reload_chan.get_nowait()
                except queue.Empty:
                    pass

    # -- bootstrap ---------------------------------------------------------

    def fetch_initial_state(self, state_url: str) -> None:
        """receiver.go:183-202."""
        with self.state_lock:
            log.info("Fetching initial state on startup...")
            state = fetch_state(state_url)
            log.info("Successfully retrieved state")
            self.current_state = state
            on_update = self.on_update
        if on_update is None:
            log.error("on_update() callback not defined!")
        else:
            on_update(state)


def update_handler(rcvr: Receiver, payload: bytes):
    """WSGI-ish wrapper for mounting the receiver in an HTTP server:
    returns (status, body_bytes) like receiver/http.go:17-63."""
    try:
        rcvr.handle_update(payload)
    except (json.JSONDecodeError, AttributeError, KeyError, TypeError,
            ValueError) as exc:
        # AttributeError included: nested shape surprises (.get on a
        # non-dict inside ChangeEvent/Service) are wire errors here,
        # same boundary rule as catalog/service decode().
        return 500, json.dumps({"errors": [str(exc)]}).encode()
    return 200, b"{}"
