"""Receiver: accepts POSTed StateChangedEvents, filters them through the
state-transition table, and batches bursts before invoking the consumer
callback (reference: receiver/receiver.go:17-202, receiver/http.go:17-63)."""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
import urllib.request
from typing import Callable, Optional

from sidecar_tpu import service as svc_mod
from sidecar_tpu.catalog import ServicesState, decode
from sidecar_tpu.catalog.state import ChangeEvent
from sidecar_tpu.runtime.looper import Looper, TimedLooper
from sidecar_tpu.service import Service

log = logging.getLogger(__name__)

RELOAD_HOLD_DOWN = 5.0  # receiver.go:18 — reload at worst every 5 s


def should_notify(old_status: int, new_status: int) -> bool:
    """The significant-transition table (receiver.go:41-69): ALIVE,
    TOMBSTONE and DRAINING always notify; UNKNOWN/UNHEALTHY only when the
    service was ALIVE."""
    if new_status in (svc_mod.ALIVE, svc_mod.TOMBSTONE, svc_mod.DRAINING):
        return True
    if new_status in (svc_mod.UNKNOWN, svc_mod.UNHEALTHY):
        return old_status == svc_mod.ALIVE
    log.error("Got unknown service change status: %d", new_status)
    return False


def fetch_state(url: str, timeout: float = 5.0) -> ServicesState:
    """Fetch a full state dump from a Sidecar /state.json endpoint
    (receiver.go:73-95)."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        if not (200 <= resp.status < 300):
            raise OSError(f"Bad status code on state fetch: {resp.status}")
        return decode(resp.read())


class Receiver:
    """receiver.go:21-37."""

    def __init__(self, capacity: int = 10,
                 on_update: Optional[Callable[[ServicesState],
                                              None]] = None,
                 looper: Optional[Looper] = None) -> None:
        self.state_lock = threading.Lock()
        self.reload_chan: "queue.Queue[float]" = queue.Queue(
            maxsize=capacity)
        self.current_state: Optional[ServicesState] = None
        self.last_svc_changed: Optional[Service] = None
        self.on_update = on_update
        self.looper = looper if looper is not None else TimedLooper(
            RELOAD_HOLD_DOWN)
        self.subscriptions: list[str] = []

    # -- subscriptions -----------------------------------------------------

    def is_subscribed(self, svc_name: str) -> bool:
        """No subscriptions means everything (receiver.go:98-111)."""
        return not self.subscriptions or svc_name in self.subscriptions

    def subscribe(self, svc_name: str) -> None:
        if svc_name not in self.subscriptions:
            self.subscriptions.append(svc_name)

    # -- update intake -----------------------------------------------------

    def enqueue_update(self) -> None:
        try:
            self.reload_chan.put_nowait(time.time())
        except queue.Full:
            pass  # already saturated; the pending flush covers us

    def handle_update(self, payload: bytes | str) -> None:
        """Accept one POSTed StateChangedEvent (receiver/http.go:17-63):
        keep the newest state by LastChanged, filter via should_notify +
        subscriptions, then enqueue a batched reload."""
        evt = json.loads(payload)
        if not isinstance(evt, dict):
            raise ValueError("StateChangedEvent: not an object")
        state_doc = evt.get("State") or {}
        change_doc = evt.get("ChangeEvent") or {}
        if not isinstance(state_doc, dict) \
                or not isinstance(change_doc, dict):
            raise ValueError("StateChangedEvent: State/ChangeEvent "
                             "not objects")
        state = decode(json.dumps(state_doc))
        change = ChangeEvent.from_json(change_doc)

        with self.state_lock:
            if self.current_state is not None and \
                    self.current_state.last_changed >= state.last_changed:
                return
            self.current_state = state
            self.last_svc_changed = change.service

            if not should_notify(change.previous_status,
                                 change.service.status):
                return
            if not self.is_subscribed(change.service.name):
                return
            if self.on_update is None:
                log.error("No on_update() callback registered!")
                return
        self.enqueue_update()

    # -- the reload loop ---------------------------------------------------

    def process_updates(self) -> None:
        """Batch bursts into single reloads with the 5 s hold-down
        (receiver.go:130-174)."""
        if self.looper is None:
            log.error("Unable to process_updates(), looper is nil!")
            return

        def one() -> None:
            first = self.reload_chan.get()
            if first is None:
                raise StopIteration
            pending = self.reload_chan.qsize()
            if self.on_update is None:
                log.error("on_update() callback not defined!")
            else:
                with self.state_lock:
                    # Deep-copy so the callback can't race the handler
                    # (receiver.go:147-152).
                    snapshot = (decode(self.current_state.encode())
                                if self.current_state is not None else None)
                if snapshot is not None:
                    self.on_update(snapshot)
            for _ in range(pending):
                try:
                    self.reload_chan.get_nowait()
                except queue.Empty:
                    break
            if pending > 0:
                log.info("Skipped %d grouped updates", pending)

        try:
            self.looper.loop(one)
        except StopIteration:
            pass

    def stop(self) -> None:
        self.looper.quit()
        # Non-blocking sentinel delivery: a full queue means process_updates
        # has work pending (or already stopped) — drain one entry and retry
        # so stop() can never hang on the bounded channel.
        while True:
            try:
                self.reload_chan.put_nowait(None)  # type: ignore[arg-type]
                return
            except queue.Full:
                try:
                    self.reload_chan.get_nowait()
                except queue.Empty:
                    pass

    # -- bootstrap ---------------------------------------------------------

    def fetch_initial_state(self, state_url: str) -> None:
        """receiver.go:183-202."""
        with self.state_lock:
            log.info("Fetching initial state on startup...")
            state = fetch_state(state_url)
            log.info("Successfully retrieved state")
            self.current_state = state
            on_update = self.on_update
        if on_update is None:
            log.error("on_update() callback not defined!")
        else:
            on_update(state)


def update_handler(rcvr: Receiver, payload: bytes):
    """WSGI-ish wrapper for mounting the receiver in an HTTP server:
    returns (status, body_bytes) like receiver/http.go:17-63."""
    try:
        rcvr.handle_update(payload)
    except (json.JSONDecodeError, AttributeError, KeyError, TypeError,
            ValueError) as exc:
        # AttributeError included: nested shape surprises (.get on a
        # non-dict inside ChangeEvent/Service) are wire errors here,
        # same boundary rule as catalog/service decode().
        return 500, json.dumps({"errors": [str(exc)]}).encode()
    return 200, b"{}"
