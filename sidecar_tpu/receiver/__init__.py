"""Receiver library — the client side for downstream consumers of
Sidecar state events (reference: receiver/ package, the haproxy-api
pattern)."""

from sidecar_tpu.receiver.receiver import (
    RELOAD_HOLD_DOWN,
    Receiver,
    fetch_state,
    should_notify,
    update_handler,
)

__all__ = ["Receiver", "should_notify", "fetch_state", "update_handler",
           "RELOAD_HOLD_DOWN"]
