"""ScenarioBatch: stacked per-scenario protocol knobs for the fleet.

A :class:`ScenarioSpec` is ONE scenario's configuration in host terms
(seconds, probabilities, seeds).  :meth:`ScenarioBatch.build` validates
S of them against a shared compile-key base (``SimParams`` /
``CompressedParams`` + ``TimeConfig`` + optional ``FaultPlan``
structure) and stacks the data axes into a ``[S]``-leaved
:class:`~sidecar_tpu.ops.knobs.RoundKnobs` pytree plus per-scenario
PRNG keys — the input the vmapped drivers (``fleet/engine.py``)
consume.

The compile-key / data-axis split (ops/knobs.py): ``fanout``,
``budget``, ``n``, ``services_per_node``, ``cache_lines`` and the
FaultPlan *structure* shape the program and must be batch-uniform —
a spec that disagrees is rejected HERE with a named error
(``sim/scenarios.validate_protocol_config``), not 400 rounds into a
compiled scan as a shape failure.  Everything else — transmit limit,
loss, cadences, suspicion window, lifespans, churn, fault seed — is
data and varies freely within a batch.

Bit-identity contract (tests/test_fleet.py): scenario *i* of a batch
run is bit-identical to an unbatched run of the matching classic sim —
``scenario_params(i)`` / ``scenario_timecfg(i)`` build exactly that
sim's config, and :func:`restart_churn_perturb` with a static ``prob``
is the unbatched twin of the fleet's knob-driven churn hook.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops.knobs import RoundKnobs
from sidecar_tpu.ops.status import ALIVE, TOMBSTONE, pack, unpack_status, unpack_ts
from sidecar_tpu.sim.scenarios import validate_protocol_config

# TimeConfig fields a spec may override per scenario (all data axes:
# they resolve to tick/round scalars the knobbed round consumes).
_TIMECFG_FIELDS = (
    "push_pull_interval_s", "sweep_interval_s", "refresh_interval_s",
    "suspicion_window_s", "alive_lifespan_s", "draining_lifespan_s",
    "tombstone_lifespan_s", "future_fudge_s", "origin_budget",
    "origin_quarantine",
)

# _TIMECFG_FIELDS entries where any negative value means "knob off"
# (exempt from the >= 0 validation below).
_SIGNED_TIMECFG_FIELDS = ("future_fudge_s", "origin_budget",
                          "origin_quarantine")


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """One scenario of a fleet batch, in host units.

    ``None`` means "inherit the batch base".  ``fanout``/``budget`` may
    be stated for self-documentation but MUST match the batch's static
    params (compile-key axes — ``fleet/grid.py`` groups grid points by
    them so a mixed grid still sweeps them, across batches)."""

    name: str
    seed: int = 0
    retransmit_limit: Optional[int] = None   # None/0 = params rule
    drop_prob: Optional[float] = None
    churn_prob: float = 0.0          # exact family: per-round restart churn
    fault_seed: Optional[int] = None  # chaos: per-scenario FaultPlan seed
    fanout: Optional[int] = None     # compile-key; must match the batch
    budget: Optional[int] = None     # compile-key; must match the batch
    topology: Optional[str] = None   # compile-key; ops/topology.from_name
    mint_frac: float = 0.0           # compressed: initial churn burst
    mint_tick: int = 10
    push_pull_interval_s: Optional[float] = None
    sweep_interval_s: Optional[float] = None
    refresh_interval_s: Optional[float] = None
    suspicion_window_s: Optional[float] = None
    alive_lifespan_s: Optional[float] = None
    draining_lifespan_s: Optional[float] = None
    tombstone_lifespan_s: Optional[float] = None
    future_fudge_s: Optional[float] = None   # negative = bound disabled
    origin_budget: Optional[int] = None      # negative = budget disabled
    origin_quarantine: Optional[int] = None  # negative = quarantine off
    tick_period: Optional[int] = None        # per-node gossip cadence
    #                                          (rounds between ticks;
    #                                          None/1 = every round)
    tick_phase: Optional[int] = None         # cadence phase offset

    def axes(self) -> dict:
        """The non-default knobs, for report/Pareto tables."""
        out: dict = {}
        for f in dataclasses.fields(self):
            if f.name in ("name",):
                continue
            v = getattr(self, f.name)
            d = f.default
            if v is not None and v != d:
                out[f.name] = v
        return out


@dataclasses.dataclass
class ScenarioBatch:
    """S validated scenarios stacked for one vmapped dispatch."""

    family: str                      # "exact" | "compressed"
    params: Any                      # SimParams | CompressedParams (base)
    timecfg: TimeConfig              # batch base clock
    specs: tuple                     # [S] ScenarioSpec
    knobs: RoundKnobs                # [S]-stacked data axes
    keys: jax.Array                  # [S] per-scenario PRNG keys
    plan: Any = None                 # shared FaultPlan structure, or None
    topology: Optional[str] = None   # batch-uniform overlay name, or None
    #                                  (= complete; ops/topology.from_name)

    @property
    def size(self) -> int:
        return len(self.specs)

    @property
    def has_churn(self) -> bool:
        return any(s.churn_prob > 0 for s in self.specs)

    # -- per-scenario classic configs (the unbatched twins) ---------------

    def scenario_params(self, i: int):
        """The classic static params of scenario ``i`` — the unbatched
        sim the lockstep oracle (and the sequential sweep baseline)
        runs."""
        s = self.specs[i]
        kw = {}
        if s.retransmit_limit is not None:
            kw["retransmit_limit"] = s.retransmit_limit
        if s.drop_prob is not None:
            kw["drop_prob"] = s.drop_prob
        return dataclasses.replace(self.params, **kw)

    def scenario_timecfg(self, i: int) -> TimeConfig:
        s = self.specs[i]
        kw = {f: getattr(s, f) for f in _TIMECFG_FIELDS
              if getattr(s, f) is not None}
        return dataclasses.replace(self.timecfg, **kw)

    def scenario_cadence(self, i: int) -> tuple:
        """Scenario ``i``'s ``(tick_period, tick_phase)`` for the
        unbatched classic twin's constructor (``ExactSim(...,
        tick_period=..., tick_phase=...)``) — ``(1, 0)`` when the spec
        states neither (the pre-cadence program)."""
        s = self.specs[i]
        return (s.tick_period if s.tick_period is not None else 1,
                s.tick_phase if s.tick_phase is not None else 0)

    def scenario_plan(self, i: int):
        """Scenario ``i``'s FaultPlan: the shared structure re-seeded
        with its fault seed."""
        if self.plan is None:
            return None
        s = self.specs[i]
        if s.fault_seed is None:
            return self.plan
        return dataclasses.replace(self.plan, seed=s.fault_seed)

    def mint_slots(self, i: int) -> Optional[np.ndarray]:
        """Compressed family: scenario ``i``'s initial churn-burst slot
        list (None when the spec mints nothing) — deterministic from
        the scenario seed, the ``sim/scenarios._mint_churn`` recipe."""
        s = self.specs[i]
        if s.mint_frac <= 0:
            return None
        m = self.params.m
        count = max(1, int(m * s.mint_frac))
        rng = np.random.default_rng(s.seed)
        return np.sort(rng.choice(m, size=count,
                                  replace=False)).astype(np.int32)

    # -- construction -----------------------------------------------------

    @classmethod
    def build(cls, specs, params, timecfg: TimeConfig = TimeConfig(),
              *, family: str = "exact", plan=None) -> "ScenarioBatch":
        """Validate ``specs`` against the batch statics and stack the
        knobs.  Raises ``ValueError`` naming the offending scenario and
        knob — the registration-time guard the ROADMAP asks for."""
        specs = tuple(specs)
        if not specs:
            raise ValueError("a ScenarioBatch needs at least 1 scenario")
        if family not in ("exact", "compressed"):
            raise ValueError(
                f"family must be 'exact' or 'compressed', got {family!r}")
        if plan is not None and family != "exact":
            raise ValueError(
                "FaultPlan scenarios run on the exact family only "
                "(the chaos plane, sidecar_tpu/chaos/)")

        seen: set = set()
        for s in specs:
            if s.name in seen:
                raise ValueError(
                    f"duplicate scenario name {s.name!r} in batch (two "
                    "scenarios silently shadowing each other would make "
                    "the sweep report the wrong config's numbers)")
            seen.add(s.name)
            # Compile-key axes must match the batch statics.
            if s.fanout is not None and s.fanout != params.fanout:
                raise ValueError(
                    f"{s.name}: fanout={s.fanout} is a compile-key axis "
                    f"and must equal the batch's fanout={params.fanout} "
                    "(it shapes the sampled-peer tensor; sweep it "
                    "ACROSS batches — fleet/grid.py groups by it)")
            if s.budget is not None and s.budget != params.budget:
                raise ValueError(
                    f"{s.name}: budget={s.budget} is a compile-key axis "
                    f"and must equal the batch's budget={params.budget}")
            if s.topology != specs[0].topology:
                raise ValueError(
                    f"{s.name}: topology={s.topology!r} is a compile-key "
                    "axis (it shapes the neighbor tables baked into the "
                    f"round) and must be batch-uniform; this batch is "
                    f"{specs[0].topology!r} — sweep it ACROSS batches "
                    "(fleet/grid.py groups by it)")
            validate_protocol_config(
                params.n, fanout=params.fanout, budget=params.budget,
                retransmit_limit=s.retransmit_limit or 0,
                services_per_node=params.services_per_node, name=s.name)
            for knob in ("drop_prob", "churn_prob", "mint_frac"):
                v = getattr(s, knob)
                if v is not None and not 0.0 <= v <= 1.0:
                    raise ValueError(
                        f"{s.name}: {knob}={v} not in [0, 1]")
            for f in _TIMECFG_FIELDS:
                v = getattr(s, f)
                if f in _SIGNED_TIMECFG_FIELDS:
                    continue  # any negative value means "knob off"
                if v is not None and v < 0:
                    raise ValueError(f"{s.name}: {f}={v} must be >= 0")
            # Cadence axes (docs/pipeline.md): named, typed rejection —
            # a float or zero period would silently stall every node
            # (x % 0) or truncate to a different grid point.
            if s.tick_period is not None and (
                    isinstance(s.tick_period, bool)
                    or not isinstance(s.tick_period, int)
                    or s.tick_period < 1):
                raise ValueError(
                    f"{s.name}: tick_period={s.tick_period!r} must be "
                    "an int >= 1 (rounds between gossip ticks; 1 = "
                    "every round)")
            if s.tick_phase is not None and (
                    isinstance(s.tick_phase, bool)
                    or not isinstance(s.tick_phase, int)
                    or s.tick_phase < 0):
                raise ValueError(
                    f"{s.name}: tick_phase={s.tick_phase!r} must be "
                    "an int >= 0 (cadence phase offset in rounds)")
            if s.fault_seed is not None and plan is None:
                raise ValueError(
                    f"{s.name}: fault_seed={s.fault_seed} needs a "
                    "batch FaultPlan (the seed re-roots the shared "
                    "plan structure)")
            if family == "compressed" and s.churn_prob > 0:
                raise ValueError(
                    f"{s.name}: churn_prob is the exact family's "
                    "restart-churn hook; compressed scenarios churn "
                    "via mint_frac (the initial burst)")
            if family == "exact" and s.mint_frac > 0:
                raise ValueError(
                    f"{s.name}: mint_frac is the compressed family's "
                    "churn burst; exact scenarios churn via churn_prob")

        def stack(fn, dtype):
            return jnp.asarray(np.array([fn(i) for i in
                                         range(len(specs))]), dtype)

        def p_of(i):
            return dataclasses.replace(
                params,
                **({"retransmit_limit": specs[i].retransmit_limit}
                   if specs[i].retransmit_limit is not None else {}))

        def t_of(i):
            s = specs[i]
            kw = {f: getattr(s, f) for f in _TIMECFG_FIELDS
                  if getattr(s, f) is not None}
            return dataclasses.replace(timecfg, **kw)

        recover = getattr(params, "recover_rounds", 1)
        knobs = RoundKnobs(
            limit=stack(lambda i: p_of(i).resolved_retransmit_limit(),
                        np.int32),
            # keep_prob precomputed host-side in double precision — the
            # PRNG bit-identity rule (ops/knobs.py module docstring).
            # A spec without its own drop_prob inherits the BASE
            # params' (matching scenario_params(i), like the
            # retransmit-limit fallback).
            keep_prob=stack(
                lambda i: 1.0 - (specs[i].drop_prob
                                 if specs[i].drop_prob is not None
                                 else params.drop_prob),
                np.float32),
            push_pull_rounds=stack(lambda i: t_of(i).push_pull_rounds,
                                   np.int32),
            sweep_rounds=stack(lambda i: t_of(i).sweep_rounds, np.int32),
            refresh_rounds=stack(lambda i: t_of(i).refresh_rounds,
                                 np.int32),
            recover_rounds=stack(lambda i: recover, np.int32),
            suspicion_window=stack(lambda i: t_of(i).suspicion_window,
                                   np.int32),
            alive_lifespan=stack(lambda i: t_of(i).alive_lifespan,
                                 np.int32),
            draining_lifespan=stack(lambda i: t_of(i).draining_lifespan,
                                    np.int32),
            tombstone_lifespan=stack(
                lambda i: t_of(i).tombstone_lifespan, np.int32),
            stale_ticks=stack(lambda i: t_of(i).stale_ticks, np.int32),
            # -1 = disabled; the traced knob path maps negatives to an
            # always-pass MAX_TICK bound (RoundKnobs.future_arg).
            future_ticks=stack(
                lambda i: (-1 if t_of(i).future_ticks is None
                           else t_of(i).future_ticks), np.int32),
            tomb_budget=stack(
                lambda i: (-1 if t_of(i).tomb_budget is None
                           else t_of(i).tomb_budget), np.int32),
            quarantine_threshold=stack(
                lambda i: (-1 if t_of(i).quarantine_threshold is None
                           else t_of(i).quarantine_threshold), np.int32),
            churn_prob=stack(lambda i: specs[i].churn_prob, np.float32),
            fault_seed=stack(
                lambda i: (specs[i].fault_seed
                           if specs[i].fault_seed is not None
                           else (plan.seed if plan is not None else 0)),
                np.int32),
            # Always stacked (every RoundKnobs field is a vmapped data
            # leaf): at period 1 the compiled cadence gate maps nothing
            # — value-identical to the unbatched pre-cadence program
            # (ops/knobs.RoundKnobs.cadence_enabled).
            tick_period=stack(
                lambda i: (specs[i].tick_period
                           if specs[i].tick_period is not None else 1),
                np.int32),
            tick_phase=stack(
                lambda i: (specs[i].tick_phase
                           if specs[i].tick_phase is not None else 0),
                np.int32),
        )
        keys = jnp.stack([jax.random.PRNGKey(s.seed) for s in specs])
        return cls(family=family, params=params, timecfg=timecfg,
                   specs=specs, knobs=knobs, keys=keys, plan=plan,
                   topology=specs[0].topology)


def restart_churn_perturb(params, prob: Optional[float] = None):
    """The config3-shaped restart churn as a perturb hook: each round a
    Bernoulli subset of live slots restarts — the old instance
    tombstoned by its owner half the time, else re-announced ALIVE.

    With ``prob=None`` the hook is knob-aware (``wants_knobs``): the
    per-round probability comes from ``kn.churn_prob`` — the fleet's
    per-scenario churn axis.  With a static ``prob`` it is the
    unbatched twin (bit-identical draw: the probability reaches the
    Bernoulli without arithmetic on either path)."""
    spn = params.services_per_node

    def perturb(state, key, now, kn=None):
        churn_p = prob if prob is not None else kn.churn_prob
        owner = jnp.arange(params.m, dtype=jnp.int32) // spn
        cols = jnp.arange(params.m, dtype=jnp.int32)
        churn = jax.random.bernoulli(key, churn_p, (params.m,))
        own = state.known[owner, cols]
        flip = churn & (unpack_ts(own) > 0) & state.node_alive[owner]
        st = unpack_status(own)
        new_status = jnp.where(st == ALIVE, TOMBSTONE, ALIVE)
        new_val = jnp.where(flip, pack(now, new_status), own)
        known = state.known.at[owner, cols].set(new_val)
        reset_rows = jnp.where(flip, owner, params.n)
        sent = state.sent.at[reset_rows, cols].set(jnp.int8(0),
                                                   mode="drop")
        return dataclasses.replace(state, known=known, sent=sent)

    perturb.wants_knobs = prob is None
    return perturb
