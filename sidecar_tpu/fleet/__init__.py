"""Batched scenario-fleet engine: S independent scenarios in ONE
compiled scan (docs/sweep.md).

* :mod:`batch`  — ``ScenarioSpec`` / ``ScenarioBatch``: per-scenario
  protocol knobs validated and stacked into a vmappable pytree.
* :mod:`engine` — ``FleetSim``: the vmapped round drivers on the exact
  (plain + FaultPlan) and compressed families, with converged-mask
  early exit and per-scenario convergence curves + trace summaries.
* :mod:`grid`   — axis-spec expansion into ``ScenarioBatch``es (grids
  larger than one batch are chunked; compile-key axes group), and the
  Pareto-front helper behind ``POST /sweep``.
"""

from sidecar_tpu.fleet.batch import (  # noqa: F401
    ScenarioBatch,
    ScenarioSpec,
    restart_churn_perturb,
)
from sidecar_tpu.fleet.engine import FleetRun, FleetSim  # noqa: F401
from sidecar_tpu.fleet.grid import (  # noqa: F401
    ParetoFront,
    build_batches,
    expand_grid,
    pareto_front,
)
