"""Grid expansion → ScenarioBatches, and the Pareto-front helper.

A grid spec is a dict of axis name → list of values (the ``POST
/sweep`` wire form).  Axes split exactly as ops/knobs.py splits the
parameter space:

* **data axes** (vary within a batch): ``retransmit_limit``,
  ``drop_prob``, ``churn_prob``, ``mint_frac``, ``fault_seed``,
  ``seed``, ``tick_period``/``tick_phase`` (the per-node gossip
  cadence, docs/pipeline.md), and the per-scenario TimeConfig overrides
  (``push_pull_interval_s``, ``sweep_interval_s``,
  ``refresh_interval_s``, ``suspicion_window_s``,
  ``alive_lifespan_s``, ``draining_lifespan_s``,
  ``tombstone_lifespan_s``, ``future_fudge_s``, ``origin_budget``,
  ``origin_quarantine``);
* **compile-key axes** (group into separate batches, each its own
  compiled program): ``fanout``, ``budget``, ``topology``
  (an ``ops/topology.from_name`` overlay name — the neighbor tables
  are baked into the compiled round).

Grids larger than one batch are chunked at
``SIDECAR_TPU_FLEET_MAX_BATCH`` scenarios (default 64) — the chunk
boundary is invisible to results (scenarios are independent), it only
bounds one dispatch's memory footprint.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
from typing import Optional

from sidecar_tpu.fleet.batch import ScenarioBatch, ScenarioSpec
from sidecar_tpu.models.timecfg import TimeConfig

_DATA_AXES = (
    "seed", "retransmit_limit", "drop_prob", "churn_prob", "mint_frac",
    "fault_seed", "push_pull_interval_s", "sweep_interval_s",
    "refresh_interval_s", "suspicion_window_s", "alive_lifespan_s",
    "draining_lifespan_s", "tombstone_lifespan_s", "future_fudge_s",
    "origin_budget", "origin_quarantine", "tick_period", "tick_phase",
)
_STATIC_AXES = ("fanout", "budget", "topology")
KNOWN_AXES = _DATA_AXES + _STATIC_AXES

DEFAULT_MAX_BATCH = 64


def max_batch_size() -> int:
    """The per-dispatch scenario cap (``SIDECAR_TPU_FLEET_MAX_BATCH``,
    default 64) — bounds one batch's stacked-state footprint; larger
    grids chunk across dispatches."""
    try:
        v = int(os.environ.get("SIDECAR_TPU_FLEET_MAX_BATCH",
                               str(DEFAULT_MAX_BATCH)))
    except ValueError:
        return DEFAULT_MAX_BATCH
    return max(1, v)


def expand_grid(axes: dict, base: Optional[dict] = None) -> list:
    """Cartesian-expand a grid spec into ``ScenarioSpec``s.

    ``axes`` maps axis names (:data:`KNOWN_AXES`) to value lists;
    ``base`` supplies fixed spec fields every point shares.  Unknown
    axis names are rejected loudly (a typoed knob silently sweeping
    nothing would report the base config 64 times)."""
    base = dict(base or {})
    bad = [k for k in axes if k not in KNOWN_AXES]
    if bad:
        raise ValueError(
            f"unknown grid axis(es) {sorted(bad)}; expected a subset of "
            f"{sorted(KNOWN_AXES)}")
    bad = [k for k in base if k not in KNOWN_AXES]
    if bad:
        raise ValueError(
            f"unknown base field(s) {sorted(bad)}; expected a subset of "
            f"{sorted(KNOWN_AXES)}")
    for k, vs in axes.items():
        if not isinstance(vs, (list, tuple)) or not vs:
            raise ValueError(
                f"grid axis {k!r} must be a non-empty list of values, "
                f"got {vs!r}")
    names = sorted(axes)
    specs = []
    for i, combo in enumerate(itertools.product(
            *(axes[k] for k in names))):
        kw = dict(base)
        kw.update(dict(zip(names, combo)))
        tag = "-".join(f"{k}={v}" for k, v in zip(names, combo))
        specs.append(ScenarioSpec(name=f"pt{i:03d}" + (f"-{tag}"
                                                       if tag else ""),
                                  **kw))
    return specs


def build_batches(specs, params, timecfg: TimeConfig = TimeConfig(),
                  *, family: str = "exact", plan=None,
                  max_batch: Optional[int] = None) -> list:
    """Group specs by their compile-key axes, chunk each group at the
    batch cap, and build validated ``ScenarioBatch``es.

    Returns ``[(batch, indices)]`` where ``indices`` maps each batch
    scenario back to its position in ``specs`` (so a chunked sweep
    reassembles one flat result table)."""
    specs = list(specs)
    cap = max_batch or max_batch_size()
    groups: dict = {}
    for idx, s in enumerate(specs):
        key = (s.fanout if s.fanout is not None else params.fanout,
               s.budget if s.budget is not None else params.budget,
               s.topology if s.topology is not None else "")
        groups.setdefault(key, []).append(idx)
    out = []
    for (fanout, budget, _topology), idxs in sorted(groups.items()):
        p = dataclasses.replace(params, fanout=fanout, budget=budget)
        for lo in range(0, len(idxs), cap):
            chunk = idxs[lo:lo + cap]
            batch = ScenarioBatch.build(
                [specs[i] for i in chunk], p, timecfg, family=family,
                plan=plan)
            out.append((batch, chunk))
    return out


class ParetoFront(list):
    """The front indices, PLUS the rows the front refused to consider.

    Behaves exactly like the plain ``list`` of non-dominated indices it
    always was (existing callers index/iterate it unchanged), with one
    extra attribute: ``excluded`` — the indices of rows dropped before
    domination testing because a key was ``None`` (never converged
    within the horizon).  The repo's no-silent-caps rule: a sweep that
    quietly discards half its grid reads as "these are the trade-offs"
    when it should read "half your configs never reached ε"."""

    def __init__(self, front=(), excluded=()):
        super().__init__(front)
        self.excluded = tuple(excluded)


def pareto_front(rows: list, *, keys=("rounds_to_eps",
                                      "exchange_bytes")) -> ParetoFront:
    """Indices of the non-dominated rows, minimizing every key (the
    convergence-time-vs-bytes trade the capacity planner reads).
    Rows with a ``None`` key (never converged within the horizon) are
    excluded from the front outright: a config that never reaches ε is
    not a capacity-planning candidate however cheap its wire bytes.
    They are NOT silently dropped — the returned :class:`ParetoFront`
    counts them in its ``excluded`` tuple and the table still lists
    them, flagged by their ``None``."""
    def val(row, k):
        v = row.get(k)
        return float("inf") if v is None else float(v)

    front, excluded = [], []
    for i, a in enumerate(rows):
        av = [val(a, k) for k in keys]
        if any(v == float("inf") for v in av):
            excluded.append(i)
            continue
        dominated = False
        for j, b in enumerate(rows):
            if i == j:
                continue
            bv = [val(b, k) for k in keys]
            if all(x <= y for x, y in zip(bv, av)) and \
                    any(x < y for x, y in zip(bv, av)):
                dominated = True
                break
        if not dominated:
            front.append(i)
    return ParetoFront(front, excluded)
