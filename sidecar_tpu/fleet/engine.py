"""FleetSim — the vmapped scenario-fleet drivers (docs/sweep.md).

One compiled scan runs S *independent* scenarios: the classic round
(``ExactSim._step`` / ``ChaosExactSim._step`` / ``CompressedSim._step``
— literally those functions, knob-parameterized via ops/knobs.py) is
``jax.vmap``-ed over (stacked state, per-scenario key, stacked knobs),
so a parameter search that used to be S traces + S compiles + S
dispatches becomes ONE of each.

Early exit (the converged-mask contract): a per-scenario ``live`` mask
freezes finished scenarios — their state, curve, round count, and
byte accounting stop advancing (a ``select`` per leaf; under vmap the
per-scenario work itself still executes, as any batched ``lax.cond``
does) — and once EVERY scenario has crossed, a batch-level ``lax.cond``
skips whole round bodies, which is where the tail's compute actually
drops.  ``stop=False`` disables freezing entirely: the run is then
bit-identical, per scenario, to S unbatched runs (the lockstep oracle,
tests/test_fleet.py).

Scenario-axis sharding: pass ``mesh=fleet_mesh(sd, nd)`` to lay the
stacked batch over a ``("scenario", "node")`` device mesh — scenario
parallelism is embarrassingly data-parallel (GSPMD never communicates
across it); the node axis composes on the exact family the all_gather
way (GSPMD inserts the gathers the sharded twins issue explicitly).
The ring / all_to_all exchange modes remain single-scenario features
of ``sidecar_tpu/parallel`` — see docs/sweep.md.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sidecar_tpu import metrics
from sidecar_tpu.fleet.batch import ScenarioBatch, restart_churn_perturb
from sidecar_tpu.models.exact import clone_state
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops import provenance as prov_ops
from sidecar_tpu.ops import trace as trace_ops
from sidecar_tpu.ops.kernels import eligible_lines
from sidecar_tpu.ops.topology import Topology, complete, from_name


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class FleetStats:
    """Per-scenario summary accumulators riding the scan carry — the
    fleet's round-trace summary (the flight recorder's census columns,
    folded instead of streamed: S full ``RoundTrace`` buffers would be
    S × cap × width of carry for numbers the sweep only needs
    aggregated)."""

    rounds: jax.Array        # int32 [S] — rounds actually executed
    eps_round: jax.Array     # int32 [S] — first round conv >= 1-eps (-1)
    exchange_bytes: jax.Array  # float32 [S] — analytic offer bytes
    frontier_max: jax.Array  # int32 [S] — sender-frontier high water
    # Record-level provenance (ops/provenance.py), fleet-shaped: the
    # sweep only needs lag CDFs, so the fleet carries first_seen (the
    # exact part of the trace) and skips parent attribution — channel
    # replay under vmap would re-derive S × per-family streams for a
    # column no sweep consumer reads.
    prov_ref: jax.Array      # int32 [S, T] traced packed-key threshold
    first_seen: jax.Array    # int32 [S, T, N] absolute round; -1


def _zero_stats(s: int, t: int, n: int) -> FleetStats:
    return FleetStats(rounds=jnp.zeros((s,), jnp.int32),
                      eps_round=jnp.full((s,), -1, jnp.int32),
                      exchange_bytes=jnp.zeros((s,), jnp.float32),
                      frontier_max=jnp.zeros((s,), jnp.int32),
                      prov_ref=jnp.zeros((s, t), jnp.int32),
                      first_seen=jnp.full((s, t, n), -1, jnp.int32))


def _select_scen(live, new_tree, old_tree):
    """Per-leaf scenario select: leaf[i] advances only while live[i]."""
    def sel(new_leaf, old_leaf):
        m = live.reshape(live.shape + (1,) * (new_leaf.ndim - 1))
        return jnp.where(m, new_leaf, old_leaf)
    return jax.tree_util.tree_map(sel, new_tree, old_tree)


@dataclasses.dataclass
class FleetRun:
    """Host-side result of one fleet dispatch."""

    names: list
    convergence: np.ndarray       # [R // conv_every, S]
    rounds: np.ndarray            # [S] executed rounds
    eps_round: list               # [S] Optional[int]
    exchange_bytes: np.ndarray    # [S] analytic offer bytes (to freeze)
    frontier_max: np.ndarray      # [S]
    conv_every: int
    wall_seconds: float
    scenarios_per_sec: float
    final_states: object = None   # stacked states (oracle / chaining)
    tracked: tuple = ()           # traced slots (ops/provenance.py)
    first_seen: np.ndarray = None  # [S, T, N] absolute rounds; -1
    # Host-side caches for digest_agreement (fetched once, lazily).
    _final_known: np.ndarray = None
    _final_alive: np.ndarray = None

    def lag_summary(self, i: int):
        """Scenario ``i``'s pooled per-record lag CDF, or None when the
        run traced nothing."""
        if not self.tracked:
            return None
        from sidecar_tpu.ops import provenance as prov_ops
        return prov_ops.pooled_lag(self.first_seen[i])

    def digest_agreement(self, i: int) -> Optional[float]:
        """Scenario ``i``'s end-state coherence: the fraction of alive
        nodes whose catalog digest (ops/digest.py NumPy oracle over the
        final belief board) matches the modal digest — 1.0 iff every
        alive node holds a bit-identical catalog, the same agreement
        statistic the live CoherenceMonitor publishes."""
        st = self.final_states
        if st is None:
            return None
        from sidecar_tpu.ops import digest as digest_ops
        if self._final_known is None:
            self._final_known = np.asarray(jax.device_get(st.known))
            self._final_alive = np.asarray(
                jax.device_get(st.node_alive))
        rows = self._final_known[i][self._final_alive[i]]
        if not len(rows):
            return None
        digs = digest_ops.node_digests_np(
            rows, digest_ops.default_idents(rows.shape[1]))
        counts: dict = {}
        for d in digs:
            k = d.tobytes()
            counts[k] = counts.get(k, 0) + 1
        return max(counts.values()) / len(rows)

    def table(self, round_ticks: int, ticks_per_second: int) -> list:
        """Per-scenario rows for the /sweep Pareto table."""
        out = []
        for i, name in enumerate(self.names):
            er = self.eps_round[i]
            lag = self.lag_summary(i)
            out.append({
                "name": name,
                "rounds_to_eps": er,
                "seconds_to_eps": (er * round_ticks / ticks_per_second
                                   if er is not None else None),
                "exchange_bytes": int(self.exchange_bytes[i]),
                "frontier_max": int(self.frontier_max[i]),
                "rounds_run": int(self.rounds[i]),
                "final_convergence": float(self.convergence[-1, i])
                if len(self.convergence) else None,
                "p99_lag_rounds": None if lag is None else lag["p99"],
                "digest_agreement": self.digest_agreement(i),
            })
        return out


def fleet_mesh(scenario_devices: int, node_devices: int = 1,
               devices=None):
    """A ``("scenario", "node")`` device mesh for the sharded fleet."""
    devs = list(devices if devices is not None else jax.devices())
    need = scenario_devices * node_devices
    if len(devs) < need:
        raise ValueError(
            f"fleet mesh needs {need} devices "
            f"({scenario_devices}x{node_devices}), have {len(devs)}")
    arr = np.array(devs[:need]).reshape(scenario_devices, node_devices)
    from jax.sharding import Mesh
    return Mesh(arr, ("scenario", "node"))


FLEET_MESH_ENV = "SIDECAR_TPU_FLEET_MESH"


def resolve_fleet_mesh(mesh=None):
    """Explicit mesh wins; else ``SIDECAR_TPU_FLEET_MESH`` ("S" or
    "SxN" — scenario×node device counts) builds one; unset → single
    device."""
    if mesh is not None:
        return mesh
    v = os.environ.get(FLEET_MESH_ENV, "").strip().lower()
    if not v:
        return None
    parts = v.split("x")
    try:
        sd = int(parts[0])
        nd = int(parts[1]) if len(parts) > 1 else 1
    except ValueError:
        raise ValueError(
            f"{FLEET_MESH_ENV}={v!r}: expected 'S' or 'SxN' device "
            "counts (e.g. '4' or '4x2')")
    return fleet_mesh(sd, nd)


class FleetSim:
    """S scenarios of one :class:`ScenarioBatch` in one compiled scan."""

    def __init__(self, batch: ScenarioBatch,
                 topo: Optional[Topology] = None, mesh=None):
        self.batch = batch
        self.mesh = mesh = resolve_fleet_mesh(mesh)
        p = batch.params
        if topo is None:
            # The batch's compile-key overlay name (fleet/grid.py groups
            # grid points by it); None/"" = the complete graph.
            batch_topo = getattr(batch, "topology", None)
            topo = (from_name(batch_topo, p.n) if batch_topo
                    else complete(p.n))
        perturb = None
        if batch.has_churn:
            perturb = restart_churn_perturb(p)   # knob-driven churn
        if batch.family == "exact":
            if batch.plan is not None:
                from sidecar_tpu.chaos.sim_inject import ChaosExactSim
                self.sim = ChaosExactSim(p, topo, batch.timecfg,
                                         plan=batch.plan,
                                         perturb=perturb)
            else:
                from sidecar_tpu.models.exact import ExactSim
                self.sim = ExactSim(p, topo, batch.timecfg,
                                    perturb=perturb)
        else:
            from sidecar_tpu.models.compressed import CompressedSim
            self.sim = CompressedSim(p, topo, batch.timecfg)
            # The fleet round must stay a pure-XLA program: a traced
            # per-scenario transmit limit cannot enter a Pallas kernel
            # signature.  The XLA twins are bit-identical by the kernel
            # parity contract (docs/kernels.md), so lockstep vs a
            # Pallas-pathed unbatched sim still holds.
            self.sim._kernels, self.sim._kernels_interpret = "xla", False
            self.sim._fused_gather = False
        if mesh is not None:
            sd, nd = mesh.devices.shape
            if batch.size % sd:
                raise ValueError(
                    f"batch size {batch.size} must divide the scenario "
                    f"mesh axis ({sd})")
            if nd > 1 and batch.family != "exact":
                raise ValueError(
                    "the node mesh axis composes on the exact family "
                    "only (compressed state is not node-major on every "
                    "leaf); use node_devices=1")
            if nd > 1 and p.n % nd:
                raise ValueError(
                    f"n={p.n} must divide the node mesh axis ({nd})")

    # -- state construction -------------------------------------------------

    def init_states(self):
        """Stacked per-scenario initial states ([S] on every leaf):
        cold start on the exact family, converged-boot + per-scenario
        mint burst on the compressed family."""
        b = self.batch
        parts = []
        for i in range(b.size):
            st = self.sim.init_state()
            slots = b.mint_slots(i) if b.family == "compressed" else None
            if slots is not None:
                st = self.sim.mint(st, slots, b.specs[i].mint_tick)
            parts.append(st)
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *parts)
        return self._place(stacked)

    def _place(self, tree):
        """Lay a stacked pytree over the fleet mesh: axis 0 over
        ``scenario``; on the exact family, node-major second axes over
        ``node``."""
        if self.mesh is None:
            return tree
        from jax.sharding import NamedSharding, PartitionSpec as P
        n, s = self.batch.params.n, self.batch.size
        nd = self.mesh.devices.shape[1]

        def put(leaf):
            if leaf.ndim >= 1 and leaf.shape[0] == s:
                if nd > 1 and leaf.ndim >= 2 and leaf.shape[1] == n:
                    spec = P("scenario", "node")
                else:
                    spec = P("scenario")
            else:
                spec = P()
            return jax.device_put(leaf, NamedSharding(self.mesh, spec))
        return jax.tree_util.tree_map(put, tree)

    # -- per-scenario probes (run under vmap) -------------------------------

    def _offer_census(self, st, kn):
        """(sender frontier, analytic exchange bytes) from the
        PRE-round eligibility — the flight recorder's census
        (ops/trace.py), per scenario."""
        p = self.batch.params
        if self.batch.family == "exact":
            sim_st = st.sim if hasattr(st, "sim") else st
            elig = gossip_ops.eligible_records(sim_st.known, sim_st.sent,
                                               kn.limit)
            budget = min(p.budget, p.m)
        else:
            elig = eligible_lines(st.cache_slot, st.cache_sent, kn.limit)
            budget = min(p.budget, p.cache_lines)
        return trace_ops.offer_census(elig, budget, p.fanout)

    # -- drivers ------------------------------------------------------------
    # The fleet scan drivers: donate the stacked state (the
    # check_jit_entrypoints donate-or-waiver contract extends to the
    # fleet plane — tests/test_jit_entrypoints.py pins both are seen).

    def _scan_body(self, keys, knobs, conv_every, eps, stop, tracked):
        """The shared round body: ``conv_every`` vmapped rounds under
        the batch-level skip cond, then one convergence sample with
        crossing detection."""
        step_v = jax.vmap(lambda st, k, kn: self.sim._step(st, k, kn=kn))
        conv_v = jax.vmap(self.sim.convergence)
        census_v = jax.vmap(self._offer_census)
        fold_v = jax.vmap(jax.random.fold_in)
        tr = jnp.asarray(tracked, jnp.int32)
        belief_v = jax.vmap(lambda st: self.sim._prov_belief(st, tr))

        def inner(carry, _):
            states, live, fs = carry

            def active(args):
                states, live, fs = args
                frontier, xbytes = census_v(states, knobs)
                keys_r = fold_v(keys, states.round_idx)
                nxt = step_v(states, keys_r, knobs)
                states = _select_scen(live, nxt, states)
                first_seen = fs.first_seen
                if tracked:
                    # Frozen scenarios kept their old state above, so
                    # they produce no new holders — no live gate needed.
                    hold = prov_ops.holders_batch(
                        fs.prov_ref, belief_v(states))     # [S, N, T]
                    newly = jnp.swapaxes(hold, 1, 2) & (first_seen < 0)
                    first_seen = jnp.where(
                        newly, states.round_idx[:, None, None],
                        first_seen)
                live_i = live.astype(jnp.int32)
                fs = FleetStats(
                    rounds=fs.rounds + live_i,
                    eps_round=fs.eps_round,
                    exchange_bytes=fs.exchange_bytes
                    + jnp.where(live, xbytes.astype(jnp.float32), 0.0),
                    frontier_max=jnp.maximum(
                        fs.frontier_max, jnp.where(live, frontier, 0)),
                    prov_ref=fs.prov_ref,
                    first_seen=first_seen)
                return states, live, fs

            # The whole-batch skip: once every scenario crossed, the
            # remaining rounds compile to a no-op branch — the actual
            # tail saving (a PER-scenario cond under vmap would still
            # execute both branches).
            return lax.cond(jnp.any(live), active, lambda a: a,
                            carry), None

        def body(carry, _):
            carry, _ = lax.scan(inner, carry, None, length=conv_every)
            states, live, fs = carry
            conv = conv_v(states)
            crossed = live & (conv >= 1.0 - eps) & (fs.eps_round < 0)
            fs = dataclasses.replace(
                fs, eps_round=jnp.where(crossed, fs.rounds,
                                        fs.eps_round))
            if stop:
                live = live & (conv < 1.0 - eps)
            return (states, live, fs), conv

        return body

    def _seed_stats(self, states, tracked) -> FleetStats:
        """Zero stats, with the provenance plane seeded: per scenario,
        pin the traced refs to the freshest current keys and mark the
        origin holders (ops/provenance.seed, fleet-shaped)."""
        fs = _zero_stats(self.batch.size, len(tracked),
                         self.batch.params.n)
        if not tracked:
            return fs
        tr = jnp.asarray(tracked, jnp.int32)
        belief0 = jax.vmap(
            lambda st: self.sim._prov_belief(st, tr))(states)
        ref = jnp.max(belief0, axis=1).astype(jnp.int32)    # [S, T]
        hold0 = prov_ops.holders_batch(ref, belief0)
        return dataclasses.replace(
            fs, prov_ref=ref,
            first_seen=jnp.where(jnp.swapaxes(hold0, 1, 2),
                                 states.round_idx[:, None, None],
                                 fs.first_seen))

    @functools.partial(jax.jit,
                       static_argnums=(0, 4, 5, 6, 7, 8),
                       donate_argnums=1)
    def _run_conv_fleet_jit(self, states, keys, knobs, num_rounds,
                            conv_every, eps, stop, tracked):
        body = self._scan_body(keys, knobs, conv_every, eps, stop,
                               tracked)
        s = self.batch.size
        (final, live, fs), conv = lax.scan(
            body, (states, jnp.ones((s,), bool),
                   self._seed_stats(states, tracked)), None,
            length=num_rounds // conv_every)
        return final, conv, fs

    @functools.partial(jax.jit,
                       static_argnums=(0, 4, 5, 6, 7, 8),
                       donate_argnums=1)
    def _run_fast_fleet_jit(self, states, keys, knobs, num_rounds,
                            conv_every, eps, stop, tracked):
        # The bench path: same body, curve discarded on device.
        body = self._scan_body(keys, knobs, conv_every, eps, stop,
                               tracked)
        s = self.batch.size

        def drop_curve(carry, _):
            carry, _ = body(carry, None)
            return carry, None

        (final, live, fs), _ = lax.scan(
            drop_curve, (states, jnp.ones((s,), bool),
                         self._seed_stats(states, tracked)),
            None, length=num_rounds // conv_every)
        return final, fs

    # -- public API ---------------------------------------------------------

    def run(self, states, num_rounds: int, conv_every: int = 1,
            eps: float = 0.01, stop: bool = False, donate: bool = True,
            curve: bool = True, tracked=None) -> FleetRun:
        """Run every scenario ``num_rounds`` rounds (fewer where the
        converged-mask freezes them, ``stop=True``), sampling the
        per-scenario convergence metric every ``conv_every`` rounds.

        ``stop=False`` (the lockstep contract) runs the full horizon —
        bit-identical per scenario to unbatched runs; ``eps`` still
        only sets where ``eps_round`` is recorded.

        ``tracked`` (static tuple of service slots) turns on the
        record-level provenance plane: per-scenario ``first_seen``
        rides the carry and the run's table gains the pooled
        ``p99_lag_rounds`` column (ops/provenance.py)."""
        b = self.batch
        tracked = tuple(int(x) for x in tracked) if tracked else ()
        for slot in tracked:
            if not 0 <= slot < b.params.m:
                raise ValueError(
                    f"tracked slot {slot} outside [0, {b.params.m})")
        if num_rounds % conv_every:
            raise ValueError(
                f"num_rounds={num_rounds} not divisible by "
                f"conv_every={conv_every}")
        start = int(np.max(np.asarray(
            jax.device_get(states.round_idx))))
        b.timecfg.validate_horizon(start + num_rounds)
        if not donate:
            states = clone_state(states)
        t0 = time.perf_counter()
        if curve:
            final, conv, fs = self._run_conv_fleet_jit(
                states, b.keys, b.knobs, num_rounds, conv_every,
                float(eps), bool(stop), tracked)
        else:
            final, fs = self._run_fast_fleet_jit(
                states, b.keys, b.knobs, num_rounds, conv_every,
                float(eps), bool(stop), tracked)
            conv = jnp.zeros((0, b.size), jnp.float32)
        jax.block_until_ready(fs.rounds)
        wall = time.perf_counter() - t0

        rounds = np.asarray(jax.device_get(fs.rounds))
        eps_round = [int(r) if r >= 0 else None
                     for r in np.asarray(jax.device_get(fs.eps_round))]
        metrics.incr("fleet.batches")
        metrics.incr("fleet.scenarios", b.size)
        metrics.incr("fleet.rounds", int(rounds.sum()))
        metrics.incr("fleet.rounds_saved",
                     int(b.size * num_rounds - rounds.sum()))
        if b.plan is not None:
            # Chaos fleet: publish the batch's injection totals the way
            # the classic chaos drivers do (fault pressure is never
            # silent).
            for name, field in (
                    ("chaos.sim.droppedPackets", "injected_drops"),
                    ("chaos.sim.delayedPackets", "injected_delays"),
                    ("chaos.sim.duplicatedPackets", "injected_dups")):
                total = int(np.asarray(
                    jax.device_get(getattr(final, field))).sum())
                if total:
                    metrics.incr(name, total)
        return FleetRun(
            names=[s.name for s in b.specs],
            convergence=np.asarray(jax.device_get(conv)),
            rounds=rounds,
            eps_round=eps_round,
            exchange_bytes=np.asarray(jax.device_get(fs.exchange_bytes)),
            frontier_max=np.asarray(jax.device_get(fs.frontier_max)),
            conv_every=conv_every,
            wall_seconds=wall,
            scenarios_per_sec=b.size / wall if wall > 0 else 0.0,
            final_states=final,
            tracked=tracked,
            first_seen=np.asarray(jax.device_get(fs.first_seen)),
        )
