"""Measure the LIVE node against the reference's footprint claims.

The reference's README row this answers: a Sidecar node runs in
**< 20 MB resident** with a "few execution threads"
(/root/reference/README.md:54-56).  The repo's live half is Python
orchestrating a C++ gossip engine, so the honest comparison needs both
the absolute numbers and the breakdown:

* **RSS per node process** — absolute, plus the Python-interpreter
  baseline (this image's ``sitecustomize`` imports JAX into every
  interpreter, so a do-nothing ``python -c pass`` process already
  carries tens of MB that have nothing to do with the node).  The
  framework's own working set is the delta.
* **Gossip packets/sec in+out** — from the native engine's counters
  (``engine.udpIn``/``udpOut``, /api/metrics.json) over a steady-state
  window at the reference protocol constants (200 ms gossip interval,
  push-pull on, static discovery announcing real services).
* **Merge latency** — the ``addServiceEntry`` timer (the reference
  instruments the same hot path with MeasureSince,
  services_state.go:294).
* **Thread count** — /proc Threads (the "few execution threads" row).
* **Churn phase** — SIGKILL one node, wait for SWIM detection and the
  tombstone storm (ExpireServer 10×, services_state.go:150-192), and
  verify the survivors tombstone the dead node's services.

Run: ``python benchmarks/live_node.py [nodes] [spn] [steady_seconds]``
(defaults 3 nodes x 10 services, 30 s).  Prints one JSON document.
Wants a quiet host — CPU contention skews the latency numbers.

``LIVE_NODE_NO_SITE=1`` runs every node under ``python -S`` — no
``site``/``sitecustomize``, hence no JAX import — which reproduces the
shipped container environment (docker/Dockerfile deliberately excludes
JAX): the RSS measured in this mode is the number comparable to the
reference's < 20 MB claim, measured on THIS host rather than inside a
container the bench host cannot run.
"""

import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = pathlib.Path(__file__).resolve().parent.parent

BASE_GOSSIP = 18700   # bind ports BASE..BASE+n-1
BASE_HTTP = 18760
NO_SITE = os.environ.get("LIVE_NODE_NO_SITE") == "1"


def make_static_fixture(tmpdir: str, spn: int) -> str:
    """A static.json with ``spn`` services (the per-node service load;
    shape of fixtures/static.json)."""
    doc = [{
        "Service": {
            "Name": f"bench-svc-{i}",
            "Image": f"example/bench:{i}",
            "Ports": [{"Type": "tcp", "Port": 21000 + i,
                       "ServicePort": 9000 + i}],
            "ProxyMode": "http",
        },
        "Check": {"Type": "AlwaysSuccessful", "Args": ""},
    } for i in range(spn)]
    path = os.path.join(tmpdir, "static.json")
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path


def spawn_node(i: int, static_file: str, tmpdir: str) -> subprocess.Popen:
    env = dict(os.environ,
               SIDECAR_DISCOVERY="static",
               STATIC_CONFIG_FILE=static_file,
               SIDECAR_ADVERTISE_IP="127.0.0.1",
               HAPROXY_DISABLE="true",
               ENVOY_USE_GRPC_API="false",
               SIDECAR_BIND_PORT=str(BASE_GOSSIP + i),
               SIDECAR_CLUSTER_NAME="bench")
    if i > 0:
        env["SIDECAR_SEEDS"] = f"127.0.0.1:{BASE_GOSSIP}"
    log = open(os.path.join(tmpdir, f"node-{i}.log"), "w")
    interp = [sys.executable] + (["-S"] if NO_SITE else [])
    return subprocess.Popen(
        interp + ["-m", "sidecar_tpu.main",
                  "--http-port", str(BASE_HTTP + i),
                  "--hostname", f"bench-{i}"],
        cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT)


def fetch_json(port: int, path: str):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return json.load(resp)


def proc_status(pid: int) -> dict:
    out = {}
    with open(f"/proc/{pid}/status") as fh:
        for line in fh:
            key, _, val = line.partition(":")
            out[key] = val.strip()
    return out


def rss_mb(pid: int) -> float:
    return int(proc_status(pid)["VmRSS"].split()[0]) / 1024.0


def interpreter_baseline() -> tuple[float, int]:
    """(RSS MB, thread count) of a do-nothing interpreter in this
    environment — whatever sitecustomize drags in (JAX here) charges
    every Python process before a single line of the framework runs."""
    interp = [sys.executable] + (["-S"] if NO_SITE else [])
    probe = subprocess.Popen(interp + ["-c",
                             "import time; time.sleep(30)"])
    try:
        time.sleep(3.0)
        st = proc_status(probe.pid)
        return rss_mb(probe.pid), int(st["Threads"])
    finally:
        probe.kill()
        probe.wait()


def engine_rates(port: int):
    m = fetch_json(port, "/api/metrics.json")
    g, t = m["gauges"], m["timers"]
    entry = t.get("addServiceEntry", {"count": 0, "total_ms": 0.0})
    return {
        "udp_in": g.get("engine.udpIn", 0),
        "udp_out": g.get("engine.udpOut", 0),
        "udp_bytes_in": g.get("engine.udpBytesIn", 0),
        "udp_bytes_out": g.get("engine.udpBytesOut", 0),
        "merge_count": entry["count"],
        "merge_total_ms": entry["total_ms"],
    }


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    spn = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    steady = float(sys.argv[3]) if len(sys.argv) > 3 else 30.0

    tmpdir = tempfile.mkdtemp(prefix="live-node-bench-")
    static_file = make_static_fixture(tmpdir, spn)
    procs = []
    try:
        procs.append(spawn_node(0, static_file, tmpdir))
        time.sleep(2.5)                     # let the seed bind first
        for i in range(1, n):
            procs.append(spawn_node(i, static_file, tmpdir))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                view = fetch_json(BASE_HTTP, "/api/state.json")
                if len(view["Servers"]) == n:
                    break
            except OSError:
                pass
            time.sleep(1.0)
        else:
            raise SystemExit(
                f"cluster never converged to {n} members "
                f"(logs in {tmpdir})")

        # -- steady state at protocol rate --------------------------------
        t0 = {i: engine_rates(BASE_HTTP + i) for i in range(n)}
        start = time.monotonic()
        time.sleep(steady)
        elapsed = time.monotonic() - start
        t1 = {i: engine_rates(BASE_HTTP + i) for i in range(n)}

        baseline, baseline_threads = interpreter_baseline()
        per_node = []
        for i, proc in enumerate(procs):
            st = proc_status(proc.pid)
            d0, d1 = t0[i], t1[i]
            merges = d1["merge_count"] - d0["merge_count"]
            merge_ms = d1["merge_total_ms"] - d0["merge_total_ms"]
            per_node.append({
                "node": f"bench-{i}",
                "rss_mb": round(rss_mb(proc.pid), 1),
                "threads": int(st["Threads"]),
                "pkts_in_per_s": round(
                    (d1["udp_in"] - d0["udp_in"]) / elapsed, 1),
                "pkts_out_per_s": round(
                    (d1["udp_out"] - d0["udp_out"]) / elapsed, 1),
                "bytes_out_per_s": round(
                    (d1["udp_bytes_out"] - d0["udp_bytes_out"]) / elapsed),
                "merges_per_s": round(merges / elapsed, 1),
                "merge_mean_ms": round(merge_ms / merges, 3)
                if merges else None,
            })

        # -- churn: kill the last node, survivors must tombstone it -------
        victim = procs[-1]
        victim.send_signal(signal.SIGKILL)
        victim.wait()
        c0 = engine_rates(BASE_HTTP)
        churn_start = time.monotonic()
        tombstoned = False
        while time.monotonic() - churn_start < 30:
            view = fetch_json(BASE_HTTP, "/api/state.json")
            dead = view["Servers"].get(f"bench-{n - 1}", {})
            svcs = dead.get("Services", {})
            if svcs and all(s["Status"] == 1 for s in svcs.values()):
                tombstoned = True
                break
            time.sleep(0.5)
        churn_elapsed = time.monotonic() - churn_start
        c1 = engine_rates(BASE_HTTP)
        churn_merges = c1["merge_count"] - c0["merge_count"]
        churn_ms = c1["merge_total_ms"] - c0["merge_total_ms"]

        print(json.dumps({
            "config": {"nodes": n, "services_per_node": spn,
                       "steady_seconds": steady,
                       "gossip_interval_ms": 200,
                       "interpreter": ("python -S (container-"
                                       "equivalent: no sitecustomize, "
                                       "no JAX)" if NO_SITE
                                       else "python (bench host: "
                                       "sitecustomize imports JAX)")},
            "interpreter_baseline_rss_mb": round(baseline, 1),
            "interpreter_baseline_threads": baseline_threads,
            "per_node": per_node,
            "framework_rss_mb_minus_baseline": [
                round(p["rss_mb"] - baseline, 1) for p in per_node],
            "churn": {
                "victim_tombstoned_on_survivor": tombstoned,
                "seconds_to_tombstones": round(churn_elapsed, 1),
                "merges": churn_merges,
                "merge_mean_ms": round(churn_ms / churn_merges, 3)
                if churn_merges else None,
            },
            "reference_rows": {
                "rss": "< 20 MB resident (README.md:55-56)",
                "threads": "a few execution threads (README.md:54-56)",
            },
        }, indent=2))
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    main()
