"""Robustness under chaos: false-positive tombstone evictions and
proxy-config churn, suspicion ON vs OFF — the bench `robustness` block.

The scenario is the config6 chaos shape (docs/chaos.md) at expiry
scale: 20% asymmetric A→B loss for the whole run plus staggered PAUSE
windows on side-A nodes, with protocol clocks tightened so refresh
expiry actually happens inside the run (refresh 4 s, alive lifespan
6 s, sweep 0.4 s, push-pull 1 s at the standard 200 ms round — the
refresh DUE rate must stay under the per-message budget, see
``_measure``).  A pause is the Lifeguard motivating fault: the node is
healthy but silent — every tombstone minted for (or by) it is a FALSE
POSITIVE.

Two identical ChaosExactSim runs differ ONLY in
``TimeConfig.suspicion_window_s`` (0 vs the window), same FaultPlan
seed, same driver seed.  Per round, host-side numpy diffs of the
carried state count:

* ``fp_tombstones`` — belief cells ENTERING tombstone status whose
  owner is a live cluster member (base ``node_alive``; a fault-plan
  pause deliberately does NOT clear it — the service never truly left)
  — the same definition as the flight recorder's ``fp_tombstones``
  column (ops/trace.py; tests/test_suspicion.py pins the two equal);
* ``proxy_churn`` — alive↔not-alive flips in the OBSERVER node's row:
  each flip is a routing change an Envoy/HAProxy attached to that node
  would reload on;
* ``damping`` — the observer's flips replayed through the live
  :class:`~sidecar_tpu.catalog.damping.FlapDamper` (the host half of
  the subprotocol) on the simulated clock: flap count + how many
  services end damped out of routing.

``rounds_to_eps`` (convergence ≥ 1 − ε) is reported for both runs so
the headline ratio is read at comparable convergence — suspicion must
not buy robustness by simply converging slower.

The ``clock_skew`` sub-block (:func:`run_skew`) swaps the pause
windows for a clock-skew pair — one node rushing minutes ahead, one
the same amount behind (``ClockFault``) — and runs the
future-admission bound (``TimeConfig.future_fudge_s``,
ops/merge.admit_gate) OFF vs ON: bound off, the rushing node's
future-stamped records and tombstones win every LWW merge and cannot
be refuted until real time catches up; bound on, receivers reject
them at admission and convergence matches the no-skew baseline.

Run standalone: ``python benchmarks/robustness.py [n]`` — prints the
JSON block bench.py embeds (BENCH_ROBUSTNESS=0 skips it there;
BENCH_ROBUSTNESS_SKEW=0 skips just the skew sub-block).
"""

from __future__ import annotations

import json
import pathlib
import sys

if __name__ == "__main__":  # standalone: resolve the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def robustness_plan(n: int, seed: int = 6, pause_len: int = 35,
                    pause_stagger: int = 45, pauses: int = 3):
    """The config6-seeded chaos shape at expiry scale: persistent 20%
    A→B loss plus ``pauses`` staggered pause windows marching over
    side-A node groups (each longer than the alive lifespan, so every
    pause forces expiry decisions cluster-wide)."""
    from sidecar_tpu.chaos import EdgeFault, FaultPlan, NodeFault

    side_a = tuple(range(n // 2))
    side_b = tuple(range(n // 2, n))
    group = max(1, n // 16)
    node_faults = []
    for i in range(pauses):
        start = 30 + i * pause_stagger
        nodes = tuple(range(i * group, (i + 1) * group))
        node_faults.append(NodeFault(nodes=nodes, start_round=start,
                                     end_round=start + pause_len,
                                     kind="pause"))
    return FaultPlan(
        seed=seed,
        edges=(EdgeFault(src=side_a, dst=side_b, drop_prob=0.2),),
        nodes=tuple(node_faults),
    )


def skew_plan(n: int, rush_ticks: int, slow_ticks: int,
              start_round: int = 10, end_round: int = 300,
              seed: int = 6):
    """Config6-style loss plus a clock-skew pair: one RUSHING node
    stamping ``rush_ticks`` in the future and one SLOW node
    ``slow_ticks`` behind, both inside a bounded window (the fault
    "ends" when NTP fixes the clock) — the docs/chaos.md skew
    methodology.  With both skews 0 the plan has no clock entries (the
    no-skew baseline compiles the pre-skew round).

    The rushing skew must stay under ``alive_lifespan − refresh``:
    past it, the rushing node's own TTL sweep expires every record it
    sees and mints tombstones at *original ts + 1 s* (the ops/ttl.py
    +1 s rule) — HONEST stamps the future bound rightly admits, a
    separate pathology the suspicion plane owns (docs/chaos.md)."""
    from sidecar_tpu.chaos import ClockFault, EdgeFault, FaultPlan

    side_a = tuple(range(n // 2))
    side_b = tuple(range(n // 2, n))
    clocks = ()
    if rush_ticks or slow_ticks:
        clocks = (
            ClockFault(nodes=(n - 1,), start_round=start_round,
                       end_round=end_round, offset_ticks=rush_ticks),
            ClockFault(nodes=(n - 2,), start_round=start_round,
                       end_round=end_round, offset_ticks=-slow_ticks),
        )
    return FaultPlan(
        seed=seed,
        edges=(EdgeFault(src=side_a, dst=side_b, drop_prob=0.2),),
        clocks=clocks,
    )


def _phase_lag(sim, rounds: int, seed: int, tracers: int = 8,
               origin_nodes=()) -> dict:
    """Per-phase propagation-lag summary (ops/provenance.py): the
    phase's EXACT trajectory re-run under the record-level tracer (the
    scan folds the same per-round keys as the measurement loop, so the
    traced run is bit-identical) and reduced to the pooled per-record
    lag percentiles in rounds.  One jitted scan — cheap next to the
    phase's per-round host loop.

    With ``origin_nodes`` (the faulted/skewed set), one record per
    origin is force-tracked and the summary gains a ``blast_radius``
    block: how much of the cluster each origin's record reached, and
    via which paths (docs/telemetry.md)."""
    import jax
    import numpy as np

    from sidecar_tpu.ops import provenance as prov_ops

    spn = sim.p.services_per_node
    tracked = set(prov_ops.default_tracked(sim.p.m, tracers))
    tracked.update(int(node) * spn for node in origin_nodes)
    tracked = tuple(sorted(tracked))
    _final, pv, _conv = sim.run_with_provenance(
        sim.init_state(), jax.random.PRNGKey(seed), rounds, tracked)
    lag = prov_ops.pooled_lag(
        np.asarray(jax.device_get(pv.first_seen)))
    lag["tracers"] = len(tracked)
    lag["seconds_per_round"] = \
        sim.t.round_ticks / sim.t.ticks_per_second
    if origin_nodes:
        lag["blast_radius"] = prov_ops.blast_radius(
            pv, tracked, spn, origin_nodes)
    return lag


def _measure_skew(n: int, spn: int, rounds: int, rush_s: float,
                  slow_s: float, future_fudge_s: float, eps: float,
                  seed: int) -> dict:
    """One skew run: the loss backdrop plus the rushing/slow pair,
    measured for the poisoning the future-admission bound exists to
    stop.

    * ``poisoned_rows_final`` — cells in HONEST nodes' tables whose
      stamp is ahead of the true clock at the end of the run.  Bound
      off, the rushing node's future refresh stamps win every LWW
      merge and out-stamp any refutation or tombstone until real time
      catches up (a minute away — steady poison); bound on they are
      rejected at admission and the count is zero.
    * ``slow_fp_tombstones_final`` — the slow node's services sitting
      TOMBSTONE in honest tables at the end.  While skewed, the slow
      node's re-announces carry ancient stamps, so receivers expire
      its services (the suspicion window, not the bound, is the
      defense on this side — docs/chaos.md).
    * ``fp_tombstones`` — every tombstone minted is a false positive
      here (no process ever stops; the only faults are loss + clocks).

    The two skewed nodes' own tables are excluded from the poison
    count: the bound protects the CLUSTER from a bad clock, not the
    bad-clock node from itself."""
    import jax
    import numpy as np

    from sidecar_tpu.chaos import ChaosExactSim
    from sidecar_tpu.models.exact import SimParams
    from sidecar_tpu.models.timecfg import TimeConfig
    from sidecar_tpu.ops import topology
    from sidecar_tpu.ops.status import TOMBSTONE

    # Refresh-scale clocks with a LONG alive lifespan: the rushing
    # skew must stay under alive_lifespan − refresh_interval or the
    # rushing node's own sweep tombstone-storms the cluster with
    # honest (+1 s rule) stamps — the pathology the suspicion plane
    # owns, which would drown the future-stamp poison this block
    # isolates (see skew_plan).  The slow node's kill chain DOES run
    # inside the fault window (the rushing node's skewed sweep expires
    # the mute slow node's records around round 140): the minted
    # tombstones carry ts+1 s stamps that are FUTURE relative to the
    # slow node's floored clock, so with the bound on the slow node
    # rejects its own eviction, keeps announcing, and resurrects when
    # NTP fixes its clock — with the bound off it admits the tombstone
    # into its own row and (tombstones are never refreshed) stays dead.
    cfg = TimeConfig(refresh_interval_s=4.0, alive_lifespan_s=80.0,
                     sweep_interval_s=0.4, push_pull_interval_s=1.0,
                     suspicion_window_s=6.0,
                     future_fudge_s=future_fudge_s)
    params = SimParams(n=n, services_per_node=spn, fanout=3, budget=15)
    skewed = bool(rush_s or slow_s)
    sim = ChaosExactSim(params, topology.complete(n), cfg,
                        plan=skew_plan(n, cfg.ticks(rush_s),
                                       cfg.ticks(slow_s)))
    cst = sim.init_state()
    key = jax.random.PRNGKey(seed)

    owner = np.arange(params.m) // spn
    honest = np.ones(n, dtype=bool)
    if skewed:
        honest[[n - 1, n - 2]] = False

    def status_of(row):
        known = (row >> 3) > 0
        return np.where(known, row & 7, -1)

    prev_known = np.asarray(cst.sim.known)
    fp_total = 0
    eps_round = None
    conv = 0.0
    conv_tail = []

    for r in range(rounds):
        cst = sim.step(cst, jax.random.fold_in(key, cst.sim.round_idx))
        known = np.asarray(cst.sim.known)
        alive = np.asarray(cst.sim.node_alive)
        st = status_of(known)
        prev_st = status_of(prev_known)
        entered = (st == TOMBSTONE) & (prev_st != TOMBSTONE)
        fp_total += int((entered & alive[owner][None, :]).sum())
        prev_known = known
        conv = float(sim.convergence(cst))
        if r >= (3 * rounds) // 4:
            conv_tail.append(conv)
        if eps_round is None and conv >= 1.0 - eps:
            eps_round = r + 1

    now_tick = int(cst.sim.round_idx) * cfg.round_ticks
    ts = known >> 3
    poisoned = int(((ts > now_tick) & honest[:, None]).sum())
    slow_tomb = 0
    if skewed:
        slow_cols = owner == (n - 2)
        slow_tomb = int(((st == TOMBSTONE) & slow_cols[None, :]
                         & honest[:, None]).sum())

    return {
        "rush_s": rush_s,
        "slow_s": slow_s,
        "future_fudge_s": future_fudge_s,
        "poisoned_rows_final": poisoned,
        "slow_fp_tombstones_final": slow_tomb,
        "fp_tombstones": fp_total,
        "rejected_future": sim.injection_counts(cst)["rejected_future"],
        "rounds_to_eps": eps_round,
        "final_convergence": round(conv, 6),
        "mean_tail_convergence": round(
            sum(conv_tail) / max(len(conv_tail), 1), 6),
        # Per-phase record-level lag (satellites the totals above:
        # the skew headlines used to report poison/tombstone COUNTS
        # only — this says how much the skew slowed actual spread),
        # with blast-radius accounting for the two skewed origins.
        "round_trace": _phase_lag(
            sim, rounds, seed,
            origin_nodes=(n - 1, n - 2) if skewed else ()),
    }


def run_skew(n: int = 128, spn: int = 2, rounds: int = 400,
             rush_s: float = 60.0, slow_s: float = 120.0,
             future_fudge_s: float = 0.5, eps: float = 0.2,
             seed: int = 6) -> dict:
    """The bench ``robustness.clock_skew`` block: one rushing node at
    +``rush_s`` and one slow node at −``slow_s`` under config6-style
    loss, future-admission bound OFF vs ON, plus the no-skew baseline
    the matched-convergence claim is read against.

    The default fudge is 0.5 s — deliberately UNDER the ttl sweep's
    +1 s supersede bump: a tombstone minted for a mute node's record
    is stamped ``last_stamp + 1 s``, so a behind-clock node (floored
    near its last stamp or below) sees its own premature eviction at
    least ~1 s in its future and rejects it; a fudge over 1 s would
    let the eviction into the node's own row, where it is permanent
    (tombstones are never refreshed).  Legitimate traffic is stamped
    at or before the receiver's present, so any non-negative fudge
    admits it (docs/chaos.md)."""
    from sidecar_tpu import metrics

    baseline = _measure_skew(n, spn, rounds, 0.0, 0.0, -1.0, eps, seed)
    off = _measure_skew(n, spn, rounds, rush_s, slow_s, -1.0, eps, seed)
    on = _measure_skew(n, spn, rounds, rush_s, slow_s, future_fudge_s,
                       eps, seed)

    metrics.incr("clock.sim.rejectedFuture", on["rejected_future"])

    return {
        "scenario": "config6-style 20%% A->B loss + clock-skew pair "
                    "(+%.0fs rushing / -%.0fs slow, rounds [10, 300)) "
                    "(docs/chaos.md)" % (rush_s, slow_s),
        "n": n,
        "rounds": rounds,
        "baseline_no_skew": baseline,
        "bound_off": off,
        "bound_on": on,
    }


def _measure(n: int, spn: int, rounds: int, suspicion_window_s: float,
             eps: float, seed: int, damping_threshold: float,
             damping_half_life_s: float) -> dict:
    import jax
    import numpy as np

    from sidecar_tpu.catalog.damping import FlapDamper, TransitionReplay
    from sidecar_tpu.chaos import ChaosExactSim
    from sidecar_tpu.models.exact import SimParams
    from sidecar_tpu.models.timecfg import TimeConfig
    from sidecar_tpu.ops import topology
    from sidecar_tpu.ops.status import (
        ALIVE,
        SUSPECT,
        TOMBSTONE,
    )

    # Expiry-scale clocks: refresh must actually lapse inside the run,
    # but the refresh DUE rate (m / refresh_rounds per round) must stay
    # under the per-message budget or the steady-state agreement is
    # backlog-bound and the on/off runs stop being comparable.
    cfg = TimeConfig(refresh_interval_s=4.0, alive_lifespan_s=6.0,
                     sweep_interval_s=0.4, push_pull_interval_s=1.0,
                     suspicion_window_s=suspicion_window_s)
    params = SimParams(n=n, services_per_node=spn, fanout=3, budget=15)
    sim = ChaosExactSim(params, topology.complete(n), cfg,
                        plan=robustness_plan(n))
    cst = sim.init_state()
    key = jax.random.PRNGKey(seed)

    owner = np.arange(params.m) // spn
    tick_ns = 1_000_000
    clock = [0]
    damper = FlapDamper(half_life_s=damping_half_life_s,
                        threshold=damping_threshold,
                        now_fn=lambda: clock[0])
    # ONE replay-rule definition (SUSPECT quarantine invisible,
    # discovery not a flap) shared with the bridge's damping prediction
    # and the cross-validation tests: catalog/damping.TransitionReplay.
    replay = TransitionReplay(damper)

    def status_of(row):
        known = (row >> 3) > 0
        return np.where(known, row & 7, -1)

    prev_known = np.asarray(cst.sim.known)
    prev_obs = status_of(prev_known[0])
    fp_total = 0
    churn_total = 0
    suspects_max = 0
    eps_round = None
    conv = 0.0
    conv_tail = []

    for r in range(rounds):
        cst = sim.step(cst, jax.random.fold_in(key, cst.sim.round_idx))
        known = np.asarray(cst.sim.known)
        alive = np.asarray(cst.sim.node_alive)
        st = status_of(known)
        prev_st = status_of(prev_known)
        entered = (st == TOMBSTONE) & (prev_st != TOMBSTONE)
        fp_total += int((entered & alive[owner][None, :]).sum())
        suspects_max = max(suspects_max, int((st == SUSPECT).sum()))

        obs = st[0]
        clock[0] = (r + 1) * cfg.round_ticks * tick_ns
        # SUSPECT is quarantine, not a routing state; first sight of a
        # record is DISCOVERY, not a flap — both rules live in
        # TransitionReplay, which mirrors the live catalog (it never
        # materializes SUSPECT).  Observer churn = flaps the replay
        # counted this round.
        was_alive = prev_obs == ALIVE
        is_alive = obs == ALIVE
        moved = (was_alive != is_alive) & (obs != SUSPECT) \
            & (prev_obs != SUSPECT) & (prev_obs >= 0)
        churn_total += int(moved.sum())
        for slot in np.nonzero(obs >= 0)[0]:
            replay.see(f"node{owner[slot]}", f"slot{slot}",
                       int(obs[slot]), clock[0])
        prev_obs = np.where(obs == SUSPECT, prev_obs, obs)
        prev_known = known

        conv = float(sim.convergence(cst))
        if r >= (3 * rounds) // 4:
            conv_tail.append(conv)
        if eps_round is None and conv >= 1.0 - eps:
            eps_round = r + 1

    return {
        "suspicion_window_s": suspicion_window_s,
        "fp_tombstones": fp_total,
        "proxy_churn_observer": churn_total,
        "suspects_max": suspects_max,
        "flaps_observed": sum(replay.flaps.values()),
        "services_damped": len(damper.damped()),
        "rounds_to_eps": eps_round,
        "final_convergence": round(conv, 6),
        # With refresh LIVE (it must be — refresh is the refutation
        # mechanism) the agreement metric equilibrates at the
        # refresh-propagation steady state rather than reaching 1.0
        # (the bench.py faithful-run note); the matched-convergence
        # comparison therefore reads the TAIL MEAN, which the two runs
        # must agree on for the fp/churn ratios to be meaningful.
        "mean_tail_convergence": round(
            sum(conv_tail) / max(len(conv_tail), 1), 6),
        # Per-phase record-level lag: the suspicion headlines used to
        # report fp/churn totals only — this adds how fast records
        # actually spread under each knob setting.
        "round_trace": _phase_lag(sim, rounds, seed),
    }


def run_robustness(n: int = 128, spn: int = 2, rounds: int = 200,
                   suspicion_window_s: float = 6.0, eps: float = 0.2,
                   seed: int = 6, damping_threshold: float = 2.0,
                   damping_half_life_s: float = 40.0) -> dict:
    """The bench `robustness` block: the config6-seeded chaos run with
    suspicion+damping OFF vs ON, and the headline ratios."""
    from sidecar_tpu import metrics

    off = _measure(n, spn, rounds, 0.0, eps, seed,
                   damping_threshold, damping_half_life_s)
    on = _measure(n, spn, rounds, suspicion_window_s, eps, seed,
                  damping_threshold, damping_half_life_s)

    def ratio(a, b):
        if b == 0:
            return None if a == 0 else float("inf")
        return round(a / b, 2)

    # Observable counters (docs/metrics.md): the suspicion plane's
    # false-positive pressure must never be silent.
    metrics.incr("suspicion.fp_tombstones", on["fp_tombstones"])
    metrics.set_gauge("suspicion.suspects_max", on["suspects_max"])

    block = {
        "scenario": "config6-seeded: 20% A->B loss + staggered pause "
                    "windows, expiry-scale clocks (docs/chaos.md)",
        "n": n,
        "rounds": rounds,
        "suspicion_off": off,
        "suspicion_on": on,
        "fp_tombstone_reduction": ratio(off["fp_tombstones"],
                                        on["fp_tombstones"]),
        "proxy_churn_reduction": ratio(off["proxy_churn_observer"],
                                       on["proxy_churn_observer"]),
    }
    # Convergence-SLO verdict over the suspicion-ON phase's lag
    # (telemetry/slo.py; BENCH_SLO=0 skips, BENCH_SLO_RULES overrides
    # the rule set — docs/env.md).
    from sidecar_tpu.telemetry.slo import SloEvaluator

    evaluator = SloEvaluator.from_env()
    if evaluator is not None:
        lag = on["round_trace"]
        block["slo"] = evaluator.evaluate_lag(
            lag, seconds_per_round=lag.get("seconds_per_round"))
    return block


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    block = run_robustness(n=n)
    block["clock_skew"] = run_skew(n=n)
    print(json.dumps(block, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
