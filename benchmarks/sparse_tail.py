"""The sparse-frontier scaling claim, pinned: per-round cost tracks the
FRONTIER (C), not the cluster (N).

Two sweeps over the compressed model, dense vs sparse round on the SAME
tail-shaped trajectory (a small churn burst on a converged floor —
exactly the regime the convergence tail lives in, docs/sparse.md):

* **N-sweep** (small burst → the TAIL regime: by the timed window the
  wave has drained and the frontier is small/empty): the dense round's
  ms/round grows with N (O(N·K) publish + O(N·F·K) merge every round);
  the sparse round's stays ~flat (O(C·K) work + an O(N·K) elementwise
  mask reduce — the residual N term is one cheap pass, visible as a
  shallow slope).
* **burst-sweep** (large bursts → the WAVE regime: mid-epidemic the
  frontier is the whole cluster): the sparse step's overflow→dense
  fallback fires every round and must cost ≈ the dense round (the
  safety half of the contract — a mispredicted sparse chunk never
  cliffs).

Run:  python benchmarks/sparse_tail.py [--rounds 30] [--reps 3]
      [--ns 2048,4096,8192] [--bursts 32,128,512]

Prints one JSON object per cell (n, burst, dense_ms, sparse_ms,
frontier_hwm, overflow_rounds) and a FINAL summary line.  CPU-budget
numbers are what tier-dev machines produce; the RESULTS.md round-8
section carries the recorded set.
"""

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import numpy as np

from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops.topology import erdos_renyi

# Refresh pinned out (the north-star tail protocol shape): the only
# traffic is the burst draining.
CFG = TimeConfig(refresh_interval_s=10_000.0, push_pull_interval_s=4.0)
CACHE_LINES = 64
SPARSE_CAP = 1024          # static across the N sweep — the point


def build(n, sparse_cap=SPARSE_CAP):
    params = CompressedParams(n=n, services_per_node=4, fanout=3,
                              budget=8, cache_lines=CACHE_LINES,
                              deep_sweep_every=0,
                              sparse_cap=sparse_cap)
    return CompressedSim(params, erdos_renyi(n, avg_degree=8.0, seed=3),
                         CFG)


def burst_state(sim, burst, seed=7):
    rng = np.random.default_rng(seed)
    slots = np.sort(rng.choice(sim.p.m, size=burst,
                               replace=False)).astype(np.int32)
    return sim.mint(sim.init_state(), slots, 10)


def time_rounds(sim, state, rounds, reps, sparse):
    """ms/round, warmed and chained through the donating driver (the
    round_phases.py measurement shape); returns (ms, stats)."""
    key = jax.random.PRNGKey(0)
    state = sim.run_fast(state, key, rounds, sparse=sparse)
    jax.device_get(state.round_idx)
    best = float("inf")
    stats = None
    for _ in range(reps):
        t0 = time.perf_counter()
        state = sim.run_fast(state, key, rounds, sparse=sparse)
        jax.device_get(state.round_idx)
        took = time.perf_counter() - t0
        if took < best:
            # Stats of the SAME rep whose time is reported, so the cell
            # is self-consistent (overflow_rounds <= rounds).
            best = took
            if sim.last_sparse_stats is not None:
                stats = np.asarray(jax.device_get(sim.last_sparse_stats))
    return best / rounds * 1000.0, stats


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=30)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--ns", default="2048,4096,8192")
    ap.add_argument("--bursts", default="32,128,512")
    opts = ap.parse_args()
    ns = [int(x) for x in opts.ns.split(",")]
    bursts = [int(x) for x in opts.bursts.split(",")]

    cells = []

    def run_cell(n, burst):
        sim = build(n)
        dense_ms, _ = time_rounds(sim, burst_state(sim, burst),
                                  opts.rounds, opts.reps, sparse=False)
        sparse_ms, stats = time_rounds(sim, burst_state(sim, burst),
                                       opts.rounds, opts.reps,
                                       sparse=True)
        cell = {"n": n, "burst": burst,
                "dense_ms_per_round": round(dense_ms, 3),
                "sparse_ms_per_round": round(sparse_ms, 3),
                "speedup": round(dense_ms / max(sparse_ms, 1e-9), 2),
                "frontier_hwm": int(stats[2]),
                "overflow_rounds": int(stats[1])}
        cells.append(cell)
        print(json.dumps(cell), flush=True)

    # N-sweep at the smallest burst: dense grows, sparse ~flat.
    for n in ns:
        run_cell(n, bursts[0])
    # burst-sweep at the largest N: sparse follows the frontier.
    for burst in bursts[1:]:
        run_cell(ns[-1], burst)

    n_cells = [c for c in cells if c["burst"] == bursts[0]]
    print("FINAL " + json.dumps({
        "platform": jax.devices()[0].platform,
        "rounds_per_scan": opts.rounds,
        "cache_lines": CACHE_LINES,
        "sparse_cap": SPARSE_CAP,
        "dense_ms_vs_n": {c["n"]: c["dense_ms_per_round"]
                          for c in n_cells},
        "sparse_ms_vs_n": {c["n"]: c["sparse_ms_per_round"]
                           for c in n_cells},
        "cells": cells,
    }))


if __name__ == "__main__":
    main()
