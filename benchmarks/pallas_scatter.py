"""Pallas scatter-merge: is the dense model's XLA scatter floor real?

The dense exact model's round is bound by two full-tensor scatters
(``known`` 671 MB + ``sent`` 168 MB rewritten per round, models/
exact.py); ``benchmarks/scatter_costs.py`` showed every XLA scatter
formulation costs the same ~13 ms at these shapes.  SURVEY.md §7 named
a hand-written Pallas kernel as the remaining escape hatch; this
experiment runs it, bounding the question from both sides:

1. **The bandwidth floor** — a full-buffer elementwise pass.  No
   in-place merge kernel can beat this: at the dense model's update
   density (~225k random rows over 4,096) every 8-row tile is dirty,
   so the whole buffer streams through the chip regardless of indexing.
2. **Pallas RMW ceiling** — the same full-buffer max-merge as a Pallas
   kernel with ``input_output_aliases`` (zero index work): what a
   PERFECT index-applying kernel could at best approach.
3. **The real thing** — a Pallas scatter-apply kernel.  Mosaic imposed
   the shape of this thing: scalar stores to VMEM don't exist (each
   update is a masked (8, 1024)-lane segment RMW), dynamic lane bases
   must be provably 1024-aligned, and dynamic scalar loads from VMEM
   don't lower — so updates are pre-bucketed DENSELY per row block
   ([num_blocks, U_max], zero-padded; a val-0 update never wins a max)
   and each grid step receives its own bucket as an SMEM block.  The
   bucketing itself (sort + gather) runs inside the measured region —
   it's part of what the kernel costs the model per round.
4. **The XLA baseline** — ``known.at[rows, cols].max(vals)`` exactly
   as the model issues it.

Run: ``python benchmarks/pallas_scatter.py [n] [spn]`` (default
4096×10, the headline dense shape).  Prints one JSON line; the dense
model only changes if (3) beats (4) meaningfully — and either way the
"no formulation escapes the scatter floor" claim becomes a measured
statement.

Timing uses the chained-loop recipe (LOOP iterations inside one
dispatch): on the tunneled chip a single dispatch is dominated by the
~100 ms host↔device round-trip.
"""

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROWS_PER_BLOCK = 8
# Mosaic can only prove alignment of a dynamic lane base at the block's
# internal tiling granularity (1024 at these shapes).
LANES = 1024
LOOP = 20


# -- the scatter-apply kernel ------------------------------------------------

def _scatter_kernel(rows_ref, cols_ref, vals_ref, known_ref, out_ref,
                    *, u_max):
    """Apply this row block's (dense, zero-padded) update bucket."""
    i = pl.program_id(0)
    out_ref[:, :] = known_ref[:, :]

    def body(j, _):
        r = rows_ref[0, 0, j] - i * ROWS_PER_BLOCK
        c = cols_ref[0, 0, j]
        v = vals_ref[0, 0, j]
        # No scalar VMEM stores on TPU: RMW the aligned (8, LANES)
        # segment containing the element, selected by a 2D mask.  A
        # padding update (v == 0) never advances a packed key.
        base = pl.multiple_of((c // LANES) * LANES, LANES)
        seg = out_ref[:, pl.ds(base, LANES)]
        row = jax.lax.broadcasted_iota(
            jnp.int32, (ROWS_PER_BLOCK, LANES), 0)
        lane = jax.lax.broadcasted_iota(
            jnp.int32, (ROWS_PER_BLOCK, LANES), 1) + base
        seg = jnp.where((row == r) & (lane == c),
                        jnp.maximum(seg, v), seg)
        out_ref[:, pl.ds(base, LANES)] = seg
        return 0

    jax.lax.fori_loop(0, u_max, body, 0)


def _bucket_updates(rows, cols, vals, num_blocks, u_max):
    """Dense per-row-block buckets [num_blocks, u_max], zero-padded."""
    block = rows // ROWS_PER_BLOCK
    order = jnp.argsort(block, stable=True)
    rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
    offs = jnp.searchsorted(
        block[order], jnp.arange(num_blocks + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    idx = offs[:num_blocks, None] + jnp.arange(u_max, dtype=jnp.int32)
    valid = idx < offs[1:num_blocks + 1, None]
    idx = jnp.clip(idx, 0, rows.shape[0] - 1)
    # [num_blocks, 1, u_max]: the singleton middle dim satisfies the
    # lowering's last-two-dims block rule for the SMEM specs.
    rb = jnp.where(valid, rows_s[idx], 0)[:, None, :]
    cb = jnp.where(valid, cols_s[idx], 0)[:, None, :]
    vb = jnp.where(valid, vals_s[idx], 0)[:, None, :]
    return rb, cb, vb


def make_pallas_scatter(n, m, u_max):
    num_blocks = n // ROWS_PER_BLOCK

    def apply(known, rows, cols, vals):
        # Carry dependency via an optimization barrier: without it, XLA
        # hoists the loop-invariant bucketing out of the timing loop
        # (LICM), understating the per-round cost the docstring
        # promises to include (in the real model updates change every
        # round).  An arithmetic no-op like `vals + (known[0,0] & 0)`
        # does NOT work — the algebraic simplifier folds it away before
        # LICM runs.
        vals, known = jax.lax.optimization_barrier((vals, known))
        rb, cb, vb = _bucket_updates(rows, cols, vals, num_blocks, u_max)
        smem = functools.partial(pl.BlockSpec, (1, 1, u_max),
                                 lambda i: (i, 0, 0),
                                 memory_space=pltpu.SMEM)
        return pl.pallas_call(
            functools.partial(_scatter_kernel, u_max=u_max),
            grid=(num_blocks,),
            in_specs=[
                smem(), smem(), smem(),
                pl.BlockSpec((ROWS_PER_BLOCK, m), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((ROWS_PER_BLOCK, m),
                                   lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n, m), jnp.int32),
            input_output_aliases={3: 0},
        )(rb, cb, vb, known)

    return apply


# -- comparison points -------------------------------------------------------

def _rmw_kernel(known_ref, other_ref, out_ref):
    out_ref[:, :] = jnp.maximum(known_ref[:, :], other_ref[:, :])


def pallas_rmw_max(known, other):
    n, m = known.shape
    return pl.pallas_call(
        _rmw_kernel,
        grid=(n // ROWS_PER_BLOCK,),
        in_specs=[
            pl.BlockSpec((ROWS_PER_BLOCK, m), lambda i: (i, 0)),
            pl.BlockSpec((ROWS_PER_BLOCK, m), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ROWS_PER_BLOCK, m), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(known.shape, known.dtype),
        input_output_aliases={0: 0},
    )(known, other)


def _time_looped(fn, known, *rest, reps=3):
    @jax.jit
    def looped(k, *r):
        return jax.lax.fori_loop(0, LOOP, lambda i, kk: fn(kk, *r), k)

    out = looped(known, *rest)           # compile + warm
    jax.device_get(out[:1, :1])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = looped(out, *rest)
        jax.device_get(out[:1, :1])
        times.append((time.perf_counter() - t0) / LOOP)
    return float(np.median(times))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    spn = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    m = n * spn
    # The grids/segments assume these; anything else would silently
    # skip tail rows (rmw grid) or overrun the block (lane segments).
    assert n % ROWS_PER_BLOCK == 0, \
        f"n={n} must be a multiple of {ROWS_PER_BLOCK}"
    assert m % LANES == 0 and m >= LANES, \
        f"m={m} must be a positive multiple of {LANES}"
    n_updates = n * 3 * 15 + m            # deliveries + announce batch
    rng = np.random.default_rng(0)

    def fresh_known():
        return jnp.asarray(
            rng.integers(1, 1 << 20, size=(n, m), dtype=np.int32))

    rows_np = rng.integers(0, n, size=n_updates, dtype=np.int32)
    rows = jnp.asarray(rows_np)
    cols = jnp.asarray(rng.integers(0, m, size=n_updates, dtype=np.int32))
    vals = jnp.asarray(
        rng.integers(1, 1 << 22, size=n_updates, dtype=np.int32))
    other = jnp.asarray(
        rng.integers(1, 1 << 20, size=(n, m), dtype=np.int32))

    # Static bucket capacity from the actual data (a model integration
    # would size it once from n_updates/num_blocks + slack).
    counts = np.bincount(rows_np // ROWS_PER_BLOCK,
                         minlength=n // ROWS_PER_BLOCK)
    u_max = int(counts.max())
    pallas_scatter = make_pallas_scatter(n, m, u_max)

    out = {"shape": [n, m], "updates": int(n_updates),
           "buffer_mb": round(n * m * 4 / 1e6, 1),
           "u_max_per_block": u_max}

    # Correctness first: pallas scatter == XLA scatter.
    k0 = np.asarray(fresh_known())
    want = np.asarray(jax.jit(
        lambda k, r, c, v: k.at[r, c].max(v))(
            jnp.asarray(k0), rows, cols, vals))
    try:
        got = np.asarray(pallas_scatter(jnp.asarray(k0), rows, cols,
                                        vals))
        np.testing.assert_array_equal(got, want)
        out["pallas_scatter_correct"] = True
    except Exception as exc:                      # noqa: BLE001
        out["pallas_scatter_correct"] = False
        out["pallas_scatter_error"] = str(exc).split("\n")[0][:200]

    out["elementwise_pass_ms"] = round(
        _time_looped(lambda k: k + 1, fresh_known()) * 1e3, 2)
    out["pallas_rmw_ceiling_ms"] = round(
        _time_looped(pallas_rmw_max, fresh_known(), other) * 1e3, 2)
    out["xla_scatter_ms"] = round(
        _time_looped(lambda k, r, c, v: k.at[r, c].max(v),
                     fresh_known(), rows, cols, vals) * 1e3, 2)
    if out["pallas_scatter_correct"]:
        out["pallas_scatter_ms"] = round(
            _time_looped(pallas_scatter, fresh_known(), rows, cols,
                         vals) * 1e3, 2)
        ratio = out["xla_scatter_ms"] / out["pallas_scatter_ms"]
        out["pallas_vs_xla"] = round(ratio, 2)
        out["verdict"] = (
            "pallas wins — consider wiring into the dense model"
            if ratio > 1.25 else
            "no meaningful win — the scatter floor stands")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
