"""The software-pipelined round, measured: publish of round i+1 issued
inside the same scan tick that folds round i (docs/pipeline.md).

Five rows over the same headline-shaped cluster (the PR-5 bench shape:
ER degree 8, fanout 3), each pinning one half of the tentpole claim:

* **exact** — the headline family lockstep vs pipelined, dense n=4096:
  ms/round, rounds/sec, and ``vs_pr5_headline`` (pipelined rounds/sec ÷
  the 28.1 rounds/sec/chip PR-5 record this PR exists to beat).
* **compressed** — the production family, dense ms/round lockstep vs
  pipelined plus the lockstep sparse-tail reference on the same burst.
  The pipelined carry holds a RAW dense board, so pipeline + sparse
  does not compose (ops/pipeline.py raises); the sparse row is the
  honest alternative the arbiter would dispatch in the tail.
* **convergence** — the cost of one-round-stale publishes: rounds to
  convergence ≥ 1 − ε from a cold start, lockstep vs pipelined, as
  ``rounds_to_eps_ratio`` (pipelined ÷ lockstep; the ISSUE bound is
  ≤ 1.10 — staleness may slow the epidemic, it must not stall it).
* **cadence** — the heterogeneous-tick sweep row: uniform period-1 vs
  mixed per-node periods {1, 2, 4}; ms/round is program-identical (the
  gate is elementwise), the convergence tax is the real cost axis.
* **sharded** — the overlap proof on the multi-chip path: lockstep vs
  pipelined ms/round on the row-sharded compressed family, with
  ``overlap_ms`` = lockstep − pipelined per round (device time the
  pipeline recovered; > 0 is the acceptance bar on TPU meshes) and the
  PR-12 static phase attribution of the PIPELINED step showing publish
  bytes and merge (gather) bytes living in the SAME compiled program.

Run:  python benchmarks/pipeline.py [--nodes 4096] [--rounds 60]
      [--reps 3]

Used by bench.py (``pipeline`` record block, BENCH_PIPELINE=0 skips;
BENCH_PIPELINE_NODES / BENCH_PIPELINE_ROUNDS resize).  Ratios are
number-or-null: a leg that cannot run (e.g. mesh build failure) nulls
its ratio instead of sinking the block (tools/check_bench_schema.py).
"""

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import numpy as np

from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
from sidecar_tpu.models.exact import ExactSim, SimParams
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops.topology import erdos_renyi

# The PR-5 single-chip headline this PR attacks (RESULTS.md round 5):
# dense exact, n=4096, spn=10, fanout 3, budget 15, ER degree 8.
PR5_HEADLINE_RPS = 28.1

# Refresh pinned out, headline anti-entropy cadence — the sparse_tail
# protocol shape, so the tail rows here compare against round 8's.
CFG = TimeConfig(refresh_interval_s=10_000.0, push_pull_interval_s=4.0)


def _build_exact(n, spn, **kw):
    params = SimParams(n=n, services_per_node=spn, fanout=3, budget=15)
    return ExactSim(params, erdos_renyi(n, avg_degree=8.0, seed=3),
                    CFG, **kw)


def _build_compressed(n, spn, cls=CompressedSim, **kw):
    params = CompressedParams(n=n, services_per_node=spn, fanout=3,
                              budget=15, cache_lines=64,
                              deep_sweep_every=0, sparse_cap=1024)
    return cls(params, erdos_renyi(n, avg_degree=8.0, seed=3), CFG,
               **kw)


def _burst_state(sim, burst, seed=7):
    rng = np.random.default_rng(seed)
    slots = np.sort(rng.choice(sim.p.m, size=burst,
                               replace=False)).astype(np.int32)
    return sim.mint(sim.init_state(), slots, 10)


def _sync(state):
    jax.device_get(state.round_idx)


def _time_lockstep(sim, state, rounds, reps, sparse=None):
    """ms/round through the donating lockstep driver, warm-then-best-of
    (the sparse_tail.py measurement shape)."""
    key = jax.random.PRNGKey(0)
    kw = {} if sparse is None else {"sparse": sparse}
    state = sim.run_fast(state, key, rounds, **kw)
    _sync(state)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        state = sim.run_fast(state, key, rounds, **kw)
        _sync(state)
        best = min(best, time.perf_counter() - t0)
    return best / rounds * 1000.0


def _time_pipelined(sim, state, rounds, reps):
    """ms/round through the pipelined driver.  The inflight carry is
    threaded rep to rep so every timed chunk is steady-state pipeline
    (no re-prime inside the timed window — priming is a one-off cost
    the scan amortizes away in production)."""
    key = jax.random.PRNGKey(0)
    state, inflight = sim.run_fast_pipelined(state, key, rounds)
    _sync(state)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        state, inflight = sim.run_fast_pipelined(state, key, rounds,
                                                 inflight=inflight)
        _sync(state)
        best = min(best, time.perf_counter() - t0)
    return best / rounds * 1000.0


def _rounds_to_eps(sim, eps, horizon, chunk=16, pipelined=False):
    """First round whose convergence >= 1 - eps from a cold start,
    early-stopping chunk by chunk (the topology_sweep.py shape); the
    pipelined walk chains the inflight carry across chunks so it is
    the same trajectory a straight pipelined run produces."""
    state = sim.init_state()
    key = jax.random.PRNGKey(11)
    inflight = None
    done = 0
    while done < horizon:
        step = min(chunk, horizon - done)
        if pipelined:
            state, conv, inflight = sim.run_pipelined(
                state, key, step, inflight=inflight, start_round=done)
        else:
            state, conv = sim.run(state, key, step, start_round=done)
        conv = jax.device_get(conv)
        for i, c in enumerate(conv):
            if float(c) >= 1.0 - eps:
                return done + i + 1
        done += step
    return None


def _ratio(num, den):
    if num is None or den is None or not den:
        return None
    return round(num / den, 3)


def _bench_exact(n, spn, rounds, reps):
    sim = _build_exact(n, spn)
    lock = _time_lockstep(sim, sim.init_state(), rounds, reps)
    pipe_sim = _build_exact(n, spn, pipeline="1")
    pipe = _time_pipelined(pipe_sim, pipe_sim.init_state(), rounds,
                           reps)
    rps = 1000.0 / pipe if pipe else None
    return {
        "lockstep_ms_per_round": round(lock, 3),
        "pipelined_ms_per_round": round(pipe, 3),
        "speedup": _ratio(lock, pipe),
        "rounds_per_sec_pipelined": round(rps, 2) if rps else None,
        "vs_pr5_headline": _ratio(rps, PR5_HEADLINE_RPS),
    }


def _bench_compressed(n, spn, rounds, reps, burst=64):
    sim = _build_compressed(n, spn)
    lock = _time_lockstep(sim, _burst_state(sim, burst), rounds, reps,
                          sparse=False)
    tail = _time_lockstep(sim, _burst_state(sim, burst), rounds, reps,
                          sparse=True)
    pipe_sim = _build_compressed(n, spn, pipeline="1")
    pipe = _time_pipelined(pipe_sim, _burst_state(pipe_sim, burst),
                           rounds, reps)
    return {
        "lockstep_ms_per_round": round(lock, 3),
        "pipelined_ms_per_round": round(pipe, 3),
        "speedup": _ratio(lock, pipe),
        # The tail regime's real competitor: pipeline + sparse doesn't
        # compose (RAW dense board in the carry — docs/pipeline.md),
        # the arbiter picks sparse lockstep there instead.
        "sparse_tail_ms_per_round": round(tail, 3),
    }


def _bench_convergence(n, spn, eps, horizon):
    lock = _rounds_to_eps(_build_exact(n, spn), eps, horizon)
    pipe = _rounds_to_eps(_build_exact(n, spn, pipeline="1"), eps,
                          horizon, pipelined=True)
    return {
        "eps": eps,
        "lockstep_rounds_to_eps": lock,
        "pipelined_rounds_to_eps": pipe,
        # ISSUE bound: <= 1.10 — one-round-stale publishes may slow
        # the epidemic a little, never stall it.
        "rounds_to_eps_ratio": _ratio(pipe, lock),
    }


def _bench_cadence(n, spn, rounds, reps, eps, horizon):
    """The heterogeneity sweep row: uniform period 1 (the pre-cadence
    program, bit for bit) vs mixed per-node periods {1, 2, 4} cycling
    node by node (⅓ of the fleet at each cadence)."""
    periods = (np.arange(n) % 3).astype(np.int32)
    mixed = np.choose(periods, [1, 2, 4]).astype(np.int32)
    phases = (np.arange(n) % 4).astype(np.int32)
    uni_sim = _build_exact(n, spn)
    uni_ms = _time_lockstep(uni_sim, uni_sim.init_state(), rounds,
                            reps)
    mix_sim = _build_exact(n, spn, tick_period=mixed, tick_phase=phases)
    mix_ms = _time_lockstep(mix_sim, mix_sim.init_state(), rounds,
                            reps)
    uni_eps = _rounds_to_eps(_build_exact(n, spn), eps, horizon)
    mix_eps = _rounds_to_eps(
        _build_exact(n, spn, tick_period=mixed, tick_phase=phases),
        eps, horizon)
    return {
        "mixed_periods": [1, 2, 4],
        "uniform_ms_per_round": round(uni_ms, 3),
        "mixed_ms_per_round": round(mix_ms, 3),
        "uniform_rounds_to_eps": uni_eps,
        "mixed_rounds_to_eps": mix_eps,
        "rounds_to_eps_ratio": _ratio(mix_eps, uni_eps),
    }


def _bench_sharded(n, spn, rounds, reps):
    """Lockstep vs pipelined on the row-sharded compressed family —
    the path where the publish of round i+1 can genuinely overlap the
    board exchange of round i.  ``overlap_ms`` is the wall-clock per
    round the pipeline recovered; the PR-12 static attribution of the
    pipelined STEP rides along as the structural proof (publish bytes
    and merge bytes attributed inside one program)."""
    from sidecar_tpu.parallel.sharded_compressed import (
        ShardedCompressedSim)
    from sidecar_tpu.telemetry import cost

    d = len(jax.devices())
    sim = _build_compressed(n, spn, cls=ShardedCompressedSim)
    lock = _time_lockstep(sim, _burst_state(sim, 64), rounds, reps,
                          sparse=False)
    pipe_sim = _build_compressed(n, spn, cls=ShardedCompressedSim,
                                 pipeline="1")
    pipe = _time_pipelined(pipe_sim, _burst_state(pipe_sim, 64),
                           rounds, reps)
    out = {
        "devices": d,
        "lockstep_ms_per_round": round(lock, 3),
        "pipelined_ms_per_round": round(pipe, 3),
        # Exposed-time recovered per round.  Positive on real meshes
        # (the acceptance bar); a single-chip CPU fallback can land
        # ~0 — the attribution below still proves the overlap exists
        # to be claimed.
        "overlap_ms": round(lock - pipe, 3),
    }
    # Static phase attribution of the pipelined single-chip step (the
    # program the sharded path re-traces under GSPMD): one compiled
    # program carrying BOTH the fold of round i and the publish of
    # round i+1 — the structural half of the overlap claim.
    probe = _build_compressed(min(n, 1024), spn, pipeline="1")
    st = probe.init_state()
    key = jax.random.PRNGKey(0)
    st, inflight = probe.prime_pipeline(st, key)
    with cost.forced_phases(True):
        rep = cost.program_report(
            "compressed.step_pipelined",
            lambda s, i, kn, kx: probe._step_pipelined(s, i, kn, kx),
            st, inflight, jax.random.fold_in(key, 0),
            jax.random.fold_in(key, 1))
    pb = rep.get("phase_bytes", {}).get("by_phase", {})
    out["pipelined_phase_bytes"] = {k: int(v) for k, v in pb.items()}
    # Round i+1's publish and round i's delivery/merge (gather phase —
    # the compressed family folds inside the gather scope) attributed
    # inside ONE compiled program: the structural overlap claim.
    out["publish_and_merge_coresident"] = bool(
        pb.get("publish") and pb.get("gather"))
    return out


def run_pipeline_bench(n=4096, spn=10, rounds=60, reps=3, eps=1e-3,
                       horizon=None, verbose=False):
    """The bench.py ``pipeline`` block.  Every row is wrapped so one
    failing leg nulls its numbers instead of sinking the block."""
    horizon = horizon or max(120, rounds * 4)
    block = {"n": n, "rounds": rounds}

    def leg(name, fn, *args):
        try:
            block[name] = fn(*args)
            if verbose:
                print(json.dumps({name: block[name]}), flush=True)
        except Exception as exc:  # one leg must not sink the block
            print(f"# pipeline bench leg {name} failed: {exc}",
                  file=sys.stderr)
            block[name] = None

    leg("exact", _bench_exact, n, spn, rounds, reps)
    leg("compressed", _bench_compressed, n, spn, rounds, reps)
    leg("convergence", _bench_convergence, n, spn, eps, horizon)
    leg("cadence", _bench_cadence, n, spn, rounds, reps, eps, horizon)
    leg("sharded", _bench_sharded, n, spn, rounds, reps)

    ex = block.get("exact") or {}
    conv = block.get("convergence") or {}
    sh = block.get("sharded") or {}
    block["summary"] = {
        "vs_pr5_headline": ex.get("vs_pr5_headline"),
        "rounds_to_eps_ratio": conv.get("rounds_to_eps_ratio"),
        "overlap_ms": sh.get("overlap_ms"),
    }
    return block


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4096)
    ap.add_argument("--spn", type=int, default=10)
    ap.add_argument("--rounds", type=int, default=60)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--eps", type=float, default=1e-3)
    opts = ap.parse_args()
    block = run_pipeline_bench(n=opts.nodes, spn=opts.spn,
                               rounds=opts.rounds, reps=opts.reps,
                               eps=opts.eps, verbose=True)
    print("FINAL " + json.dumps(block), flush=True)


if __name__ == "__main__":
    main()
