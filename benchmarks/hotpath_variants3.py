"""DEAD-END LEDGER: every variant in this file was measured and the
conclusions are CONSOLIDATED in benchmarks/RESULTS.md ("Measured
primitive floors and dead ends") — read that table before re-running
anything here.  Round 6 superseded the XLA-level attack entirely: the
publish floors are now addressed by the fused Pallas kernels in
sidecar_tpu/ops/kernels/ (docs/kernels.md).

Round 3: can the publish threshold beat exact int32 top_k?

  topk32    exact top_k on int32 [N, 256] (current)
  topk16    top_k on an int16 surrogate (dynamic shift keeps ~13-bit
            freshness resolution; the tie-rank admission makes ANY
            coarser threshold budget-exact, so this is safe-by-
            construction)
  hist64    64-bin recency histogram (one-hot matmul) + cumsum
            threshold — freshness at window/64 granularity

Run: python benchmarks/hotpath_variants3.py
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

K = 256
BUDGET = 15
N = 100_000


def make_priority(seed=0):
    rng = np.random.default_rng(seed)
    occ = rng.random((N, K)) < 0.15
    # realistic packed keys: recent ticks in a narrow window
    val = np.where(occ, (rng.integers(20_000, 25_000, (N, K)) << 3),
                   0).astype(np.int32)
    return jnp.asarray(val)


def timed_scan(body, carry, iters=60, reps=3):
    @jax.jit
    def run(c):
        return lax.scan(body, c, jnp.arange(iters, dtype=jnp.int32))[0]

    out = run(carry)
    jax.device_get(jax.tree_util.tree_leaves(out)[0])
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(carry)
        jax.device_get(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1000.0


def main():
    pv0 = make_priority()
    results = {}

    def topk32(carry, i):
        acc, pv = carry
        p = pv ^ (i & 1)
        thresh = lax.top_k(p, BUDGET)[0][:, -1:]
        sel = (p > thresh) | ((p == thresh) & (p > 0))
        return (acc + jnp.sum(sel.astype(jnp.int32)), pv), None

    def topk16(carry, i):
        acc, pv = carry
        p = pv ^ (i & 1)
        now_max = jnp.max(p)
        shift = jnp.maximum(
            0, 32 - jnp.int32(lax.clz(jnp.maximum(now_max, 1))) - 13)
        p16 = (p >> shift).astype(jnp.int16)
        thresh = lax.top_k(p16, BUDGET)[0][:, -1:]
        sel = (p16 > thresh) | ((p16 == thresh) & (p > 0))
        return (acc + jnp.sum(sel.astype(jnp.int32)), pv), None

    def hist64(carry, i):
        acc, pv = carry
        p = pv ^ (i & 1)
        now_max = jnp.max(p)
        lo = now_max - (1 << 15)       # window floor
        b = jnp.clip((p - lo) >> 9, 0, 63)      # 64 bins, newest high
        b = jnp.where(p > 0, b, -1)
        oh = jax.nn.one_hot(b, 64, dtype=jnp.bfloat16)  # [N, K, 64]
        hist = jnp.sum(oh, axis=1).astype(jnp.int32)    # [N, 64]
        # admit from the newest bin downward
        rev = jnp.cumsum(hist[:, ::-1], axis=1)[:, ::-1]
        tbin = 63 - jnp.argmax((rev >= BUDGET)[:, ::-1], axis=1)
        have = jnp.any(rev >= BUDGET, axis=1)
        tbin = jnp.where(have, tbin, 0)
        sel = (b > tbin[:, None]) | ((b == tbin[:, None]) & (p > 0))
        return (acc + jnp.sum(sel.astype(jnp.int32)), pv), None

    for name, fn in [("topk32", topk32), ("topk16", topk16),
                     ("hist64", hist64)]:
        results[name] = round(
            timed_scan(fn, (jnp.zeros((), jnp.int32), pv0)), 3)
        print(json.dumps(results), flush=True)

    print("FINAL " + json.dumps(
        {"n": N, "platform": jax.devices()[0].platform, **results}))


if __name__ == "__main__":
    main()
