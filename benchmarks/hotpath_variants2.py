"""DEAD-END LEDGER: every variant in this file was measured and the
conclusions are CONSOLIDATED in benchmarks/RESULTS.md ("Measured
primitive floors and dead ends") — read that table before re-running
anything here.  Round 6 superseded the XLA-level attack entirely: the
publish floors are now addressed by the fused Pallas kernels in
sidecar_tpu/ops/kernels/ (docs/kernels.md).

Round 2 of hot-path experiments (int32-only; see hotpath_variants.py
for the harness rationale).  Questions:

* pub_approx  — does TPU-native ``lax.approx_max_k`` beat exact top_k
               for the publish threshold?  (We only need the B-th
               largest VALUE per row, not indices.)
* g3x1row    — three [N]-row gathers vs one [N,3] row gather.
* g_fused    — gather feeding straight into an F-axis max (no ps, no
               merge): the lower bound if XLA fuses the reduce into
               the gather consumer instead of materializing [N,F,K].
* g_half     — val-only gather (no slot gather): what the ps gather
               costs on top.

Run: python benchmarks/hotpath_variants2.py
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

K = 256
F = 3
BUDGET = 15
N = 100_000


def make_inputs(seed=0):
    rng = np.random.default_rng(seed)
    occ = rng.random((N, K)) < 0.15
    val = np.where(occ, rng.integers(1 << 6, 1 << 24, (N, K)), 0) \
        .astype(np.int32)
    slot = np.where(occ, rng.integers(0, N * 10, (N, K)), -1) \
        .astype(np.int32)
    return jnp.asarray(val), jnp.asarray(slot)


def timed_scan(body, carry, iters=60, reps=3):
    @jax.jit
    def run(c):
        return lax.scan(body, c, jnp.arange(iters, dtype=jnp.int32))[0]

    out = run(carry)
    jax.device_get(jax.tree_util.tree_leaves(out)[0])
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(carry)
        jax.device_get(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1000.0


def main():
    val, slot = make_inputs()
    key0 = jax.random.PRNGKey(1)
    results = {}

    # -- publish threshold: exact top_k vs approx_max_k ---------------------
    def mk_thresh(kind):
        def body(carry, i):
            acc, v = carry
            pv = v ^ (i & 1)
            if kind == "exact":
                top = lax.top_k(pv, BUDGET)[0]
            else:
                top = lax.approx_max_k(pv.astype(jnp.float32), BUDGET,
                                       recall_target=0.95)[0] \
                    .astype(jnp.int32)
            thresh = top[:, -1:]
            sel = jnp.where(pv >= thresh, pv, 0)
            return (acc + jnp.sum(sel), v), None
        return body

    results["thresh_topk"] = round(
        timed_scan(mk_thresh("exact"), (jnp.zeros((), jnp.int32), val)), 3)
    print(json.dumps(results), flush=True)
    results["thresh_approx"] = round(
        timed_scan(mk_thresh("approx"), (jnp.zeros((), jnp.int32), val)),
        3)
    print(json.dumps(results), flush=True)

    # approx quality at this shape: how far off is the returned B-th
    # value, and how many rows get it exactly right?
    exact_t = lax.top_k(val, BUDGET)[0][:, -1]
    approx_t = lax.approx_max_k(val.astype(jnp.float32), BUDGET,
                                recall_target=0.95)[0][:, -1] \
        .astype(jnp.int32)
    results["approx_rows_exact_pct"] = round(float(
        jnp.mean((exact_t == approx_t).astype(jnp.float32))) * 100, 2)
    print(json.dumps(results), flush=True)

    # -- gather forms -------------------------------------------------------
    def g_rows(carry, i):            # one [N, F] row gather, both arrays
        acc, k = carry
        k, sub = jax.random.split(k)
        src = jax.random.randint(sub, (N, F), 0, N, dtype=jnp.int32)
        pv = val[src]
        ps = slot[src]
        return (acc + jnp.sum(pv) + jnp.sum(ps), k), None

    def g3x1row(carry, i):           # three [N] row gathers, both arrays
        acc, k = carry
        k, sub = jax.random.split(k)
        src = jax.random.randint(sub, (N, F), 0, N, dtype=jnp.int32)
        acc2 = acc
        for f in range(F):
            acc2 = acc2 + jnp.sum(val[src[:, f]]) \
                + jnp.sum(slot[src[:, f]])
        return (acc2, k), None

    def g_fused(carry, i):           # gather → F-axis max, no slot
        acc, k = carry
        k, sub = jax.random.split(k)
        src = jax.random.randint(sub, (N, F), 0, N, dtype=jnp.int32)
        wv = jnp.max(val[src], axis=1)           # [N, K]
        return (acc + jnp.sum(wv), k), None

    def g_half(carry, i):            # val-only gather
        acc, k = carry
        k, sub = jax.random.split(k)
        src = jax.random.randint(sub, (N, F), 0, N, dtype=jnp.int32)
        pv = val[src]
        return (acc + jnp.sum(pv), k), None

    for name, fn in [("g_rows", g_rows), ("g3x1row", g3x1row),
                     ("g_fused", g_fused), ("g_half", g_half)]:
        results[name] = round(
            timed_scan(fn, (jnp.zeros((), jnp.int32), key0)), 3)
        print(json.dumps(results), flush=True)

    print("FINAL " + json.dumps(
        {"n": N, "platform": jax.devices()[0].platform, **results}))


if __name__ == "__main__":
    main()
