"""Query-plane benchmark: resolve throughput + watch fan-out latency.

The north star serves heavy read traffic from millions of users; the
query plane's job is making those reads (a) lock-free against the
writer and (b) cheap — serialization at most once per version.  Two
measurements over a SHARDED snapshot (many hosts, the shape a real
cluster catalog has):

* **resolve throughput** — `hub.current()` + a by-service group lookup
  per resolve, the `/api/services/{name}.json` hot path, measured in
  resolves/sec single-threaded AND with the writer concurrently
  publishing (the lock-free claim under load).
* **watch fan-out latency** — N hub subscribers, one change published:
  wall time from publish until EVERY subscriber has the delta
  (p50/p99 over many events) — the `/watch` push latency floor, and
  the latency ADS now sees instead of its old 1 s poll.

Host-side only (no TPU, no network): this isolates the subsystem the
PR added.  Run: python benchmarks/bench_query.py  → one JSON line.
"""

from __future__ import annotations

import json
import math
import os
import pathlib
import statistics
import sys
import threading
import time
from typing import Optional

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from sidecar_tpu import metrics  # noqa: E402
from sidecar_tpu import service as S  # noqa: E402
from sidecar_tpu.catalog import ServicesState  # noqa: E402
from sidecar_tpu.query.hub import relay_tree  # noqa: E402

NS = S.NS_PER_SECOND
T0 = 1_700_000_000 * NS

# Largest subscriber count at which the per-subscriber-serialization
# baseline is actually executed (it is O(n_subs × events) json.dumps
# calls — the exact cost the zero-copy path deletes; running it at 100k
# would dominate the bench for no extra information).
BASELINE_MAX_SUBS = 2000


def build_state(hosts: int, services_per_host: int) -> ServicesState:
    state = ServicesState(hostname="host000", cluster_name="bench")
    state.set_clock(lambda: T0)
    for hi in range(hosts):
        host = f"host{hi:03d}"
        for si in range(services_per_host):
            state.add_service_entry(S.Service(
                id=f"{host}-svc{si:03d}", name=f"svc{si:03d}",
                image="bench:1", hostname=host,
                updated=T0 + hi * 1000 + si, status=S.ALIVE,
                ports=[S.Port("tcp", 32000 + si, 8000 + si,
                              f"10.0.{hi}.{si}")]))
    return state


def bench_resolve(state: ServicesState, duration_s: float,
                  with_writer: bool) -> dict:
    hub = state.query_hub()
    stop = threading.Event()
    writer_published = [0]

    def writer():
        # ALIVE ↔ UNHEALTHY alternation: a re-announce with an
        # unchanged status emits no change event (reference merge
        # semantics), so each write must flip to actually publish.
        i = 0
        while not stop.is_set():
            state.add_service_entry(S.Service(
                id="host000-svc000", name="svc000", image="bench:1",
                hostname="host000", updated=T0 + 10**12 + i,
                status=S.ALIVE if i % 2 else S.UNHEALTHY))
            writer_published[0] += 1
            i += 1

    wt = None
    if with_writer:
        wt = threading.Thread(target=writer, daemon=True)
        wt.start()

    resolves = 0
    t0 = time.perf_counter()
    deadline = t0 + duration_s
    while time.perf_counter() < deadline:
        snap = hub.current()
        group = snap.by_service().get("svc001")
        assert group
        resolves += 1
    elapsed = time.perf_counter() - t0
    stop.set()
    if wt is not None:
        wt.join(timeout=5)
    return {
        "resolves_per_sec": round(resolves / elapsed, 1),
        "concurrent_writer_publishes": writer_published[0],
    }


def bench_watch_fanout(state: ServicesState, n_subs: int,
                       events: int) -> dict:
    hub = state.query_hub()
    barrier = threading.Barrier(n_subs + 1)
    done = [threading.Event() for _ in range(events)]
    counts = [0] * events
    count_lock = threading.Lock()
    base_version = hub.current().version

    def subscriber(idx: int):
        sub = hub.subscribe(f"bench{idx}", buffer=events + 8,
                            prime=False)
        barrier.wait(timeout=10)
        seen = 0
        while seen < events:
            ev = sub.get(timeout=5)
            if ev is None:
                return
            ei = ev.version - base_version - 1
            with count_lock:
                counts[ei] += 1
                if counts[ei] == n_subs:
                    done[ei].set()
            seen += 1
        sub.close()

    threads = [threading.Thread(target=subscriber, args=(i,),
                                daemon=True) for i in range(n_subs)]
    for t in threads:
        t.start()
    barrier.wait(timeout=10)

    latencies = []
    for ei in range(events):
        t0 = time.perf_counter()
        # Status flip per event — unchanged-status re-announces emit no
        # change event (see bench_resolve's writer).
        state.add_service_entry(S.Service(
            id="host001-svc001", name="svc001", image="bench:1",
            hostname="host001", updated=T0 + 10**13 + ei,
            status=S.ALIVE if ei % 2 else S.UNHEALTHY))
        if not done[ei].wait(timeout=5):
            raise RuntimeError(f"fan-out stalled at event {ei}")
        latencies.append((time.perf_counter() - t0) * 1e6)
    for t in threads:
        t.join(timeout=5)
    latencies.sort()
    return {
        "subscribers": n_subs,
        "events": events,
        "fanout_p50_us": round(statistics.median(latencies), 1),
        "fanout_p99_us": round(
            latencies[min(len(latencies) - 1,
                          int(len(latencies) * 0.99))], 1),
    }


def run_query_bench(hosts: int = 64, services_per_host: int = 16,
                    duration_s: float = 0.5,
                    n_subs: Optional[int] = None,
                    events: int = 200) -> dict:
    if n_subs is None:
        n_subs = int(os.environ.get("BENCH_QUERY_SUBS", "32"))
    state = build_state(hosts, services_per_host)
    out = {
        "snapshot_hosts": hosts,
        "snapshot_services": hosts * services_per_host,
        "resolve": bench_resolve(state, duration_s, with_writer=False),
        "resolve_under_write_load": bench_resolve(
            build_state(hosts, services_per_host), duration_s,
            with_writer=True),
        "watch_fanout": bench_watch_fanout(
            build_state(hosts, services_per_host), n_subs, events),
    }
    return out


# -- the 100k-watcher synthetic soak (the query_scale bench block) ---------

def _percentile(sorted_vals: list, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(len(sorted_vals) * q))
    return sorted_vals[idx]


def _scale_level(n_subs: int, hosts: int, services_per_host: int,
                 events: int, workers: int, max_fanout: int,
                 subs_per_relay: int) -> dict:
    """One ramp level: n_subs synthetic watchers (Subscription objects
    drained by a small worker pool — no thread per watcher) spread
    across a relay tree, `events` versions published through it.

    Measures per level: root publish wall time (O(relays), the
    writer-path claim), sampled p50/p99 publish-to-deliver lag in ms
    and in versions, gap-free delivery, and serialization work — bytes
    actually encoded per published version (query.encode.* deltas)
    vs the per-subscriber-serialization baseline re-encoding the same
    documents once per watcher (executed only up to BASELINE_MAX_SUBS).
    """
    state = build_state(hosts, services_per_host)
    hub = state.query_hub()
    enc_bytes0 = metrics.counter("query.encode.bytes")
    enc_count0 = metrics.counter("query.encode.count")
    dropped0 = metrics.counter("query.hub.dropped")
    coalesced0 = metrics.counter("query.hub.coalesced")

    relays: list = []
    if n_subs > subs_per_relay:
        n_leaves = math.ceil(n_subs / subs_per_relay)
        leaves, relays = relay_tree(hub, n_leaves,
                                    max_fanout=max_fanout)
        tiers = 1
        while n_leaves > max_fanout:
            n_leaves = math.ceil(n_leaves / max_fanout)
            tiers += 1
    else:
        leaves, tiers = [hub], 0
    subs = [leaves[i % len(leaves)].subscribe(f"s{i}",
                                              buffer=events + 8,
                                              prime=False)
            for i in range(n_subs)]
    base_version = hub.current().version

    # Per-sub cursors: expect[i] is the next delta version sub i must
    # see; a resync marker legally jumps it (cursor reset), anything
    # else is a gap.
    expect = [base_version + 1] * n_subs
    target = base_version + events
    gaps = [0]
    resyncs = [0]
    deliveries = [0]
    bytes_handed = [0]
    lag_ms_samples: list = []
    lag_ver_samples: list = []
    stats_lock = threading.Lock()
    first_events: list = []   # sub 0's events, for the baseline replay
    deadline = time.perf_counter() + 180.0

    def worker(lo: int, hi: int) -> None:
        remaining = set(range(lo, hi))
        l_gaps = l_resyncs = l_deliv = l_bytes = 0
        l_ms: list = []
        l_ver: list = []
        while remaining and time.perf_counter() < deadline:
            progressed = False
            for i in list(remaining):
                evs = subs[i].drain()
                if evs:
                    progressed = True
                for ev in evs:
                    l_deliv += 1
                    if ev.kind == "snapshot":
                        l_resyncs += 1
                        expect[i] = ev.version + 1
                        buf = ev.snapshot.resync_doc_bytes()
                    else:
                        if ev.version != expect[i]:
                            l_gaps += 1
                        expect[i] = ev.version + 1
                        # The zero-copy handoff: the shared cached wire
                        # buffer, as the /watch writer and UrlListener
                        # POST it.
                        buf = ev.delta_doc_bytes()
                        if l_deliv % 97 == 1:
                            l_ms.append(max(0.0, (time.time_ns()
                                                  - ev.published_ns)
                                            / 1e6))
                            cur = hub.current().version
                            l_ver.append(max(0, cur - ev.version))
                    l_bytes += len(buf)
                    if i == 0:
                        first_events.append(ev)
                if expect[i] > target:
                    remaining.discard(i)
            if not progressed:
                time.sleep(0.002)
        with stats_lock:
            gaps[0] += l_gaps
            resyncs[0] += l_resyncs
            deliveries[0] += l_deliv
            bytes_handed[0] += l_bytes
            lag_ms_samples.extend(l_ms)
            lag_ver_samples.extend(l_ver)
            if remaining:
                gaps[0] += len(remaining)  # stalled subs count as gaps

    n_workers = min(workers, n_subs)
    bounds = [(k * n_subs // n_workers, (k + 1) * n_subs // n_workers)
              for k in range(n_workers)]
    threads = [threading.Thread(target=worker, args=b, daemon=True)
               for b in bounds]
    for t in threads:
        t.start()

    publish_ms = []
    for ei in range(events):
        t0 = time.perf_counter()
        # Status flip per event (unchanged-status re-announces emit no
        # change event, see bench_resolve's writer).
        state.add_service_entry(S.Service(
            id="host001-svc001", name="svc001", image="bench:1",
            hostname="host001", updated=T0 + 10**13 + ei,
            status=S.ALIVE if ei % 2 else S.UNHEALTHY))
        publish_ms.append((time.perf_counter() - t0) * 1e3)
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.perf_counter()) + 5)
    drained = all(not t.is_alive() for t in threads)

    enc_bytes = metrics.counter("query.encode.bytes") - enc_bytes0
    enc_count = metrics.counter("query.encode.count") - enc_count0
    zero_copy_bpv = enc_bytes / events

    baseline = None
    if n_subs <= BASELINE_MAX_SUBS and first_events:
        # The old read path, replayed honestly: one json.dumps of the
        # SAME document per subscriber per event.
        bl_bytes = 0
        t0 = time.perf_counter()
        for ev in first_events:
            if ev.kind != "delta":
                continue
            for _ in range(n_subs):
                bl_bytes += len(json.dumps(
                    {"Version": ev.version,
                     "ChangeEvent": ev.change.to_json()},
                    separators=(",", ":")).encode())
        baseline = {
            "bytes_per_version": round(bl_bytes
                                       / max(1, len(first_events))),
            "wall_ms": round((time.perf_counter() - t0) * 1e3, 2),
        }

    for r in relays:
        r.close()
    if not relays:
        for sub in subs:
            sub.close()
    publish_ms.sort()
    lag_ms_samples.sort()
    lag_ver_samples.sort()
    return {
        "subscribers": n_subs,
        "events": events,
        "relays": len(relays),
        "tiers": tiers,
        "gap_free": drained and gaps[0] == 0,
        "gaps": gaps[0],
        "resyncs": resyncs[0],
        "deliveries": deliveries[0],
        "dropped": metrics.counter("query.hub.dropped") - dropped0,
        "coalesced": metrics.counter("query.hub.coalesced") - coalesced0,
        "publish_p50_ms": round(_percentile(publish_ms, 0.5), 3),
        "publish_p99_ms": round(_percentile(publish_ms, 0.99), 3),
        "lag_p50_ms": (round(_percentile(lag_ms_samples, 0.5), 3)
                       if lag_ms_samples else None),
        "lag_p99_ms": (round(_percentile(lag_ms_samples, 0.99), 3)
                       if lag_ms_samples else None),
        "lag_p50_versions": (_percentile(lag_ver_samples, 0.5)
                             if lag_ver_samples else None),
        "lag_p99_versions": (_percentile(lag_ver_samples, 0.99)
                             if lag_ver_samples else None),
        "bytes_encoded_per_version": round(zero_copy_bpv, 1),
        "encodings_per_version": round(enc_count / events, 2),
        "bytes_handed_off": bytes_handed[0],
        **({"baseline": baseline} if baseline else {}),
    }


def run_query_scale(hosts: int = 16, services_per_host: int = 8,
                    events: int = 6, workers: int = 8,
                    max_fanout: int = 16,
                    subs_per_relay: int = 2048) -> dict:
    """The 100k-watcher soak: subscriber ramp 32 → BENCH_QUERY_SCALE_SUBS
    (default 100000) across relay tiers; headline = gap-free at max
    scale, bounded p99 version lag, and the zero-copy serialization
    ratio (baseline bytes per version / bytes actually encoded per
    version) at the largest level where the baseline runs (≥1k subs)."""
    max_subs = int(os.environ.get("BENCH_QUERY_SCALE_SUBS", "100000"))
    ramp = sorted({n for n in (32, 1000, 10000, 100000)
                   if n < max_subs} | {max_subs})
    levels = [_scale_level(n, hosts, services_per_host, events, workers,
                           max_fanout, subs_per_relay) for n in ramp]
    ratio = None
    for lv in levels:
        bl = lv.get("baseline")
        if bl and lv["bytes_encoded_per_version"] > 0:
            ratio = round(bl["bytes_per_version"]
                          / lv["bytes_encoded_per_version"], 1)
    top = levels[-1]
    return {
        "levels": levels,
        "max_subscribers": top["subscribers"],
        "gap_free": all(lv["gap_free"] for lv in levels),
        "lag_p99_ms": top["lag_p99_ms"],
        "lag_p99_versions": top["lag_p99_versions"],
        "publish_p99_ms": top["publish_p99_ms"],
        "serialization_ratio": ratio,
    }


def main() -> int:
    doc = {"metric": "query-plane resolve/fanout", **run_query_bench()}
    if os.environ.get("BENCH_QUERY_SCALE", "0") != "0":
        doc["query_scale"] = run_query_scale()
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
