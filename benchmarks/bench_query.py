"""Query-plane benchmark: resolve throughput + watch fan-out latency.

The north star serves heavy read traffic from millions of users; the
query plane's job is making those reads (a) lock-free against the
writer and (b) cheap — serialization at most once per version.  Two
measurements over a SHARDED snapshot (many hosts, the shape a real
cluster catalog has):

* **resolve throughput** — `hub.current()` + a by-service group lookup
  per resolve, the `/api/services/{name}.json` hot path, measured in
  resolves/sec single-threaded AND with the writer concurrently
  publishing (the lock-free claim under load).
* **watch fan-out latency** — N hub subscribers, one change published:
  wall time from publish until EVERY subscriber has the delta
  (p50/p99 over many events) — the `/watch` push latency floor, and
  the latency ADS now sees instead of its old 1 s poll.

Host-side only (no TPU, no network): this isolates the subsystem the
PR added.  Run: python benchmarks/bench_query.py  → one JSON line.
"""

from __future__ import annotations

import json
import pathlib
import statistics
import sys
import threading
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from sidecar_tpu import service as S  # noqa: E402
from sidecar_tpu.catalog import ServicesState  # noqa: E402

NS = S.NS_PER_SECOND
T0 = 1_700_000_000 * NS


def build_state(hosts: int, services_per_host: int) -> ServicesState:
    state = ServicesState(hostname="host000", cluster_name="bench")
    state.set_clock(lambda: T0)
    for hi in range(hosts):
        host = f"host{hi:03d}"
        for si in range(services_per_host):
            state.add_service_entry(S.Service(
                id=f"{host}-svc{si:03d}", name=f"svc{si:03d}",
                image="bench:1", hostname=host,
                updated=T0 + hi * 1000 + si, status=S.ALIVE,
                ports=[S.Port("tcp", 32000 + si, 8000 + si,
                              f"10.0.{hi}.{si}")]))
    return state


def bench_resolve(state: ServicesState, duration_s: float,
                  with_writer: bool) -> dict:
    hub = state.query_hub()
    stop = threading.Event()
    writer_published = [0]

    def writer():
        # ALIVE ↔ UNHEALTHY alternation: a re-announce with an
        # unchanged status emits no change event (reference merge
        # semantics), so each write must flip to actually publish.
        i = 0
        while not stop.is_set():
            state.add_service_entry(S.Service(
                id="host000-svc000", name="svc000", image="bench:1",
                hostname="host000", updated=T0 + 10**12 + i,
                status=S.ALIVE if i % 2 else S.UNHEALTHY))
            writer_published[0] += 1
            i += 1

    wt = None
    if with_writer:
        wt = threading.Thread(target=writer, daemon=True)
        wt.start()

    resolves = 0
    t0 = time.perf_counter()
    deadline = t0 + duration_s
    while time.perf_counter() < deadline:
        snap = hub.current()
        group = snap.by_service().get("svc001")
        assert group
        resolves += 1
    elapsed = time.perf_counter() - t0
    stop.set()
    if wt is not None:
        wt.join(timeout=5)
    return {
        "resolves_per_sec": round(resolves / elapsed, 1),
        "concurrent_writer_publishes": writer_published[0],
    }


def bench_watch_fanout(state: ServicesState, n_subs: int,
                       events: int) -> dict:
    hub = state.query_hub()
    barrier = threading.Barrier(n_subs + 1)
    done = [threading.Event() for _ in range(events)]
    counts = [0] * events
    count_lock = threading.Lock()
    base_version = hub.current().version

    def subscriber(idx: int):
        sub = hub.subscribe(f"bench{idx}", buffer=events + 8,
                            prime=False)
        barrier.wait(timeout=10)
        seen = 0
        while seen < events:
            ev = sub.get(timeout=5)
            if ev is None:
                return
            ei = ev.version - base_version - 1
            with count_lock:
                counts[ei] += 1
                if counts[ei] == n_subs:
                    done[ei].set()
            seen += 1
        sub.close()

    threads = [threading.Thread(target=subscriber, args=(i,),
                                daemon=True) for i in range(n_subs)]
    for t in threads:
        t.start()
    barrier.wait(timeout=10)

    latencies = []
    for ei in range(events):
        t0 = time.perf_counter()
        # Status flip per event — unchanged-status re-announces emit no
        # change event (see bench_resolve's writer).
        state.add_service_entry(S.Service(
            id="host001-svc001", name="svc001", image="bench:1",
            hostname="host001", updated=T0 + 10**13 + ei,
            status=S.ALIVE if ei % 2 else S.UNHEALTHY))
        if not done[ei].wait(timeout=5):
            raise RuntimeError(f"fan-out stalled at event {ei}")
        latencies.append((time.perf_counter() - t0) * 1e6)
    for t in threads:
        t.join(timeout=5)
    latencies.sort()
    return {
        "subscribers": n_subs,
        "events": events,
        "fanout_p50_us": round(statistics.median(latencies), 1),
        "fanout_p99_us": round(
            latencies[min(len(latencies) - 1,
                          int(len(latencies) * 0.99))], 1),
    }


def run_query_bench(hosts: int = 64, services_per_host: int = 16,
                    duration_s: float = 0.5, n_subs: int = 32,
                    events: int = 200) -> dict:
    state = build_state(hosts, services_per_host)
    out = {
        "snapshot_hosts": hosts,
        "snapshot_services": hosts * services_per_host,
        "resolve": bench_resolve(state, duration_s, with_writer=False),
        "resolve_under_write_load": bench_resolve(
            build_state(hosts, services_per_host), duration_s,
            with_writer=True),
        "watch_fanout": bench_watch_fanout(
            build_state(hosts, services_per_host), n_subs, events),
    }
    return out


def main() -> int:
    print(json.dumps({"metric": "query-plane resolve/fanout",
                      **run_query_bench()}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
