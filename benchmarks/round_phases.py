"""Per-phase cost of the compressed-model round at north-star scale.

The faithful north-star run is per-round-cost bound (BENCH_r04: 525
rounds x ~43 ms = 22.5 s vs the <10 s target), so optimization has to be
guided by where the milliseconds actually are.  This script times the
round's phases CUMULATIVELY — scan variants that add one phase at a
time — so each phase's cost is the successive difference, measured the
only way this tunneled chip measures reliably (inside one lax.scan
dispatch, warmed at the same scan length, synced with device_get; see
the measurement notes in benchmarks/scatter_costs.py).

Usage:  python benchmarks/round_phases.py [--n 100000] [--rounds 60]

Prints one JSON object with ms/round per cumulative variant and the
derived per-phase deltas.
"""

import argparse
import dataclasses
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops.topology import erdos_renyi

PHASE_ORDER = ["base", "publish", "gather", "merge", "announce",
               "push_pull", "sweep"]


class PhasedSim(CompressedSim):
    """CompressedSim with the round truncated after a chosen phase.

    Phases not yet enabled are skipped; the last enabled partial phase
    folds a cheap checksum into ``evictions`` so XLA cannot dead-code
    the work under test."""

    def __init__(self, *args, upto: str, **kw):
        super().__init__(*args, **kw)
        if upto not in PHASE_ORDER:
            raise ValueError(f"unknown phase {upto}")
        self._upto = PHASE_ORDER.index(upto)

    def _on(self, phase: str) -> bool:
        return self._upto >= PHASE_ORDER.index(phase)

    def _step(self, state, key):
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)

        if self._on("publish"):
            bval, bslot, sent = self._publish(state, limit)
            if not self._on("gather"):
                state = dataclasses.replace(
                    state, evictions=state.evictions + jnp.sum(bval)
                    + jnp.sum(sent.astype(jnp.int32)))
        if self._on("gather"):
            src = gossip_ops.sample_peers(
                k_peers, p.n, p.fanout, nbrs=self._nbrs, deg=self._deg,
                node_alive=state.node_alive, cut_mask=self._cut)
            pv = bval[src]
            ps = bslot[src]
            ok = state.node_alive[src] & state.node_alive[:, None]
            if not self._on("merge"):
                state = dataclasses.replace(
                    state, evictions=state.evictions + jnp.sum(pv)
                    + jnp.sum(ps) + jnp.sum(sent.astype(jnp.int32))
                    + jnp.sum(ok.astype(jnp.int32)))
        if self._on("merge"):
            state = self._merge_pulled(state, sent, pv, ps, ok, now,
                                       drop_key=k_drop)
        if self._on("announce"):
            state = self._announce(state, round_idx, now)
        if self._on("push_pull"):
            state = lax.cond(
                round_idx % t.push_pull_rounds == 0,
                lambda st: self._push_pull_stride(st, k_pp, now),
                lambda st: st, state)
        if self._on("sweep"):
            state = lax.cond(
                round_idx % t.sweep_rounds == 0,
                lambda st: self._floor_advance_and_sweep(st, now),
                lambda st: st, state)
        return dataclasses.replace(state, round_idx=round_idx)


def time_variant(sim, state, key, rounds, reps=3):
    # Warm at the same scan length (scan length is a static argnum —
    # timing a different length times a fresh compile).
    out = sim.run_fast(state, key, rounds)
    jax.device_get(out.round_idx)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = sim.run_fast(state, key, rounds)
        jax.device_get(out.round_idx)
        best = min(best, time.perf_counter() - t0)
    return best / rounds * 1000.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--rounds", type=int, default=60)
    ap.add_argument("--upto", default=None,
                    help="time only this cumulative variant")
    opts = ap.parse_args()

    params = CompressedParams(n=opts.n, services_per_node=10, fanout=3,
                              budget=15, cache_lines=256,
                              fold_quorum=1.0, deep_sweep_every=0)
    topo = erdos_renyi(opts.n, avg_degree=8.0, seed=3)
    cfg = TimeConfig(refresh_interval_s=10_000.0)  # faithful constants
    rng = np.random.default_rng(7)
    slots = np.sort(rng.choice(params.m, size=params.m // 1000,
                               replace=False)).astype(np.int32)
    key = jax.random.PRNGKey(0)

    names = [opts.upto] if opts.upto else PHASE_ORDER
    results = {}
    for upto in names:
        sim = PhasedSim(params, topo, cfg, upto=upto)
        state = sim.mint(sim.init_state(), slots, 10)
        results[upto] = round(
            time_variant(sim, state, key, opts.rounds), 3)

    deltas = {}
    for a, b in zip(PHASE_ORDER, PHASE_ORDER[1:]):
        if a in results and b in results:
            deltas[b] = round(results[b] - results[a], 3)
    print(json.dumps({
        "n": opts.n, "rounds_per_scan": opts.rounds,
        "platform": jax.devices()[0].platform,
        "cumulative_ms_per_round": results,
        "phase_delta_ms": deltas,
    }))


if __name__ == "__main__":
    main()
