"""Per-phase cost of the compressed-model round at north-star scale.

The faithful north-star run is per-round-cost bound (BENCH_r04: 525
rounds x ~43 ms = 22.5 s vs the <10 s target), so optimization has to be
guided by where the milliseconds actually are.  This script times the
round's phases CUMULATIVELY — scan variants that add one phase at a
time — so each phase's cost is the successive difference, measured the
only way this tunneled chip measures reliably (inside one lax.scan
dispatch, warmed at the same scan length, synced with device_get; see
the measurement notes in benchmarks/scatter_costs.py).

Usage:  python benchmarks/round_phases.py [--n 100000] [--rounds 60]

Prints one JSON object with ms/round per cumulative variant and the
derived per-phase deltas.
"""

import argparse
import dataclasses
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops.topology import erdos_renyi

PHASE_ORDER = ["base", "publish", "gather", "merge", "announce",
               "push_pull", "sweep"]


class PhasedSim(CompressedSim):
    """CompressedSim with the round truncated after a chosen phase.

    Phases not yet enabled are skipped; the last enabled partial phase
    folds a cheap checksum into ``evictions`` so XLA cannot dead-code
    the work under test.

    Under the fused Pallas path (SIDECAR_TPU_KERNELS=pallas with the
    in-kernel gather, ops/kernels) publish and gather are ONE kernel:
    the whole fused cost lands in the ``publish`` variant and the
    ``gather`` delta reads ~0 — compare the pallas ``publish`` line
    against the xla ``publish``+``gather`` sum (the 6.2 + 4.1 ms
    floors) to judge the fusion."""

    def __init__(self, *args, upto: str, **kw):
        super().__init__(*args, **kw)
        if upto not in PHASE_ORDER:
            raise ValueError(f"unknown phase {upto}")
        self._upto = PHASE_ORDER.index(upto)

    def _on(self, phase: str) -> bool:
        return self._upto >= PHASE_ORDER.index(phase)

    def _step(self, state, key):
        p, t = self.p, self.t
        limit = p.resolved_retransmit_limit()
        round_idx = state.round_idx + 1
        now = round_idx * t.round_ticks
        k_perturb, k_peers, k_drop, k_pp = jax.random.split(key, 4)

        if self._fused_gather:
            from sidecar_tpu.ops import kernels as kernel_ops
            if self._on("publish"):
                src = gossip_ops.sample_peers(
                    k_peers, p.n, p.fanout, nbrs=self._nbrs,
                    deg=self._deg, node_alive=state.node_alive,
                    cut_mask=self._cut)
                sent, pv, ps = kernel_ops.fused_publish_gather_pallas(
                    state.cache_val, state.cache_slot, state.cache_sent,
                    src, now, stale_ticks=t.stale_ticks,
                    budget=min(p.budget, p.cache_lines), limit=limit,
                    fanout=p.fanout, cache_lines=p.cache_lines,
                    interpret=self._kernels_interpret)
                ok = state.node_alive[src] & state.node_alive[:, None]
                if not self._on("merge"):
                    state = dataclasses.replace(
                        state, evictions=state.evictions + jnp.sum(pv)
                        + jnp.sum(ps) + jnp.sum(sent.astype(jnp.int32)))
            if self._on("merge"):
                state = self._merge_pulled(state, sent, pv, ps, ok, now,
                                           drop_key=k_drop,
                                           stale_filtered=True)
        else:
            if self._on("publish"):
                bval, bslot, sent = self._publish(state, limit)
                if not self._on("gather"):
                    state = dataclasses.replace(
                        state, evictions=state.evictions + jnp.sum(bval)
                        + jnp.sum(sent.astype(jnp.int32)))
            if self._on("gather"):
                src = gossip_ops.sample_peers(
                    k_peers, p.n, p.fanout, nbrs=self._nbrs,
                    deg=self._deg, node_alive=state.node_alive,
                    cut_mask=self._cut)
                pv = bval[src]
                ps = bslot[src]
                ok = state.node_alive[src] & state.node_alive[:, None]
                if not self._on("merge"):
                    state = dataclasses.replace(
                        state, evictions=state.evictions + jnp.sum(pv)
                        + jnp.sum(ps) + jnp.sum(sent.astype(jnp.int32))
                        + jnp.sum(ok.astype(jnp.int32)))
            if self._on("merge"):
                state = self._merge_pulled(state, sent, pv, ps, ok, now,
                                           drop_key=k_drop)
        if self._on("announce"):
            state = self._announce(state, round_idx, now)
        if self._on("push_pull"):
            state = lax.cond(
                round_idx % t.push_pull_rounds == 0,
                lambda st: self._push_pull_stride(st, k_pp, now),
                lambda st: st, state)
        if self._on("sweep"):
            state = lax.cond(
                round_idx % t.sweep_rounds == 0,
                lambda st: self._floor_advance_and_sweep(st, now),
                lambda st: st, state)
        return dataclasses.replace(state, round_idx=round_idx)


def time_variant(sim, state, key, rounds, reps=3):
    # Warm at the same scan length (scan length is a static argnum —
    # timing a different length times a fresh compile).  The drivers
    # DONATE their input, so each rep chains off the previous output —
    # the donated in-place rewrite IS the steady state being measured.
    state = sim.run_fast(state, key, rounds)
    jax.device_get(state.round_idx)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        state = sim.run_fast(state, key, rounds)
        jax.device_get(state.round_idx)
        best = min(best, time.perf_counter() - t0)
    return best / rounds * 1000.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--rounds", type=int, default=60)
    ap.add_argument("--upto", default=None,
                    help="time only this cumulative variant")
    ap.add_argument("--kernels", default=None,
                    choices=["pallas", "xla", "auto"],
                    help="force SIDECAR_TPU_KERNELS for this run "
                         "(default: inherit the environment)")
    opts = ap.parse_args()
    if opts.kernels:
        import os
        os.environ["SIDECAR_TPU_KERNELS"] = opts.kernels

    params = CompressedParams(n=opts.n, services_per_node=10, fanout=3,
                              budget=15, cache_lines=256,
                              fold_quorum=1.0, deep_sweep_every=0)
    topo = erdos_renyi(opts.n, avg_degree=8.0, seed=3)
    cfg = TimeConfig(refresh_interval_s=10_000.0)  # faithful constants
    rng = np.random.default_rng(7)
    slots = np.sort(rng.choice(params.m, size=params.m // 1000,
                               replace=False)).astype(np.int32)
    key = jax.random.PRNGKey(0)

    names = [opts.upto] if opts.upto else PHASE_ORDER
    results = {}
    kernels_path = None
    for upto in names:
        sim = PhasedSim(params, topo, cfg, upto=upto)
        kernels_path = sim._kernels
        state = sim.mint(sim.init_state(), slots, 10)
        results[upto] = round(
            time_variant(sim, state, key, opts.rounds), 3)

    deltas = {}
    for a, b in zip(PHASE_ORDER, PHASE_ORDER[1:]):
        if a in results and b in results:
            deltas[b] = round(results[b] - results[a], 3)
    out = {
        "n": opts.n, "rounds_per_scan": opts.rounds,
        "platform": jax.devices()[0].platform,
        "kernels": kernels_path,
        "cumulative_ms_per_round": results,
        "phase_delta_ms": deltas,
    }
    # The acceptance number for the fused path: publish+gather together
    # (under pallas fusion the pair is one kernel, so the sum IS the
    # fused phase; under xla it is the 6.2 + 4.1 ms floor pair).
    if "publish" in deltas and "gather" in deltas:
        out["publish_gather_ms"] = round(
            deltas["publish"] + deltas["gather"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
