"""Collective-cost measurements behind the v5e-8 north-star projection.

The README's projection row needs its collective terms to be MEASURED,
not paper arithmetic (VERDICT r4 weak #3).  Only ONE TPU chip is
attached here, so this script measures what this hardware can measure
and labels each number with what it is:

* ``hbm_copy_ms`` — a 100 MB on-chip HBM round trip (read+write) on the
  real TPU, timed inside one scan dispatch.  This is the single-chip
  memory floor under any board exchange: an all_gather's per-device
  receive buffer is written at most at HBM speed, so the collective
  cannot beat this number; on v5e ICI (~45 GB/s/link bidirectional, 2D
  torus) the wire adds its own term on top.
* ``cpu_mesh_all_gather_ms`` / ``cpu_mesh_all_to_all_ms`` — the SAME
  jitted shard_map programs the sharded twin runs, over the virtual
  8-device CPU mesh.  STRUCTURAL evidence only (host memcpy bandwidth,
  no ICI): they prove the collective schedules XLA emits for this
  program shape and give a relative all_gather : all_to_all ratio, not
  TPU wall-clock.
* ``ici_projection_ms`` — the arithmetic term, now stated WITH its
  inputs: board_bytes / (links × per-link bandwidth), printed so the
  projection's provenance is auditable in-repo rather than a README
  footnote.

Run:  python benchmarks/collectives.py            (TPU part)
      JAX_PLATFORMS= python benchmarks/collectives.py --cpu-mesh
      (the CPU-mesh part forces the virtual 8-device host platform
      in-process; run it as a separate invocation so the TPU numbers
      are never taken under a forced-CPU config)
"""

import argparse
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# North-star board shape: [N, K] int32.
N = 100_000
K = 256
BOARD_BYTES = N * K * 4          # ~100 MB


def timed(fn, arg, iters=30, reps=3):
    """Time ``iters`` applications of ``fn`` inside ONE lax.scan
    dispatch (per-dispatch overhead on the tunneled chip is ~10-100 ms,
    so chained individual calls measure the tunnel, not the op)."""
    import jax
    from jax import lax

    @jax.jit
    def run(v):
        out = lax.scan(lambda c, _: (fn(c), None), v, None,
                       length=iters)[0]
        # Sync on a SCALAR: device_get of the full operand would pull
        # ~100 MB back through the tunnel and dominate the measurement.
        return out, jnp_sum_scalar(out)

    import jax.numpy as jnp

    def jnp_sum_scalar(t):
        leaves = jax.tree_util.tree_leaves(t)
        return sum(jnp.sum(leaf) for leaf in leaves)

    out, s = run(arg)
    jax.device_get(s)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out, s = run(out)
        jax.device_get(s)
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1000.0


def tpu_hbm_floor():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((N, K), jnp.int32)

    def copy(v):
        return v + 1                   # read 100 MB + write 100 MB

    ms = timed(copy, x)
    return {
        "what": "100 MB board read+write on one chip's HBM (the "
                "single-chip floor under any board exchange)",
        "platform": jax.devices()[0].platform,
        "board_mb": round(BOARD_BYTES / 1e6, 1),
        "hbm_copy_ms": round(ms, 3),
        "implied_hbm_gbps": round(2 * BOARD_BYTES / (ms / 1e3) / 1e9, 1),
    }


def cpu_mesh_collectives():
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import numpy as np

    # Version-portable shard_map (jax moved it out of experimental —
    # same shim the sharded twins use).
    from sidecar_tpu.parallel.mesh import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)

    d = 8
    mesh = Mesh(np.asarray(jax.devices()[:d]), ("x",))
    row = NamedSharding(mesh, P("x"))
    x = jax.device_put(jnp.ones((N, K), jnp.int32), row)

    def ag(v):
        def f(vl):
            g = lax.all_gather(vl, "x", tiled=True)    # [N, K] per dev
            return vl + g[0, 0]
        return shard_map(f, mesh=mesh, in_specs=P("x"),
                         out_specs=P("x"))(v)

    # The a2a moves each device's fanout-sampled request load:
    # [d, C, K] per device with C = slack·nl·F/d — the twin's response
    # leg shape at F=3, slack=2.
    nl = N // d
    C = 2 * (nl * 3 // d)
    y = jax.device_put(jnp.ones((d * d, C, K), jnp.int32),
                       NamedSharding(mesh, P("x")))

    def a2a(v):
        def f(vl):
            return lax.all_to_all(vl, "x", 0, 0) + 1
        return shard_map(f, mesh=mesh, in_specs=P("x"),
                         out_specs=P("x"))(v)

    # The ring exchange's collective: d-1 ppermute hops of one [nl, K]
    # block (the board_exchange="ring" schedule, docs/sharding.md) —
    # the streamed alternative to replicating the whole board.
    perm = [(i, (i - 1) % d) for i in range(d)]

    def ring(v):
        def f(vl):
            buf = vl
            acc = vl
            for _ in range(d - 1):
                buf = lax.ppermute(buf, "x", perm)
                acc = acc + buf[0, 0]
            return acc
        return shard_map(f, mesh=mesh, in_specs=P("x"),
                         out_specs=P("x"))(v)

    ag_ms = timed(ag, x)
    a2a_ms = timed(a2a, y)
    ring_ms = timed(ring, x)
    # Per-device receive payloads, for the per-byte comparison: the
    # all_gather receives the other shards' blocks ((d-1)/d of the
    # board), the a2a its bucketed responses, the ring d-1 blocks.
    ag_mb = BOARD_BYTES * (d - 1) / d / 1e6
    a2a_mb = d * C * K * 4 / 1e6
    ring_mb = (d - 1) * (N // d) * K * 4 / 1e6
    return {
        "what": "the twin's board-exchange collectives over the "
                "virtual 8-device CPU mesh — STRUCTURAL evidence "
                "(schedule + relative cost), not TPU wall-clock",
        "devices": d,
        "board_mb": round(BOARD_BYTES / 1e6, 1),
        "a2a_payload_mb": round(d * d * C * K * 4 / 1e6, 1),
        "cpu_mesh_all_gather_ms": round(ag_ms, 3),
        "cpu_mesh_all_to_all_ms": round(a2a_ms, 3),
        "cpu_mesh_ppermute_ring_ms": round(ring_ms, 3),
        "cpu_mesh_ms_per_recv_mb": {
            "all_gather": round(ag_ms / ag_mb, 4),
            "all_to_all": round(a2a_ms / a2a_mb, 4),
            "ppermute_ring": round(ring_ms / ring_mb, 4),
        },
    }


def ici_projection():
    # v5e: 4 ICI links/chip in the 2D torus at ~45 GB/s bidirectional
    # each ("How to Scale Your Model", v5e row).  An all_gather of B
    # bytes over a d-device ring moves B·(d-1)/d per device.
    links_gbps = 45.0
    d = 8
    per_dev = BOARD_BYTES * (d - 1) / d
    ms = per_dev / (links_gbps * 1e9) * 1e3
    return {
        "what": "PROJECTION arithmetic, stated with inputs (no "
                "multi-chip hardware attached to measure it)",
        "assumed_ici_gbps_per_direction": links_gbps,
        "devices": d,
        "all_gather_bytes_per_device": int(per_dev),
        "projected_all_gather_ms": round(ms, 3),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu-mesh", action="store_true")
    opts = ap.parse_args()
    if opts.cpu_mesh:
        out = {"cpu_mesh": cpu_mesh_collectives(),
               "ici_projection": ici_projection()}
    else:
        out = {"tpu": tpu_hbm_floor(),
               "ici_projection": ici_projection()}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
