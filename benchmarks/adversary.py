"""Byzantine-peer blast radius: the combined attack program with the
defense ladder OFF vs ON — the bench `adversary` block.

The scenario is the config6 cluster shape (docs/chaos.md) under the
COMBINED attack program docs/chaos.md's defense-ladder section names:

* a **tombstone bomb** — two colluding nodes forge TOMBSTONE records
  for the victim half's slots at their current tick, every round
  (LWW poison that kills live services until the next refresh, then
  kills them again);
* a **future flood** — one node stamps forged ALIVE records a minute
  into the future (unrefreshable poison only the future-admission
  bound or the origin budget can stop);
* a **sybil flood** — one node floods forged-fresh ALIVE records
  *under* the future bound (caught only by the per-origin budget and
  the quarantine it feeds).

Three runs share one driver seed and one AdversaryPlan:

* ``baseline`` — attack OFF, defenses OFF: the honest rounds-to-ε the
  headline's convergence-tax claim is read against;
* ``defense_off`` — attack ON, every defense knob off (the pre-PR
  protocol under attack): the unmitigated blast radius;
* ``defense_on`` — attack ON, the full ladder on
  (``future_fudge_s`` + ``origin_budget`` + ``origin_quarantine``).

Per round, host-side numpy diffs of the carried state count the blast:

* ``fp_tombstones`` — belief cells ENTERING tombstone status with a
  live owner (the flight recorder's definition, ops/trace.py).  The
  alive lifespan is longer than the run, so no honest expiry fires:
  every single one is attack damage.
* ``poisoned_rows_final`` — cells in HONEST (non-attacker) tables
  stamped ahead of the true clock at the end of the run — the future
  flood's footprint (the sybil flood's small displacement ages out).
* ``proxy_churn_observer`` — alive↔not-alive flips in an honest
  victim's row: routing reloads an attached proxy would take.
* ``bytes`` — two components, reported separately: the analytic
  honest offer volume (ops/trace.offer_census — attack-induced churn
  re-arms transmissions, so the bomb amplifies HONEST bytes too) and
  the forged wire volume (forged columns × fanout ×
  RECORD_WIRE_BYTES).  Quarantine zeroes an attacker's send channel,
  so the ON run's forged volume stops growing at the quarantine
  round.
* ``rounds_to_eps`` — defenses must not buy their reduction by
  converging slower (the headline pins ON ≤ 1.10× baseline).

Run standalone: ``python benchmarks/adversary.py [n]`` — prints the
JSON block bench.py embeds (BENCH_ADVERSARY=0 skips it there).
"""

from __future__ import annotations

import json
import pathlib
import sys

if __name__ == "__main__":  # standalone: resolve the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# Defense-knob values for the ON run: the fudge sits under the ttl
# sweep's +1 s supersede bump (the run_skew rationale,
# benchmarks/robustness.py); the budget admits one suspicious
# third-party record per packet (honest packets almost never carry
# more — a relayed tombstone travels alone); the quarantine threshold
# is under one round of sustained config6-fanout flooding beyond the
# budget, yet several isolated noisy packets away for an honest node.
DEFENSE_FUDGE_S = 0.5
DEFENSE_BUDGET = 1
DEFENSE_QUARANTINE = 12


def combined_attack(n: int, start_round: int = 10,
                    future_s: float = 60.0, sybil_s: float = 0.4,
                    seed: int = 6):
    """The headline AdversaryPlan: bomb + future flood + sybil flood
    from four colluding nodes, for the rest of the run."""
    from sidecar_tpu.chaos.adversary import AdversaryPlan, Attack
    from sidecar_tpu.models.timecfg import TimeConfig

    tc = TimeConfig()  # tick scale only (ticks() is cfg-independent)
    victims = tuple(range(n // 2, n))
    return AdversaryPlan(seed=seed, attacks=(
        Attack(kind="tombstone_bomb", nodes=(0, 1), victims=victims,
               rate=0.5, start_round=start_round),
        Attack(kind="future_flood", nodes=(2,), victims=victims,
               rate=0.4, magnitude_ticks=tc.ticks(future_s),
               start_round=start_round),
        Attack(kind="sybil_flood", nodes=(3,), victims=victims,
               rate=0.4, magnitude_ticks=tc.ticks(sybil_s),
               start_round=start_round),
    ))


def _measure_adv(n: int, spn: int, rounds: int, *, attack: bool,
                 defenses: bool, eps: float, seed: int,
                 topo=None) -> dict:
    """One run of the scenario.  ``attack`` arms the AdversaryPlan;
    ``defenses`` turns the whole ladder on.  Defenses-off runs leave
    every knob at its negative sentinel, so they execute the pre-PR
    merge program bit for bit (tests/test_adversary.py pins this).
    ``topo`` overrides the complete-graph overlay — the ``--chaos``
    topology chart (benchmarks/topology_sweep.py) reuses this loop
    per overlay."""
    import jax
    import numpy as np

    from sidecar_tpu.chaos import ChaosExactSim, FaultPlan
    from sidecar_tpu.models.exact import SimParams
    from sidecar_tpu.models.timecfg import TimeConfig
    from sidecar_tpu.ops import topology
    from sidecar_tpu.ops.gossip import eligible_records
    from sidecar_tpu.ops.status import ALIVE, TOMBSTONE
    from sidecar_tpu.ops.trace import RECORD_WIRE_BYTES, offer_census

    cfg = TimeConfig(
        refresh_interval_s=4.0, alive_lifespan_s=80.0,
        sweep_interval_s=0.4, push_pull_interval_s=1.0,
        future_fudge_s=DEFENSE_FUDGE_S if defenses else -1.0,
        origin_budget=DEFENSE_BUDGET if defenses else -1,
        origin_quarantine=DEFENSE_QUARANTINE if defenses else -1)
    params = SimParams(n=n, services_per_node=spn, fanout=3, budget=15)
    adv = combined_attack(n) if attack else None
    sim = ChaosExactSim(params, topo or topology.complete(n), cfg,
                        plan=FaultPlan(seed=6), adversary=adv)
    cst = sim.init_state()
    key = jax.random.PRNGKey(seed)

    owner = np.arange(params.m) // spn
    attackers = np.zeros(n, dtype=bool)
    if attack:
        attackers[list(adv.attackers(n))] = True
    honest = ~attackers
    observer = n - 1  # an honest victim's routing view
    limit = params.resolved_retransmit_limit()
    budget = min(params.budget, params.m)

    def status_of(row):
        known = (row >> 3) > 0
        return np.where(known, row & 7, -1)

    prev_known = np.asarray(cst.sim.known)
    prev_obs = status_of(prev_known[observer])
    fp_total = 0
    churn_total = 0
    honest_bytes = 0
    eps_round = None
    conv = 0.0
    conv_tail = []

    for r in range(rounds):
        # Pre-round analytic offer census (the flight recorder's
        # exchange_bytes definition) — the attack's HONEST-traffic
        # amplification: poisoned cells re-arm their transmissions.
        elig = np.asarray(eligible_records(
            cst.sim.known, cst.sim.sent, limit))
        per_row = elig.sum(axis=1)
        honest_bytes += int(np.minimum(per_row, budget).sum()
                            * params.fanout * RECORD_WIRE_BYTES)
        cst = sim.step(cst, jax.random.fold_in(key, cst.sim.round_idx))
        known = np.asarray(cst.sim.known)
        alive = np.asarray(cst.sim.node_alive)
        st = status_of(known)
        prev_st = status_of(prev_known)
        entered = (st == TOMBSTONE) & (prev_st != TOMBSTONE)
        fp_total += int((entered & alive[owner][None, :]).sum())
        obs = st[observer]
        moved = ((prev_obs == ALIVE) != (obs == ALIVE)) & (prev_obs >= 0)
        churn_total += int(moved.sum())
        prev_obs = obs
        prev_known = known
        conv = float(sim.convergence(cst))
        if r >= (3 * rounds) // 4:
            conv_tail.append(conv)
        if eps_round is None and conv >= 1.0 - eps:
            eps_round = r + 1

    now_tick = int(cst.sim.round_idx) * cfg.round_ticks
    ts = known >> 3
    poisoned = int(((ts > now_tick) & honest[:, None]).sum())
    counts = sim.injection_counts(cst)
    forged_bytes = counts["forged"] * params.fanout * RECORD_WIRE_BYTES

    return {
        "attack": attack,
        "defenses": defenses,
        "fp_tombstones": fp_total,
        "poisoned_rows_final": poisoned,
        "proxy_churn_observer": churn_total,
        "honest_offer_bytes": honest_bytes,
        "forged_wire_bytes": forged_bytes,
        "forged_records": counts["forged"],
        "rejected_future": counts["rejected_future"],
        "rejected_budget": counts["rejected_budget"],
        "quarantined_origins": counts["quarantined"],
        "rounds_to_eps": eps_round,
        "final_convergence": round(conv, 6),
        "mean_tail_convergence": round(
            sum(conv_tail) / max(len(conv_tail), 1), 6),
    }


def run_adversary(n: int = 128, spn: int = 2, rounds: int = 200,
                  eps: float = 0.2, seed: int = 6) -> dict:
    """The bench ``adversary`` block: baseline (no attack), attack with
    defenses OFF, attack with the full ladder ON, and the headline
    reduction ratios (docs/chaos.md pins ≥ 10× on poisoned rows and FP
    tombstones at ≤ 1.10× baseline rounds-to-ε)."""
    from sidecar_tpu import metrics

    baseline = _measure_adv(n, spn, rounds, attack=False,
                            defenses=False, eps=eps, seed=seed)
    off = _measure_adv(n, spn, rounds, attack=True, defenses=False,
                       eps=eps, seed=seed)
    on = _measure_adv(n, spn, rounds, attack=True, defenses=True,
                      eps=eps, seed=seed)

    def ratio(a, b):
        if b == 0:
            return None if a == 0 else float("inf")
        return round(a / b, 2)

    metrics.incr("adversary.sim.forgedRecords", on["forged_records"])
    metrics.incr("defense.sim.rejectedBudget", on["rejected_budget"])

    conv_tax = None
    if baseline["rounds_to_eps"] and on["rounds_to_eps"]:
        conv_tax = round(on["rounds_to_eps"] / baseline["rounds_to_eps"],
                         3)
    return {
        "scenario": "config6 scale, combined tombstone-bomb + "
                    "future-flood + sybil-flood from 4 colluding "
                    "nodes; defense ladder OFF vs ON (docs/chaos.md)",
        "n": n,
        "rounds": rounds,
        "defense_knobs": {"future_fudge_s": DEFENSE_FUDGE_S,
                          "origin_budget": DEFENSE_BUDGET,
                          "origin_quarantine": DEFENSE_QUARANTINE},
        "baseline": baseline,
        "defense_off": off,
        "defense_on": on,
        "poisoned_row_reduction": ratio(off["poisoned_rows_final"],
                                        on["poisoned_rows_final"]),
        "fp_tombstone_reduction": ratio(off["fp_tombstones"],
                                        on["fp_tombstones"]),
        "proxy_churn_reduction": ratio(off["proxy_churn_observer"],
                                       on["proxy_churn_observer"]),
        "bytes_amplification_off": ratio(
            off["honest_offer_bytes"] + off["forged_wire_bytes"],
            baseline["honest_offer_bytes"]),
        "bytes_amplification_on": ratio(
            on["honest_offer_bytes"] + on["forged_wire_bytes"],
            baseline["honest_offer_bytes"]),
        "convergence_tax_on": conv_tax,
    }


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    print(json.dumps(run_adversary(n=n), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
