"""Topology byte-cut bench — the locality-aware overlay proof.

The claim (docs/topology.md, docs/sharding.md): a zone-aware overlay
whose zones align with mesh shards lets the sharded board exchange
ship only the narrow cross-shard row blocks the overlay can actually
sample (``board_exchange="zoned"``), cutting cross-shard exchange
bytes by >= 2x vs the uniform ``all_gather`` board — while the
overlay's mixing stays good enough that rounds-to-epsilon lands
within 10% of the complete-graph baseline.

Both sides of the trade are measured, not asserted:

* **bytes** — twice over: the analytic per-round model
  (``sim.exchange_bytes_per_round``, cross-shard rows only on both
  modes) AND the bytes the compiled program actually moves, read off
  the optimized HLO by ``telemetry/cost.measured_exchange_bytes``
  under forced phase scopes (the benchmarks/sharded_scaling.py
  cost-row pattern; measured == analytic exactly for d > 1).
* **rounds** — both sims cold-start (every owner knows only its own
  services) and run the REAL protocol to epsilon-convergence; the
  ratio ``zoned / complete`` is the locality tax.

Run standalone (spins up an 8-virtual-device CPU mesh)::

    python benchmarks/topology_sweep.py [n]

or via bench.py (BENCH_TOPOLOGY=1, default on; knobs below).  Inside
bench.py the mesh width adapts to the devices the run actually has —
fewer than 2 devices skips the block (no cross-shard wire exists).
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

if __name__ == "__main__":
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

from sidecar_tpu.telemetry import cost  # noqa: E402
from sidecar_tpu.models.exact import SimParams  # noqa: E402
from sidecar_tpu.models.timecfg import TimeConfig  # noqa: E402
from sidecar_tpu.ops import topology  # noqa: E402
from sidecar_tpu.parallel.mesh import make_mesh  # noqa: E402
from sidecar_tpu.parallel.sharded import ShardedSim  # noqa: E402


def _pick_devices(n: int, d=None) -> int:
    """Widest power-of-two mesh this process can actually build: at
    most 8 (the bench's reference width), bounded by the devices
    present, and dividing n (the shard contract)."""
    if d is not None:
        return int(d)
    avail = len(jax.devices())
    for cand in (8, 4, 2, 1):
        if cand <= avail and n % cand == 0:
            return cand
    return 1


def _rounds_to_eps(sim, key, eps: float, horizon: int, chunk: int = 8):
    """First round index whose convergence >= 1 - eps (cold start),
    early-stopping on the chunk that crosses.  Returns ``(round or
    None, final convergence seen)``."""
    state = sim.init_state()
    done = 0
    final = 0.0
    while done < horizon:
        step = min(chunk, horizon - done)
        key, sub = jax.random.split(key)
        state, conv = sim.run(state, sub, step, start_round=done)
        conv = jax.device_get(conv)
        final = float(conv[-1])
        for i, c in enumerate(conv):
            if float(c) >= 1.0 - eps:
                return done + i + 1, float(c)
        done += step
    return None, final


def _cost_row(label: str, sim, mode: str, d: int) -> dict:
    """Measured-from-HLO exchange bytes for one compiled step (the
    sharded_scaling.py pattern): exact agreement with the analytic
    model is part of the contract for d > 1."""
    st0 = sim.init_state()
    key = jax.random.PRNGKey(0)
    with cost.forced_phases(True):
        rep = cost.program_report(
            label, (lambda s: (lambda st, k: s._step(st, k)))(sim),
            st0, key, exchange_mode=mode, num_devices=d)
    analytic = int(sim.exchange_bytes_per_round)
    measured = int(rep.get("measured_exchange_bytes", 0))
    return {
        "exchange_bytes_analytic": analytic,
        "exchange_bytes_measured": measured,
        "exchange_bytes_match": measured == (analytic if d > 1 else 0),
    }


def run_topology_bench(n: int = 4096, *, d=None, zones=None,
                       spn: int = 1, fanout: int = 3, budget: int = 256,
                       rounds: int = 64, eps: float = 0.01,
                       local_hops: int = 32, remote_deg: int = 6,
                       local_bias: float = 0.4, gateways: int = 2,
                       seed: int = 0) -> dict:
    """The zoned-vs-all_gather trade at one configuration.

    Defaults follow the headline claim: n=4096 over an 8-shard mesh
    with whole-shard zones (zones = d — the strongest case of the
    alignment rule, docs/topology.md) and a dense local lattice:
    within-zone links are free wire (same shard), so a wide local tier
    buys mixing without bytes, and the narrow remote tier carries the
    only cross-shard traffic.  ``budget`` is raised above the protocol
    default so the cold-start fill is budget-bound in a tractable
    number of rounds on CPU; the byte-cut ratio is budget-invariant
    (both modes scale with the same per-row payload)."""
    d = _pick_devices(n, d)
    if d < 2:
        return {}
    if zones is None:
        zones = d
    params = SimParams(n=n, services_per_node=spn, fanout=fanout,
                       budget=budget)
    # Cold-start clock: no owner refresh re-stamps during the fill, so
    # convergence measures pure propagation (benchmarks/sweep.py).
    cfg = TimeConfig(refresh_interval_s=10_000.0)
    mesh = make_mesh(jax.devices()[:d])

    topo_z = topology.zoned(n, zones, local_hops=local_hops,
                            remote_deg=remote_deg, local_bias=local_bias,
                            gateways=gateways, seed=seed)
    sims = {
        "baseline": (ShardedSim(params, topology.complete(n), cfg,
                                mesh=mesh, board_exchange="all_gather"),
                     "all_gather", "complete"),
        "zoned": (ShardedSim(params, topo_z, cfg, mesh=mesh,
                             board_exchange="zoned"),
                  "zoned", topo_z.name),
    }
    out = {"n": n, "d": d, "zones": zones, "services_per_node": spn,
           "fanout": fanout, "budget": budget, "eps": eps,
           "rounds_horizon": rounds}
    for side, (sim, mode, tname) in sims.items():
        r2e, final = _rounds_to_eps(sim, jax.random.PRNGKey(seed), eps,
                                    rounds)
        row = {"topology": tname, "board_exchange": mode,
               "rounds_to_eps": r2e,
               "final_convergence": round(final, 6)}
        row.update(_cost_row(
            f"topology_sweep.{mode}.{tname}.n{n}.d{d}.b{budget}",
            sim, mode, d))
        out[side] = row
    ba, bz = out["baseline"], out["zoned"]
    if ba["exchange_bytes_analytic"] and bz["exchange_bytes_analytic"]:
        out["byte_cut_analytic_x"] = round(
            ba["exchange_bytes_analytic"] / bz["exchange_bytes_analytic"],
            2)
    if ba["exchange_bytes_measured"] and bz["exchange_bytes_measured"]:
        out["byte_cut_measured_x"] = round(
            ba["exchange_bytes_measured"] / bz["exchange_bytes_measured"],
            2)
    if ba["rounds_to_eps"] and bz["rounds_to_eps"]:
        out["rounds_ratio"] = round(
            bz["rounds_to_eps"] / ba["rounds_to_eps"], 3)
    # The acceptance flags the capacity planner reads off the record:
    # >= 2x cheaper wire, <= 10% more rounds.
    out["byte_cut_ok"] = (out.get("byte_cut_analytic_x", 0) >= 2.0
                          and out.get("byte_cut_measured_x", 0) >= 2.0)
    out["rounds_ok"] = (out.get("rounds_ratio") is not None
                        and out["rounds_ratio"] <= 1.10)
    return out


CHAOS_OVERLAYS = ("complete", "chord", "expander4", "er6", "ba2")


def run_chaos_topologies(n: int = 128, overlays=CHAOS_OVERLAYS, *,
                         spn: int = 2, rounds: int = 60, eps: float = 0.2,
                         seed: int = 6) -> dict:
    """``--chaos`` mode: the combined config6 attack program
    (benchmarks/adversary.combined_attack — tombstone bomb + future
    flood + sybil flood) with the full defense ladder ON, charted
    PER OVERLAY: rounds-to-ε vs the honest offer bytes each overlay
    spends getting there (docs/topology.md records the chart).

    Sparse random overlays are :func:`sidecar_tpu.ops.topology.repair`'d
    first — a fragmented ER draw never converges, and that would read
    as attack damage when it is a builder artifact.  The chart answers
    a capacity question the complete-graph headline cannot: which
    overlay families keep converging under Byzantine pressure, and at
    what wire cost.
    """
    from benchmarks.adversary import _measure_adv
    from sidecar_tpu.ops import topology as topo_mod

    out = {"n": n, "rounds_horizon": rounds, "eps": eps,
           "attack": "config6 combined plan, defense ladder ON",
           "overlays": {}}
    for name in overlays:
        topo = topo_mod.repair(topo_mod.from_name(name, n, seed=seed))
        row = _measure_adv(n, spn, rounds, attack=True, defenses=True,
                           eps=eps, seed=seed, topo=topo)
        out["overlays"][topo.name] = {
            "rounds_to_eps": row["rounds_to_eps"],
            "final_convergence": row["final_convergence"],
            "honest_offer_bytes": row["honest_offer_bytes"],
            "fp_tombstones": row["fp_tombstones"],
            "quarantined_origins": row["quarantined_origins"],
        }
    return out


def main() -> int:
    # The environment's sitecustomize pins jax to the default platform
    # at interpreter start; re-assert an explicit JAX_PLATFORMS choice.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    args = [a for a in sys.argv[1:] if a != "--chaos"]
    if "--chaos" in sys.argv[1:]:
        n = int(args[0]) if args else 128
        print(json.dumps(run_chaos_topologies(n=n), indent=2))
        return 0
    n = int(args[0]) if args else 4096
    print(json.dumps(run_topology_bench(n=n), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
