"""Scatter cost model on TPU — the measured roofline behind the two
state representations (models/exact.py vs models/compressed.py).

The dense exact model's round applies its gossip deliveries with XLA
scatters into ``known[N, M]`` (the batched ``AddServiceEntry`` merge,
catalog/services_state.go:293-373).  This benchmark measures what those
scatters actually cost at the headline bench shapes (N=4096, spn=10 →
671 MB operand) and pins the design conclusion stated in bench.py:

* **Scatter cost is a fixed property of the operand, not the update
  count.**  Measured v5e: ~7.5 ms at 1k updates → ~13 ms at 225k →
  ~20 ms at 900k, against a 5.4 ms full-tensor copy and a 6.7 ms
  elementwise max.  The scatter is NOT index-throughput-bound; it costs
  a full buffer rewrite plus ~2× overhead almost regardless of how few
  cells change.
* **No scatter formulation escapes it.**  1D-flattened, pre-sorted
  indices, ``indices_are_sorted=True`` + ``unique_indices=True``,
  row-aligned (rows = iota) forms, and donated/in-place buffers all
  measure within noise of the naive 2D scatter; a scatter inside a
  ``lax.scan`` body (the real setting, where XLA could alias the carried
  buffer) is identical.  There is no flag or layout that makes XLA TPU
  scatter cheap at these operand sizes.
* **Arbitrary-index gathers are nearly as bad** (~6-9 ms for 225k
  elements from the 671 MB tensor) while row-gathers and elementwise
  passes run at memory bandwidth.

Consequences (both taken by this codebase):

1. models/exact.py budgets ONE scatter per big tensor per round and
   concatenates every update source into it — more scatters, not more
   indices, is what costs.
2. models/compressed.py exists because of this wall: its board/pull
   round is pure elementwise/row-gather compute (ZERO per-round
   scatters) and clocks ~9× the dense model at equal N — the measured
   gap between the scatter-bound and bandwidth-bound regimes.

Run: python benchmarks/scatter_costs.py  → one JSON line with every
measurement, so the conclusion is re-checkable on any chip.
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

N, SPN, FANOUT, BUDGET = 4096, 10, 3, 15
M = N * SPN
U_ROUND = N * FANOUT * BUDGET  # deliveries per round at the bench shapes


def _timeit(fn, *args, iters=20):
    out = fn(*args)
    jax.block_until_ready(out)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    return round((time.perf_counter() - t0) / iters * 1e3, 2)


def main() -> None:
    rng = np.random.default_rng(0)
    known = jnp.asarray(rng.integers(1, 1 << 30, size=(N, M), dtype=np.int32))
    results: dict[str, float] = {}

    results["copy_ms"] = _timeit(jax.jit(lambda k: k + 0), known)
    results["elementwise_max_ms"] = _timeit(
        jax.jit(lambda k: jnp.maximum(k, k + 1)), known)

    # Index-count scaling: fixed cost dominates.
    for u in (1_000, U_ROUND, 900_000):
        r = jnp.asarray(rng.integers(0, N, size=u, dtype=np.int32))
        c = jnp.asarray(rng.integers(0, M, size=u, dtype=np.int32))
        v = jnp.asarray(rng.integers(1, 1 << 30, size=u, dtype=np.int32))
        results[f"scatter_max_u{u}_ms"] = _timeit(
            jax.jit(lambda k, r=r, c=c, v=v: k.at[r, c].max(v, mode="drop")),
            known)

    # Sorted + unique + flags: no better.
    idx = np.sort(rng.choice(N * M, size=U_ROUND, replace=False)).astype(
        np.int32)
    v = jnp.asarray(rng.integers(1, 1 << 30, size=U_ROUND, dtype=np.int32))

    @jax.jit
    def scat_flags(k, i, v):
        out = lax.scatter_max(
            k.reshape(-1), i[:, None], v,
            lax.ScatterDimensionNumbers(
                update_window_dims=(), inserted_window_dims=(0,),
                scatter_dims_to_operand_dims=(0,)),
            indices_are_sorted=True, unique_indices=True,
            mode=lax.GatherScatterMode.FILL_OR_DROP)
        return out.reshape(N, M)

    results["scatter_max_sorted_unique_ms"] = _timeit(
        scat_flags, known, jnp.asarray(idx), v)

    # Row-aligned (rows = iota, the record_transmissions shape): no better.
    si = jnp.asarray(
        rng.integers(0, M, size=(N, FANOUT * BUDGET), dtype=np.int32))
    sv = jnp.asarray(
        rng.integers(1, 1 << 30, size=(N, FANOUT * BUDGET), dtype=np.int32))

    @jax.jit
    def rowscat(k, si, sv):
        r = jnp.arange(N, dtype=jnp.int32)[:, None]
        return k.at[r, si].max(sv, mode="drop")

    results["scatter_max_row_aligned_ms"] = _timeit(rowscat, known, si, sv)

    # Inside a scan body (carried buffer — XLA could alias): identical.
    r_s = jnp.asarray(rng.integers(0, N, size=U_ROUND, dtype=np.int32))
    c_s = jnp.asarray(rng.integers(0, M, size=U_ROUND, dtype=np.int32))
    v_s = jnp.asarray(rng.integers(1, 1 << 30, size=U_ROUND, dtype=np.int32))

    @partial(jax.jit, static_argnums=1)
    def scan_scatter(k, iters):
        def body(kk, i):
            return kk.at[(r_s + i) % N, c_s].max(v_s + i, mode="drop"), None
        out, _ = lax.scan(body, k, jnp.arange(iters, dtype=jnp.int32))
        return out

    out = scan_scatter(known, 20)
    jax.device_get(out.ravel()[:1])
    t0 = time.perf_counter()
    out = scan_scatter(known, 20)
    jax.device_get(out.ravel()[:1])
    results["scatter_max_in_scan_ms"] = round(
        (time.perf_counter() - t0) / 20 * 1e3, 2)

    # Arbitrary-index gather (prepare_deliveries' pre-value read).
    results["gather_arbitrary_ms"] = _timeit(
        jax.jit(lambda k: k[r_s, c_s]), known)

    fixed = results["scatter_max_u1000_ms"]
    full = results[f"scatter_max_u{U_ROUND}_ms"]
    print(json.dumps({
        "metric": f"XLA scatter cost model, int32 [{N}, {M}] (671 MB)",
        "platform": jax.devices()[0].platform,
        "verdict": "scatter-bound: fixed cost ~= "
                   f"{fixed:.1f} ms at 1k updates vs {full:.1f} ms at "
                   f"{U_ROUND} (one round's deliveries); copy "
                   f"{results['copy_ms']:.1f} ms; no formulation escapes",
        **results,
    }))


if __name__ == "__main__":
    sys.exit(main())
