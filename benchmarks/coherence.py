"""Coherence-observatory bench block (bench.py ``coherence`` key).

Two claims the digest plane makes, measured:

1. **The in-scan digest is free where it matters** — ``run_with_digest``
   must not perturb the trajectory (same per-round fold_in keys, digest
   columns ride alongside), so rounds-to-ε is identical to the
   digest-off run by construction; the block VERIFIES that by final-
   state bit-comparison and reports the rounds-to-ε ratio (the
   acceptance bound is ≤ 1.02) plus the honest wall-clock overhead of
   computing the digest columns every round.

2. **The live incremental digest is cheap and lock-free to read** —
   a writer micro-bench (adds/sec through the full
   ``add_service_entry`` merge kernel with the digest maintained) and
   a reader micro-bench (``digest_doc`` snapshot reads/sec, which
   never touch ``state._lock``).

Env contract (docs/env.md): ``BENCH_COHERENCE=0`` skips the block;
``BENCH_COHERENCE_NODES`` (default 4096), ``BENCH_COHERENCE_ROUNDS``
(default 96) and ``BENCH_COHERENCE_BUCKETS`` (default
ops/digest.DEFAULT_BUCKETS) size it.
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np

from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import digest as digest_ops
from sidecar_tpu.ops.topology import erdos_renyi


def _tree_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def run_coherence_bench(n: int = 4096, spn: int = 4, rounds: int = 96,
                        buckets: int = digest_ops.DEFAULT_BUCKETS,
                        eps: float = 1e-3) -> dict:
    """One digest-off + one digest-on run from the SAME churn burst,
    same key — the digest-on trajectory must be bit-identical, so the
    rounds-to-ε ratio the acceptance bound caps at 1.02 is exactly 1.0
    whenever ``bit_identical`` holds (and reported null, never a
    silent pass, when it does not)."""
    cfg = TimeConfig(refresh_interval_s=10_000.0, push_pull_interval_s=4.0)
    params = CompressedParams(n=n, services_per_node=spn, fanout=3,
                              budget=15, cache_lines=64)
    sim = CompressedSim(params, erdos_renyi(n, avg_degree=8.0, seed=3),
                        cfg)
    rng = np.random.default_rng(7)
    slots = np.sort(rng.choice(params.m, size=max(1, params.m // 1000),
                               replace=False)).astype(np.int32)
    state = sim.mint(sim.init_state(), slots, 10)
    key = jax.random.PRNGKey(0)

    # Warm both programs off-trajectory (donate=False copies).
    off_w, c_w = sim.run_behind(state, key, rounds, 1, donate=False,
                                sparse=False)
    jax.device_get(c_w)
    del off_w, c_w
    on_w = sim.run_with_digest(state, key, rounds, cap=rounds,
                               buckets=buckets, donate=False,
                               sparse=False)
    jax.device_get(jax.tree_util.tree_leaves(on_w[1]))
    del on_w

    t0 = time.perf_counter()
    final_off, behind = sim.run_behind(state, key, rounds, 1,
                                       donate=False, sparse=False)
    behind = np.asarray(jax.device_get(behind), dtype=np.float64)
    wall_off = time.perf_counter() - t0

    t0 = time.perf_counter()
    final_on, dt = sim.run_with_digest(state, key, rounds, cap=rounds,
                                       buckets=buckets, donate=False,
                                       sparse=False)
    jax.device_get(jax.tree_util.tree_leaves(dt))
    wall_on = time.perf_counter() - t0

    nm = float(n) * float(params.m)
    thr = eps * nm
    hit = next((i + 1 for i, b in enumerate(behind) if b <= thr), None)
    bit_identical = _tree_equal(final_off, final_on)
    summary = digest_ops.summarize_digest(dt)

    # Live writer/reader micro-bench: the merge kernel with the digest
    # maintained, then the lock-free snapshot read path.
    from sidecar_tpu import service as S
    from sidecar_tpu.catalog.state import ServicesState

    NS = S.NS_PER_SECOND
    t_base = 1_700_000_000 * NS
    st = ServicesState(hostname="bench-host")
    st.set_clock(lambda: t_base)
    adds = 2000
    t0 = time.perf_counter()
    for i in range(adds):
        st.add_service_entry(S.Service(
            id=f"svc{i % 500}", name="bench", image="i:1",
            hostname=f"host{i % 8}", updated=t_base + i,
            status=S.ALIVE))
    wall_adds = time.perf_counter() - t0
    reads = 20000
    t0 = time.perf_counter()
    for _ in range(reads):
        st.digest_doc()
    wall_reads = time.perf_counter() - t0

    return {
        "n": n, "spn": spn, "rounds": rounds, "buckets": buckets,
        "eps": eps,
        "digest_off": {
            "rounds_to_eps": hit,
            "wall_s": round(wall_off, 4),
            "rounds_per_sec": round(rounds / wall_off, 2),
        },
        "digest_on": {
            "wall_s": round(wall_on, 4),
            "rounds_per_sec": round(rounds / wall_on, 2),
            "round_coherent": summary["round_coherent"],
            "agreement_last": summary["agreement_last"],
            "diff_total_last": summary["diff_total_last"],
        },
        "bit_identical": bit_identical,
        # State-identical trajectories cross every ε threshold on the
        # same round — the ratio is 1.0 by construction, null (never a
        # silent pass) if bit-identity were ever lost.
        "rounds_to_eps_ratio": 1.0 if bit_identical else None,
        "wall_overhead_ratio": round(wall_on / wall_off, 4)
        if wall_off > 0 else None,
        "live": {
            "adds": adds,
            "adds_per_sec": round(adds / wall_adds, 1),
            "digest_records": st.digest_snapshot[0],
            "snapshot_reads_per_sec": round(reads / wall_reads, 1),
            "lock_free_read": True,
        },
    }


if __name__ == "__main__":  # pragma: no cover - manual runs
    import json

    print(json.dumps(run_coherence_bench(
        n=int(os.environ.get("BENCH_COHERENCE_NODES", "4096")),
        rounds=int(os.environ.get("BENCH_COHERENCE_ROUNDS", "96")),
        buckets=int(os.environ.get("BENCH_COHERENCE_BUCKETS", "64"))),
        indent=2))
