"""Anti-entropy heal bench block (bench.py ``antientropy`` key).

The claim docs/antientropy.md makes, measured twice:

1. **Live twin (measured bytes)** — two real ``ServicesState``
   catalogs diverged by a partition-shaped delta heal two ways from
   identical starting pairs: the full-body push-pull exchange (both
   annotated catalogs cross the wire, the pre-ladder status quo) and a
   digest-directed ``ReconcileSession`` (Merkle-ladder walk, then only
   the records in differing leaf buckets).  Both must land on
   byte-identical digests; the block reports the measured JSON bytes
   and wall-clock of each, so ``bytes_ratio`` (full/digest, the ≥ 5×
   acceptance bar) and ``heal_time_ratio`` (digest/full, the ≤ 1.10
   bar) are real measurements, not estimates.

2. **Sim twin (cluster-scale extrapolation)** — one config6-style
   asymmetric partition (full cut rounds [10, 40) plus 20% A→B loss
   for the whole run, churn on side A only, mid-partition) through
   ``ChaosExactSim.run_with_digest``.  The digest trace gives the
   per-round diverged-bucket counts and the heal round; the byte model
   prices each post-heal session both ways — full body = the whole
   catalog in both directions, digest-directed = the ladder walk plus
   the diverged records — using the *live twin's measured* per-record
   and per-bucket byte costs, so the sim ratio extrapolates measured
   constants rather than inventing them.  Digest direction changes
   which BYTES carry the records, never which records arrive (the
   full body is a superset of every divergent record), so the
   heal-round trajectory is shared and the sim heal-time ratio is 1.0
   by construction — reported null, never a silent pass, if the heal
   never completes inside the horizon.

Env contract (docs/env.md): ``BENCH_ANTIENTROPY=0`` skips the block;
``BENCH_ANTIENTROPY_NODES`` (default 64) sizes the sim cluster,
``BENCH_ANTIENTROPY_ROUNDS`` (default 120) its horizon,
``BENCH_ANTIENTROPY_CATALOG`` (default 1500) the live catalog size and
``BENCH_ANTIENTROPY_DIVERGED`` (default 30) the live divergence.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import jax
import numpy as np

from sidecar_tpu import service as S
from sidecar_tpu.catalog.state import ServicesState
from sidecar_tpu.models.exact import SimParams
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import digest as digest_ops
from sidecar_tpu.ops import topology as topo_mod
from sidecar_tpu.transport.antientropy import (AntiEntropyResponder,
                                               LoopbackChannel,
                                               ReconcileSession,
                                               SessionConfig, merge_body)

NS = S.NS_PER_SECOND
_T_BASE = 1_700_000_000 * NS


# -- live twin ---------------------------------------------------------------

def _build_pair(catalog: int, diverged: int):
    """One partition-shaped divergence: ``catalog`` shared records both
    sides agree on, plus ``diverged`` records split 2:1 across the cut
    (churn landed mostly on side A — the config6 asymmetry).  Built
    fresh per measurement so the full-body and digest-directed heals
    start from identical pairs."""
    a = ServicesState(hostname="side-a")
    b = ServicesState(hostname="side-b")
    for st in (a, b):
        st.set_clock(lambda: _T_BASE + 3600 * NS)
    for i in range(catalog):
        svc = S.Service(id=f"svc{i}", name=f"app{i % 40}",
                        image=f"img:{i % 7}", hostname=f"host{i % 64}",
                        updated=_T_BASE + i, status=S.ALIVE)
        a.add_service_entry(svc)
        b.add_service_entry(svc)
    cut_a = (2 * diverged) // 3
    for i in range(diverged):
        svc = S.Service(id=f"churn{i}", name="churned",
                        image="img:new", hostname=f"host{i % 64}",
                        updated=_T_BASE + catalog + i, status=S.ALIVE)
        (a if i < cut_a else b).add_service_entry(svc)
    return a, b


def _heal_full(a: ServicesState, b: ServicesState) -> dict:
    """The status-quo heal: both annotated catalogs cross the wire and
    both sides merge the other's body whole."""
    t0 = time.perf_counter()
    doc_a = a.encode_annotated()
    doc_b = b.encode_annotated()
    merge_body(b, json.loads(doc_a))
    merge_body(a, json.loads(doc_b))
    wall = time.perf_counter() - t0
    return {
        "bytes": len(doc_a) + len(doc_b),
        "wall_s": round(wall, 6),
        "coherent": a.digest_snapshot == b.digest_snapshot,
    }


def _heal_digest(a: ServicesState, b: ServicesState) -> dict:
    """The ladder heal: one ``ReconcileSession`` over a loopback
    channel — hello, narrowing levels, then only the records in
    differing leaf buckets, both directions."""
    chan = LoopbackChannel(AntiEntropyResponder(b))
    t0 = time.perf_counter()
    rep = ReconcileSession(a, chan, config=SessionConfig(),
                           enabled=True).run()
    wall = time.perf_counter() - t0
    return {
        "bytes": rep.total_bytes,
        "digest_bytes": rep.digest_bytes,
        "record_bytes": rep.record_bytes,
        "records_moved": rep.records_sent + rep.records_received,
        "levels_walked": rep.levels_walked,
        "mode": rep.mode,
        "wall_s": round(wall, 6),
        "coherent": bool(rep.coherent)
        and a.digest_snapshot == b.digest_snapshot,
    }


def _live_twin(catalog: int, diverged: int) -> dict:
    full = _heal_full(*_build_pair(catalog, diverged))
    digest = _heal_digest(*_build_pair(catalog, diverged))
    ok = full["coherent"] and digest["coherent"] \
        and digest["mode"] == "digest"
    return {
        "catalog": catalog, "diverged": diverged,
        "full": full, "digest": digest,
        "bytes_ratio": round(full["bytes"] / digest["bytes"], 2)
        if ok and digest["bytes"] else None,
        "heal_time_ratio": round(digest["wall_s"] / full["wall_s"], 4)
        if ok and full["wall_s"] > 0 else None,
    }


# -- sim twin ----------------------------------------------------------------

def _sim_twin(n: int, rounds: int, rec_bytes: float,
              bucket_hdr_bytes: float, seed: int = 6) -> dict:
    """config6-shaped partition → heal on the exact chaos model, byte
    model priced with the live twin's measured constants."""
    from sidecar_tpu.chaos import ChaosExactSim, EdgeFault, FaultPlan
    from sidecar_tpu.ops.status import ALIVE as _ALIVE
    from sidecar_tpu.ops.status import TOMBSTONE as _TOMB
    from sidecar_tpu.ops.status import pack as _pack
    from sidecar_tpu.ops.status import unpack_status as _ust
    from sidecar_tpu.ops.status import unpack_ts as _uts

    import jax.numpy as jnp

    n = max(16, n - n % 2)
    spn = 4
    split_at, lift_at = 10, 40
    side_a = tuple(range(n // 2))
    side_b = tuple(range(n // 2, n))
    plan = FaultPlan(
        seed=seed,
        edges=(EdgeFault(src=side_a, dst=side_b, drop_prob=0.2),),
    ).with_edges(*FaultPlan.partition(side_a, side_b, split_at, lift_at))

    params = SimParams(n=n, services_per_node=spn, fanout=3, budget=15)
    cfg = TimeConfig(refresh_interval_s=10_000.0, push_pull_interval_s=2.0)

    # Side-A-only churn mid-partition (config6's asymmetry): the heal
    # must carry the backlog across the cut.
    def perturb(state, key, now):
        round_idx = now // cfg.round_ticks
        active = (round_idx >= split_at + 5) & (round_idx < lift_at - 5)
        owner = jnp.arange(params.m, dtype=jnp.int32) // spn
        cols = jnp.arange(params.m, dtype=jnp.int32)
        churn = jax.random.bernoulli(key, 0.02 / spn, (params.m,))
        own = state.known[owner, cols]
        flip = churn & active & (owner < (n // 2)) & (_uts(own) > 0) & \
            state.node_alive[owner]
        st = _ust(own)
        new_val = jnp.where(
            flip, _pack(now, jnp.where(st == _ALIVE, _TOMB, _ALIVE)), own)
        known = state.known.at[owner, cols].set(new_val)
        reset = jnp.where(flip, owner, params.n)
        sent = state.sent.at[reset, cols].set(jnp.int8(0), mode="drop")
        return dataclasses.replace(state, known=known, sent=sent)

    sim = ChaosExactSim(params, topo_mod.complete(n), cfg, plan=plan,
                        perturb=perturb)
    _, dt, _ = sim.run_with_digest(sim.init_state(),
                                   jax.random.PRNGKey(seed), rounds,
                                   cap=rounds)
    rec = np.asarray(dt.rec)[:min(int(np.asarray(dt.count)), rounds)]
    rnds = rec[:, digest_ops.DIG_ROUND]
    alive = np.maximum(rec[:, digest_ops.DIG_ALIVE], 1)
    diff_total = rec[:, digest_ops.DIG_DIFF_TOTAL]
    coherent = (rec[:, digest_ops.DIG_AGREE] == rec[:, digest_ops.DIG_ALIVE])
    post = np.flatnonzero(coherent & (rnds >= lift_at))
    heal_round = int(rnds[post[0]]) if post.size else None

    # Byte model over the heal window [lift, heal]: one push-pull
    # session per alive node per round (the pp cadence at this cfg).
    # Full body ships the whole catalog both ways; digest-directed
    # ships the level-0 ladder + one narrowing header per differing
    # bucket per level + the diverged records (diff_total is the
    # digest plane's documented per-round diverged lower bound).
    # rec_bytes / bucket_hdr_bytes come MEASURED from the live twin.
    depth = digest_ops.DEFAULT_LADDER_DEPTH
    base = digest_ops.DEFAULT_BUCKETS
    full_bytes = digest_bytes = 0.0
    if heal_round is not None:
        window = (rnds >= lift_at) & (rnds <= heal_round)
        for a_r, d_r in zip(alive[window], diff_total[window]):
            sessions = float(a_r)
            full_bytes += sessions * 2 * params.m * rec_bytes
            digest_bytes += sessions * 2 * base * bucket_hdr_bytes
            digest_bytes += float(d_r) * depth * bucket_hdr_bytes
            digest_bytes += 2.0 * float(d_r) * rec_bytes
    return {
        "n": n, "spn": spn, "rounds": rounds,
        "partition": [split_at, lift_at],
        "heal_round": heal_round,
        "heal_rounds_after_lift": (heal_round - lift_at
                                   if heal_round is not None else None),
        "diff_peak": int(diff_total.max()) if diff_total.size else 0,
        "full_bytes_model": int(full_bytes),
        "digest_bytes_model": int(digest_bytes),
        "bytes_ratio": round(full_bytes / digest_bytes, 2)
        if heal_round is not None and digest_bytes > 0 else None,
        # Same records arrive either way (the full body is a superset
        # of the divergence), so the heal-round trajectory is shared:
        # 1.0 by construction, null if the heal never lands.
        "heal_time_ratio": 1.0 if heal_round is not None else None,
    }


# -- entry point -------------------------------------------------------------

def run_antientropy_bench(n: int = 64, rounds: int = 120,
                          catalog: int = 1500,
                          diverged: int = 30) -> dict:
    live = _live_twin(catalog, diverged)
    # Calibrate the sim byte model from the live measurement: bytes
    # per record from the full-body wire, bytes per ladder bucket
    # header from the session's digest traffic.
    rec_bytes = live["full"]["bytes"] / max(1, 2 * (catalog + diverged))
    dig = live["digest"]
    hdr = dig["digest_bytes"] / max(1, 2 * digest_ops.DEFAULT_BUCKETS
                                    + dig["levels_walked"])
    sim = _sim_twin(n, rounds, rec_bytes=rec_bytes, bucket_hdr_bytes=hdr)
    return {
        "live": live,
        "sim": sim,
        "rec_bytes_measured": round(rec_bytes, 1),
        "bytes_ratio": live["bytes_ratio"],
        "heal_time_ratio": live["heal_time_ratio"],
    }


if __name__ == "__main__":  # pragma: no cover - manual runs
    print(json.dumps(run_antientropy_bench(
        n=int(os.environ.get("BENCH_ANTIENTROPY_NODES", "64")),
        rounds=int(os.environ.get("BENCH_ANTIENTROPY_ROUNDS", "120")),
        catalog=int(os.environ.get("BENCH_ANTIENTROPY_CATALOG", "1500")),
        diverged=int(os.environ.get("BENCH_ANTIENTROPY_DIVERGED", "30"))),
        indent=2))
