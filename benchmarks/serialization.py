"""Serialization hot-path measurement — closes the ffjson question.

The reference generated ~7k LoC of pooled reflection-free JSON codecs
(ffjson) for its gossip hot path and recycles encode buffers through a
pool (services_delegate.go:136-141; catalog/services_state_ffjson.go).
This benchmark measures whether the Python rebuild needs an equivalent:
it times record encode/decode (the NotifyMsg / GetBroadcasts unit) and
full-state encode/decode (the LocalState / MergeRemoteState unit) and
compares against the protocol's actual demand rates.

Demand envelope (per node, defaults):
* gossip: GossipInterval 200 ms × GossipMessages 15 × fan-out 3 — the
  outbound loop encodes each record ONCE when broadcast (re-sends reuse
  the bytes), and inbound decodes ≤ 15 msgs × peers gossiping at us per
  round; worst-case order 10³ records/sec.
* anti-entropy: one full-state encode + decode per PushPullInterval
  (20 s) plus one per join.

Run: python benchmarks/serialization.py  → one JSON line.

Measured in this image (Python 3.12, stdlib json): record encode
~14 µs / decode ~40 µs → ~18k records/sec per core — ~80× the demand
envelope, ~1.2% of a core at protocol rates; a 100-server ×
10-service state (283 kB) encodes in ~11 ms / decodes in ~35 ms,
amortized over the 20 s push-pull interval (~0.2% of a core).
Verdict: stdlib json is NOT a meaningful fraction of live-path CPU; a
pooled/compiled codec (the ffjson analog) is not warranted at these
rates.  The numbers print fresh on every run so the conclusion is
re-checkable — the 5% core-fraction threshold flips the verdict string
if a future change makes encode hot."""

from __future__ import annotations

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from sidecar_tpu import service as S  # noqa: E402
from sidecar_tpu.catalog import ServicesState, decode as state_decode

NS = S.NS_PER_SECOND
T0 = 1_700_000_000 * NS


def bench(fn, n):
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def main() -> None:
    svc = S.Service(
        id="deadbeef1234", name="bench-svc", image="registry/app:1.2.3",
        hostname="bench-host-01", created=T0, updated=T0, status=S.ALIVE,
        proxy_mode="http",
        ports=[S.Port("tcp", 32768, 8080, "10.1.2.3"),
               S.Port("tcp", 32769, 8443, "10.1.2.3")])
    payload = svc.encode()

    enc_s = bench(svc.encode, 20_000)
    dec_s = bench(lambda: S.decode(payload), 20_000)

    # Full-state round trip: 100 servers × 10 services (a mid-size
    # cluster's push-pull payload).
    state = ServicesState(hostname="bench-host-01")
    state.set_clock(lambda: T0)
    for host in range(100):
        for i in range(10):
            state.add_service_entry(S.Service(
                id=f"{host:04d}{i:08d}", name=f"svc-{i}",
                image=f"registry/svc-{i}:9", hostname=f"host-{host:03d}",
                updated=T0, status=S.ALIVE,
                ports=[S.Port("tcp", 30000 + i, 8000 + i,
                              f"10.0.{host % 256}.{i}")]))
    blob = state.encode()
    state_enc_s = bench(state.encode, 50)
    state_dec_s = bench(lambda: state_decode(blob), 50)

    # Demand: outbound one encode per broadcast record (15 records/s at
    # the 1 Hz SendServices cadence is generous), inbound worst case all
    # peers' gossip budgets landing here.
    gossip_records_per_sec = 15 * 3 / 0.2    # budget × fanout / interval
    frac_core = gossip_records_per_sec * (enc_s + dec_s)

    print(json.dumps({
        "record_encode_us": round(enc_s * 1e6, 2),
        "record_decode_us": round(dec_s * 1e6, 2),
        "records_per_sec_per_core": int(1 / (enc_s + dec_s)),
        "state_1000_services_encode_ms": round(state_enc_s * 1e3, 2),
        "state_1000_services_decode_ms": round(state_dec_s * 1e3, 2),
        "state_bytes": len(blob),
        "gossip_demand_records_per_sec": int(gossip_records_per_sec),
        "gossip_serialization_core_fraction": round(frac_core, 5),
        "verdict": "stdlib json — pooled codec not warranted"
        if frac_core < 0.05 else "hot: consider a compiled codec",
    }))


if __name__ == "__main__":
    main()
