"""Closed-loop autopilot demo (docs/autopilot.md, bench `autopilot`).

The measured claim chain the ISSUE's acceptance criteria name, end to
end on one seeded scenario:

1. a "live" cluster — config6-seeded chaos (20% asymmetric A→B loss
   throughout, full 2-way partition rounds 20–80, one-sided churn
   rounds 30–60) running the STATUS-QUO clock — is observed through
   its flight-recorder trace + chaos injection counters;
2. ``fit_from_trace`` inverts the telemetry into a
   ``ConditionEstimate`` (no access to the FaultPlan ground truth);
3. the controller sweeps the knob space against operator SLO rules
   under the fitted twin: the status-quo baseline FAILS the SLO, the
   recommended bundle MEETS it;
4. the optimizer spends measurably fewer simulator evaluations than
   the exhaustive grid over the same axes (``eval_ratio``), and the
   winner's unbatched ``ExactSim``/``ChaosExactSim`` replay is
   bit-identical to its ``FleetSim`` row (``replay_bit_identical``).

Everything is deterministic under the block's seed; the block is the
regression gate for the whole loop.
"""

from __future__ import annotations

import dataclasses
import time

import jax

from sidecar_tpu.autopilot import AutopilotController, fit_from_trace
from sidecar_tpu.models.exact import SimParams
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import topology as topo_mod
from sidecar_tpu.ops.trace import trace_to_dicts

DEFAULT_RULES = ("converge <= 30 rounds", "agreement >= 0.99")


def _config6_sim(n: int, seed: int, cfg: TimeConfig, params: SimParams):
    """The bench's ground-truth environment: the sim/scenarios.py
    config6 chaos shape (asymmetric loss + partition + one-sided
    windowed churn) at bench scale, on the status-quo clock."""
    import jax.numpy as jnp

    from sidecar_tpu.chaos import ChaosExactSim, EdgeFault, FaultPlan
    from sidecar_tpu.ops.status import ALIVE, TOMBSTONE, pack
    from sidecar_tpu.ops.status import unpack_status, unpack_ts

    spn = params.services_per_node
    side_a = tuple(range(n // 2))
    side_b = tuple(range(n // 2, n))
    plan = FaultPlan(
        seed=seed,
        edges=(EdgeFault(src=side_a, dst=side_b, drop_prob=0.2),),
    ).with_edges(*FaultPlan.partition(side_a, side_b, 20, 80))

    def perturb(state, key, now):
        round_idx = now // cfg.round_ticks
        active = (round_idx >= 30) & (round_idx < 60)
        owner = jnp.arange(params.m, dtype=jnp.int32) // spn
        cols = jnp.arange(params.m, dtype=jnp.int32)
        on_side_a = owner < (n // 2)
        churn = jax.random.bernoulli(key, 0.02 / spn, (params.m,))
        own = state.known[owner, cols]
        flip = churn & active & on_side_a & (unpack_ts(own) > 0) & \
            state.node_alive[owner]
        st = unpack_status(own)
        new_status = jnp.where(st == ALIVE, TOMBSTONE, ALIVE)
        new_val = jnp.where(flip, pack(now, new_status), own)
        known = state.known.at[owner, cols].set(new_val)
        reset_rows = jnp.where(flip, owner, params.n)
        sent = state.sent.at[reset_rows, cols].set(jnp.int8(0),
                                                   mode="drop")
        return dataclasses.replace(state, known=known, sent=sent)

    return ChaosExactSim(params, topo_mod.complete(n), cfg, plan=plan,
                         perturb=perturb)


def run_autopilot_bench(*, n: int = 32, trace_rounds: int = 120,
                        rounds: int = 60, seed: int = 6,
                        rules=None, generations: int = 2,
                        population: int = 6) -> dict:
    """Run the closed loop and return the bench block."""
    t0 = time.perf_counter()
    n = max(8, n - n % 2)
    rules = list(rules or DEFAULT_RULES)
    params = SimParams(n=n, services_per_node=4, fanout=3, budget=15)
    # The status-quo clock the cluster is "running": reference-faithful
    # 20 s push-pull, cold-start refresh pinned (the sweep convention).
    cfg = TimeConfig(refresh_interval_s=10_000.0)

    # 1. observe the live cluster through its telemetry
    sim = _config6_sim(n, seed, cfg, params)
    final, tr, _conv = sim.run_with_trace(
        sim.init_state(), jax.random.PRNGKey(seed), trace_rounds,
        cap=trace_rounds)
    estimate = fit_from_trace(
        trace_to_dicts(tr), params=params,
        injections=sim.injection_counts(final), timecfg=cfg)

    # 2-4. fit → search → replay-verify, one controller pass
    ctl = AutopilotController(timecfg=cfg)
    report = ctl.recommend(
        rules=rules, estimate=estimate, n=n,
        services_per_node=params.services_per_node,
        fanout=params.fanout, budget=params.budget, rounds=rounds,
        seed=seed, generations=generations, population=population)

    base = report["baseline"]
    rec = report["recommended"]
    evals = report["evaluations"]
    grid = report["grid_points"]
    base_pass = None if base is None else base["slo"]["pass"]
    rec_pass = rec["slo"]["pass"]
    return {
        "n": n,
        "trace_rounds": trace_rounds,
        "rounds": rounds,
        "seed": seed,
        "scenario": "config6-seeded chaos: 20% A->B loss, partition "
                    "rounds 20-80, one-sided churn rounds 30-60, "
                    "status-quo 20 s push-pull clock",
        "slo": report["rules"],
        "fit": report["estimate"],
        "baseline": None if base is None else {
            "config": base["candidate"], "score": base["score"],
            "slo": base["slo"], "pass": base_pass},
        "recommended": {
            "config": rec["candidate"], "score": rec["score"],
            "slo": rec["slo"], "pass": rec_pass},
        # The three acceptance claims, measured:
        "closed_loop": bool(rec_pass) and base_pass is False,
        "evaluations": evals,
        "grid_points": grid,
        "eval_ratio": round(evals / grid, 4) if grid else None,
        "replay_bit_identical": report["replay"]["identical"]
        if report["replay"]["checked"] else None,
        "wall_seconds": round(time.perf_counter() - t0, 2),
    }


def main() -> None:
    import json
    import os

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    print(json.dumps(run_autopilot_bench(
        n=int(os.environ.get("BENCH_AUTOPILOT_NODES", "32")),
        rounds=int(os.environ.get("BENCH_AUTOPILOT_ROUNDS", "60"))),
        indent=2))


if __name__ == "__main__":
    main()
