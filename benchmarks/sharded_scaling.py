"""d-scaling + comm-overlap evidence for the sharded compressed twin
(VERDICT r4 #3b; exchange modes + overlap pipeline: RESULTS.md round 7).

Runs the SAME jitted step (ShardedCompressedSim.run_fast) at
d = 1/2/4/8 over the virtual CPU host platform.  On this bench host all
virtual "devices" share ONE physical core, so what the curve can and
does show is TOTAL-WORK CONSERVATION: wall-clock per round stays flat
as d grows, i.e. sharding introduces no hidden serial phase, no
superlinear collective blowup, and no replicated recompute — per-device
work is total/d by SPMD construction.  Wall-clock SPEEDUP with d
requires d real compute units (the v5e-8); this curve is the structural
half of that projection, the ICI half is benchmarks/collectives.py.

Two additions for the split-phase round (docs/sharding.md):

* every ``--exchange`` mode (all_gather | all_to_all | ring) runs
  through the same harness, and the record carries the mode plus its
  analytic per-round per-device exchange bytes;
* ``overlap_exposed_ms`` — the comm time NOT hidden behind compute,
  measured by differencing the full round against an exchange-stubbed
  build of the same program (``exchange_stub=True`` consumes only
  own-shard rows and skips the collectives) at the largest d.  On the
  shared-core virtual mesh "comm" is memcpy + schedule, so this is a
  structural bound, not an ICI wall-clock; the value is also published
  as the ``parallel.overlap.exposed_ms`` gauge.

The final stdout line is ONE machine-parseable JSON record (the
MULTICHIP_r*.json tail contract).

Run: python benchmarks/sharded_scaling.py [--n 32768] [--rounds 40]
     [--exchange all_gather|all_to_all|ring]
"""

import argparse
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from sidecar_tpu import metrics  # noqa: E402
from sidecar_tpu.telemetry import cost  # noqa: E402
from sidecar_tpu.models.compressed import CompressedParams  # noqa: E402
from sidecar_tpu.models.timecfg import TimeConfig  # noqa: E402
from sidecar_tpu.ops.topology import erdos_renyi  # noqa: E402
from sidecar_tpu.parallel.mesh import make_mesh  # noqa: E402
from sidecar_tpu.parallel.sharded_compressed import (  # noqa: E402
    ShardedCompressedSim,
)


def build(d, params, topo, cfg, exchange, stub=False):
    return ShardedCompressedSim(
        params, topo, cfg, mesh=make_mesh(jax.devices()[:d]),
        board_exchange=exchange, exchange_stub=stub)


def cost_row(sim, exchange, d):
    """Per-mode cost row (telemetry/cost.py): compile a FRESH phase-
    instrumented step, report lower/compile ms, HBM peak, and the
    measured-from-HLO exchange bytes cross-checked against the sim's
    analytic ``exchange_bytes_per_round``.  The pinned agreement bound
    (docs/perf.md): EXACT for d > 1; at d = 1 the collective is elided
    by XLA so measured is 0 (all_to_all's analytic formula still counts
    self-rows there)."""
    st0 = sim.init_state()
    key = jax.random.PRNGKey(0)
    with cost.forced_phases(True):
        rep = cost.program_report(
            f"sharded_scaling.{exchange}.d{d}",
            (lambda s: (lambda st, k: s._step(st, k)))(sim),
            st0, key, exchange_mode=exchange, num_devices=d)
    analytic = sim.exchange_bytes_per_round
    measured = rep.get("measured_exchange_bytes", 0)
    match = measured == (analytic if d > 1 else 0)
    return {
        "lower_ms": rep.get("lower_ms"),
        "compile_ms": rep.get("compile_ms"),
        "flops": rep.get("flops"),
        "bytes_accessed": rep.get("bytes_accessed"),
        "hbm_peak_bytes": rep.get("memory", {}).get("peak_bytes"),
        "exchange_bytes_measured": measured,
        "exchange_bytes_analytic": analytic,
        "exchange_bytes_match": match,
    }


def time_sim(sim, slots, rounds):
    state = sim.mint(sim.init_state(), slots, 10)
    key = jax.random.PRNGKey(0)
    # Warm then chain each rep off the previous output: the drivers
    # donate their input state (models/compressed.py).
    state = sim.run_fast(state, key, rounds)        # warm (same length)
    jax.device_get(state.round_idx)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        state = sim.run_fast(state, key, rounds)
        jax.device_get(state.round_idx)
        best = min(best, time.perf_counter() - t0)
    return best / rounds * 1000.0, sim.sync_exchange_metrics(state)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=32768)
    ap.add_argument("--rounds", type=int, default=40)
    ap.add_argument("--exchange", default="all_gather",
                    choices=["all_gather", "all_to_all", "ring"])
    opts = ap.parse_args()

    params = CompressedParams(n=opts.n, services_per_node=10, fanout=3,
                              budget=15, cache_lines=256,
                              fold_quorum=1.0, deep_sweep_every=0)
    topo = erdos_renyi(opts.n, avg_degree=8.0, seed=3)
    cfg = TimeConfig(refresh_interval_s=10_000.0)
    rng = np.random.default_rng(7)
    slots = np.sort(rng.choice(params.m, size=max(1, params.m // 1000),
                               replace=False)).astype(np.int32)

    curve, bytes_by_d, cost_by_d, dropped = {}, {}, {}, 0
    sim_dmax = None
    want_cost = os.environ.get("BENCH_COST", "1") != "0"
    for d in (1, 2, 4, 8):
        sim = build(d, params, topo, cfg, opts.exchange)
        if want_cost:
            cost_by_d[str(d)] = cost_row(sim, opts.exchange, d)
        ms, drops = time_sim(sim, slots, opts.rounds)
        curve[str(d)] = round(ms, 3)
        bytes_by_d[str(d)] = sim.exchange_bytes_per_round
        dropped += drops
        sim_dmax = sim

    # Exposed (non-overlapped) comm at the largest d: full round minus
    # the exchange-stubbed build of the same program.
    d_max = 8
    stub_ms, _ = time_sim(build(d_max, params, topo, cfg, opts.exchange,
                                stub=True), slots, opts.rounds)
    exposed = max(0.0, curve[str(d_max)] - stub_ms)
    metrics.set_gauge("parallel.overlap.exposed_ms", round(exposed, 3))

    # Flight-recorder pass at the largest d (ops/trace.py): per-round
    # MEASURED offer volume for this mode, alongside the analytic
    # per-device receive bytes — the comm telemetry the MULTICHIP
    # record carries per exchange mode.
    from sidecar_tpu.ops import trace as trace_ops
    tstate = sim_dmax.mint(sim_dmax.init_state(), slots, 10)
    _, tr = sim_dmax.run_with_trace(tstate, jax.random.PRNGKey(0), 8)
    round_trace = trace_ops.summarize(tr)

    d1 = curve["1"]
    print(json.dumps({
        "what": "sharded-twin ms/round vs device count on a 1-core "
                "virtual CPU mesh — flat curve = total work conserved "
                "under sharding (no hidden serial phases); wall-clock "
                "speedup needs d real compute units",
        "n": opts.n, "rounds_per_scan": opts.rounds,
        "board_exchange": opts.exchange,
        "ms_per_round_by_d": curve,
        "total_work_overhead_vs_d1": {
            d: round(v / d1 - 1.0, 3) for d, v in curve.items()},
        "exchange_bytes_per_round_per_device_by_d": bytes_by_d,
        **({"cost_by_d": cost_by_d} if cost_by_d else {}),
        "overlap_exposed_ms_d8": round(exposed, 3),
        "overlap_stub_ms_per_round_d8": round(stub_ms, 3),
        "dropped_pulls": dropped,
        "round_trace_d8": round_trace,
    }))


if __name__ == "__main__":
    main()
