"""DEAD-END LEDGER: every variant in this file was measured and the
conclusions are CONSOLIDATED in benchmarks/RESULTS.md ("Measured
primitive floors and dead ends") — read that table before re-running
anything here.  Round 6 superseded the XLA-level attack entirely: the
publish floors are now addressed by the fused Pallas kernels in
sidecar_tpu/ops/kernels/ (docs/kernels.md), and round 8 attacks the
remaining per-round cost from the other side (the sparse-frontier
path, docs/sparse.md).

All three experiment rounds live here as SUBCOMMANDS (they shipped as
hotpath_variants{,2,3}.py through round 7; consolidated in round 8 —
same variants, same harness, same numbers):

  r1  candidate optimizations for the compressed round's hot phases
      at north-star shapes:
        pub_roll    round-4 publish: top_k threshold + 16
                    conditional-roll tie rotation
        pub_cumsum  WINNER (shipped in round 5): same top_k threshold,
                    tie rank via ONE cumsum + a per-row gather (the
                    rotated prefix-sum identity; no rolls)
        pub_topk    top_k + threshold only (what the tie logic costs)
        g2x32       round-4 board gather: bval[src] + bslot[src]
        g1x64       dead end: pack (val,slot) into one int64 board,
                    gather once, unpack
        merge_loop  shipped merge: per-f sticky_adjust + lex_max
        merge_key   dead end: int64-key tree-reduce over F
  r2  int32-only follow-ups: approx_max_k vs exact top_k for the
      publish threshold (pub quality check included), gather forms
      (one [N,F] row gather vs 3×[N], fused reduce, val-only).
  r3  can the publish threshold beat exact int32 top_k?  (int16
      surrogate with dynamic shift; 64-bin recency histogram via
      one-hot matmul + cumsum.)  Answer: no — topk32 stands.

Each variant runs inside one lax.scan dispatch with per-iteration
varying inputs (so XLA cannot hoist the work out of the loop — the
trap the round-4 Pallas measurement caught) and folds a checksum into
the carry (so nothing dead-codes).  Times are ms per iteration, best
of 3.

Run: python benchmarks/hotpath_variants.py {r1,r2,r3} [--n 100000]
     (r1 also takes --only pub,gather,merge)
"""

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sidecar_tpu.ops import gossip as gossip_ops

K = 256
F = 3
BUDGET = 15
SLOT_BITS = 20          # M = 1M slots fits; key = (val << 20) | slot


def make_inputs(n, seed=0):
    rng = np.random.default_rng(seed)
    # A realistic cache: ~15% occupied lines, packed int32 vals.
    occ = rng.random((n, K)) < 0.15
    val = np.where(occ, rng.integers(1 << 6, 1 << 24, (n, K)), 0) \
        .astype(np.int32)
    slot = np.where(occ, rng.integers(0, n * 10, (n, K)), -1) \
        .astype(np.int32)
    sent = np.zeros((n, K), np.int8)
    return jnp.asarray(val), jnp.asarray(slot), jnp.asarray(sent)


def timed_scan(body, carry, iters=60, reps=3):
    @jax.jit
    def run(c):
        return lax.scan(body, c, jnp.arange(iters, dtype=jnp.int32))[0]

    out = run(carry)
    jax.device_get(jax.tree_util.tree_leaves(out)[0])
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(carry)
        jax.device_get(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1000.0


# -- r1: publish variants ----------------------------------------------------

def publish_roll(val, slot, sent, limit=15):
    eligible = (slot >= 0) & (sent.astype(jnp.int32) < limit)
    priority = jnp.where(eligible, val, 0)
    top = lax.top_k(priority, BUDGET)[0]
    thresh = top[:, -1:]
    above = priority > thresh
    tie = (priority == thresh) & (priority > 0)
    n_above = jnp.sum(above, axis=1, keepdims=True)
    n = priority.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    rot = (rows.astype(jnp.uint32) * jnp.uint32(gossip_ops.PHASE_MULT)
           & jnp.uint32(K - 1)).astype(jnp.int32)
    view = tie
    for b in range(K.bit_length() - 1):
        bit = ((rot >> b) & 1)[:, None] == 1
        view = jnp.where(bit, jnp.roll(view, -(1 << b), axis=1), view)
    rank = jnp.cumsum(view.astype(jnp.int32), axis=1)
    admit_rot = view & (rank <= BUDGET - n_above)
    for b in range(K.bit_length() - 1):
        bit = ((rot >> b) & 1)[:, None] == 1
        admit_rot = jnp.where(
            bit, jnp.roll(admit_rot, 1 << b, axis=1), admit_rot)
    selected = above | admit_rot
    return jnp.where(selected, val, 0), jnp.where(selected, slot, -1)


def publish_cumsum(val, slot, sent, limit=15):
    eligible = (slot >= 0) & (sent.astype(jnp.int32) < limit)
    priority = jnp.where(eligible, val, 0)
    top = lax.top_k(priority, BUDGET)[0]
    thresh = top[:, -1:]
    above = priority > thresh
    tie = (priority == thresh) & (priority > 0)
    n_above = jnp.sum(above, axis=1, keepdims=True)
    n = priority.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    rot = (rows.astype(jnp.uint32) * jnp.uint32(gossip_ops.PHASE_MULT)
           & jnp.uint32(K - 1)).astype(jnp.int32)
    # Rank of column j in the per-row rotation starting at rot:
    #   rank(j) = S[j] - S[rot-1]          for j >= rot
    #             S[j] + T - S[rot-1]      for j <  rot
    # with S the inclusive prefix sum and T the row total — the rotated
    # cumsum identity, replacing 16 conditional roll passes with one
    # cumsum and a [N]-sized gather.
    s = jnp.cumsum(tie.astype(jnp.int32), axis=1)
    total = s[:, -1:]
    base = jnp.where(rot[:, None] > 0,
                     jnp.take_along_axis(
                         s, jnp.maximum(rot[:, None] - 1, 0), axis=1),
                     0)
    cols = jnp.arange(K, dtype=jnp.int32)[None, :]
    rank = jnp.where(cols >= rot[:, None], s - base, s + total - base)
    admit = tie & (rank <= BUDGET - n_above)
    selected = above | admit
    return jnp.where(selected, val, 0), jnp.where(selected, slot, -1)


def publish_topk(val, slot, sent, limit=15):
    eligible = (slot >= 0) & (sent.astype(jnp.int32) < limit)
    priority = jnp.where(eligible, val, 0)
    top = lax.top_k(priority, BUDGET)[0]
    thresh = top[:, -1:]
    selected = priority >= thresh
    return jnp.where(selected, val, 0), jnp.where(selected, slot, -1)


# -- r1: gather + merge pieces -----------------------------------------------

def lex_max(wv, ws, cv, cs):
    adv = (cv > wv) | ((cv == wv) & (cs > ws))
    return jnp.where(adv, cv, wv), jnp.where(adv, cs, ws)


def sticky_adjust_stub(cand_v, cur_v, mask):
    # Shape/op-equivalent stand-in for ops.merge.sticky_adjust (status
    # rewrite on same-slot advance) — keeps the variant timing honest
    # without importing merge internals here.
    draining = (cur_v & 7) == 4
    rewrite = mask & draining
    return jnp.where(rewrite, (cand_v & ~7) | 4, cand_v)


def run_r1(opts):
    only = set(opts.only.split(",")) if opts.only else None

    def want(group):
        return only is None or group in only
    n = opts.n
    val, slot, sent = make_inputs(n)
    key0 = jax.random.PRNGKey(1)
    results = {}

    # publish variants: vary `sent` per iteration so nothing hoists.
    def mk_pub(fn):
        def body(carry, i):
            acc, sent_c = carry
            bval, bslot = fn(val, slot, sent_c)
            acc = acc + jnp.sum(bval) + jnp.sum(bslot)
            sent_c = (sent_c + jnp.int8(1)) % jnp.int8(8)
            return (acc, sent_c), None
        return body

    if want("pub"):
        for name, fn in [("pub_roll", publish_roll),
                         ("pub_cumsum", publish_cumsum),
                         ("pub_topk", publish_topk)]:
            results[name] = round(timed_scan(
                mk_pub(fn), (jnp.zeros((), jnp.int64), sent)), 3)
            print(json.dumps(results), flush=True)

    # Equivalence check for the cumsum rank (must match the roll form
    # bit-for-bit — same selected set).
    if want("pub"):
        bv_a, bs_a = jax.jit(publish_roll)(val, slot, sent)
        bv_b, bs_b = jax.jit(publish_cumsum)(val, slot, sent)
        results["pub_cumsum_equal"] = bool(
            jnp.array_equal(bv_a, bv_b) & jnp.array_equal(bs_a, bs_b))
        print(json.dumps(results), flush=True)

    # gather variants: src varies per iteration.
    bval, bslot = jax.jit(publish_roll)(val, slot, sent)
    key64 = (bval.astype(jnp.int64) << SLOT_BITS) | \
        jnp.where(bslot >= 0, bslot, 0).astype(jnp.int64)

    def g2x32(carry, i):
        acc, k = carry
        k, sub = jax.random.split(k)
        src = jax.random.randint(sub, (n, F), 0, n, dtype=jnp.int32)
        pv = bval[src]
        ps = bslot[src]
        return (acc + jnp.sum(pv) + jnp.sum(ps), k), None

    def g1x64(carry, i):
        acc, k = carry
        k, sub = jax.random.split(k)
        src = jax.random.randint(sub, (n, F), 0, n, dtype=jnp.int32)
        pk = key64[src]
        pv = (pk >> SLOT_BITS).astype(jnp.int32)
        ps = (pk & ((1 << SLOT_BITS) - 1)).astype(jnp.int32)
        return (acc + jnp.sum(pv) + jnp.sum(ps), k), None

    if want("gather"):
        for name, fn in [("g2x32", g2x32), ("g1x64", g1x64)]:
            results[name] = round(timed_scan(
                fn, (jnp.zeros((), jnp.int64), key0)), 3)
            print(json.dumps(results), flush=True)

    # merge variants on pre-gathered candidates [N, F, K].  The big
    # arrays ride the scan CARRY, not the closure: closure constants
    # ship with the compile request and 300 MB of them overflows the
    # remote-compile body limit on this tunneled chip.
    src0 = jax.random.randint(key0, (n, F), 0, n, dtype=jnp.int32)
    pv0 = bval[src0]
    ps0 = bslot[src0]

    def merge_loop(carry, i):
        acc, cv0, cs0, pvc, psc = carry
        pv = pvc ^ (i & 1)          # vary per iter, cheap
        wv, ws = cv0, cs0
        for f in range(F):
            cand_v, cand_s = pv[:, f], psc[:, f]
            cand_v = sticky_adjust_stub(
                cand_v, cv0, (cand_s == cs0) & (cand_v > cv0))
            wv, ws = lex_max(wv, ws, cand_v, cand_s)
        return (acc + jnp.sum(wv) + jnp.sum(ws), cv0, cs0, pvc, psc), \
            None

    def merge_key(carry, i):
        acc, cv0, cs0, pvc, psc = carry
        pv = pvc ^ (i & 1)
        cand_v = sticky_adjust_stub(
            pv, cv0[:, None, :],
            (psc == cs0[:, None, :]) & (pv > cv0[:, None, :]))
        keys = (cand_v.astype(jnp.int64) << SLOT_BITS) | \
            jnp.where(psc >= 0, psc, 0).astype(jnp.int64)
        keys = jnp.where(cand_v > 0, keys, 0)
        best = jnp.max(keys, axis=1)
        bv = (best >> SLOT_BITS).astype(jnp.int32)
        bs = jnp.where(best > 0,
                       (best & ((1 << SLOT_BITS) - 1)).astype(jnp.int32),
                       -1)
        wv, ws = lex_max(cv0, cs0, bv, bs)
        return (acc + jnp.sum(wv) + jnp.sum(ws), cv0, cs0, pvc, psc), \
            None

    if want("merge"):
        for name, fn in [("merge_loop", merge_loop),
                         ("merge_key", merge_key)]:
            results[name] = round(timed_scan(
                fn, (jnp.zeros((), jnp.int64), val, slot, pv0, ps0)), 3)
            print(json.dumps(results), flush=True)

    return results


# -- r2: approx threshold + gather forms (formerly hotpath_variants2) --------

def run_r2(opts):
    n = opts.n
    val, slot, _ = make_inputs(n)
    key0 = jax.random.PRNGKey(1)
    results = {}

    # publish threshold: exact top_k vs approx_max_k
    def mk_thresh(kind):
        def body(carry, i):
            acc, v = carry
            pv = v ^ (i & 1)
            if kind == "exact":
                top = lax.top_k(pv, BUDGET)[0]
            else:
                top = lax.approx_max_k(pv.astype(jnp.float32), BUDGET,
                                       recall_target=0.95)[0] \
                    .astype(jnp.int32)
            thresh = top[:, -1:]
            sel = jnp.where(pv >= thresh, pv, 0)
            return (acc + jnp.sum(sel), v), None
        return body

    results["thresh_topk"] = round(
        timed_scan(mk_thresh("exact"), (jnp.zeros((), jnp.int32), val)),
        3)
    print(json.dumps(results), flush=True)
    results["thresh_approx"] = round(
        timed_scan(mk_thresh("approx"), (jnp.zeros((), jnp.int32), val)),
        3)
    print(json.dumps(results), flush=True)

    # approx quality at this shape: how far off is the returned B-th
    # value, and how many rows get it exactly right?
    exact_t = lax.top_k(val, BUDGET)[0][:, -1]
    approx_t = lax.approx_max_k(val.astype(jnp.float32), BUDGET,
                                recall_target=0.95)[0][:, -1] \
        .astype(jnp.int32)
    results["approx_rows_exact_pct"] = round(float(
        jnp.mean((exact_t == approx_t).astype(jnp.float32))) * 100, 2)
    print(json.dumps(results), flush=True)

    # gather forms
    def g_rows(carry, i):            # one [N, F] row gather, both arrays
        acc, k = carry
        k, sub = jax.random.split(k)
        src = jax.random.randint(sub, (n, F), 0, n, dtype=jnp.int32)
        pv = val[src]
        ps = slot[src]
        return (acc + jnp.sum(pv) + jnp.sum(ps), k), None

    def g3x1row(carry, i):           # three [N] row gathers, both arrays
        acc, k = carry
        k, sub = jax.random.split(k)
        src = jax.random.randint(sub, (n, F), 0, n, dtype=jnp.int32)
        acc2 = acc
        for f in range(F):
            acc2 = acc2 + jnp.sum(val[src[:, f]]) \
                + jnp.sum(slot[src[:, f]])
        return (acc2, k), None

    def g_fused(carry, i):           # gather → F-axis max, no slot
        acc, k = carry
        k, sub = jax.random.split(k)
        src = jax.random.randint(sub, (n, F), 0, n, dtype=jnp.int32)
        wv = jnp.max(val[src], axis=1)           # [N, K]
        return (acc + jnp.sum(wv), k), None

    def g_half(carry, i):            # val-only gather
        acc, k = carry
        k, sub = jax.random.split(k)
        src = jax.random.randint(sub, (n, F), 0, n, dtype=jnp.int32)
        pv = val[src]
        return (acc + jnp.sum(pv), k), None

    for name, fn in [("g_rows", g_rows), ("g3x1row", g3x1row),
                     ("g_fused", g_fused), ("g_half", g_half)]:
        results[name] = round(
            timed_scan(fn, (jnp.zeros((), jnp.int32), key0)), 3)
        print(json.dumps(results), flush=True)

    return results


# -- r3: cheaper publish thresholds (formerly hotpath_variants3) -------------

def run_r3(opts):
    n = opts.n
    rng = np.random.default_rng(0)
    occ = rng.random((n, K)) < 0.15
    # realistic packed keys: recent ticks in a narrow window
    pv0 = jnp.asarray(np.where(
        occ, (rng.integers(20_000, 25_000, (n, K)) << 3), 0)
        .astype(np.int32))
    results = {}

    def topk32(carry, i):
        acc, pv = carry
        p = pv ^ (i & 1)
        thresh = lax.top_k(p, BUDGET)[0][:, -1:]
        sel = (p > thresh) | ((p == thresh) & (p > 0))
        return (acc + jnp.sum(sel.astype(jnp.int32)), pv), None

    def topk16(carry, i):
        acc, pv = carry
        p = pv ^ (i & 1)
        now_max = jnp.max(p)
        shift = jnp.maximum(
            0, 32 - jnp.int32(lax.clz(jnp.maximum(now_max, 1))) - 13)
        p16 = (p >> shift).astype(jnp.int16)
        thresh = lax.top_k(p16, BUDGET)[0][:, -1:]
        sel = (p16 > thresh) | ((p16 == thresh) & (p > 0))
        return (acc + jnp.sum(sel.astype(jnp.int32)), pv), None

    def hist64(carry, i):
        acc, pv = carry
        p = pv ^ (i & 1)
        now_max = jnp.max(p)
        lo = now_max - (1 << 15)       # window floor
        b = jnp.clip((p - lo) >> 9, 0, 63)      # 64 bins, newest high
        b = jnp.where(p > 0, b, -1)
        oh = jax.nn.one_hot(b, 64, dtype=jnp.bfloat16)  # [N, K, 64]
        hist = jnp.sum(oh, axis=1).astype(jnp.int32)    # [N, 64]
        # admit from the newest bin downward
        rev = jnp.cumsum(hist[:, ::-1], axis=1)[:, ::-1]
        tbin = 63 - jnp.argmax((rev >= BUDGET)[:, ::-1], axis=1)
        have = jnp.any(rev >= BUDGET, axis=1)
        tbin = jnp.where(have, tbin, 0)
        sel = (b > tbin[:, None]) | ((b == tbin[:, None]) & (p > 0))
        return (acc + jnp.sum(sel.astype(jnp.int32)), pv), None

    for name, fn in [("topk32", topk32), ("topk16", topk16),
                     ("hist64", hist64)]:
        results[name] = round(
            timed_scan(fn, (jnp.zeros((), jnp.int32), pv0)), 3)
        print(json.dumps(results), flush=True)

    return results


def main():
    ap = argparse.ArgumentParser(
        description="hot-path variant dead-end ledger (see module "
                    "docstring and benchmarks/RESULTS.md)")
    sub = ap.add_subparsers(dest="round", required=True)
    for name, help_txt in (("r1", "publish/gather/merge candidates"),
                           ("r2", "approx threshold + gather forms"),
                           ("r3", "cheaper publish thresholds")):
        sp = sub.add_parser(name, help=help_txt)
        sp.add_argument("--n", type=int, default=100_000)
        if name == "r1":
            sp.add_argument(
                "--only", default="",
                help="comma list of variant groups: pub,gather,merge")
    opts = ap.parse_args()

    if opts.round == "r1":
        # The packed-key variants need real int64 on device; x64 is
        # experiment-local (the model itself stays int32 unless a
        # variant wins AND the global-dtype cost is acceptable).  r2/r3
        # ran int32-only when they shipped and stay that way.
        jax.config.update("jax_enable_x64", True)

    results = {"r1": run_r1, "r2": run_r2, "r3": run_r3}[opts.round](opts)
    print("FINAL " + json.dumps(
        {"round": opts.round, "n": opts.n,
         "platform": jax.devices()[0].platform, **results}))


if __name__ == "__main__":
    main()
