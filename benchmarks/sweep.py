"""Scenario-fleet sweep bench: one vmapped dispatch vs N classic ones.

The claim under measurement (ROADMAP "vmap the whole simulator"): a
protocol-configuration grid that used to cost one TRACE + COMPILE +
DISPATCH per point — every grid point is a distinct static
``SimParams``/``TimeConfig``, so jit can never reuse a program across
points — becomes ONE of each through the fleet engine
(``sidecar_tpu/fleet``), because the swept knobs are data
(ops/knobs.py), not compile keys.

Method (CPU-budget honest):

* **batched** — one 64-point grid (push-pull × suspicion × loss ×
  transmit-limit; fanout fixed — a compile-key axis — so the whole
  grid is literally one ``ScenarioBatch``) through one fleet dispatch.
  Reported end to end (trace+compile+run) AND warm (a second dispatch
  on fresh states — the steady-state ``scenarios/sec/chip`` headline).
* **sequential** — the status quo: each point builds its classic
  ``ExactSim`` and runs the same horizon, paying its own trace+compile
  (``BENCH_SWEEP_SEQ`` caps how many points are measured; the
  remainder is extrapolated per-point — sequential cost is per-config
  uniform — and the JSON says so).
* **bit-identity** — every sequentially-run point's final state is
  compared cell-for-cell against its fleet lane (the acceptance
  oracle riding the measurement for free).

Run standalone: ``python benchmarks/sweep.py [n]`` — prints the JSON
block bench.py embeds (``BENCH_SWEEP=0`` skips it there), including a
sample Pareto table of the grid.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

if __name__ == "__main__":  # standalone: resolve the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def sweep_axes() -> dict:
    """The 64-point grid: 4 push-pull cadences × 2 suspicion windows ×
    4 loss rates × 2 transmit limits — all data axes, one batch."""
    return {
        "push_pull_interval_s": [1.0, 2.0, 4.0, 8.0],
        "suspicion_window_s": [0.0, 2.0],
        "drop_prob": [0.0, 0.05, 0.1, 0.2],
        "retransmit_limit": [0, 8],
    }


def run_sweep_bench(n: int = 32, spn: int = 4, rounds: int = 100,
                    seq_points: int | None = None,
                    seed: int = 0) -> dict:
    import jax
    import numpy as np

    from sidecar_tpu.fleet import (
        FleetSim,
        ScenarioBatch,
        expand_grid,
    )
    from sidecar_tpu.fleet.grid import pareto_front
    from sidecar_tpu.models.exact import ExactSim, SimParams
    from sidecar_tpu.models.timecfg import TimeConfig
    from sidecar_tpu.ops import topology as topo_mod

    specs = expand_grid(sweep_axes(), base={"seed": seed})
    s = len(specs)
    params = SimParams(n=n, services_per_node=spn, fanout=3, budget=15)
    cfg = TimeConfig(refresh_interval_s=10_000.0)
    batch = ScenarioBatch.build(specs, params, cfg, family="exact")
    topo = topo_mod.complete(n)

    # -- batched: end-to-end (trace+compile+run), then warm ---------------
    fleet = FleetSim(batch, topo=topo)
    t0 = time.perf_counter()
    run_cold = fleet.run(fleet.init_states(), rounds, eps=0.01,
                         stop=False)
    batched_total = time.perf_counter() - t0
    run_warm = fleet.run(fleet.init_states(), rounds, eps=0.01,
                         stop=False)
    batched_warm = run_warm.wall_seconds

    # -- sequential status quo: per-point trace+compile+dispatch ----------
    if seq_points is None:
        seq_points = int(os.environ.get("BENCH_SWEEP_SEQ", str(s)))
    seq_points = max(1, min(s, seq_points))
    seq_wall = 0.0
    mismatches = []
    for i in range(seq_points):
        p_i = batch.scenario_params(i)
        t_i = batch.scenario_timecfg(i)
        t1 = time.perf_counter()
        sim = ExactSim(p_i, topo, t_i)
        st = sim.init_state()
        final, _conv = sim.run(st, jax.random.PRNGKey(specs[i].seed),
                               rounds)
        jax.block_until_ready(final.known)
        seq_wall += time.perf_counter() - t1
        for name in ("known", "sent", "node_alive", "round_idx"):
            a = np.asarray(getattr(run_warm.final_states, name))[i]
            b = np.asarray(getattr(final, name))
            if not np.array_equal(a, b):
                mismatches.append(f"{specs[i].name}:{name}")
    seq_total = seq_wall * (s / seq_points)

    ratio = seq_total / batched_total if batched_total > 0 else None
    ratio_warm = (seq_total / batched_warm
                  if batched_warm > 0 else None)

    table = run_warm.table(cfg.round_ticks, cfg.ticks_per_second)
    for j, spec in enumerate(specs):
        table[j]["config"] = spec.axes()
    front = pareto_front(table)

    return {
        "points": s,
        "n": n,
        "services_per_node": spn,
        "rounds": rounds,
        "scenarios_per_sec_chip": round(s / batched_warm, 2)
        if batched_warm > 0 else None,
        "batched_total_s": round(batched_total, 3),
        "batched_warm_s": round(batched_warm, 3),
        "sequential_total_s": round(seq_total, 3),
        "sequential_points_measured": seq_points,
        "sequential_extrapolated": seq_points < s,
        "ratio_vs_sequential": round(ratio, 2) if ratio else None,
        "ratio_vs_sequential_warm_batched": round(ratio_warm, 2)
        if ratio_warm else None,
        "bit_identical_points": seq_points - len(
            {m.split(":")[0] for m in mismatches}),
        "mismatches": mismatches[:8],
        "pareto_front": front,
        "pareto_table": [table[i] for i in front],
    }


def main() -> int:
    # The environment's sitecustomize pins jax to the default platform
    # at interpreter start; re-assert an explicit JAX_PLATFORMS choice.
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    print(json.dumps(run_sweep_bench(n=n), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
