// Live catalog view. Primary feed: the /watch long-poll stream (a fresh
// services.json-shaped snapshot per ChangeEvent, http_api.go:56-131);
// fallback: polling /api/services.json every 2 s, the reference UI's
// only mode (ui/app/services/services.js:12-33).
"use strict";

const STATUS = ["Alive", "Tombstone", "Unhealthy", "Unknown", "Draining"];

function el(tag, attrs, ...children) {
  const node = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs || {})) {
    if (k === "class") node.className = v;
    else node.setAttribute(k, v);
  }
  for (const child of children) {
    node.append(child);
  }
  return node;
}

function timeAgo(ns) {
  if (!ns) return "never";
  const s = Math.max(0, Date.now() / 1000 - ns / 1e9);
  if (s < 60) return `${Math.round(s)}s ago`;
  if (s < 3600) return `${Math.round(s / 60)}m ago`;
  if (s < 86400) return `${Math.round(s / 3600)}h ago`;
  return `${Math.round(s / 86400)}d ago`;
}

function chip(status) {
  const idx = (status >= 0 && status < STATUS.length) ? status : 3;
  return el("span", { class: `chip s${idx}` }, STATUS[idx]);
}

function render(data) {
  document.getElementById("cluster").textContent =
    data.ClusterName ? `· ${data.ClusterName}` : "";

  const members = document.getElementById("members");
  members.replaceChildren();
  const byName = data.ClusterMembers || {};
  for (const name of Object.keys(byName).sort()) {
    const m = byName[name];
    members.append(el("div", { class: "member" }, name,
      el("span", { class: "count" }, `${m.ServiceCount ?? 0} svc`)));
  }
  if (!members.children.length) {
    members.append(el("div", { class: "member" }, "no members known"));
  }

  const wrap = document.getElementById("services");
  const services = data.Services || {};
  const names = Object.keys(services).sort();
  if (!names.length) {
    wrap.replaceChildren(el("div", { class: "empty" },
      "No services in the catalog yet."));
    return;
  }
  const table = el("table", {},
    el("thead", {}, el("tr", {},
      el("th", {}, "Service"), el("th", {}, "Host"),
      el("th", {}, "Status"), el("th", {}, "Ports"),
      el("th", {}, "Updated"))));
  const body = el("tbody", {});
  for (const name of names) {
    const instances = services[name];
    instances.forEach((svc, i) => {
      const ports = (svc.Ports || [])
        .map(p => p.ServicePort ? `${p.ServicePort}→${p.Port}` : `${p.Port}`)
        .join(", ");
      const row = el("tr", {});
      const label = i === 0
        ? el("td", { class: "svc", rowspan: String(instances.length) },
            name, el("div", { class: "img" }, svc.Image || ""))
        : null;
      if (label) row.append(label);
      row.append(
        el("td", {}, svc.Hostname || "?"),
        el("td", {}, chip(svc.Status)),
        el("td", { class: "ports" }, ports),
        el("td", {}, timeAgo(svc.Updated)));
      body.append(row);
    });
  }
  table.append(body);
  wrap.replaceChildren(table);
}

function setStatus(text, err) {
  const node = document.getElementById("status");
  node.textContent = text;
  node.className = err ? "err" : "";
}

async function pollLoop() {
  for (;;) {
    try {
      const resp = await fetch("/api/services.json");
      render(await resp.json());
      setStatus(`polling · ${new Date().toLocaleTimeString()}`);
    } catch (err) {
      setStatus(`poll failed: ${err}`, true);
    }
    await new Promise(resolve => setTimeout(resolve, 2000));
  }
}

// /watch snapshots carry only the {service: [instances]} map; the
// member list + cluster name come from the full envelope, refreshed on
// a slow cadence.
let envelope = { Services: {} };

async function refreshEnvelope() {
  const resp = await fetch("/api/services.json");
  envelope = await resp.json();
  render(envelope);
}

async function watchLoop() {
  // /watch streams chunked JSON snapshots; consume incrementally and
  // render each complete JSON document (snapshots are newline-free
  // single objects, so brace-depth framing is enough).
  setInterval(() => refreshEnvelope().catch(() => {}), 10000);
  for (;;) {
    try {
      await refreshEnvelope().catch(() => {});
      const resp = await fetch("/watch");
      if (!resp.ok || !resp.body) throw new Error(`HTTP ${resp.status}`);
      const reader = resp.body.getReader();
      const decoder = new TextDecoder();
      let buf = "";
      for (;;) {
        const { done, value } = await reader.read();
        if (done) break;
        buf += decoder.decode(value, { stream: true });
        let depth = 0, start = -1, inStr = false, esc = false;
        for (let i = 0; i < buf.length; i++) {
          const c = buf[i];
          if (esc) { esc = false; continue; }
          if (c === "\\") { esc = inStr; continue; }
          if (c === '"') { inStr = !inStr; continue; }
          if (inStr) continue;
          if (c === "{") { if (depth === 0) start = i; depth++; }
          else if (c === "}") {
            depth--;
            if (depth === 0 && start >= 0) {
              envelope.Services = JSON.parse(buf.slice(start, i + 1));
              render(envelope);
              setStatus(`live · ${new Date().toLocaleTimeString()}`);
              buf = buf.slice(i + 1);
              i = -1;
            }
          }
        }
      }
      throw new Error("stream ended");
    } catch (err) {
      setStatus(`watch lost (${err}); retrying…`, true);
      try {
        const resp = await fetch("/api/services.json");
        render(await resp.json());
      } catch (_) { /* keep the last view */ }
      await new Promise(resolve => setTimeout(resolve, 2000));
    }
  }
}

if (window.ReadableStream) {
  watchLoop();
} else {
  pollLoop();
}
