// Live catalog view. Primary feed: the /watch long-poll stream (a fresh
// services.json-shaped snapshot per ChangeEvent, http_api.go:56-131);
// fallback: polling /api/services.json every 2 s, the reference UI's
// only mode (ui/app/services/services.js:12-33).
//
// Pure logic (CSV parsing, time formatting, stream framing) lives in
// lib.js — loaded before this file — so it is unit-testable
// (ui/test/lib_test.js) without a DOM.
"use strict";

function el(tag, attrs, ...children) {
  const node = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs || {})) {
    if (k === "class") node.className = v;
    else node.setAttribute(k, v);
  }
  for (const child of children) {
    node.append(child);
  }
  return node;
}

function chip(status) {
  const idx = statusIndex(status);
  return el("span", { class: `chip s${idx}` }, STATUS[idx]);
}

// -- HAProxy stats (reference UI's second data source: the stats CSV,
// ui/app/services/services.js:21-33 + the transform at :139-158 — here
// read through the sidecar API to stay same-origin) -------------------

// svcName → hostname → containerID → csv row, plus the raw backend rows.
let haproxy = { map: {}, rows: [], ok: false };

function haproxyHas(svc) {
  return haproxyHasIn(haproxy.map, svc);
}

function renderHaproxy() {
  const section = document.getElementById("haproxy-section");
  const wrap = document.getElementById("haproxy");
  if (!haproxy.ok) { section.style.display = "none"; return; }
  section.style.display = "";
  if (!haproxy.rows.length) {
    wrap.replaceChildren(el("div", { class: "empty" },
      "HAProxy is up but serves no backends."));
    return;
  }
  const table = el("table", {},
    el("thead", {}, el("tr", {},
      el("th", {}, "Backend"), el("th", {}, "Server"),
      el("th", {}, "State"), el("th", {}, "Sessions"),
      el("th", {}, "Total"))));
  const body = el("tbody", {});
  for (const row of haproxy.rows) {
    const up = (row.status || "").startsWith("UP");
    body.append(el("tr", {},
      el("td", { class: "svc" }, row.pxname),
      el("td", {}, row.svname),
      el("td", {}, el("span", { class: `chip ${up ? "s0" : "s2"}` },
        row.status || "?")),
      el("td", {}, row.scur || "0"),
      el("td", {}, row.stot || "0")));
  }
  table.append(body);
  wrap.replaceChildren(table);
}

async function haproxyLoop() {
  for (;;) {
    let delay = 4000;
    const wasOk = haproxy.ok;
    try {
      const resp = await fetch("/api/haproxy/stats.csv");
      if (resp.status === 404) {
        // This node manages no HAProxy — a static fact for the
        // process lifetime; re-check lazily in case of operator lore.
        haproxy = { map: {}, rows: [], ok: false };
        delay = 60000;
      } else {
        haproxy = resp.ok ? parseHaproxyCsv(await resp.text())
                          : { map: {}, rows: [], ok: false };
      }
    } catch (err) {
      haproxy = { map: {}, rows: [], ok: false };
    }
    if (haproxy.ok || wasOk) {
      renderHaproxy();
      render(envelope);   // refresh the per-instance proxy ticks
    }
    await new Promise(resolve => setTimeout(resolve, delay));
  }
}

// -- operator action: drain (POST /api/services/{id}/drain;
// local-only by design, like the reference http_api.go:297-343) -------

async function drain(svc) {
  try {
    const resp = await fetch(`/api/services/${svc.ID}/drain`,
                             { method: "POST" });
    const doc = await resp.json();
    if (resp.ok) {
      setStatus(`drained: ${doc.Message || svc.ID}`);
    } else if (resp.status === 404) {
      setStatus(`drain refused: ${svc.ID} is not local to this node ` +
                "(drains are local-only)", true);
    } else {
      setStatus(`drain failed: ${doc.message || resp.status}`, true);
    }
  } catch (err) {
    setStatus(`drain failed: ${err}`, true);
  }
}

function render(data) {
  document.getElementById("cluster").textContent =
    data.ClusterName ? `· ${data.ClusterName}` : "";

  const members = document.getElementById("members");
  members.replaceChildren();
  const byName = data.ClusterMembers || {};
  for (const name of Object.keys(byName).sort()) {
    const m = byName[name];
    members.append(el("div", { class: "member" }, name,
      el("span", { class: "count" }, `${m.ServiceCount ?? 0} svc`)));
  }
  if (!members.children.length) {
    members.append(el("div", { class: "member" }, "no members known"));
  }

  const wrap = document.getElementById("services");
  const services = data.Services || {};
  const names = Object.keys(services).sort();
  if (!names.length) {
    wrap.replaceChildren(el("div", { class: "empty" },
      "No services in the catalog yet."));
    return;
  }
  const head = el("tr", {},
    el("th", {}, "Service"), el("th", {}, "Host"),
    el("th", {}, "Status"), el("th", {}, "Ports"),
    el("th", {}, "Updated"));
  if (haproxy.ok) head.append(el("th", {}, "Proxy"));
  head.append(el("th", {}, ""));
  const table = el("table", {}, el("thead", {}, head));
  const body = el("tbody", {});
  for (const name of names) {
    const instances = services[name];
    instances.forEach((svc, i) => {
      const ports = formatPorts(svc.Ports);
      const row = el("tr", {});
      const label = i === 0
        ? el("td", { class: "svc", rowspan: String(instances.length) },
            name, el("div", { class: "img" }, svc.Image || ""))
        : null;
      if (label) row.append(label);
      row.append(
        el("td", {}, svc.Hostname || "?"),
        el("td", {}, chip(svc.Status)),
        el("td", { class: "ports" }, ports),
        el("td", {}, timeAgo(svc.Updated)));
      if (haproxy.ok) {
        // The reference's per-instance "is it in HAProxy" tick
        // (services.html:102-103).
        row.append(el("td", { class: haproxyHas(svc) ? "ok" : "miss" },
                      haproxyHas(svc) ? "✓" : "✗"));
      }
      const actions = el("td", { class: "actions" });
      if (svc.Status === 0) {   // only a live instance can drain
        const btn = el("button", { class: "drain", type: "button",
                                   title: "Set this instance DRAINING " +
                                          "(local instances only)" },
                       "drain");
        btn.addEventListener("click", () => drain(svc));
        actions.append(btn);
      }
      row.append(actions);
      body.append(row);
    });
  }
  table.append(body);
  wrap.replaceChildren(table);
}

function setStatus(text, err) {
  const node = document.getElementById("status");
  node.textContent = text;
  node.className = err ? "err" : "";
}

async function pollLoop() {
  for (;;) {
    try {
      const resp = await fetch("/api/services.json");
      // Keep the shared envelope current: haproxyLoop re-renders from
      // it, and a stale empty one would wipe the table every 4 s.
      envelope = await resp.json();
      render(envelope);
      setStatus(`polling · ${new Date().toLocaleTimeString()}`);
    } catch (err) {
      setStatus(`poll failed: ${err}`, true);
    }
    await new Promise(resolve => setTimeout(resolve, 2000));
  }
}

// /watch documents carry only the {service: [instances]} map (as a
// versioned snapshot or delta patch — docs/query.md); the member list
// + cluster name come from the full envelope, refreshed on a slow
// cadence.
let envelope = { Services: {} };

async function refreshEnvelope() {
  const resp = await fetch("/api/services.json");
  envelope = await resp.json();
  render(envelope);
}

async function watchLoop() {
  // /watch streams chunked JSON snapshots; consume incrementally and
  // render each complete JSON document (snapshots are newline-free
  // single objects, so brace-depth framing is enough).
  setInterval(() => refreshEnvelope().catch(() => {}), 10000);
  for (;;) {
    try {
      await refreshEnvelope().catch(() => {});
      const resp = await fetch("/watch");
      if (!resp.ok || !resp.body) throw new Error(`HTTP ${resp.status}`);
      const reader = resp.body.getReader();
      const decoder = new TextDecoder();
      let buf = "";
      for (;;) {
        const { done, value } = await reader.read();
        if (done) break;
        buf += decoder.decode(value, { stream: true });
        const { docs, rest } = extractJsonDocs(buf);
        buf = rest;
        for (const doc of docs) {
          // Versioned watch documents (docs/query.md): snapshot docs
          // replace the view, delta docs patch it.
          envelope.Services = applyWatchDoc(envelope.Services, doc);
          render(envelope);
          setStatus(`live v${doc.Version} · ` +
                    new Date().toLocaleTimeString());
        }
      }
      throw new Error("stream ended");
    } catch (err) {
      setStatus(`watch lost (${err}); retrying…`, true);
      try {
        const resp = await fetch("/api/services.json");
        render(await resp.json());
      } catch (_) { /* keep the last view */ }
      await new Promise(resolve => setTimeout(resolve, 2000));
    }
  }
}

if (window.ReadableStream) {
  watchLoop();
} else {
  pollLoop();
}
haproxyLoop();
