// Pure UI logic, extracted from app.js so it is unit-testable without a
// browser (ui/test/lib_test.js runs under node or in the browser test
// page ui/test/index.html — the karma-unit analog of the reference's
// ui/karma.conf.js).  No DOM access in this file.
"use strict";

const STATUS = ["Alive", "Tombstone", "Unhealthy", "Unknown", "Draining"];

// Clamp an arbitrary wire status to a renderable index (unknown = 3).
function statusIndex(status) {
  return (status >= 0 && status < STATUS.length) ? status : 3;
}

function timeAgo(ns, nowMs) {
  if (!ns) return "never";
  // The wire format ships RFC3339 strings (Service.to_json); accept
  // raw nanoseconds too for older payloads.
  if (typeof ns === "string") {
    const ms = Date.parse(ns);
    if (Number.isNaN(ms)) return "never";
    ns = ms * 1e6;
  }
  const now = (nowMs === undefined ? Date.now() : nowMs);
  const s = Math.max(0, now / 1000 - ns / 1e9);
  if (s < 60) return `${Math.round(s)}s ago`;
  if (s < 3600) return `${Math.round(s / 60)}m ago`;
  if (s < 86400) return `${Math.round(s / 3600)}h ago`;
  return `${Math.round(s / 86400)}d ago`;
}

// The HAProxy template writes sanitized backend names
// (sanitize_name: [^a-z0-9-] → "-", haproxy.go:86-89), so catalog
// names must be transformed the same way before lookup.
function sanitizeName(name) {
  return (name || "").replace(/[^a-z0-9-]/g, "-");
}

// "8080→31000, 9090" — the per-instance ports cell.
function formatPorts(ports) {
  return (ports || [])
    .map(p => p.ServicePort ? `${p.ServicePort}→${p.Port}` : `${p.Port}`)
    .join(", ");
}

// HAProxy stats CSV → { map: svcName→hostname→containerID→row,
// rows: backend server rows, ok }.  Mirrors the reference UI's
// transform (ui/app/services/services.js:139-158).
function parseHaproxyCsv(text) {
  const lines = text.split("\n").filter(l => l.trim());
  if (!lines.length) return { map: {}, rows: [], ok: false };
  const header = lines[0].replace(/^# /, "").split(",");
  const map = {}, rows = [];
  for (const line of lines.slice(1)) {
    const cells = line.split(",");
    const item = {};
    header.forEach((h, i) => { item[h] = cells[i]; });
    const px = item.pxname || "";
    if (item.svname === "FRONTEND" || item.svname === "BACKEND" ||
        px === "stats" || px === "stats_proxy" || px === "") continue;
    rows.push(item);
    // pxname = "<svcName>-<port>", svname = "<hostname>-<containerID>"
    // (the template's naming, views/haproxy.cfg:56-58).
    let f = px.split("-");
    const svcName = f.slice(0, f.length - 1).join("-");
    f = item.svname.split("-");
    const hostname = f.slice(0, f.length - 1).join("-");
    const id = f[f.length - 1];
    ((map[svcName] ||= {})[hostname] ||= {})[id] = item;
  }
  return { map, rows, ok: true };
}

// Is this catalog instance present in the parsed HAProxy map?
function haproxyHasIn(map, svc) {
  const byHost = map[sanitizeName(svc.Name)];
  return !!(byHost && byHost[svc.Hostname] && byHost[svc.Hostname][svc.ID]);
}

// Incremental JSON framing for the /watch chunked stream: pull every
// complete top-level {...} document out of buf (string-aware brace
// depth — snapshots are newline-free single objects).  Returns
// { docs: [parsed...], rest: remaining partial input }.
function extractJsonDocs(buf) {
  const docs = [];
  let depth = 0, start = -1, inStr = false, esc = false;
  let consumed = 0;
  for (let i = 0; i < buf.length; i++) {
    const c = buf[i];
    if (esc) { esc = false; continue; }
    if (c === "\\") { esc = inStr; continue; }
    if (c === '"') { inStr = !inStr; continue; }
    if (inStr) continue;
    if (c === "{") { if (depth === 0) start = i; depth++; }
    else if (c === "}") {
      depth--;
      if (depth === 0 && start >= 0) {
        docs.push(JSON.parse(buf.slice(start, i + 1)));
        consumed = i + 1;
        start = -1;
      }
    }
  }
  return { docs, rest: buf.slice(consumed) };
}

// One /watch document (docs/query.md) applied to the by-service map:
// snapshot documents replace it, delta documents patch it in place —
// upsert each changed instance by ID within its service group.
// Tombstoned instances are KEPT (rendered with their Tombstone chip),
// exactly like snapshot documents show them — the same catalog must
// render identically whether the client learned of it by snapshot or
// by delta; rows disappear when catalog GC drops the record from the
// next snapshot.  Returns the NEW map (never mutates the input) so
// callers can keep rendering the old view on a bad doc.
function applyWatchDoc(services, doc) {
  if (!doc || typeof doc !== "object") return services;
  if (doc.Snapshot !== undefined) return doc.Snapshot || {};
  if (!Array.isArray(doc.Deltas)) return services;
  const out = {};
  for (const name of Object.keys(services || {})) {
    out[name] = services[name].slice();
  }
  for (const change of doc.Deltas) {
    const svc = change && change.Service;
    if (!svc || !svc.Name || !svc.ID) continue;
    const list = (out[svc.Name] || []).filter(s => s.ID !== svc.ID);
    list.push(svc);
    out[svc.Name] = list;
  }
  return out;
}

// node (the unit-test runner) sees a module; the browser just gets
// globals on the shared script scope.
if (typeof module !== "undefined" && module.exports) {
  module.exports = { STATUS, statusIndex, timeAgo, sanitizeName,
                     formatPorts, parseHaproxyCsv, haproxyHasIn,
                     extractJsonDocs, applyWatchDoc };
}
