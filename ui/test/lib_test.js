// Unit tests for ui/app/lib.js — the UI's pure logic (the karma-unit
// analog of the reference's ui/karma.conf.js test stack).  Runs two
// ways with zero dependencies:
//   node ui/test/lib_test.js            (CI / tests/test_ui_logic.py)
//   open ui/test/index.html             (any browser; same assertions)
"use strict";

/* global STATUS, statusIndex, timeAgo, sanitizeName, formatPorts,
   parseHaproxyCsv, haproxyHasIn, extractJsonDocs */
const L = (typeof require !== "undefined" && typeof window === "undefined")
  ? require("../app/lib.js")
  : { STATUS, statusIndex, timeAgo, sanitizeName, formatPorts,
      parseHaproxyCsv, haproxyHasIn, extractJsonDocs };

const failures = [];
let checks = 0;

function eq(got, want, label) {
  checks++;
  const g = JSON.stringify(got), w = JSON.stringify(want);
  if (g !== w) failures.push(`${label}: got ${g}, want ${w}`);
}

// -- statusIndex -------------------------------------------------------------
eq(L.statusIndex(0), 0, "statusIndex alive");
eq(L.statusIndex(4), 4, "statusIndex draining");
eq(L.statusIndex(9), 3, "statusIndex out-of-range -> unknown");
eq(L.statusIndex(-1), 3, "statusIndex negative -> unknown");
eq(L.STATUS[L.statusIndex(1)], "Tombstone", "status name");

// -- timeAgo (nowMs pinned so assertions are deterministic) ------------------
const NOW = Date.UTC(2026, 0, 2, 0, 0, 0);            // 2026-01-02T00:00Z
const ns = ms => ms * 1e6;
eq(L.timeAgo(0, NOW), "never", "timeAgo zero");
eq(L.timeAgo(null, NOW), "never", "timeAgo null");
eq(L.timeAgo(ns(NOW - 5000), NOW), "5s ago", "timeAgo seconds");
eq(L.timeAgo(ns(NOW - 120000), NOW), "2m ago", "timeAgo minutes");
eq(L.timeAgo(ns(NOW - 7200000), NOW), "2h ago", "timeAgo hours");
eq(L.timeAgo(ns(NOW - 172800000), NOW), "2d ago", "timeAgo days");
eq(L.timeAgo(ns(NOW + 60000), NOW), "0s ago", "timeAgo future clamps");
eq(L.timeAgo("2026-01-01T23:59:30Z", NOW), "30s ago", "timeAgo RFC3339");
eq(L.timeAgo("not-a-date", NOW), "never", "timeAgo malformed string");

// -- sanitizeName (haproxy.go:86-89 sanitize rule) ---------------------------
eq(L.sanitizeName("chaucer"), "chaucer", "sanitize clean");
eq(L.sanitizeName("svc_one.v2"), "svc-one-v2", "sanitize specials");
eq(L.sanitizeName("UPPER"), "-----", "sanitize uppercase");
eq(L.sanitizeName(null), "", "sanitize null");

// -- formatPorts -------------------------------------------------------------
eq(L.formatPorts([{ ServicePort: 8080, Port: 31000 }, { Port: 9090 }]),
   "8080→31000, 9090", "formatPorts mapped+bare");
eq(L.formatPorts([]), "", "formatPorts empty");
eq(L.formatPorts(null), "", "formatPorts null");

// -- parseHaproxyCsv ---------------------------------------------------------
const CSV = [
  "# pxname,svname,scur,stot,status",
  "chaucer-8000,FRONTEND,0,5,OPEN",
  "chaucer-8000,node1-deadbeef01,1,4,UP",
  "chaucer-8000,node2-deadbeef02,0,1,DOWN",
  "chaucer-8000,BACKEND,1,5,UP",
  "stats,FRONTEND,0,0,OPEN",
  "bocaccio-9000,node1-cafe0002,2,9,UP 1/2",
  "",
].join("\n");
const parsed = L.parseHaproxyCsv(CSV);
eq(parsed.ok, true, "csv ok");
eq(parsed.rows.length, 3, "csv keeps only backend server rows");
eq(parsed.map["chaucer"]["node1"]["deadbeef01"].status, "UP",
   "csv map path svc->host->container");
eq(parsed.map["bocaccio"]["node1"]["cafe0002"].scur, "2", "csv cell");
eq(L.parseHaproxyCsv("").ok, false, "csv empty input not ok");
eq(L.parseHaproxyCsv("\n\n").ok, false, "csv blank lines not ok");

// -- haproxyHasIn (catalog instance -> proxy presence tick) ------------------
const svc = { Name: "chaucer", Hostname: "node1", ID: "deadbeef01" };
eq(L.haproxyHasIn(parsed.map, svc), true, "haproxyHas present");
eq(L.haproxyHasIn(parsed.map,
                  { ...svc, ID: "nope" }), false, "haproxyHas absent id");
eq(L.haproxyHasIn(parsed.map,
                  { ...svc, Name: "gone" }), false, "haproxyHas absent svc");
// catalog name with specials matches its sanitized proxy name
const p2 = L.parseHaproxyCsv([
  "# pxname,svname,status",
  "svc-one-v2-8000,h1-abc,UP"].join("\n"));
eq(L.haproxyHasIn(p2.map, { Name: "svc_one.v2", Hostname: "h1",
                            ID: "abc" }),
   true, "haproxyHas sanitizes catalog name");

// -- extractJsonDocs (the /watch chunked-stream framer) ----------------------
let r = L.extractJsonDocs('{"a":1}{"b":{"c":2}}{"d"');
eq(r.docs, [{ a: 1 }, { b: { c: 2 } }], "frames two complete docs");
eq(r.rest, '{"d"', "keeps the partial tail");
r = L.extractJsonDocs(r.rest + ':4}');
eq(r.docs, [{ d: 4 }], "completes across chunk boundary");
eq(r.rest, "", "tail consumed");
r = L.extractJsonDocs('{"s":"a}b{c","t":"\\"{"}');
eq(r.docs, [{ s: "a}b{c", t: '"{' }], "braces inside strings ignored");
r = L.extractJsonDocs('  {"x":1} trailing');
eq(r.docs, [{ x: 1 }], "leading junk tolerated");
eq(r.rest, " trailing", "non-brace tail kept");
r = L.extractJsonDocs("");
eq(r.docs, [], "empty input no docs");

// -- applyWatchDoc (the versioned /watch protocol, docs/query.md) ------------
const base = { web: [{ Name: "web", ID: "a1", Status: 0 }] };
eq(L.applyWatchDoc(base, { Version: 3, Snapshot: { db: [] } }),
   { db: [] }, "snapshot doc replaces the view");
let patched = L.applyWatchDoc(base, {
  From: 4, Version: 5, Deltas: [
    { Service: { Name: "web", ID: "a2", Status: 0 } },
    { Service: { Name: "db", ID: "d1", Status: 0 } }],
});
eq(patched.web.length, 2, "delta upserts new instance");
eq(Object.keys(patched).sort(), ["db", "web"], "delta adds new service");
patched = L.applyWatchDoc(patched, {
  From: 6, Version: 6,
  Deltas: [{ Service: { Name: "web", ID: "a1", Status: 1 } }],
});
// Tombstones stay visible (with their chip) — delta and snapshot views
// of the same catalog must render identically.
eq(patched.web.map(s => s.ID).sort(), ["a1", "a2"],
   "tombstone kept, not removed");
eq(patched.web.find(s => s.ID === "a1").Status, 1,
   "tombstone status patched in");
eq(L.applyWatchDoc(base, { Version: 9, Deltas: "bogus" }), base,
   "malformed doc leaves the view untouched");
eq(base.web.length, 1, "input map never mutated");

// -- report ------------------------------------------------------------------
const summary = failures.length
  ? `FAIL ${failures.length}/${checks}:\n  ${failures.join("\n  ")}`
  : `PASS ${checks} checks`;
if (typeof process !== "undefined" && process.exit) {
  console.log(summary);
  process.exit(failures.length ? 1 : 0);
} else if (typeof document !== "undefined") {
  document.body.textContent = summary;
  document.title = failures.length ? "UI tests: FAIL" : "UI tests: PASS";
}
