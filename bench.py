"""Headline benchmark: simulated gossip rounds/sec/chip + the north star.

The reference runs gossip in real time — one round per GossipInterval
(200 ms, config/config.go:47), i.e. 5 rounds/sec regardless of hardware.
The TPU framework's whole point is to run the same broadcast→merge
protocol as batched on-chip compute, so the headline metric is how many
full cluster-wide gossip rounds one chip simulates per second, and
``vs_baseline`` is the speedup over the reference's 5 rounds/sec
wall-clock rate (BASELINE.md north-star table).

Two models are measured on the same 4,096-node Erdős–Rényi cluster
(BASELINE.json config 3's graph: avg degree 8, seed 3; 10 services/node,
fanout 3, budget 15):

* ``value`` — the DENSE exact model (``known[N, N·spn]``, oracle-grade
  record-level semantics).  Roofline: the dense round is bound by its
  two full-tensor scatters (known 671 MB + sent 168 MB rewritten per
  round); measured v5e scatter cost at these shapes is 10-18 ms per
  buffer touch nearly independent of update count (~7.5 ms even at
  1k updates vs a 5.4 ms copy), and no formulation escapes it —
  1D/sorted/unique-flagged/row-aligned/donated/in-scan variants all
  measure the same (benchmarks/scatter_costs.py re-runs the whole
  cost model).  ~36 ms/round ≈ 28 rounds/sec sits within ~2× of the
  scatter-imposed floor — more speed requires a different state
  representation, not a faster kernel.
* ``compressed_rounds_per_sec`` — the bounded-memory large-cluster model
  (models/compressed.py) on the SAME cluster: O(N·K + M) state with the
  global line-aligned cache, whose board/pull delivery is pure
  elementwise compute (zero per-round scatters) — ~25× the dense model
  at equal N (~700-750 rounds/sec measured), and the only
  representation that reaches 100k+ nodes.

``north_star`` reports BASELINE.md's second target: wall-clock to
ε-convergence of a churn burst on a 100k-node / 1M-service cluster.
The burst drains through the real protocol budget (15 records per
~1398 B packet per peer, fanout 3), so SIMULATED time is
bandwidth-bound exactly as the reference would be; the benchmark
measures how fast one chip crunches those rounds.  The <10 s target is
set for a v5e-8; this runs on the driver's SINGLE chip and — after the
scatter-free per-line census — beats it there (measured 9.6 s,
225 rounds at ~43 ms).  The sharded twin
(parallel/sharded_compressed.py, validated on the virtual 8-device
mesh) scales it further.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "compressed_rounds_per_sec": N, "north_star": {...}}
"""

from __future__ import annotations

import json
import os
import sys
import time


def _bench_dense(n, spn, rounds):
    import jax

    from sidecar_tpu.models.exact import ExactSim, SimParams
    from sidecar_tpu.ops.topology import erdos_renyi

    params = SimParams(n=n, services_per_node=spn, fanout=3, budget=15)
    sim = ExactSim(params, erdos_renyi(n, avg_degree=8.0, seed=3))
    state = sim.init_state()
    key = jax.random.PRNGKey(0)

    # Warm-up: compile + one short run.  Sync via device_get — on remote
    # TPU platforms block_until_ready can return before execution ends.
    warm = sim.run_fast(state, key, rounds)
    jax.device_get(warm.known[0, :4])

    t0 = time.perf_counter()
    final = sim.run_fast(state, key, rounds)
    jax.device_get(final.known[0, :4])
    return rounds / (time.perf_counter() - t0)


def _bench_compressed(n, spn, rounds):
    import jax

    from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
    from sidecar_tpu.models.timecfg import TimeConfig
    from sidecar_tpu.ops.topology import erdos_renyi

    cfg = TimeConfig(refresh_interval_s=10_000.0, push_pull_interval_s=4.0)
    params = CompressedParams(n=n, services_per_node=spn, fanout=3,
                              budget=15, cache_lines=256,
                              # Refresh is pinned out (cfg above), so no
                              # refresh folds can occur and the exact
                              # below-floor sweep has nothing to do.
                              deep_sweep_every=0)
    sim = CompressedSim(params, erdos_renyi(n, avg_degree=8.0, seed=3), cfg)
    state = sim.init_state()
    key = jax.random.PRNGKey(0)

    warm = sim.run_fast(state, key, rounds)
    jax.device_get(warm.own[0, :4])
    t0 = time.perf_counter()
    final = sim.run_fast(state, key, rounds)
    jax.device_get(final.own[0, :4])
    return rounds / (time.perf_counter() - t0)


def _bench_north_star(n, spn, churn_frac, eps, conv_every, max_rounds):
    """Wall-clock for one chip to simulate a ``churn_frac`` burst on an
    n-node / n·spn-service cluster to ε-convergence (compressed model;
    the churn workload of BASELINE config 4 at north-star scale)."""
    import jax
    import numpy as np

    from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
    from sidecar_tpu.models.timecfg import TimeConfig
    from sidecar_tpu.ops.topology import erdos_renyi

    cfg = TimeConfig(refresh_interval_s=10_000.0, push_pull_interval_s=4.0)
    params = CompressedParams(n=n, services_per_node=spn, fanout=3,
                              budget=15, cache_lines=256,
                              # Refresh is pinned out (cfg above), so no
                              # refresh folds can occur and the exact
                              # below-floor sweep has nothing to do.
                              deep_sweep_every=0)
    sim = CompressedSim(params, erdos_renyi(n, avg_degree=8.0, seed=3), cfg)
    rng = np.random.default_rng(7)
    slots = np.sort(
        rng.choice(params.m, size=max(1, int(params.m * churn_frac)),
                   replace=False)).astype(np.int32)
    state = sim.mint(sim.init_state(), slots, 10)
    key = jax.random.PRNGKey(0)

    # Chunk is 3 metric samples per dispatch: the ε check still has
    # conv_every granularity (the returned curve is scanned per sample)
    # while the host↔device round-trip — ~100 ms on a tunneled chip —
    # amortizes over 3× more rounds.
    chunk = 3 * conv_every
    warm, c = sim.run(state, key, chunk, conv_every)
    jax.device_get(c)

    t0 = time.perf_counter()
    total, executed, conv_last, conv_max = 0, 0, 0.0, 0.0
    while executed < max_rounds:
        state, conv = sim.run(state, key, chunk, conv_every)
        conv = np.asarray(jax.device_get(conv))
        executed += chunk
        conv_last = float(conv[-1])
        conv_max = max(conv_max, float(conv.max()))
        if conv_max >= 1.0 - eps:
            # rounds_to_eps at conv_every granularity: the first sample
            # in this chunk that crossed ε (the full chunk still ran —
            # per-round cost divides by `executed`, not `total`).
            hit = int(np.argmax(conv >= 1.0 - eps)) + 1
            total += hit * conv_every
            break
        total += chunk
    wall = time.perf_counter() - t0
    reached = conv_max >= 1.0 - eps
    round_s = cfg.round_ticks / cfg.ticks_per_second
    return {
        "n": n,
        "services": n * spn,
        "churn_frac": churn_frac,
        "eps": eps,
        "rounds_to_eps": total if reached else None,
        "sim_seconds_to_eps": round(total * round_s, 1)
        if reached else None,
        "final_convergence": round(conv_last, 6),
        "wall_seconds_single_chip": round(wall, 2),
        "wall_ms_per_round": round(wall / executed * 1000, 1),
        "target": "<10 s on v5e-8 (this is 1 chip; scaling path: "
                  "parallel/sharded_compressed.py)",
    }


def main() -> None:
    import jax

    n = int(os.environ.get("BENCH_NODES", "4096"))
    spn = int(os.environ.get("BENCH_SERVICES_PER_NODE", "10"))
    rounds = int(os.environ.get("BENCH_ROUNDS", "200"))
    ns_n = int(os.environ.get("BENCH_NORTH_STAR_NODES", "100000"))

    platform = jax.devices()[0].platform
    if platform == "cpu":
        # CPU fallback (no TPU attached): shrink so the bench still
        # runs; explicit env overrides are honored.
        if "BENCH_NODES" not in os.environ:
            n, rounds = 512, 50
        if "BENCH_NORTH_STAR_NODES" not in os.environ:
            ns_n = 4096

    # Device-level tracing (SURVEY.md §5): BENCH_TRACE=<dir> wraps the
    # measured runs in a jax.profiler trace (TensorBoard/xprof format) —
    # the per-kernel timeline behind the roofline numbers above.
    import contextlib
    trace_dir = os.environ.get("BENCH_TRACE")
    trace = (jax.profiler.trace(trace_dir) if trace_dir
             else contextlib.nullcontext())
    with trace:
        dense_rps = _bench_dense(n, spn, rounds)
        compressed_rps = _bench_compressed(n, spn, rounds)
        north_star = _bench_north_star(ns_n, spn, churn_frac=0.001,
                                       eps=1e-4, conv_every=25,
                                       max_rounds=400)

    # Baseline: the reference's wall-clock gossip cadence — 5 rounds/sec
    # (GossipInterval 200 ms), hardware-independent.
    print(json.dumps({
        "metric": f"simulated gossip rounds/sec/chip (n={n}, spn={spn}, "
                  f"{platform})",
        "value": round(dense_rps, 3),
        "unit": "rounds/sec/chip",
        "vs_baseline": round(dense_rps / 5.0, 3),
        "compressed_rounds_per_sec": round(compressed_rps, 3),
        "north_star": north_star,
    }))


if __name__ == "__main__":
    sys.exit(main())
