"""Headline benchmark: simulated gossip rounds/sec/chip.

The reference runs gossip in real time — one round per GossipInterval
(200 ms, config/config.go:47), i.e. 5 rounds/sec regardless of hardware.
The TPU framework's whole point is to run the same broadcast→merge
protocol as batched on-chip compute, so the headline metric is how many
full cluster-wide gossip rounds one chip simulates per second, and
``vs_baseline`` is the speedup over the reference's 5 rounds/sec
wall-clock rate (BASELINE.md north-star table).

Default config: 4,096-node Erdős–Rényi cluster (BASELINE.json config 3's
graph: avg degree 8, seed 3 — matching sim/scenarios.py) with 10
services/node — 4096 × 40,960 packed-int32 state (~670 MB), fanout 3,
budget 15.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    # Keep the virtual-CPU test config out of the way: bench runs on
    # whatever real platform the driver provides.
    import jax

    from sidecar_tpu.models.exact import ExactSim, SimParams
    from sidecar_tpu.ops.topology import erdos_renyi

    n = int(os.environ.get("BENCH_NODES", "4096"))
    spn = int(os.environ.get("BENCH_SERVICES_PER_NODE", "10"))
    rounds = int(os.environ.get("BENCH_ROUNDS", "200"))

    platform = jax.devices()[0].platform
    if platform == "cpu" and "BENCH_NODES" not in os.environ:
        # CPU fallback (no TPU attached): shrink so the bench still runs.
        n, rounds = 512, 50

    params = SimParams(n=n, services_per_node=spn, fanout=3, budget=15)
    sim = ExactSim(params, erdos_renyi(n, avg_degree=8.0, seed=3))
    state = sim.init_state()
    key = jax.random.PRNGKey(0)

    # Warm-up: compile + one short run.  Sync via device_get — on remote
    # TPU platforms block_until_ready can return before execution ends.
    warm = sim.run_fast(state, key, rounds)
    jax.device_get(warm.known[0, :4])

    t0 = time.perf_counter()
    final = sim.run_fast(state, key, rounds)
    jax.device_get(final.known[0, :4])
    dt = time.perf_counter() - t0

    rounds_per_sec = rounds / dt
    # Reference wall-clock rate: 1 round / 200 ms gossip interval.
    baseline_rounds_per_sec = 5.0

    print(json.dumps({
        "metric": f"simulated gossip rounds/sec/chip (n={n}, spn={spn}, {platform})",
        "value": round(rounds_per_sec, 3),
        "unit": "rounds/sec/chip",
        "vs_baseline": round(rounds_per_sec / baseline_rounds_per_sec, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
