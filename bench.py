"""Headline benchmark: simulated gossip rounds/sec/chip + the north star.

The reference runs gossip in real time — one round per GossipInterval
(200 ms, config/config.go:47), i.e. 5 rounds/sec regardless of hardware.
The TPU framework's whole point is to run the same broadcast→merge
protocol as batched on-chip compute, so the headline metric is how many
full cluster-wide gossip rounds one chip simulates per second, and
``vs_baseline`` is the speedup over the reference's 5 rounds/sec
wall-clock rate (BASELINE.md north-star table).

Two models are measured on the same 4,096-node Erdős–Rényi cluster
(BASELINE.json config 3's graph: avg degree 8, seed 3; 10 services/node,
fanout 3, budget 15):

* ``value`` — the DENSE exact model (``known[N, N·spn]``, oracle-grade
  record-level semantics).  Roofline: the dense round is bound by its
  two full-tensor scatters (known 671 MB + sent 168 MB rewritten per
  round); measured v5e scatter cost at these shapes is 10-18 ms per
  buffer touch nearly independent of update count, and no formulation
  escapes it: 1D/sorted/unique-flagged/row-aligned/donated/in-scan XLA
  variants all measure the same (benchmarks/scatter_costs.py), and a
  hand-written Pallas scatter-apply kernel — dense per-row-block
  buckets, masked segment RMW, in-place via input_output_aliases —
  LOSES outright at the headline shape: 28.3 ms/round including its
  required per-round bucketing sort vs XLA's 13.4, with the kernel
  body alone (~13 ms, bucketing amortized away) merely tying XLA,
  against a measured ~8-9 ms zero-index in-place-RMW ceiling
  (benchmarks/pallas_scatter.py; every 8-row tile is dirty at this
  update density, so the full buffer must stream regardless of
  indexing).  ~36 ms/round ≈ 28 rounds/sec therefore sits within ~1.6×
  of the physical floor — more speed requires a different state
  representation, not a faster kernel.
* ``compressed_rounds_per_sec`` — the bounded-memory large-cluster model
  (models/compressed.py) on the SAME cluster: O(N·K + M) state with the
  global line-aligned cache, whose board/pull delivery is pure
  elementwise compute (zero per-round scatters) — ~25× the dense model
  at equal N (~700-750 rounds/sec measured), and the only
  representation that reaches 100k+ nodes.

``north_star`` reports BASELINE.md's second target: wall-clock to
ε-convergence of a churn burst on a 100k-node / 1M-service cluster.
The burst drains through the real protocol budget (15 records per
~1398 B packet per peer, fanout 3), so SIMULATED time is
bandwidth-bound exactly as the reference would be; the benchmark
measures how fast one chip crunches those rounds.  The <10 s target is
set for a v5e-8; this runs on the driver's SINGLE chip.

``north_star_faithful`` reruns the same burst under the REFERENCE'S
protocol constants (20 s PushPullInterval instead of the headline's
4 s) with ``fold_quorum=1.0`` — no analytic straggler fold; every
delivery, including to the ~40 isolated nodes of the ER graph, is
carried by simulated gossip + anti-entropy and the run ends at
convergence == 1.0 exactly.  Both blocks report ε against BOTH
denominators: the total belief space (the easy bar — a 0.1% burst
unsettles ~10⁻³ of beliefs, so ε=10⁻⁴ ≈ 90% of the unsettled
delivered) and the burst's own unsettled set (the strict bar — 99.99%
of demanded deliveries done), with wall-clock at each crossing.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "compressed_rounds_per_sec": N, "north_star": {...},
   "north_star_faithful": {...}}
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

# -- timeout watchdog --------------------------------------------------------
# BENCH_r05 postmortem, part 2: the harness timeout (`timeout -k 10 870`)
# sends SIGTERM, and a bench that dies mid-north-star leaves rc=124 with
# `parsed: null` — zero salvageable data even though hundreds of rounds
# already ran.  The watchdog keeps a host-side progress record (phase +
# partial north-star numbers, updated as the pipelined loop consumes
# chunks) and flushes it as ONE parseable JSON line on SIGTERM (and on
# SIGALRM when BENCH_WATCHDOG_S arms a self-timer below the harness
# deadline), then exits 124.

_WATCHDOG: dict = {"phase": "init", "partial": None, "deadline": None}


def _watchdog_note(phase: str, partial=None) -> None:
    """Advance the watchdog's phase label and MERGE ``partial`` into
    the progress record — merge, not replace, so a later phase's loop
    progress never clobbers an earlier phase's completed block (the
    faithful rerun must not erase the finished headline north star)."""
    _WATCHDOG["phase"] = phase
    if partial is not None:
        merged = _WATCHDOG["partial"] or {}
        merged.update(partial)
        _WATCHDOG["partial"] = merged


def _watchdog_record() -> dict:
    return {"error": "bench_timeout", "watchdog": True,
            "phase": _WATCHDOG["phase"],
            "partial": _WATCHDOG["partial"]}


def _watchdog_handler(signum, frame):  # pragma: no cover - signal path
    print(json.dumps(_watchdog_record()), flush=True)
    sys.exit(124)


def install_watchdog() -> None:
    signal.signal(signal.SIGTERM, _watchdog_handler)
    alarm_s = int(os.environ.get("BENCH_WATCHDOG_S", "0"))
    if alarm_s > 0:
        signal.signal(signal.SIGALRM, _watchdog_handler)
        signal.alarm(alarm_s)
        # Remembered so host-side sleeps (the device-init retry) can
        # bound themselves by the remaining budget instead of sleeping
        # through the deadline (BENCH_r05 postmortem, part 3).
        _WATCHDOG["deadline"] = time.monotonic() + alarm_s


def watchdog_budget_s():
    """Seconds left before the self-timer fires, or None when unarmed
    (no BENCH_WATCHDOG_S) — the bound host-side retry sleeps must
    respect."""
    deadline = _WATCHDOG.get("deadline")
    if deadline is None:
        return None
    return max(0.0, deadline - time.monotonic())


def disarm_watchdog() -> None:
    """Cancel the self-timer once the measured phases are done — a run
    that completes just before the alarm must exit 0 with the real
    result record, not a spurious timeout one mid-teardown."""
    signal.alarm(0)


def _bench_dense(n, spn, rounds):
    import jax

    from sidecar_tpu.models.exact import ExactSim, SimParams
    from sidecar_tpu.ops.topology import erdos_renyi

    params = SimParams(n=n, services_per_node=spn, fanout=3, budget=15)
    sim = ExactSim(params, erdos_renyi(n, avg_degree=8.0, seed=3))
    state = sim.init_state()
    key = jax.random.PRNGKey(0)

    # Warm-up: compile + one short run.  Sync via device_get — on remote
    # TPU platforms block_until_ready can return before execution ends.
    # The drivers DONATE their input, so the timed run chains off the
    # warm-up's output (same shapes ⇒ same executable; the donated
    # in-place rewrite is exactly the steady-state the bench reports).
    state = sim.run_fast(state, key, rounds)
    jax.device_get(state.known[0, :4])

    t0 = time.perf_counter()
    final = sim.run_fast(state, key, rounds)
    jax.device_get(final.known[0, :4])
    return rounds / (time.perf_counter() - t0)


def _bench_compressed(n, spn, rounds):
    import jax

    from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
    from sidecar_tpu.models.timecfg import TimeConfig
    from sidecar_tpu.ops.topology import erdos_renyi

    cfg = TimeConfig(refresh_interval_s=10_000.0, push_pull_interval_s=4.0)
    params = CompressedParams(n=n, services_per_node=spn, fanout=3,
                              budget=15, cache_lines=256,
                              # Refresh is pinned out (cfg above), so no
                              # refresh folds can occur and the exact
                              # below-floor sweep has nothing to do.
                              deep_sweep_every=0)
    sim = CompressedSim(params, erdos_renyi(n, avg_degree=8.0, seed=3), cfg)
    state = sim.init_state()
    key = jax.random.PRNGKey(0)

    # Chain warm → timed (donating drivers; see _bench_dense).
    state = sim.run_fast(state, key, rounds)
    jax.device_get(state.own[0, :4])
    t0 = time.perf_counter()
    final = sim.run_fast(state, key, rounds)
    jax.device_get(final.own[0, :4])
    return rounds / (time.perf_counter() - t0)


def _bench_north_star(n, spn, churn_frac, eps, conv_every, max_rounds,
                      timecfg=None, fold_quorum=0.995, deep_sweep_every=0,
                      cache_lines=256, sharded=False, note="",
                      phase="north_star"):
    """Wall-clock for one chip to simulate a ``churn_frac`` burst on an
    n-node / n·spn-service cluster to ε-convergence (compressed model;
    the churn workload of BASELINE config 4 at north-star scale).

    ε is reported against BOTH denominators:

    * ``rounds_to_eps`` — ε over the TOTAL belief space (n·m cells, the
      convergence metric's native denominator).  A 0.1% burst unsettles
      ~10⁻³ of all beliefs, so ε=10⁻⁴ here means delivering ~90% of the
      unsettled beliefs — the easier bar.
    * ``rounds_to_eps_unsettled`` — ε over the burst's own unsettled
      set (burst·(n−1) beliefs that actually need delivery): 1−ε of the
      deliveries the churn demanded have happened — the strict bar.

    The default protocol constants here (4 s push-pull, quorum folds)
    are the HEADLINE configuration; ``north_star_faithful`` in the
    output reruns with the reference's own constants
    (PushPullInterval 20 s — config/config.go:45, main.go:252-256 —
    1-minute refresh live, ``fold_quorum=1.0`` so every delivery is
    carried by simulated gossip, no analytic straggler fold)."""
    import jax
    import numpy as np

    from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
    from sidecar_tpu.models.timecfg import TimeConfig
    from sidecar_tpu.ops.topology import erdos_renyi

    cfg = timecfg if timecfg is not None else \
        TimeConfig(refresh_interval_s=10_000.0, push_pull_interval_s=4.0)
    params = CompressedParams(n=n, services_per_node=spn, fanout=3,
                              budget=15, cache_lines=cache_lines,
                              fold_quorum=fold_quorum,
                              deep_sweep_every=deep_sweep_every)
    topo = erdos_renyi(n, avg_degree=8.0, seed=3)
    if sharded:
        from sidecar_tpu.parallel.sharded_compressed import (
            ShardedCompressedSim,
        )
        # Exchange selection: BENCH_BOARD_EXCHANGE (bench-local
        # override) > SIDECAR_TPU_BOARD_EXCHANGE > all_gather — the
        # same env contract the sim constructor resolves
        # (docs/sharding.md).
        sim = ShardedCompressedSim(
            params, topo, cfg,
            board_exchange=os.environ.get("BENCH_BOARD_EXCHANGE") or None)
    else:
        sim = CompressedSim(params, topo, cfg)
    rng = np.random.default_rng(7)
    slots = np.sort(
        rng.choice(params.m, size=max(1, int(params.m * churn_frac)),
                   replace=False)).astype(np.int32)
    state = sim.mint(sim.init_state(), slots, 10)
    key = jax.random.PRNGKey(0)

    # ε thresholds as raw BEHIND counts (the device samples the count,
    # not the normalized fraction: near 1.0 one float32 ulp of the
    # ratio spans thousands of cells at this denominator, which would
    # quantize the crossings).  Total-space: behind ≤ eps·n·m.
    # Unsettled-set: behind ≤ eps·behind₀, behind₀ = burst·(n−1)
    # (every non-owner starts behind).
    behind0 = float(len(slots)) * (n - 1)
    nm = float(n) * float(n * spn)
    thr_total = eps * nm
    thr_unsettled = eps * behind0

    # Chunk several metric samples per dispatch: the ε check still has
    # conv_every granularity (the returned curve is scanned per sample)
    # while the host↔device round-trip — ~100 ms on a tunneled chip —
    # amortizes over more rounds.  Clamped to ≤150 rounds/dispatch: the
    # tunnel worker crashes on very long scan dispatches, and the clamp
    # must not depend on call sites keeping conv_every small.
    chunk = conv_every * max(1, 150 // conv_every)

    # Sparse-frontier arbiter (docs/sparse.md): dense vs sparse per
    # pipelined chunk, driven by the behind census this loop already
    # pulls, with hysteresis and the overflow→dense cooldown.
    # BENCH_SPARSE=0 pins dense (the pre-round-8 bench); otherwise the
    # SIDECAR_TPU_SPARSE contract applies (auto = census-driven, entry
    # heuristic shared with the bridge via for_census).
    from sidecar_tpu.ops import sparse as sparse_ops
    if os.environ.get("BENCH_SPARSE", "1") == "0":
        sparse_mode = "0"
    else:
        sparse_mode = sparse_ops.resolve_sparse(record=False)
    arbiter = sparse_ops.SparseArbiter.for_census(sparse_mode, n)

    # Warm-up compiles without advancing the measured trajectory:
    # donate=False copies the state so the run below starts from the
    # same burst (the drivers donate their input by default).  Only the
    # programs the arbiter can actually dispatch are warmed (mode "1"
    # never dispatches the standalone dense program — its overflow
    # fallback lives inside the sparse scan), and the warm-up outputs
    # are dropped immediately so they don't pin device memory alongside
    # the two in-flight pipelined states below.
    if sparse_mode != "1":
        warm, c = sim.run_behind(state, key, chunk, conv_every,
                                 donate=False, sparse=False)
        jax.device_get(c)
        del warm, c
    if sparse_mode != "0":
        warm_s, c_s = sim.run_behind(state, key, chunk, conv_every,
                                     donate=False, sparse=True)
        jax.device_get(c_s)
        del warm_s, c_s

    # Chunked-dispatch PIPELINE: chunk i+1 is enqueued (async, donated
    # zero-copy carry) BEFORE chunk i's scalar curve is pulled back, so
    # the device never idles through the tunnel RTT + host-side ε
    # bookkeeping.  The horizon check rides the host-side round counter
    # (start_round=) — reading the in-flight state's round_idx would
    # block on the running chunk and re-serialize the pipeline.  On
    # convergence one speculative chunk is discarded (its rounds are
    # not counted in rounds_executed).
    t0 = time.perf_counter()
    executed, behind_last = 0, float("inf")
    hit_total, hit_unsettled = None, None
    wall_total, wall_unsettled = None, None

    from sidecar_tpu import metrics
    from sidecar_tpu.telemetry import profiling

    def dispatch(st, start):
        # The arbiter's decision applies to the chunk being enqueued —
        # passed EXPLICITLY both ways (dispatch_kwargs: an omitted
        # kwarg would resolve the sim's env default and defeat the
        # BENCH_SPARSE=0 pin); sparse dispatches also hand back the
        # device stats handle (grabbing it never blocks — it is read
        # with the chunk's census, after the chunk has finished).
        use_sparse = arbiter.sparse
        with profiling.annotate(f"sidecar.bench.{phase}.chunk"):
            st2, behind = sim.run_behind(st, key, chunk, conv_every,
                                         start_round=start,
                                         **arbiter.dispatch_kwargs())
        return st2, behind, (sim.last_sparse_stats if use_sparse
                             else None)

    pend_state, pend_behind, pend_stats = dispatch(state, 0)
    dispatched = chunk
    while True:
        if dispatched < max_rounds:
            pend_state, nxt_behind, nxt_stats = dispatch(
                pend_state, dispatched)
            dispatched += chunk
        else:
            nxt_behind = nxt_stats = None
        t_chunk = time.perf_counter()
        behind = np.asarray(jax.device_get(pend_behind),
                            dtype=np.float64)
        # Per-chunk wall (device_get drains the chunk's compute) into
        # the telemetry histograms (docs/metrics.md) — the bench JSON's
        # `telemetry` block reports their percentiles.
        metrics.histogram_since(f"bench.{phase}.chunk", t_chunk)
        arbiter.record_chunk(
            chunk, None if pend_stats is None
            else np.asarray(jax.device_get(pend_stats)))
        for j, b in enumerate(behind):
            at = executed + (j + 1) * conv_every
            if hit_total is None and b <= thr_total:
                hit_total = at
            if hit_unsettled is None and b <= thr_unsettled:
                hit_unsettled = at
        executed += chunk
        behind_last = float(behind[-1])
        arbiter.update_census(behind_last)
        # Wall-clock at each crossing, measured at the end of the chunk
        # that crossed (the whole chunk ran on-device either way).
        now_wall = time.perf_counter() - t0
        if hit_total is not None and wall_total is None:
            wall_total = now_wall
        if hit_unsettled is not None and wall_unsettled is None:
            wall_unsettled = now_wall
        # Namespaced under this run's phase label so concurrent/later
        # north-star variants each keep their own progress block.
        _watchdog_note(phase, {phase + "_progress": {
            "n": n, "rounds_executed": executed,
            "behind_last": behind_last,
            "rounds_to_eps": hit_total,
            "rounds_to_eps_unsettled": hit_unsettled,
            "sparse": arbiter.snapshot(),
            "wall_seconds": round(now_wall, 2), "note": note or None,
        }})
        if (hit_unsettled is not None and hit_total is not None) \
                or nxt_behind is None:
            break
        pend_behind, pend_stats = nxt_behind, nxt_stats
    wall = time.perf_counter() - t0
    conv_last = 1.0 - behind_last / nm

    # Sharded exchange accounting reads the LAST dispatched state —
    # captured BEFORE the trace probe below donates/advances it, so
    # dropped_pulls counts only the measured run (and a probe failure
    # after donation can never poison the headline read).  The sync
    # also publishes the count as parallel.exchange.overflow.
    dropped_pulls = sim.sync_exchange_metrics(pend_state) if sharded \
        else None

    # Flight-recorder tail probe (AFTER the timed loop — the measured
    # numbers above are untouched): a short traced run off the final
    # pipelined state summarizes the convergence tail round-for-round
    # (frontier size, behind census, exchange bytes — ops/trace.py).
    # BENCH_TRACE_TAIL=0 skips it.
    trace_tail = None
    if os.environ.get("BENCH_TRACE_TAIL", "1") != "0":
        try:
            from sidecar_tpu.ops import trace as trace_ops
            tail_rounds = 8
            pend_state, tail_tr = sim.run_with_trace(
                pend_state, key, tail_rounds, start_round=dispatched,
                **arbiter.dispatch_kwargs())
            trace_tail = trace_ops.summarize(tail_tr)
        except Exception as exc:  # the headline must survive the probe
            print(f"# trace tail probe skipped: {exc}", file=sys.stderr)

    round_s = cfg.round_ticks / cfg.ticks_per_second
    out = {
        "n": n,
        "services": n * spn,
        "churn_frac": churn_frac,
        "eps": eps,
        "push_pull_interval_s": cfg.push_pull_interval_s,
        "refresh_interval_s": cfg.refresh_interval_s,
        "fold_quorum": fold_quorum,
        "cache_lines": cache_lines,
        "rounds_to_eps": hit_total,
        "sim_seconds_to_eps": round(hit_total * round_s, 1)
        if hit_total else None,
        "wall_seconds_to_eps": round(wall_total, 2)
        if wall_total is not None else None,
        "rounds_to_eps_unsettled": hit_unsettled,
        "sim_seconds_to_eps_unsettled": round(hit_unsettled * round_s, 1)
        if hit_unsettled else None,
        "wall_seconds_to_eps_unsettled": round(wall_unsettled, 2)
        if wall_unsettled is not None else None,
        "final_convergence": round(conv_last, 9),
        "final_behind_count": round(behind_last),
        "rounds_executed": executed,
        "wall_seconds_single_chip": round(wall, 2),
        "wall_ms_per_round": round(wall / executed * 1000, 1),
        "target": "<10 s on v5e-8 (this is 1 chip; scaling path: "
                  "parallel/sharded_compressed.py, BENCH_SHARDED=1)",
        "sparse": {"mode": sparse_mode, **arbiter.snapshot()},
        **({"round_trace_tail": trace_tail} if trace_tail else {}),
    }
    if sharded:
        # No silent caps: an all_to_all run with bucket overflows must
        # be distinguishable from a drop-free one.  ``dropped_pulls``
        # was read off the LAST dispatched state above, pre-probe —
        # the input ``state`` was donated into the pipeline (may
        # include one speculative chunk's drops).
        out["devices"] = len(jax.devices())
        out["board_exchange"] = sim.board_exchange
        out["a2a_slack"] = sim.a2a_slack
        out["exchange_bytes_per_round"] = sim.exchange_bytes_per_round
        out["dropped_pulls"] = dropped_pulls
    if note:
        out["note"] = note
    return out


def _bench_cost(n, spn, dense_rps=None, compressed_rps=None,
                north_star=None, trace_dir=None):
    """The kernel-cost block (docs/perf.md): compile each single-chip
    family's step ONCE with phase scopes on (a fresh jit wrapper — the
    production programs and caches are untouched) and report where the
    compiled bytes, FLOPs, and HBM go, with the per-phase shares
    reconciled against the measured ms/round.

    Attribution is static (compiled-output-bytes per ``sidecar.phase``
    metadata label); when this run also captured a profiler trace, the
    trace's per-phase device-time reduction and its reconciliation
    against the north star's wall ms/round ride along."""
    import jax

    from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
    from sidecar_tpu.models.exact import ExactSim, SimParams
    from sidecar_tpu.models.timecfg import TimeConfig
    from sidecar_tpu.ops.topology import erdos_renyi
    from sidecar_tpu.telemetry import cost

    # Probe shape: every phase's cost is linear in the same [N, M]
    # state, so the byte SHARES are scale-stable and the probe compiles
    # at a bounded size — compile time, not attribution accuracy, is
    # what scales with N.
    cn = min(n, int(os.environ.get("BENCH_COST_NODES", "1024")))
    key = jax.random.PRNGKey(0)

    exact = ExactSim(SimParams(n=cn, services_per_node=spn, fanout=3,
                               budget=15),
                     erdos_renyi(cn, avg_degree=8.0, seed=3))
    ex_state = exact.init_state()
    cfg = TimeConfig(refresh_interval_s=10_000.0,
                     push_pull_interval_s=4.0)
    comp = CompressedSim(
        CompressedParams(n=cn, services_per_node=spn, fanout=3,
                         budget=15, cache_lines=256, deep_sweep_every=0),
        erdos_renyi(cn, avg_degree=8.0, seed=3), cfg)
    co_state = comp.init_state()

    measured = {
        "exact.step": (1000.0 / dense_rps) if dense_rps else None,
        "compressed.step": (1000.0 / compressed_rps)
        if compressed_rps else None,
    }
    out = {"probe_nodes": cn,
           "attribution": "compiled-output-bytes (docs/perf.md)",
           "programs": {}, "reconciliation": {}}
    with cost.forced_phases(True):
        probes = {
            "exact.step": (lambda st, k: exact._step(st, k),
                           (ex_state, key)),
            "compressed.step": (lambda st, k: comp._step(st, k),
                                (co_state, key)),
        }
        for fam, (fn, args) in probes.items():
            rep = cost.program_report(fam, fn, *args)
            prog = {k: rep[k] for k in ("lower_ms", "compile_ms",
                                        "flops", "bytes_accessed")
                    if k in rep}
            if "memory" in rep:
                prog["hbm_peak_bytes"] = rep["memory"]["peak_bytes"]
                prog["hbm"] = rep["memory"]
            if "collectives" in rep:
                prog["collectives"] = rep["collectives"]
            out["programs"][fam] = prog
            table = cost.phase_share_table(rep.get("phase_bytes", {}),
                                           measured[fam])
            out["reconciliation"][fam] = {
                "measured_ms_per_round":
                    round(measured[fam], 4) if measured[fam] else None,
                "phases": table["phases"],
                "attributed_fraction": table["attributed_fraction"],
                "min_attributed_fraction":
                    cost.MIN_ATTRIBUTED_FRACTION,
                "within_tolerance": (table["attributed_fraction"]
                                     >= cost.MIN_ATTRIBUTED_FRACTION),
            }
    if trace_dir and os.path.isdir(trace_dir):
        prof = cost.parse_profile_dir(trace_dir)
        out["profile"] = prof
        if north_star and prof.get("attributed_ms"):
            rr = north_star.get("rounds_executed")
            wmr = north_star.get("wall_ms_per_round")
            if rr and wmr:
                out["profile_reconciliation"] = cost.reconcile(
                    prof["attributed_ms"] / rr, wmr)
    out["compile"] = cost.snapshot()["compile"]
    cost.record_report("bench.cost", out)
    return out


def _bench_regression(record):
    """Verdict vs the previous bench record (tools/bench_compare):
    BENCH_COMPARE names the baseline record (or a directory of them —
    newest wins); unset, the newest ``BENCH_r*.json`` next to bench.py
    is used; ``0`` disables.  Returns None when there is nothing to
    compare against."""
    target = os.environ.get("BENCH_COMPARE")
    if target == "0":
        return None
    import glob as _glob
    import importlib.util as _ilu

    root = os.path.dirname(os.path.abspath(__file__))
    if target and os.path.isdir(target):
        hits = sorted(_glob.glob(os.path.join(target, "BENCH_*.json")))
        prev_path = hits[-1] if hits else None
    elif target:
        prev_path = target
    else:
        hits = sorted(_glob.glob(os.path.join(root, "BENCH_r*.json")))
        prev_path = hits[-1] if hits else None
    if not prev_path or not os.path.exists(prev_path):
        return None
    spec = _ilu.spec_from_file_location(
        "bench_compare",
        os.path.join(root, "tools", "bench_compare.py"))
    bc = _ilu.module_from_spec(spec)
    spec.loader.exec_module(bc)
    with open(prev_path, "r", encoding="utf-8") as fh:
        prev = json.load(fh)
    verdict = bc.compare(prev, record)
    verdict["base_record"] = os.path.basename(prev_path)
    return verdict


def main() -> None:
    import jax

    install_watchdog()
    n = int(os.environ.get("BENCH_NODES", "4096"))
    spn = int(os.environ.get("BENCH_SERVICES_PER_NODE", "10"))
    rounds = int(os.environ.get("BENCH_ROUNDS", "200"))
    ns_n = int(os.environ.get("BENCH_NORTH_STAR_NODES", "100000"))

    # The tunneled TPU backend can be transiently unavailable (worker
    # restart); failing the whole bench on the first init attempt
    # throws the run away.  Retrying is only sound when JAX_PLATFORMS
    # pins a non-cpu backend (as this environment does: =axon): jax
    # otherwise leaves an already-initialized CPU backend in its cache
    # after a TPU init failure, and the "retry" would silently return
    # that CPU backend — publishing shrunken-fallback numbers as the
    # headline.  Unpinned platforms fail fast instead.
    #
    # Bounded fail-fast (BENCH_r05 postmortem): the old 60 s sleeps ate
    # the driver's whole timeout (rc=124, no output, `parsed: null`).
    # Now: ≤3 attempts with short backoff, then ONE parseable JSON
    # error record on stdout and a nonzero exit — a dead backend must
    # cost seconds and still produce a machine-readable verdict.
    want = os.environ.get("JAX_PLATFORMS", "")
    pinned = bool(want) and want != "cpu"
    attempts = max(1, int(os.environ.get("BENCH_INIT_ATTEMPTS",
                                         "3" if pinned else "1")))
    if not pinned:
        # Retries stay hard-disabled on unpinned/cpu platforms even via
        # the env override — see the backend-cache hazard above.
        attempts = 1
    backoffs = (5, 15)
    # Emit-before-sleep margin: a retry sleep is only taken when the
    # error record could still be flushed with this much watchdog
    # budget to spare AFTER the sleep — otherwise the watchdog (or the
    # harness timeout behind it) would reduce the whole run to a bare
    # rc=124 with `parsed: null` while we slept (BENCH_r05 postmortem).
    init_margin_s = 5.0
    platform = None
    backend_fallback = False
    for attempt in range(attempts):
        try:
            platform = jax.devices()[0].platform
            break
        except RuntimeError as exc:
            # Progress into the watchdog record FIRST: even a SIGTERM
            # that beats the margin math now carries the init failure.
            _watchdog_note("device_init", {"device_init": {
                "attempt": attempt + 1, "attempts": attempts,
                "message": str(exc)[:200]}})
            delay = backoffs[min(attempt, len(backoffs) - 1)]
            budget = watchdog_budget_s()
            exhausted = (budget is not None
                         and budget <= delay + init_margin_s)
            if attempt == attempts - 1 or exhausted:
                # Last resort before throwing the run away: unpin the
                # platform (JAX_PLATFORMS='' → jax's own autodetect,
                # which falls back to CPU) and try ONCE more.  The
                # backend-cache hazard documented above does not apply
                # — this is deliberate: the record is TAGGED
                # ``backend_fallback`` so shrunken-CPU numbers can
                # never be mistaken for the pinned platform's headline.
                if pinned:
                    try:
                        os.environ["JAX_PLATFORMS"] = ""
                        jax.config.update("jax_platforms", None)
                        platform = jax.devices()[0].platform
                        backend_fallback = True
                        _watchdog_note("device_init", {"device_init": {
                            "backend_fallback": True,
                            "platform": platform}})
                        print(f"# {want} init failed {attempt + 1}x; "
                              f"falling back to JAX_PLATFORMS='' "
                              f"({platform})", file=sys.stderr)
                        break
                    except RuntimeError as exc2:
                        print(f"# unpinned fallback also failed: "
                              f"{exc2}", file=sys.stderr)
                print(json.dumps({
                    "error": "device_init_failed",
                    "platform_requested": want or "default",
                    "attempts": attempt + 1,
                    **({"watchdog_budget_exhausted": True}
                       if exhausted and attempt < attempts - 1 else {}),
                    "message": str(exc),
                }), flush=True)
                sys.exit(1)
            print(f"# device init failed ({exc}); retry "
                  f"{attempt + 2}/{attempts} in {delay} s",
                  file=sys.stderr)
            time.sleep(delay)
    if platform == "cpu":
        # CPU fallback (no TPU attached): shrink so the bench still
        # runs; explicit env overrides are honored.
        if "BENCH_NODES" not in os.environ:
            n, rounds = 512, 50
        if "BENCH_NORTH_STAR_NODES" not in os.environ:
            ns_n = 4096

    # Device-level tracing (SURVEY.md §5): BENCH_TRACE=<dir> (or the
    # framework-wide SIDECAR_TPU_PROFILE_DIR — docs/telemetry.md) wraps
    # the measured runs in a jax.profiler trace (TensorBoard/xprof
    # format) — the per-kernel timeline behind the roofline numbers
    # above; the north-star chunk dispatches annotate themselves on it.
    import contextlib
    from sidecar_tpu.telemetry import profiling
    trace_dir = os.environ.get("BENCH_TRACE") or profiling.profile_dir()
    trace = (jax.profiler.trace(trace_dir) if trace_dir
             else contextlib.nullcontext())
    with trace:
        _watchdog_note("dense_headline")
        dense_rps = _bench_dense(n, spn, rounds)
        _watchdog_note("compressed_headline",
                       {"dense_rounds_per_sec": round(dense_rps, 3)})
        compressed_rps = _bench_compressed(n, spn, rounds)
        _watchdog_note("north_star")
        north_star = _bench_north_star(
            ns_n, spn, churn_frac=0.001, eps=1e-4, conv_every=25,
            max_rounds=600,
            note="headline protocol: 4 s push-pull, refresh pinned, "
                 "quorum straggler fold (0.995) — the builder-chosen "
                 "constants")
        # The reference-faithful rerun: the reference's OWN anti-entropy
        # cadence (PushPullInterval 20 s, config/config.go:45,
        # main.go:252-256) and NO quorum fold — every delivery carried
        # by simulated gossip to every node, stragglers and the ~40
        # ER-isolated nodes included.  Identical model capacity
        # (cache_lines=256) so the ONLY deltas vs the headline are
        # protocol constants.  Refresh stays pinned in both: with it
        # live, the convergence metric chases re-mint churn — every
        # refresh of a still-in-flight record resets its cluster-wide
        # agreement, so the metric equilibrates at (re-mint rate ×
        # delivery latency) ≈ 1e-5 disagreement instead of reaching 1.0
        # (measured: conv plateaus ~0.99999 at round 1650, never 1.0),
        # exactly as a real 1M-service cluster never sits at 100%
        # instantaneous agreement while refreshes fire.  The pinned runs
        # measure the burst in isolation; both ε denominators are
        # reported.
        from sidecar_tpu.models.timecfg import TimeConfig
        faithful_cfg = TimeConfig(refresh_interval_s=10_000.0)
        _watchdog_note("north_star_faithful",
                       {"north_star": north_star})
        north_star_faithful = _bench_north_star(
            ns_n, spn, churn_frac=0.001, eps=1e-4, conv_every=25,
            max_rounds=1500, timecfg=faithful_cfg, fold_quorum=1.0,
            deep_sweep_every=0, phase="north_star_faithful",
            note="reference-faithful: PushPullInterval 20 s "
                 "(config/config.go:45), fold_quorum=1.0 (no analytic "
                 "straggler fold), same capacity as headline")
        # Optional capacity-sensitivity rerun (BENCH_FAITHFUL_K1024=1):
        # quantifies how much of the faithful drain is direct-mapped
        # cache-collision serialization (1000 same-tick records hash
        # into 256 lines, λ≈3.9; chains drain one generation per
        # push-pull cycle).  Measured 2026-07: K=1024 cuts
        # rounds_to_eps 525→325 and unsettled 1125→625 at ~4× the
        # per-round cost (32→135 ms) — wall-clock favors K=256, sim
        # time favors K=1024.
        # BENCH_SHARDED=1: the same north star on the sharded twin over
        # EVERY attached device (jax.sharding.Mesh) — on a v5e-8 this
        # is the real 8-chip target run in one command; board exchange
        # via SIDECAR_TPU_BOARD_EXCHANGE / BENCH_BOARD_EXCHANGE
        # (all_gather | all_to_all | ring — docs/sharding.md).
        north_star_sharded = None
        if os.environ.get("BENCH_SHARDED"):
            north_star_sharded = _bench_north_star(
                ns_n, spn, churn_frac=0.001, eps=1e-4, conv_every=25,
                max_rounds=600, sharded=True, phase="north_star_sharded",
                note=f"sharded twin over {len(jax.devices())} device(s), "
                     "headline protocol constants")
        north_star_k1024 = None
        if os.environ.get("BENCH_FAITHFUL_K1024"):
            north_star_k1024 = _bench_north_star(
                ns_n, spn, churn_frac=0.001, eps=1e-4, conv_every=25,
                max_rounds=1500, timecfg=faithful_cfg, fold_quorum=1.0,
                deep_sweep_every=0, cache_lines=1024,
                phase="north_star_faithful_k1024",
                note="faithful at 4x cache capacity — collision-"
                     "serialization sensitivity")

    # The query plane's host-side read path (benchmarks/bench_query.py):
    # resolve throughput off the immutable snapshot + watch fan-out
    # latency.  No TPU involved; BENCH_QUERY=0 skips it.
    query_bench = None
    if os.environ.get("BENCH_QUERY", "1") != "0":
        try:
            import importlib.util as _ilu
            _spec = _ilu.spec_from_file_location(
                "bench_query",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "benchmarks", "bench_query.py"))
            _bq = _ilu.module_from_spec(_spec)
            _spec.loader.exec_module(_bq)
            query_bench = _bq.run_query_bench()
        except Exception as exc:  # the headline must survive a side bench
            print(f"# query bench failed: {exc}", file=sys.stderr)

    # The 100k-watcher read-path soak (benchmarks/bench_query.py
    # run_query_scale): subscriber ramp across relay tiers, gap-free
    # delivery, p50/p99 hub lag, and the zero-copy serialization ratio.
    # BENCH_QUERY_SCALE=0 skips it; BENCH_QUERY_SCALE_SUBS caps the ramp.
    query_scale = None
    if os.environ.get("BENCH_QUERY", "1") != "0" and \
            os.environ.get("BENCH_QUERY_SCALE", "1") != "0":
        try:
            _watchdog_note("query_scale")
            import importlib.util as _ilu
            _spec = _ilu.spec_from_file_location(
                "bench_query_scale",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "benchmarks", "bench_query.py"))
            _bqs = _ilu.module_from_spec(_spec)
            _spec.loader.exec_module(_bqs)
            query_scale = _bqs.run_query_scale()
            _watchdog_note("query_scale", {"query_scale": query_scale})
        except Exception as exc:
            print(f"# query scale bench failed: {exc}", file=sys.stderr)

    # Robustness under chaos (benchmarks/robustness.py, docs/chaos.md):
    # false-positive tombstone evictions + proxy-config churn under
    # config6-seeded loss/pause chaos, suspicion+damping ON vs OFF at
    # matched tail convergence.  BENCH_ROBUSTNESS=0 skips it;
    # BENCH_ROBUSTNESS_NODES overrides the cluster size.
    robustness = None
    if os.environ.get("BENCH_ROBUSTNESS", "1") != "0":
        try:
            from benchmarks.robustness import run_robustness
            _watchdog_note("robustness")
            robustness = run_robustness(
                n=int(os.environ.get("BENCH_ROBUSTNESS_NODES", "128")))
            _watchdog_note("robustness", {"robustness": robustness})
        except Exception as exc:  # the headline must survive a side bench
            print(f"# robustness bench failed: {exc}", file=sys.stderr)
    # Clock-skew sub-block (benchmarks/robustness.run_skew): one
    # rushing + one slow node, future-admission bound OFF vs ON.
    # BENCH_ROBUSTNESS_SKEW=0 skips it; BENCH_ROBUSTNESS_SKEW_RUSH_S /
    # BENCH_ROBUSTNESS_SKEW_SLOW_S set the skew magnitudes (seconds),
    # BENCH_ROBUSTNESS_SKEW_FUDGE_S the bound used for the ON run.
    if robustness is not None and \
            os.environ.get("BENCH_ROBUSTNESS_SKEW", "1") != "0":
        try:
            from benchmarks.robustness import run_skew
            _watchdog_note("robustness-skew")
            robustness["clock_skew"] = run_skew(
                n=int(os.environ.get("BENCH_ROBUSTNESS_NODES", "128")),
                rush_s=float(os.environ.get(
                    "BENCH_ROBUSTNESS_SKEW_RUSH_S", "60")),
                slow_s=float(os.environ.get(
                    "BENCH_ROBUSTNESS_SKEW_SLOW_S", "120")),
                future_fudge_s=float(os.environ.get(
                    "BENCH_ROBUSTNESS_SKEW_FUDGE_S", "0.5")))
            _watchdog_note("robustness-skew",
                           {"clock_skew": robustness["clock_skew"]})
        except Exception as exc:  # the headline must survive a side bench
            print(f"# clock-skew bench failed: {exc}", file=sys.stderr)

    # Byzantine blast radius (benchmarks/adversary.py, docs/chaos.md):
    # the combined tombstone-bomb + future-flood + sybil attack with
    # the defense ladder OFF vs ON — poisoned rows, FP tombstones,
    # proxy churn, bytes amplification, and the convergence tax.
    # BENCH_ADVERSARY=0 skips it; BENCH_ADVERSARY_NODES sizes the
    # cluster.  Watchdog notes bracket the block so a hung run leaves
    # a partial record naming the phase.
    adversary = None
    if os.environ.get("BENCH_ADVERSARY", "1") != "0":
        try:
            from benchmarks.adversary import run_adversary
            _watchdog_note("adversary")
            adversary = run_adversary(
                n=int(os.environ.get("BENCH_ADVERSARY_NODES", "128")))
            _watchdog_note("adversary", {"adversary": adversary})
        except Exception as exc:  # the headline must survive a side bench
            print(f"# adversary bench failed: {exc}", file=sys.stderr)

    # Scenario-fleet sweep (benchmarks/sweep.py, docs/sweep.md): the
    # 64-point protocol grid in ONE vmapped dispatch vs the per-point
    # trace+compile+dispatch status quo, with the per-scenario
    # bit-identity oracle riding along.  BENCH_SWEEP=0 skips it;
    # BENCH_SWEEP_NODES sizes the cluster; BENCH_SWEEP_SEQ caps how
    # many sequential baseline points are compiled (the rest is
    # extrapolated per point — sequential cost is per-config uniform).
    sweep = None
    if os.environ.get("BENCH_SWEEP", "1") != "0":
        try:
            from benchmarks.sweep import run_sweep_bench
            _watchdog_note("sweep")
            sweep = run_sweep_bench(
                n=int(os.environ.get("BENCH_SWEEP_NODES", "32")),
                seq_points=int(os.environ.get("BENCH_SWEEP_SEQ", "12")))
            _watchdog_note("sweep", {"sweep": sweep})
        except Exception as exc:  # the headline must survive a side bench
            print(f"# sweep bench failed: {exc}", file=sys.stderr)

    # Locality-aware overlay block (benchmarks/topology_sweep.py,
    # docs/topology.md): zoned overlay + board_exchange="zoned" vs
    # complete + all_gather on a sharded mesh — analytic AND
    # measured-from-HLO cross-shard byte cut at matched rounds-to-ε.
    # Skipped outright below 2 devices (no cross-shard wire exists).
    # BENCH_TOPOLOGY=0 skips it; BENCH_TOPOLOGY_NODES sizes the
    # cluster; BENCH_TOPOLOGY_ROUNDS caps the convergence horizon.
    topology_block = None
    if os.environ.get("BENCH_TOPOLOGY", "1") != "0" \
            and len(jax.devices()) >= 2:
        try:
            from benchmarks.topology_sweep import run_topology_bench
            _watchdog_note("topology")
            topology_block = run_topology_bench(
                n=int(os.environ.get("BENCH_TOPOLOGY_NODES", "4096")),
                rounds=int(os.environ.get("BENCH_TOPOLOGY_ROUNDS",
                                          "64"))) or None
            _watchdog_note("topology", {"topology": topology_block})
        except Exception as exc:  # the headline must survive a side bench
            print(f"# topology bench failed: {exc}", file=sys.stderr)

    # Coherence-observatory block (benchmarks/coherence.py,
    # docs/telemetry.md): digest-off vs digest-on from the same minted
    # churn state — final-state bit-identity (so the rounds-to-ε ratio
    # the acceptance bound caps at 1.02 is exactly 1.0), the honest
    # wall-clock overhead of the in-scan digest columns, and the live
    # writer/lock-free-reader micro-bench.  BENCH_COHERENCE=0 skips
    # it; BENCH_COHERENCE_NODES / BENCH_COHERENCE_ROUNDS /
    # BENCH_COHERENCE_BUCKETS size it.
    coherence_block = None
    if os.environ.get("BENCH_COHERENCE", "1") != "0":
        try:
            from benchmarks.coherence import run_coherence_bench
            _watchdog_note("coherence")
            coherence_block = run_coherence_bench(
                n=int(os.environ.get("BENCH_COHERENCE_NODES", "4096")),
                rounds=int(os.environ.get("BENCH_COHERENCE_ROUNDS",
                                          "96")),
                buckets=int(os.environ.get("BENCH_COHERENCE_BUCKETS",
                                           "64")))
            # The coherence SLO verdicts ride inside the block so the
            # regression gate sees "p99 ttc <= 2 s" / "agreement >=
            # 0.99" next to the numbers they bound (BENCH_SLO gate).
            from sidecar_tpu.telemetry.slo import SloEvaluator
            _ev = SloEvaluator.coherence_from_env()
            if _ev is not None:
                coherence_block["slo"] = _ev.evaluate_coherence()
            _watchdog_note("coherence", {"coherence": coherence_block})
        except Exception as exc:  # the headline must survive a side bench
            print(f"# coherence bench failed: {exc}", file=sys.stderr)

    # Anti-entropy heal block (benchmarks/antientropy.py,
    # docs/antientropy.md): a config6-style partition healed full-body
    # vs digest-directed — measured session bytes and heal wall-clock
    # on two live catalogs, plus the cluster-scale byte model over the
    # chaos twin's digest trace priced with the live-measured
    # constants.  BENCH_ANTIENTROPY=0 skips it;
    # BENCH_ANTIENTROPY_NODES / BENCH_ANTIENTROPY_ROUNDS size the sim,
    # BENCH_ANTIENTROPY_CATALOG / BENCH_ANTIENTROPY_DIVERGED the live
    # pair.
    antientropy_block = None
    if os.environ.get("BENCH_ANTIENTROPY", "1") != "0":
        try:
            from benchmarks.antientropy import run_antientropy_bench
            _watchdog_note("antientropy")
            antientropy_block = run_antientropy_bench(
                n=int(os.environ.get("BENCH_ANTIENTROPY_NODES", "64")),
                rounds=int(os.environ.get("BENCH_ANTIENTROPY_ROUNDS",
                                          "120")),
                catalog=int(os.environ.get("BENCH_ANTIENTROPY_CATALOG",
                                           "1500")),
                diverged=int(os.environ.get("BENCH_ANTIENTROPY_DIVERGED",
                                            "30")))
            _watchdog_note("antientropy",
                           {"antientropy": antientropy_block})
        except Exception as exc:  # the headline must survive a side bench
            print(f"# antientropy bench failed: {exc}", file=sys.stderr)

    # Autopilot closed-loop block (benchmarks/autopilot.py,
    # docs/autopilot.md): observe a config6-seeded chaos run through
    # its telemetry, fit the conditions, sweep the knob space against
    # the SLO rules, and verify the winner by bit-identical unbatched
    # replay.  The block carries the acceptance claims as measured
    # fields: closed_loop (recommendation passes the SLO the status-quo
    # baseline fails), eval_ratio (ES evaluations / exhaustive grid),
    # replay_bit_identical.  BENCH_AUTOPILOT=0 skips it;
    # BENCH_AUTOPILOT_NODES / BENCH_AUTOPILOT_ROUNDS size the sweep.
    autopilot_block = None
    if os.environ.get("BENCH_AUTOPILOT", "1") != "0":
        try:
            from benchmarks.autopilot import run_autopilot_bench
            _watchdog_note("autopilot")
            autopilot_block = run_autopilot_bench(
                n=int(os.environ.get("BENCH_AUTOPILOT_NODES", "32")),
                rounds=int(os.environ.get("BENCH_AUTOPILOT_ROUNDS",
                                          "60")))
            _watchdog_note("autopilot", {"autopilot": autopilot_block})
        except Exception as exc:  # the headline must survive a side bench
            print(f"# autopilot bench failed: {exc}", file=sys.stderr)

    # Software-pipelined round block (benchmarks/pipeline.py,
    # docs/pipeline.md): lockstep vs pipelined ms/round on the exact
    # headline shape and the compressed/sharded families, the
    # one-round-stale rounds-to-ε ratio (ISSUE bound ≤ 1.10), the
    # heterogeneous tick-cadence sweep row, and the sharded overlap
    # proof (``pipeline.summary.overlap_ms`` + the PR-12 attribution
    # of the pipelined program).  BENCH_PIPELINE=0 skips it;
    # BENCH_PIPELINE_NODES / BENCH_PIPELINE_ROUNDS size it (defaults
    # follow the platform shrink above).
    pipeline_block = None
    if os.environ.get("BENCH_PIPELINE", "1") != "0":
        try:
            from benchmarks.pipeline import run_pipeline_bench
            _watchdog_note("pipeline")
            pipeline_block = run_pipeline_bench(
                n=int(os.environ.get("BENCH_PIPELINE_NODES", str(n))),
                spn=spn,
                rounds=int(os.environ.get("BENCH_PIPELINE_ROUNDS",
                                          "60")))
            _watchdog_note("pipeline", {"pipeline": pipeline_block})
        except Exception as exc:  # the headline must survive a side bench
            print(f"# pipeline bench failed: {exc}", file=sys.stderr)

    # Kernel-cost observatory block (sidecar_tpu/telemetry/cost.py,
    # docs/perf.md): per-phase attribution + compile/HBM telemetry for
    # the single-chip families, reconciled against the measured
    # ms/round above.  BENCH_COST=0 skips it.
    cost_block = None
    if os.environ.get("BENCH_COST", "1") != "0":
        try:
            _watchdog_note("cost")
            cost_block = _bench_cost(
                n, spn, dense_rps=dense_rps,
                compressed_rps=compressed_rps, north_star=north_star,
                trace_dir=trace_dir)
        except Exception as exc:  # the headline must survive a side bench
            print(f"# cost block failed: {exc}", file=sys.stderr)

    # Baseline: the reference's wall-clock gossip cadence — 5 rounds/sec
    # (GossipInterval 200 ms), hardware-independent.
    disarm_watchdog()
    from sidecar_tpu import metrics as metrics_mod
    from sidecar_tpu.ops import kernels as kernel_ops

    # The self-describing telemetry block (docs/telemetry.md): the
    # per-phase chunk histograms this process accumulated plus the
    # headline north star's round-trace tail summary.
    telemetry = {
        "histograms": metrics_mod.snapshot()["histograms"],
        "round_trace_tail": north_star.get("round_trace_tail"),
    }
    record = {
        "metric": f"simulated gossip rounds/sec/chip (n={n}, spn={spn}, "
                  f"{platform})",
        **({"backend_fallback": True} if backend_fallback else {}),
        "kernels": kernel_ops.resolve_path(record=False)[0],
        "value": round(dense_rps, 3),
        "unit": "rounds/sec/chip",
        "vs_baseline": round(dense_rps / 5.0, 3),
        "compressed_rounds_per_sec": round(compressed_rps, 3),
        "north_star": north_star,
        "north_star_faithful": north_star_faithful,
        **({"north_star_sharded": north_star_sharded}
           if north_star_sharded else {}),
        **({"north_star_faithful_k1024": north_star_k1024}
           if north_star_k1024 else {}),
        **({"query": query_bench} if query_bench else {}),
        **({"query_scale": query_scale} if query_scale else {}),
        **({"robustness": robustness} if robustness else {}),
        **({"adversary": adversary} if adversary else {}),
        **({"sweep": sweep} if sweep else {}),
        **({"topology": topology_block} if topology_block else {}),
        **({"coherence": coherence_block} if coherence_block else {}),
        **({"antientropy": antientropy_block}
           if antientropy_block else {}),
        **({"autopilot": autopilot_block} if autopilot_block else {}),
        **({"pipeline": pipeline_block} if pipeline_block else {}),
        **({"cost": cost_block} if cost_block else {}),
        "telemetry": telemetry,
    }
    # Regression verdict vs the previous trajectory record
    # (tools/bench_compare.py; BENCH_COMPARE=0 disables, =path pins
    # the baseline).
    try:
        verdict = _bench_regression(record)
        if verdict:
            record["regression"] = verdict
    except Exception as exc:  # the headline must survive the verdict
        print(f"# regression verdict failed: {exc}", file=sys.stderr)
    print(json.dumps(record))


if __name__ == "__main__":
    sys.exit(main())
