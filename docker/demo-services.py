"""Demo backends for the compose cluster — tiny HTTP/TCP listeners on
the ports fixtures/static.json announces (the reference's run-services
script starts nginx containers for the same purpose), so HAProxy has
something real to route to and health checks can hit a live port."""

import http.server
import socketserver
import threading


class Version(http.server.BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def do_GET(self):
        body = b'{"service": "static-web", "version": "0.3"}'
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class Echo(socketserver.BaseRequestHandler):
    def handle(self):
        data = self.request.recv(4096)
        if data:
            self.request.sendall(data)


def main():
    web = socketserver.ThreadingTCPServer(("0.0.0.0", 18080), Version)
    tcp = socketserver.ThreadingTCPServer(("0.0.0.0", 18081), Echo)
    for srv in (web, tcp):
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
    threading.Event().wait()


if __name__ == "__main__":
    main()
