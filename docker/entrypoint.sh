#!/bin/sh
# Container entry: start the demo backend services the static discovery
# announces (the reference's run-services analog), then the sidecar
# node itself.  SIDECAR_SEEDS / SIDECAR_HOSTNAME come from compose.
set -e

python docker/demo-services.py &

exec python -m sidecar_tpu.main --hostname "${SIDECAR_HOSTNAME:-$(hostname)}"
