#!/usr/bin/env python3
"""Structural invariant checker for the overlay builders
(``sidecar_tpu/ops/topology.py``) — runs IN tier-1
(tests/test_topology.py) and standalone.

Every overlay the registry can hand to a sim must satisfy the padded
neighbor-list contract the gossip kernel samples against
(``nbrs[n, randint(deg[n])]``, docs/topology.md):

* **shape/domain** — ``nbrs`` int32 ``[n, K]``, ``deg`` int32 ``[n]``
  with ``0 <= deg <= K``; every entry a valid node id.
* **self-pad only past deg** — columns ``>= deg[i]`` hold exactly
  ``i`` (the self-loop no-op the sampler may land on is ONLY ever the
  pad region), and no column ``< deg[i]`` is a self-loop (a real
  neighbor slot wasting fan-out on a self-send would silently slow
  convergence, invisible to any correctness test).
* **symmetry** — for the undirected families (ring, chord, er, ba,
  expander, mesh) the edge SET is symmetric: ``j in nbrs[i]`` iff
  ``i in nbrs[j]`` (multiplicity ignored — zoned's bias replication
  is a sampling weight, and zoned's remote tier is directed by
  design, so the zoned family is exempt).
* **connectivity** — families connected by construction (ring, chord,
  expander, zoned via its gateway ring, mesh) must yield ONE
  undirected component.  Erdős–Rényi and Barabási–Albert make no such
  promise (the headline ER graph carries ~40 isolated nodes — bench.py
  docstring) and are exempt.

Usage: ``python tools/check_topology.py [n]`` — checks the default
catalog at cluster size n (default 64); exits 0 when clean, 1 with a
per-overlay report otherwise.
"""

from __future__ import annotations

import pathlib
import sys
from typing import List, Optional

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

# Families whose builders promise an undirected edge set / a connected
# graph — see the module docstring for the exemptions.
SYMMETRIC_FAMILIES = ("ring", "chord", "er", "ba", "expander", "mesh")
CONNECTED_FAMILIES = ("ring", "chord", "expander", "zoned", "mesh")


def _family(name: str) -> str:
    return name.rstrip("0123456789x0123456789") or name


def components(nbrs: np.ndarray, deg: np.ndarray) -> int:
    """Count undirected components over the valid (non-pad) edges."""
    n = nbrs.shape[0]
    K = nbrs.shape[1]
    ok = np.arange(K)[None, :] < deg[:, None]
    src = np.repeat(np.arange(n), K)[ok.ravel()]
    dst = nbrs.ravel()[ok.ravel()]
    # Union-find, path-halving.
    parent = np.arange(n)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in zip(src.tolist(), dst.tolist()):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb
    return len({find(i) for i in range(n)})


def check_topology(topo, *, symmetric: Optional[bool] = None,
                   connected: Optional[bool] = None) -> List[str]:
    """All invariant violations for one built overlay (empty = clean).

    ``symmetric``/``connected`` default by family (the module
    docstring's lists); pass explicitly for custom-built overlays."""
    name = topo.name
    issues: List[str] = []
    if topo.nbrs is None:
        # The complete graph has no materialized structure to check.
        if topo.deg is not None:
            issues.append(f"{name}: complete graph with a deg vector")
        return issues
    fam = _family(name)
    if symmetric is None:
        symmetric = fam in SYMMETRIC_FAMILIES
    if connected is None:
        connected = fam in CONNECTED_FAMILIES
    nbrs, deg, n = np.asarray(topo.nbrs), np.asarray(topo.deg), topo.n
    if nbrs.ndim != 2 or nbrs.shape[0] != n:
        return [f"{name}: nbrs shape {nbrs.shape}, expected ({n}, K)"]
    if deg.shape != (n,):
        return [f"{name}: deg shape {deg.shape}, expected ({n},)"]
    K = nbrs.shape[1]
    if nbrs.dtype != np.int32 or deg.dtype != np.int32:
        issues.append(f"{name}: dtypes {nbrs.dtype}/{deg.dtype}, "
                      "expected int32/int32")
    if (deg < 0).any() or (deg > K).any():
        issues.append(f"{name}: deg outside [0, K={K}]")
    if (nbrs < 0).any() or (nbrs >= n).any():
        issues.append(f"{name}: neighbor ids outside [0, {n})")
    idx = np.arange(n, dtype=nbrs.dtype)
    col = np.arange(K)[None, :]
    valid = col < deg[:, None]
    pad_ok = np.where(~valid, nbrs == idx[:, None], True).all()
    if not pad_ok:
        bad = int(np.argwhere(~valid & (nbrs != idx[:, None]))[0][0])
        issues.append(f"{name}: pad column not self (first bad row "
                      f"{bad}) — self-pad must fill strictly past deg")
    if np.where(valid, nbrs == idx[:, None], False).any():
        bad = int(np.argwhere(valid & (nbrs == idx[:, None]))[0][0])
        issues.append(f"{name}: self-loop inside the valid region "
                      f"(row {bad}, col < deg)")
    if symmetric and not issues:
        fwd = set(zip(
            np.repeat(idx, K)[valid.ravel()].tolist(),
            nbrs.ravel()[valid.ravel()].tolist()))
        asym = [e for e in fwd if (e[1], e[0]) not in fwd]
        if asym:
            issues.append(f"{name}: {len(asym)} asymmetric edge(s), "
                          f"first {asym[0]} — undirected families must "
                          "add both directions")
    if connected and not issues:
        c = components(nbrs, deg)
        if c != 1:
            issues.append(f"{name}: {c} components — this family is "
                          "connected by construction")
    return issues


def default_catalog(n: int = 64):
    """The registry families at cluster size n (ops/topology.from_name
    resolves the same names for /sweep grids)."""
    from sidecar_tpu.ops import topology

    names = ["complete", "ring2", "chord", "expander4", "er8", "ba2",
             f"zoned{max(2, n // 8)}"]
    r = 8
    if n % r == 0:
        names.append(f"mesh{r}x{n // r}")
    return [topology.from_name(name, n) for name in names]


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    n = int(args[0]) if args else 64
    issues: List[str] = []
    topos = default_catalog(n)
    for topo in topos:
        issues.extend(check_topology(topo))
    if issues:
        print(f"check_topology: {len(issues)} issue(s) at n={n}")
        for issue in issues:
            print(f"  {issue}")
        return 1
    print(f"check_topology: {len(topos)} overlay(s) OK at n={n}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
