#!/usr/bin/env python3
"""Static check: every metric name emitted from ``sidecar_tpu/`` is
documented in ``docs/metrics.md``.

Why this exists (PR 6): the metrics reference only stays trustworthy if
it is COMPLETE — an operator alerting off ``/metrics`` output has to be
able to look any name up, and the failure mode is silent: a new
``incr``/``set_gauge``/``histogram`` call site ships, nothing breaks,
and the name is simply absent from the doc forever.  So tier-1 runs
this check (tests/test_metric_docs.py, the ``check_jit_entrypoints``
pattern) and fails the build instead.

Mechanics: the ``sidecar_tpu/`` tree is AST-scanned for calls to
``incr`` / ``set_gauge`` / ``histogram`` / ``histogram_since``
(attribute or bare-name form).  A string-literal first argument must
appear in the doc verbatim, or match a documented placeholder pattern
(backticked names may contain ``<...>`` wildcards: ``sparse.mode.<m>``
covers ``sparse.mode.auto``).  An f-string first argument contributes
its constant PREFIX, which must prefix some documented name (so
``f"kernels.path.{path}"`` is covered by ``kernels.path.pallas``...).
Fully dynamic names (a bare variable) are skipped — they are relays of
names documented at their origin (e.g. the chaos counter sync and the
engine stats relay, both documented as families).

``sidecar_tpu/metrics.py`` itself is excluded: it is the instrument
implementation, not a call site.

Usage: ``python tools/check_metric_docs.py [src_root [docs_file]]`` —
exits 0 when clean, 1 with a per-offender report otherwise.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

METRIC_FNS = ("incr", "set_gauge", "histogram", "histogram_since")


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def emitted_names(root: pathlib.Path):
    """Yield ``(path, lineno, name, is_prefix)`` for every metric-name
    literal (or f-string constant prefix) passed to an instrument call
    under ``root``."""
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts or path.name == "metrics.py":
            continue
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:  # pragma: no cover — broken file
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if _call_name(node) not in METRIC_FNS:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                yield path, node.lineno, arg.value, False
            elif isinstance(arg, ast.JoinedStr):
                prefix = ""
                for part in arg.values:
                    if isinstance(part, ast.Constant) and \
                            isinstance(part.value, str):
                        prefix += part.value
                    else:
                        break
                if prefix:
                    yield path, node.lineno, prefix, True
            # else: fully dynamic — a relay; skipped by design.


def documented_names(docs_text: str) -> list[str]:
    """Every backticked token in the doc that looks like a metric name
    (dotted or a known bare timer/gauge name) — ``<...>`` placeholders
    kept verbatim for the matchers below."""
    return [tok for tok in re.findall(r"`([^`\s]+)`", docs_text)
            if re.fullmatch(r"[A-Za-z0-9_.<>*-]+", tok)]


def _pattern(token: str) -> "re.Pattern":
    """A documented token as a regex: ``<...>`` placeholders match any
    non-empty run."""
    out = []
    for piece in re.split(r"(<[^>]*>)", token):
        out.append(".+" if piece.startswith("<") else re.escape(piece))
    return re.compile("".join(out))


def check(src_root: pathlib.Path, docs_file: pathlib.Path) -> list[str]:
    """Violation strings (empty = every emitted name is documented)."""
    docs_text = docs_file.read_text()
    tokens = documented_names(docs_text)
    patterns = [(_pattern(t), t) for t in tokens]
    problems = []
    for path, lineno, name, is_prefix in emitted_names(src_root):
        if is_prefix:
            ok = any(t.startswith(name) for t in tokens)
            kind = f"f-string metric prefix {name!r}"
        else:
            ok = name in tokens or any(p.fullmatch(name)
                                       for p, _ in patterns)
            kind = f"metric name {name!r}"
        if not ok:
            problems.append(
                f"{path}:{lineno}: {kind} is not documented in "
                f"{docs_file.name}")
    return problems


_PROM_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def check_prometheus(docs_file: pathlib.Path) -> list[str]:
    """Sanitization-drift check (PR 11): every documented metric name
    must render to a well-formed, COLLISION-FREE Prometheus family
    through the REAL exposition pipeline
    (``sidecar_tpu.telemetry.prometheus``).

    The ``/metrics`` scrape names are derived, not documented — an
    operator looks up ``sidecar_query_hub_published_total`` by
    mentally reversing the sanitizer.  That reversal only works while
    sanitization stays injective over the documented set: if a rename
    (or a sanitizer change) maps two documented names onto one family,
    Prometheus rejects the duplicate family or silently merges
    series, and nothing else in the build notices.  So this check
    substitutes placeholder names (``<x>`` → ``x``), renders ALL
    documented names through ``render_prometheus`` as counters, and
    fails on invalid family names, collisions, or a renderer that
    stops emitting a documented name."""
    here = pathlib.Path(__file__).resolve().parent.parent
    sys.path.insert(0, str(here))
    from sidecar_tpu.telemetry.prometheus import (  # noqa: E402
        _sanitize,
        render_prometheus,
    )

    tokens = documented_names(docs_file.read_text())
    concrete = sorted({re.sub(r"<[^>]*>", "x", t) for t in tokens})
    problems = []
    families: dict[str, str] = {}
    for name in concrete:
        family = _sanitize(name)
        if not _PROM_NAME.fullmatch(family):
            problems.append(
                f"{docs_file.name}: `{name}` sanitizes to invalid "
                f"Prometheus family {family!r}")
            continue
        if family in families:
            problems.append(
                f"{docs_file.name}: `{name}` and "
                f"`{families[family]}` collide on Prometheus family "
                f"{family!r} after sanitization")
            continue
        families[family] = name
    # End-to-end: the renderer must expose every documented name.  A
    # synthetic counters-only snapshot is enough — sanitization is
    # kind-independent, and counters exercise the `_total` suffixing.
    rendered = render_prometheus(
        {"counters": {name: 1 for name in concrete}})
    exposed = {line.split()[0] for line in rendered.splitlines()
               if line and not line.startswith("#")}
    for family, name in sorted(families.items()):
        if f"{family}_total" not in exposed:
            problems.append(
                f"{docs_file.name}: `{name}` did not render to "
                f"{family}_total in the Prometheus exposition")
    return problems


def main(argv: list[str]) -> int:
    here = pathlib.Path(__file__).resolve().parent.parent
    src = pathlib.Path(argv[1]) if len(argv) > 1 else here / "sidecar_tpu"
    docs = pathlib.Path(argv[2]) if len(argv) > 2 else \
        here / "docs" / "metrics.md"
    problems = check(src, docs) + check_prometheus(docs)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"{len(problems)} metric-doc problem(s) — fix them "
              f"against {docs}", file=sys.stderr)
        return 1
    print(f"check_metric_docs: OK ({src} vs {docs})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
