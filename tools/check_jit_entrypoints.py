#!/usr/bin/env python3
"""Static check: every ``@jax.jit`` scan driver in ``sidecar_tpu/``
either donates its state (``donate_argnums``) or carries an explicit
``# no-donate:`` justification.

Why this exists (PR 3): threading ``donate_argnums`` through the
``_run*_jit`` entry points stops the ~100 MB belief tensors from being
double-buffered across chunked dispatches — HBM headroom that directly
raises max N per chip.  The failure mode this guards against is silent:
a NEW scan driver added without donation compiles, runs, and quietly
costs a full extra copy of the state; nothing in the test suite would
notice.  So tier-1 runs this check (tests/test_jit_entrypoints.py) and
fails the build instead.

A "scan driver" is a function decorated with ``jax.jit`` (directly or
via ``functools.partial(jax.jit, ...)``) whose body reaches
``lax.scan``/``jax.lax.scan`` — directly, OR through calls to other
functions/methods defined in the SAME file (resolved by name, to a
fixpoint).  The transitive rule exists for the sharded twins (PR 4): a
jitted driver that delegates its scan to a helper (``self._run_scan``
and the like) would otherwise slip back to double-buffering unnoticed.
Name-based resolution is deliberately conservative — a false positive
costs one ``# no-donate:`` comment; a false negative costs HBM.  To opt
a driver out, put a comment containing ``# no-donate: <reason>`` in the
decorator/body source or immediately above the decorator.

Usage: ``python tools/check_jit_entrypoints.py [root]`` — exits 0 when
clean, 1 with a per-offender report otherwise.
"""

from __future__ import annotations

import ast
import pathlib
import sys

NO_DONATE_TAG = "# no-donate:"


def _is_jit_decorator(node: ast.expr) -> bool:
    """Matches ``@jax.jit``, ``@jit``, and
    ``@functools.partial(jax.jit, ...)`` / ``@partial(jit, ...)``."""

    def names_jit(expr: ast.expr) -> bool:
        if isinstance(expr, ast.Attribute):
            return expr.attr == "jit"
        if isinstance(expr, ast.Name):
            return expr.id == "jit"
        return False

    if names_jit(node):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        is_partial = (isinstance(fn, ast.Attribute) and fn.attr == "partial") \
            or (isinstance(fn, ast.Name) and fn.id == "partial")
        if is_partial and node.args and names_jit(node.args[0]):
            return True
        # jax.jit(...) called directly as a decorator factory
        if names_jit(fn):
            return True
    return False


def _declares_donation(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    return any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in node.keywords)


def _calls_scan(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            callee = sub.func
            if isinstance(callee, ast.Attribute) and callee.attr == "scan":
                return True
            if isinstance(callee, ast.Name) and callee.id == "scan":
                return True
    return False


def _called_local_names(fn: ast.AST) -> set:
    """Names of functions/methods this function calls that COULD be
    defined in the same file: bare names (``helper(...)``) and
    attribute calls (``self._run_scan(...)`` — matched by attr name;
    any-object attrs are included, which over-approximates safely)."""
    names = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            callee = sub.func
            if isinstance(callee, ast.Name):
                names.add(callee.id)
            elif isinstance(callee, ast.Attribute):
                names.add(callee.attr)
    return names


def _scan_reachers(tree: ast.AST) -> set:
    """Fixpoint over the file's call graph (by function NAME): the set
    of function names from which ``scan`` is reachable through
    same-file calls."""
    defs: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    reach = {name for name, fns in defs.items()
             if any(_calls_scan(fn) for fn in fns)}
    changed = True
    while changed:
        changed = False
        for name, fns in defs.items():
            if name in reach:
                continue
            for fn in fns:
                if _called_local_names(fn) & reach:
                    reach.add(name)
                    changed = True
                    break
    return reach


def _has_waiver(src_lines: list[str], fn: ast.FunctionDef) -> bool:
    """``# no-donate:`` anywhere in the function's source span or in the
    3 lines above its first decorator."""
    first = min([d.lineno for d in fn.decorator_list] + [fn.lineno])
    lo = max(0, first - 1 - 3)
    hi = fn.end_lineno or fn.lineno
    return any(NO_DONATE_TAG in line for line in src_lines[lo:hi])


def _walk_drivers(root: pathlib.Path):
    """Yield every jitted scan driver under ``root`` as
    ``(path, lineno, name, status)`` with status one of ``donates`` /
    ``waived`` / ``violation``."""
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        src = path.read_text()
        try:
            tree = ast.parse(src)
        except SyntaxError as exc:  # pragma: no cover - broken file
            yield path, 0, f"<unparseable: {exc}>", "violation"
            continue
        lines = src.splitlines()
        reach = _scan_reachers(tree)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            jit_decs = [d for d in node.decorator_list
                        if _is_jit_decorator(d)]
            if not jit_decs:
                continue
            reaches_scan = _calls_scan(node) or \
                bool(_called_local_names(node) & reach)
            if not reaches_scan:
                continue
            if any(_declares_donation(d) for d in jit_decs):
                status = "donates"
            elif _has_waiver(lines, node):
                status = "waived"
            else:
                status = "violation"
            yield path, node.lineno, node.name, status


def check_tree(root: pathlib.Path) -> list[str]:
    """Returns a list of violation strings (empty = clean)."""
    problems = []
    for path, lineno, name, status in _walk_drivers(root):
        if status != "violation":
            continue
        if name.startswith("<unparseable"):
            problems.append(f"{path}: {name[1:-1]}")
        else:
            problems.append(
                f"{path}:{lineno}: jitted scan driver "
                f"'{name}' neither declares donate_argnums nor "
                f"carries a '{NO_DONATE_TAG} <reason>' comment")
    return problems


def list_drivers(root: pathlib.Path) -> list[str]:
    """Coverage report: every jitted scan driver the contract governs,
    one ``path:name status`` line each.  Exists so the test suite can
    PIN that newly added driver families (the round-8 sparse drivers,
    ``_run_*_sparse_jit``) are actually seen by the checker — a
    contract that silently stops matching is worse than none."""
    return [f"{path}:{name} {status}"
            for path, _, name, status in _walk_drivers(root)]


def main(argv: list[str]) -> int:
    args = [a for a in argv[1:] if a != "--list"]
    do_list = len(args) != len(argv) - 1
    root = pathlib.Path(args[0]) if args else \
        pathlib.Path(__file__).resolve().parent.parent / "sidecar_tpu"
    if do_list:
        for line in list_drivers(root):
            print(line)
        return 0
    problems = check_tree(root)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"{len(problems)} undonated jitted scan driver(s) — donate "
              f"the state or justify with '{NO_DONATE_TAG} <reason>'",
              file=sys.stderr)
        return 1
    print(f"check_jit_entrypoints: OK ({root})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
