"""Generate docs/CONFIGURATION.md from the config system itself.

The reference documents its knob catalog by hand (README.md:155-237);
hand-written tables drift.  This generator records every ``_env`` call
each ``from_env`` constructor makes (env var name, default, inferred
type) by temporarily swapping the resolver, so the doc IS the wiring:
``tests/test_config_docs.py`` regenerates and diffs it, failing the
suite whenever a knob is added without the doc.

Run: ``python tools/gen_config_docs.py [--check]`` (``--check`` exits
non-zero when docs/CONFIGURATION.md is stale instead of rewriting it).
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from sidecar_tpu import config as config_mod  # noqa: E402

OUT = pathlib.Path(__file__).resolve().parent.parent / "docs" / \
    "CONFIGURATION.md"

SECTIONS = [
    ("Core node (`SIDECAR_*`)", config_mod.SidecarConfig,
     "config.go:41-59"),
    ("Docker discovery (`DOCKER_*`)", config_mod.DockerConfig,
     "config.go:15-18"),
    ("Static discovery (`STATIC_*`)", config_mod.StaticConfig,
     "config.go:20-23"),
    ("Kubernetes API discovery (`K8S_*`)", config_mod.K8sAPIConfig,
     "config.go:25-33 analog"),
    ("Service naming (`SERVICES_*`)", config_mod.ServicesConfig,
     "config.go:35-39"),
    ("HAProxy driver (`HAPROXY_*`)", config_mod.HAproxyConfig,
     "config.go:61-79"),
    ("Envoy control plane (`ENVOY_*`)", config_mod.EnvoyConfig,
     "config.go:27-33"),
    ("Event listeners (`LISTENERS_*`)", config_mod.ListenerUrlsConfig,
     "config.go:11-13"),
]


# Per-knob behavior notes that belong next to the row (deviations from
# the reference an operator comparing against memberlist semantics
# should know about).
NOTES = {
    "SIDECAR_HANDOFF_QUEUE_DEPTH":
        "On overflow the engine sheds the OLDEST queued inbound "
        "records; memberlist's HandoffQueueDepth drops the INCOMING "
        "message instead. Deliberate deviation: anti-entropy redelivers "
        "shed records, and keeping the newest preserves the freshest "
        "versions under a stalled consumer.",
}


def _describe_default(value) -> str:
    if isinstance(value, bool):
        return "`true`" if value else "`false`"
    if isinstance(value, list):
        return "`" + ",".join(str(v) for v in value) + "`" if value \
            else "(empty)"
    if value == "":
        return "(empty)"
    return f"`{value}`"


def _describe_type(default, cast) -> str:
    if cast is not None:
        return "duration" if cast is config_mod.parse_duration else \
            getattr(cast, "__name__", "custom")
    if isinstance(default, bool):
        return "bool"
    if isinstance(default, int):
        return "int"
    if isinstance(default, float):
        return "duration (Go syntax: `200ms`, `20s`, `1m`)"
    if isinstance(default, list):
        return "comma-separated list"
    return "string"


def collect():
    """(section, rows) pairs by recording each from_env's _env calls.

    The caller's environment is irrelevant (rows record the DEFAULT
    argument, not the resolved value), but a malformed exported var
    (e.g. ``SIDECAR_BIND_PORT=abc``) would make from_env throw
    mid-recording — so the prefixes are scrubbed for the duration."""
    import os

    saved = {k: os.environ.pop(k) for k in list(os.environ)
             if k.split("_")[0] in ("SIDECAR", "DOCKER", "STATIC", "K8S",
                                    "SERVICES", "HAPROXY", "ENVOY",
                                    "LISTENERS")}
    try:
        return _collect_scrubbed()
    finally:
        os.environ.update(saved)


def _collect_scrubbed():
    out = []
    real_env = config_mod._env
    for title, cls, ref in SECTIONS:
        rows = []

        def recording(prefix, name, default, cast=None):
            rows.append((f"{prefix}_{name}",
                         _describe_type(default, cast),
                         _describe_default(default)))
            return real_env(prefix, name, default, cast)

        config_mod._env = recording
        try:
            cls.from_env()
        finally:
            config_mod._env = real_env
        out.append((title, ref, rows))
    return out


def render() -> str:
    lines = [
        "# Configuration reference",
        "",
        "Every knob, resolved exactly as `sidecar_tpu.config` resolves",
        "it (this file is GENERATED — `python tools/gen_config_docs.py`",
        "— and the test suite fails if it drifts from the code).  The",
        "scheme mirrors the reference's envconfig catalog",
        "(/root/reference/README.md:155-237, config/config.go); CLI",
        "flags (`python -m sidecar_tpu.main --help`) override env vars",
        "the same way the reference's kingpin flags do (cli.go:25-41).",
        "",
        "Durations accept Go syntax (`200ms`, `20s`, `1m`); booleans",
        "accept `1/true/yes/on`; lists are comma-separated.",
        "",
    ]
    for title, ref, rows in collect():
        lines.append(f"## {title}")
        lines.append("")
        lines.append(f"Reference: {ref}")
        lines.append("")
        lines.append("| Variable | Type | Default |")
        lines.append("|---|---|---|")
        noted = []
        for var, typ, default in rows:
            lines.append(f"| `{var}` | {typ} | {default} |")
            if var in NOTES:
                noted.append(var)
        lines.append("")
        for var in noted:
            lines.append(f"**`{var}`** — {NOTES[var]}")
            lines.append("")
    return "\n".join(lines)


def main() -> int:
    text = render()
    if "--check" in sys.argv:
        if not OUT.exists() or OUT.read_text() != text:
            print(f"{OUT} is stale — run python tools/gen_config_docs.py",
                  file=sys.stderr)
            return 1
        return 0
    OUT.parent.mkdir(exist_ok=True)
    OUT.write_text(text)
    print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
