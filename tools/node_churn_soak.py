"""Live-cluster churn soak: N full SidecarNodes on localhost, random
abrupt kills and fresh-incarnation rejoins, then a convergence audit.

This is the harness that exposed the permanent-membership-split bug
fixed by the death-certificate unicast (native/transport.cc): two nodes
that both restarted could stay invisible to each other forever.  It
drives the REAL stack — native SWIM engine, catalog, discovery, health,
broadcast loops — with timing chaos no unit test reproduces, so keep
running it after membership/engine changes:

    python tools/node_churn_soak.py [seed] [duration_s]

Exit 0 = every alive node agrees on membership, sees every alive peer's
services ALIVE, and holds no ALIVE records from dead nodes.  Not a
pytest test on purpose: wall-clock heavy (~80 s) and timing-sensitive.
Note: the audit verdict prints BEFORE teardown; after long/heavy churn
the graceful stop of every node ever created can take a further minute
or two (listener drains), so give external timeouts headroom past
duration_s + ~60 s — a timeout after "SOAK PASS" printed is teardown,
not a failed soak.
"""
import os
import pathlib
import random
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from sidecar_tpu import service as S
from sidecar_tpu.config import (
    Config, DockerConfig, EnvoyConfig, HAproxyConfig, K8sAPIConfig,
    ListenerUrlsConfig, ServicesConfig, SidecarConfig, StaticConfig)
from sidecar_tpu.main import SidecarNode
from sidecar_tpu.transport import GossipTransport

SWIM = dict(probe_interval=0.1, probe_timeout=0.15,
            suspect_timeout=0.6, indirect_probes=3)


def make_config():
    return Config(
        sidecar=SidecarConfig(discovery=["static"],
                              advertise_ip="127.0.0.1", seeds=[],
                              cluster_name="soak"),
        docker_discovery=DockerConfig(),
        static_discovery=StaticConfig(
            config_file=str(pathlib.Path(__file__).resolve().parent.parent
                            / "fixtures" / "static.json")),
        k8s_api_discovery=K8sAPIConfig(),
        services=ServicesConfig(),
        haproxy=HAproxyConfig(disable=True),
        envoy=EnvoyConfig(use_grpc_api=False),
        listeners=ListenerUrlsConfig(),
    )


def make_node(name):
    t = GossipTransport(node_name=name, cluster_name="soak",
                        bind_ip="127.0.0.1", bind_port=0,
                        advertise_ip="127.0.0.1",
                        gossip_interval=0.05, push_pull_interval=0.5,
                        **SWIM)
    n = SidecarNode(config=make_config(), hostname=name, transport=t)
    n.start(serve=False)
    return n


rnd = random.Random(int(sys.argv[1]) if len(sys.argv) > 1 else 7)
DURATION = float(sys.argv[2]) if len(sys.argv) > 2 else 50.0
nodes = {}
seed_port = None
for i in range(5):
    name = f"soak-{i}"
    n = make_node(name)
    if seed_port is None:
        seed_port = n.transport.bind_port
    else:
        n.transport.join("127.0.0.1", seed_port)
    nodes[name] = n

time.sleep(4)
alive = set(nodes)
print("members on seed:", sorted(nodes["soak-0"].transport.members()),
      flush=True)

t_end = time.monotonic() + DURATION
events = 0
while time.monotonic() < t_end:
    time.sleep(rnd.uniform(1.5, 3.5))
    killable = [n for n in alive if n != "soak-0"]
    dead = [n for n in nodes if n not in alive]
    if rnd.random() < 0.5 and len(killable) > 1:
        victim = rnd.choice(killable)
        nodes[victim].stop()
        alive.discard(victim)
        events += 1
        print(f"killed {victim}", flush=True)
    elif dead:
        name = rnd.choice(dead)
        nodes[name] = make_node(name)
        nodes[name].transport.join("127.0.0.1", seed_port)
        alive.add(name)
        events += 1
        print(f"rejoined {name}", flush=True)

print(f"{events} churn events; settling...", flush=True)
time.sleep(12)

ok = True
for name in sorted(alive):
    node = nodes[name]
    members = set(node.transport.members())
    if members != alive:
        print(f"{name}: membership {sorted(members)} != {sorted(alive)}",
              flush=True)
        ok = False
    for other in sorted(nodes):
        server = node.state.servers.get(other)
        recs = list(server.services.values()) if server else []
        live_names = {svc.name for svc in recs if svc.status == S.ALIVE}
        if other in alive:
            if live_names != {"static-tcp", "static-web"}:
                print(f"{name}: {other} ALIVE set wrong: {live_names} "
                      f"({[(r.name, r.status) for r in recs]})",
                      flush=True)
                ok = False
        else:
            if live_names:
                print(f"{name}: dead {other} still ALIVE: {live_names}",
                      flush=True)
                ok = False
print("SOAK", "PASS" if ok else "FAIL", flush=True)
for name in nodes:
    nodes[name].stop()
sys.exit(0 if ok else 1)
