#!/usr/bin/env python3
"""Schema check for ``BENCH_*.json`` records — the bench trajectory's
contract with its consumers.

Three layers read these records: tools/bench_compare.py (the verdict
plane), benchmarks/RESULTS.md (humans), and the harness driver that
wraps bench.py's stdout.  The BENCH_r05 postmortem showed the failure
mode this guards: a run can die in ways that leave a record SHAPED
wrong (``parsed: null`` with rc 124 and no watchdog payload), and
nothing complained until a human opened the file.  This tool validates
every known record shape and fails loudly on drift; tier-1 runs it
over fixtures and the repo's real records (tests/test_bench_schema.py).

Shapes validated:

* **driver wrapper** — ``{"cmd": str, "n": int, "parsed": object|null,
  "rc": int, "tail": str}``.  ``parsed: null`` is legal ONLY for a
  non-zero rc (a successful run must parse).
* **result record** — requires ``metric``/``value``/``unit``;
  optional blocks (``north_star``, ``north_star_faithful``, ``cost``,
  ``regression``, ``sharded``…) are type-checked when present.
* **error records** — ``{"error": "device_init_failed", ...}`` needs
  ``platform_requested``/``attempts``/``message``;
  ``{"error": "bench_timeout", "watchdog": true, ...}`` needs
  ``phase``/``partial``.

Usage: ``python tools/check_bench_schema.py [FILES...]`` — defaults to
``BENCH_*.json`` in the repo root; exits 0 when clean, 1 with a
per-record report otherwise.
"""

from __future__ import annotations

import glob
import json
import os
import sys
from typing import List

NUMBER = (int, float)

# Optional result-record blocks: name -> minimal type contract, checked
# only when present (older records legitimately predate newer blocks).
KNOWN_RESULT_BLOCKS = {
    "north_star": dict,
    "north_star_faithful": dict,
    "sharded": dict,
    "query": dict,
    "query_scale": dict,
    "robustness": dict,
    "adversary": dict,
    "sweep": dict,
    "topology": dict,
    "coherence": dict,
    "antientropy": dict,
    "autopilot": dict,
    "pipeline": dict,
    "cost": dict,
    "regression": dict,
    "telemetry": dict,
    "kernels": (str, dict),
}


def _require(doc: dict, key: str, types, issues: List[str],
             ctx: str) -> bool:
    if key not in doc:
        issues.append(f"{ctx}: missing required key {key!r}")
        return False
    if not isinstance(doc[key], types):
        issues.append(
            f"{ctx}: key {key!r} has type "
            f"{type(doc[key]).__name__}, expected "
            f"{types if isinstance(types, type) else types}")
        return False
    return True


def validate_result(doc: dict, issues: List[str],
                    ctx: str = "result") -> None:
    _require(doc, "metric", str, issues, ctx)
    _require(doc, "value", NUMBER, issues, ctx)
    _require(doc, "unit", str, issues, ctx)
    if "vs_baseline" in doc and not isinstance(doc["vs_baseline"],
                                               NUMBER):
        issues.append(f"{ctx}: vs_baseline is not a number")
    for name, types in KNOWN_RESULT_BLOCKS.items():
        if name in doc and not isinstance(doc[name], types):
            issues.append(
                f"{ctx}: block {name!r} has type "
                f"{type(doc[name]).__name__}")
    if isinstance(doc.get("regression"), dict):
        overall = doc["regression"].get("overall")
        if overall not in ("regression", "improvement", "neutral",
                           "incomparable"):
            issues.append(
                f"{ctx}: regression.overall is {overall!r}")
    if isinstance(doc.get("cost"), dict):
        cost = doc["cost"]
        for key in ("programs", "reconciliation"):
            if key in cost and not isinstance(cost[key], dict):
                issues.append(f"{ctx}: cost.{key} is not an object")
    if isinstance(doc.get("coherence"), dict):
        coh = doc["coherence"]
        for key in ("digest_off", "digest_on", "live"):
            if key in coh and not isinstance(coh[key], dict):
                issues.append(
                    f"{ctx}: coherence.{key} is not an object")
        if "bit_identical" in coh \
                and not isinstance(coh["bit_identical"], bool):
            issues.append(
                f"{ctx}: coherence.bit_identical is not a bool")
        ratio = coh.get("rounds_to_eps_ratio")
        if ratio is not None and not isinstance(ratio, NUMBER):
            issues.append(
                f"{ctx}: coherence.rounds_to_eps_ratio is neither "
                "null nor a number")
    if isinstance(doc.get("query_scale"), dict):
        qs = doc["query_scale"]
        levels = qs.get("levels")
        if levels is not None:
            if not isinstance(levels, list):
                issues.append(
                    f"{ctx}: query_scale.levels is not a list")
            else:
                for i, level in enumerate(levels):
                    if not isinstance(level, dict):
                        issues.append(
                            f"{ctx}: query_scale.levels[{i}] is not "
                            "an object")
        if "max_subscribers" in qs \
                and not isinstance(qs["max_subscribers"], int):
            issues.append(
                f"{ctx}: query_scale.max_subscribers is not an int")
        if "gap_free" in qs and not isinstance(qs["gap_free"], bool):
            issues.append(
                f"{ctx}: query_scale.gap_free is not a bool")
        # The acceptance headlines: null (an honest non-result — e.g.
        # the ramp was capped below the baseline threshold, or a
        # watchdog cut the run short) or a number; never anything else.
        for key in ("serialization_ratio", "lag_p99_ms",
                    "lag_p99_versions", "publish_p99_ms"):
            val = qs.get(key)
            if val is not None and not isinstance(val, NUMBER):
                issues.append(
                    f"{ctx}: query_scale.{key} is neither "
                    "null nor a number")
    if isinstance(doc.get("antientropy"), dict):
        ae = doc["antientropy"]
        for key in ("live", "sim"):
            if key in ae and not isinstance(ae[key], dict):
                issues.append(
                    f"{ctx}: antientropy.{key} is not an object")
        # The two acceptance headlines: null (an honest non-result —
        # fallback taken or heal never landed) or a number; anything
        # else is a schema break.
        for key in ("bytes_ratio", "heal_time_ratio"):
            val = ae.get(key)
            if val is not None and not isinstance(val, NUMBER):
                issues.append(
                    f"{ctx}: antientropy.{key} is neither "
                    "null nor a number")
    if isinstance(doc.get("pipeline"), dict):
        pl = doc["pipeline"]
        # Per-family legs may be null (one failing leg must not sink
        # the block — benchmarks/pipeline.py) but never a non-object.
        for key in ("exact", "compressed", "convergence", "cadence",
                    "sharded", "summary"):
            if key in pl and pl[key] is not None \
                    and not isinstance(pl[key], dict):
                issues.append(
                    f"{ctx}: pipeline.{key} is neither null nor an "
                    "object")
        # The acceptance headlines ride in summary: each is null (an
        # honest non-result — the leg failed or a denominator was
        # missing) or a number; anything else is a schema break.
        summary = pl.get("summary")
        if isinstance(summary, dict):
            for key in ("vs_pr5_headline", "rounds_to_eps_ratio",
                        "overlap_ms"):
                val = summary.get(key)
                if val is not None and not isinstance(val, NUMBER):
                    issues.append(
                        f"{ctx}: pipeline.summary.{key} is neither "
                        "null nor a number")
    if isinstance(doc.get("autopilot"), dict):
        ap = doc["autopilot"]
        for key in ("fit", "recommended"):
            if key in ap and not isinstance(ap[key], dict):
                issues.append(
                    f"{ctx}: autopilot.{key} is not an object")
        # baseline may be null (include_baseline off) but never a
        # non-object; the headline eval_ratio is number-or-null and
        # replay_bit_identical bool-or-null (honest non-results).
        if "baseline" in ap and ap["baseline"] is not None \
                and not isinstance(ap["baseline"], dict):
            issues.append(
                f"{ctx}: autopilot.baseline is neither null nor an "
                "object")
        ratio = ap.get("eval_ratio")
        if ratio is not None and not isinstance(ratio, NUMBER):
            issues.append(
                f"{ctx}: autopilot.eval_ratio is neither null nor "
                "a number")
        replay = ap.get("replay_bit_identical")
        if replay is not None and not isinstance(replay, bool):
            issues.append(
                f"{ctx}: autopilot.replay_bit_identical is neither "
                "null nor a bool")
        if "closed_loop" in ap \
                and not isinstance(ap["closed_loop"], bool):
            issues.append(
                f"{ctx}: autopilot.closed_loop is not a bool")


def validate_error(doc: dict, issues: List[str],
                   ctx: str = "error") -> None:
    err = doc.get("error")
    if not isinstance(err, str):
        issues.append(f"{ctx}: error key is not a string")
        return
    if err == "device_init_failed":
        _require(doc, "platform_requested", str, issues, ctx)
        _require(doc, "attempts", int, issues, ctx)
        _require(doc, "message", str, issues, ctx)
    elif err == "bench_timeout":
        if doc.get("watchdog") is not True:
            issues.append(f"{ctx}: bench_timeout without watchdog: true")
        _require(doc, "phase", str, issues, ctx)
        _require(doc, "partial", dict, issues, ctx)
    # Unknown error kinds are legal (forward compatible) as long as the
    # error key itself is a string.


def validate_record(doc, issues: List[str], ctx: str = "record") -> None:
    """Validate a bare bench record (result or error)."""
    if not isinstance(doc, dict):
        issues.append(f"{ctx}: not a JSON object")
        return
    if "error" in doc:
        validate_error(doc, issues, ctx)
    else:
        validate_result(doc, issues, ctx)


def validate_wrapper(doc: dict, issues: List[str],
                     ctx: str = "wrapper") -> None:
    """Validate a driver wrapper (``{"cmd", "n", "parsed", "rc",
    "tail"}``) including its ``parsed`` payload."""
    _require(doc, "cmd", str, issues, ctx)
    _require(doc, "rc", int, issues, ctx)
    _require(doc, "tail", str, issues, ctx)
    if "n" in doc and not isinstance(doc["n"], int):
        issues.append(f"{ctx}: n is not an int")
    if "parsed" not in doc:
        issues.append(f"{ctx}: missing parsed key")
        return
    parsed = doc["parsed"]
    if parsed is None:
        if doc.get("rc") == 0:
            issues.append(
                f"{ctx}: rc 0 with parsed: null — a successful run "
                "must emit a parseable record")
        return
    validate_record(parsed, issues, f"{ctx}.parsed")
    rc = doc.get("rc")
    if isinstance(parsed, dict) and "error" not in parsed and rc not in (0, None):
        issues.append(
            f"{ctx}: result record with non-zero rc {rc}")


def validate(doc, issues: List[str], ctx: str = "record") -> None:
    """Validate any known top-level shape (wrapper or bare record)."""
    if isinstance(doc, dict) and "parsed" in doc and "rc" in doc:
        validate_wrapper(doc, issues, ctx)
    else:
        validate_record(doc, issues, ctx)


def check_file(path: str) -> List[str]:
    issues: List[str] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as exc:
        return [f"{path}: unreadable ({exc})"]
    validate(doc, issues, ctx=os.path.basename(path))
    return issues


def main(argv=None) -> int:
    args = (argv if argv is not None else sys.argv[1:])
    paths = args or sorted(
        glob.glob(os.path.join(os.path.dirname(__file__), os.pardir,
                               "BENCH_*.json")))
    if not paths:
        print("check_bench_schema: no records found")
        return 1
    all_issues: List[str] = []
    for p in paths:
        all_issues.extend(check_file(p))
    if all_issues:
        print(f"check_bench_schema: {len(all_issues)} issue(s)")
        for issue in all_issues:
            print(f"  {issue}")
        return 1
    print(f"check_bench_schema: {len(paths)} record(s) OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
