#!/usr/bin/env python3
"""Perf-regression verdict plane over bench.py JSON records.

The bench trajectory (``BENCH_r*.json``) is the repo's only
longitudinal performance record, but until now reading it meant
eyeballing floats: the headline has been flat since PR 5 and nothing
would have SAID SO had it regressed.  This tool turns any two bench
records — or the whole trajectory — into parseable per-metric verdicts
with explicit noise tolerances.

Record shapes understood (see tools/check_bench_schema.py for the
enforced schema):

* a bare bench.py result object (``{"metric": ..., "value": ...}``),
* a driver wrapper (``{"cmd", "n", "parsed", "rc", "tail"}``) — the
  ``parsed`` payload is unwrapped, ``parsed: null`` is incomparable,
* error records (``{"error": "device_init_failed" | "bench_timeout"}``)
  — never compared, always surfaced as incomparable with the reason.

Verdict semantics, per metric: the relative delta in the metric's
GOOD direction (higher rounds/sec is good, lower ms/round is good) is
compared against that metric's noise tolerance.  Inside the band →
``neutral``; better beyond it → ``improvement``; worse beyond it →
``regression``.  The overall verdict is the worst per-metric one
(any regression ⇒ regression).

Exit codes (CLI): 0 verdict computed and no regression, 3 regression
found, 2 records incomparable, 1 usage/IO error — so CI can gate on
``rc == 3`` without parsing, while the JSON on stdout carries the
details.

Library use (bench.py's ``regression`` block, tests):

    from tools.bench_compare import compare, extract_record
    verdict = compare(extract_record(prev_doc), extract_record(cur_doc))
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
from typing import Optional

# (dotted key path, good direction, relative noise tolerance).
# Tolerances are per-metric because their noise floors differ: wall
# times on a busy host jitter far more than round counts, which are
# deterministic given a seed.  A tolerance is the HALF-WIDTH of the
# neutral band around zero delta.
DEFAULT_SPECS = (
    ("value", "higher", 0.08),
    ("compressed_rounds_per_sec", "higher", 0.08),
    ("north_star.wall_ms_per_round", "lower", 0.10),
    ("north_star.wall_seconds_to_eps", "lower", 0.10),
    ("north_star.rounds_to_eps", "lower", 0.02),
    ("north_star_faithful.wall_ms_per_round", "lower", 0.10),
    ("north_star_faithful.wall_seconds_to_eps", "lower", 0.10),
    ("sharded.wall_ms_per_round", "lower", 0.10),
)

VERDICTS = ("regression", "improvement", "neutral")


def get_path(doc: dict, path: str):
    """``get_path({"a": {"b": 3}}, "a.b") -> 3``; None when any hop is
    missing or not a dict."""
    cur = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def extract_record(doc) -> tuple:
    """Normalize any known record shape to ``(kind, payload)``:
    ``("result", parsed_dict)`` for a comparable bench result,
    ``("error"|"watchdog"|"incomparable", info)`` otherwise."""
    if not isinstance(doc, dict):
        return ("incomparable", {"reason": "not an object"})
    if "parsed" in doc and "rc" in doc:  # driver wrapper
        inner = doc.get("parsed")
        if inner is None:
            return ("incomparable",
                    {"reason": "parsed: null", "rc": doc.get("rc")})
        return extract_record(inner)
    if "error" in doc:
        kind = "watchdog" if doc.get("watchdog") else "error"
        return (kind, {"reason": doc["error"]})
    if "metric" in doc or "value" in doc:
        return ("result", doc)
    return ("incomparable", {"reason": "unrecognized record shape"})


def compare_metric(path: str, direction: str, tolerance: float,
                   base: dict, cand: dict) -> Optional[dict]:
    """One per-metric verdict, or None when either side lacks the
    metric (absent metrics are skipped, not failed — older records
    predate newer blocks)."""
    b = get_path(base, path)
    c = get_path(cand, path)
    if not isinstance(b, (int, float)) or not isinstance(c, (int, float)):
        return None
    if b == 0:
        return None  # no meaningful relative delta
    raw = (c - b) / abs(b)
    good = raw if direction == "higher" else -raw
    if good > tolerance:
        verdict = "improvement"
    elif good < -tolerance:
        verdict = "regression"
    else:
        verdict = "neutral"
    return {
        "metric": path,
        "direction": direction,
        "tolerance": tolerance,
        "base": b,
        "candidate": c,
        "delta": round(raw, 6),
        "delta_good": round(good, 6),
        "verdict": verdict,
    }


def compare(base, cand, specs=DEFAULT_SPECS) -> dict:
    """Verdict document for candidate-vs-base.  Either argument may be
    any known record shape; incomparable inputs produce an
    ``{"overall": "incomparable"}`` verdict rather than an exception."""
    bkind, bdoc = extract_record(base)
    ckind, cdoc = extract_record(cand)
    if bkind != "result" or ckind != "result":
        return {
            "overall": "incomparable",
            "base_kind": bkind,
            "candidate_kind": ckind,
            "base_info": bdoc if bkind != "result" else None,
            "candidate_info": cdoc if ckind != "result" else None,
            "metrics": [],
        }
    rows = []
    for path, direction, tol in specs:
        row = compare_metric(path, direction, tol, bdoc, cdoc)
        if row is not None:
            rows.append(row)
    if any(r["verdict"] == "regression" for r in rows):
        overall = "regression"
    elif any(r["verdict"] == "improvement" for r in rows):
        overall = "improvement"
    elif rows:
        overall = "neutral"
    else:
        overall = "incomparable"
    return {"overall": overall, "metrics": rows,
            "compared": len(rows)}


def compare_trajectory(docs: list, labels: Optional[list] = None,
                       specs=DEFAULT_SPECS) -> dict:
    """Consecutive-pair verdicts over an ordered record sequence
    (incomparable records are reported but skipped as comparison
    anchors — the next comparable record compares against the last
    comparable one, so one watchdogged run doesn't blind the plane)."""
    labels = labels or [str(i) for i in range(len(docs))]
    steps = []
    last = None      # (label, doc) of last comparable record
    worst = "neutral"
    for label, doc in zip(labels, docs):
        kind, info = extract_record(doc)
        if kind != "result":
            steps.append({"record": label, "kind": kind,
                          "info": info, "verdict": "incomparable"})
            continue
        if last is not None:
            v = compare(last[1], doc, specs)
            v["base_record"] = last[0]
            v["record"] = label
            steps.append(v)
            if v["overall"] == "regression":
                worst = "regression"
            elif v["overall"] == "improvement" and worst != "regression":
                worst = "improvement"
        else:
            steps.append({"record": label, "kind": kind,
                          "verdict": "baseline"})
        last = (label, doc)
    return {"overall": worst, "steps": steps}


def _load(path: str):
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff bench.py JSON records with noise-tolerant "
                    "regression verdicts.")
    ap.add_argument("records", nargs="+",
                    help="Two records (base candidate), or 3+ / a glob "
                         "for trajectory mode.")
    ap.add_argument("--trajectory", action="store_true",
                    help="Force trajectory mode even with two records.")
    args = ap.parse_args(argv)

    paths = []
    for pat in args.records:
        hits = sorted(glob.glob(pat))
        paths.extend(hits if hits else [pat])
    try:
        docs = [_load(p) for p in paths]
    except (OSError, ValueError) as exc:
        print(json.dumps({"error": "load_failed", "message": str(exc)}))
        return 1

    if len(docs) == 2 and not args.trajectory:
        out = compare(docs[0], docs[1])
        out["base_record"] = paths[0]
        out["record"] = paths[1]
    else:
        out = compare_trajectory(docs, labels=paths)
    print(json.dumps(out, indent=2, sort_keys=True))
    if out["overall"] == "regression":
        return 3
    if out["overall"] == "incomparable":
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
