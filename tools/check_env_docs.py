#!/usr/bin/env python3
"""Static check: every ``SIDECAR_TPU_*`` / ``BENCH_*`` env var the code
reads is documented in ``docs/env.md``.

The ``check_metric_docs.py`` pattern applied to the env-knob surface:
the knob catalog only stays trustworthy if it is COMPLETE — an operator
tuning a bench run or a sim toggle has to be able to look any name up,
and the failure mode is silent (a new ``os.environ.get`` ships, nothing
breaks, the name is simply absent from the doc forever).  Tier-1 runs
this check (tests/test_env_docs.py) and fails the build instead.

Mechanics: the scanned trees are AST-walked for STRING LITERALS that
fully match ``(SIDECAR_TPU_|BENCH_)[A-Z0-9_]+`` — this catches both
direct ``os.environ.get("SIDECAR_TPU_X")`` reads and the named-constant
form (``SPARSE_ENV = "SIDECAR_TPU_SPARSE"``) the resolver modules use.
Names that only appear in docstrings/comments never match (a docstring
is one big constant that fails the fullmatch).  Every matched name must
appear backticked in the doc; the doc may also list names the code no
longer reads — flagged as stale so removals stay honest too.

Live-node config (``SIDECAR_*`` etc.) is out of scope: that catalog is
GENERATED from the config wiring (tools/gen_config_docs.py).

Usage: ``python tools/check_env_docs.py [repo_root [docs_file]]`` —
exits 0 when clean, 1 with a per-offender report otherwise.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

NAME_RE = re.compile(r"(SIDECAR_TPU_|BENCH_)[A-Z0-9_]+")

# Trees (relative to the repo root) whose env reads the doc must cover.
SCAN = ("sidecar_tpu", "benchmarks", "tools", "bench.py",
        "__graft_entry__.py")


def read_names(repo: pathlib.Path):
    """Yield ``(path, lineno, name)`` for every matching string literal
    under the scanned trees."""
    for root in SCAN:
        p = repo / root
        files = [p] if p.suffix == ".py" else sorted(p.rglob("*.py"))
        for f in files:
            if "__pycache__" in f.parts or not f.exists():
                continue
            try:
                tree = ast.parse(f.read_text())
            except SyntaxError:  # pragma: no cover — broken file
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and NAME_RE.fullmatch(node.value):
                    yield f, node.lineno, node.value


def documented_names(docs_text: str) -> set[str]:
    """Names with a REAL catalog entry: the backticked token in the
    FIRST column of a table row.  Prose mentions elsewhere (another
    row's meaning column, a paragraph) deliberately do not count — a
    knob name-dropped in passing is not documented, and a deleted row
    must not stay 'covered' by a cross-reference."""
    out = set()
    for line in docs_text.splitlines():
        m = re.match(r"\s*\|\s*`([^`\s]+)`", line)
        if m and NAME_RE.fullmatch(m.group(1)):
            out.add(m.group(1))
    return out


def check(repo: pathlib.Path, docs_file: pathlib.Path) -> list[str]:
    """Violation strings (empty = doc and code agree)."""
    docs = documented_names(docs_file.read_text())
    problems = []
    seen: set[str] = set()
    for path, lineno, name in read_names(repo):
        seen.add(name)
        if name not in docs:
            problems.append(
                f"{path}:{lineno}: env var {name!r} is not documented "
                f"in {docs_file.name}")
    for stale in sorted(docs - seen):
        problems.append(
            f"{docs_file}: documents {stale!r} but nothing reads it — "
            "remove the row or restore the knob")
    return problems


def main(argv: list[str]) -> int:
    here = pathlib.Path(__file__).resolve().parent.parent
    repo = pathlib.Path(argv[1]) if len(argv) > 1 else here
    docs = pathlib.Path(argv[2]) if len(argv) > 2 else \
        repo / "docs" / "env.md"
    problems = check(repo, docs)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"{len(problems)} env-doc violation(s) — fix {docs}",
              file=sys.stderr)
        return 1
    print(f"check_env_docs: OK ({repo} vs {docs})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
