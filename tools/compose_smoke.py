#!/usr/bin/env python3
"""End-to-end smoke for the shipped docker-compose demo.

Brings up the 3-node compose cluster (docker-compose.yml), waits for all
three nodes to report 3 cluster members with every static service Alive
through their HTTP APIs, prints PASS/FAIL, and tears the stack down.
This is the check the round-4 verdict found missing: the compose demo's
one job is to show three nodes converging, so CI (or an operator) can
run this script to prove it.

Usage:
    python tools/compose_smoke.py [--timeout 120] [--keep-up]

Exit codes: 0 = converged, 1 = failed to converge, 2 = docker missing.

The same topology is also pinned container-free in
tests/test_compose_topology.py (three in-process SidecarNodes seeded by
hostname), so environments without a Docker daemon still regression-test
the seed-resolution path this demo depends on.
"""

import argparse
import json
import pathlib
import shutil
import subprocess
import sys
import time
import urllib.request

# Host ports from docker-compose.yml: seed, sidecar-2, sidecar-3.
NODE_PORTS = [7777, 7877, 7977]
EXPECTED_MEMBERS = {"sidecar-seed", "sidecar-2", "sidecar-3"}
STATIC_SERVICES = ("static-web", "static-tcp")
COMPOSE_FILE = pathlib.Path(__file__).resolve().parent.parent \
    / "docker-compose.yml"


def compose(*args, check=True):
    cmd = ["docker", "compose", "-f", str(COMPOSE_FILE), *args]
    print("+", " ".join(cmd), flush=True)
    return subprocess.run(cmd, check=check)


def node_view(port):
    url = f"http://localhost:{port}/api/services.json"
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read())


def converged():
    for port in NODE_PORTS:
        try:
            doc = node_view(port)
        except OSError:
            return False
        if set(doc.get("ClusterMembers") or {}) != EXPECTED_MEMBERS:
            return False
        services = doc.get("Services") or {}
        for name in STATIC_SERVICES:
            instances = services.get(name) or []
            # one instance per node, all Alive (status 0)
            if len(instances) != len(EXPECTED_MEMBERS):
                return False
            if any(inst.get("Status") != 0 for inst in instances):
                return False
    return True


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--timeout", type=float, default=120.0,
                        help="seconds to wait for convergence")
    parser.add_argument("--keep-up", action="store_true",
                        help="leave the stack running after the check")
    opts = parser.parse_args()

    if shutil.which("docker") is None:
        print("SKIP: docker not found on PATH", file=sys.stderr)
        return 2

    try:
        try:
            compose("up", "--build", "-d")
        except subprocess.CalledProcessError as exc:
            print(f"FAIL: docker compose up failed: {exc}",
                  file=sys.stderr)
            return 1
        deadline = time.monotonic() + opts.timeout
        while time.monotonic() < deadline:
            if converged():
                print("PASS: 3 members, all static services Alive on "
                      f"ports {NODE_PORTS}")
                return 0
            time.sleep(2.0)
        print("FAIL: cluster did not converge within "
              f"{opts.timeout:.0f}s", file=sys.stderr)
        for port in NODE_PORTS:
            try:
                doc = node_view(port)
                print(f"  :{port} members="
                      f"{sorted(doc.get('ClusterMembers') or {})}",
                      file=sys.stderr)
            except OSError as exc:
                print(f"  :{port} unreachable: {exc}", file=sys.stderr)
        return 1
    finally:
        if not opts.keep_up:
            compose("down", "-v", check=False)


if __name__ == "__main__":
    sys.exit(main())
