"""UI logic coverage (VERDICT r4 #7 — the reference ships karma unit
tests + protractor scaffolding, ui/karma.conf.js, ui/e2e-tests/).

The UI's pure logic lives in ui/app/lib.js (no DOM access) and its
assertions in ui/test/lib_test.js, which runs under node or as a
browser page (ui/test/index.html).  Here:

* when a node runtime exists, the real JS test file runs and must pass;
* always (this image has no JS runtime), structural drift guards pin
  the extraction: index.html loads lib.js before app.js, app.js does
  not re-define the extracted functions, and the test file covers every
  exported symbol — so the suite cannot silently rot into testing
  nothing.
"""

import pathlib
import re
import shutil
import subprocess

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
LIB = REPO / "ui" / "app" / "lib.js"
APP = REPO / "ui" / "app" / "app.js"
TEST = REPO / "ui" / "test" / "lib_test.js"

EXPORTED = ["statusIndex", "timeAgo", "sanitizeName", "formatPorts",
            "parseHaproxyCsv", "haproxyHasIn", "extractJsonDocs",
            "applyWatchDoc"]


class TestRunUnderNode:
    @pytest.mark.skipif(shutil.which("node") is None,
                        reason="no node runtime in this image; the "
                               "drift guards below still run")
    def test_lib_tests_pass(self):
        proc = subprocess.run(
            ["node", str(TEST)], capture_output=True, text=True,
            timeout=60)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "PASS" in proc.stdout


class TestExtractionDriftGuards:
    def test_lib_loaded_before_app(self):
        html = (REPO / "ui" / "app" / "index.html").read_text()
        scripts = re.findall(r'<script src="([^"]+)"', html)
        assert scripts.index("lib.js") < scripts.index("app.js")

    def test_app_does_not_redefine_extracted_functions(self):
        app = APP.read_text()
        for name in EXPORTED:
            assert f"function {name}(" not in app, (
                f"{name} re-defined in app.js — it must live only in "
                "lib.js so the unit tests test what the page runs")

    def test_lib_defines_and_exports_everything(self):
        lib = LIB.read_text()
        exports = lib.split("module.exports")[-1]
        for name in EXPORTED:
            assert f"function {name}(" in lib, f"{name} not defined"
            assert name in exports, f"{name} not exported"

    def test_lib_is_domless(self):
        # lib.js must stay testable without a browser: no DOM globals.
        lib = LIB.read_text()
        for banned in ("document.", "window.", "fetch(", "setTimeout("):
            assert banned not in lib, f"lib.js uses {banned}"

    def test_every_export_is_asserted(self):
        test_src = TEST.read_text()
        for name in EXPORTED:
            assert f"L.{name}" in test_src, (
                f"ui/test/lib_test.js never exercises {name}")
