"""Clock-skew resilience (docs/chaos.md): the future-admission bound.

Covers the gate semantics in ops/merge (strict ``>``, reject-not-clamp,
``None`` = not compiled), the int32 packed-key horizon guard with
injected skew folded in, the host/sim staleness cross-pin
(``Service.is_stale`` vs ``ops/merge.staleness_mask`` must draw the
same line), the live writer's reject path and its interplay with
``send_services``' +50 ns re-broadcast bump, and the bound-disabled /
bound-enabled bit-identity pins across every model family (single-chip
dense + sparse, compressed, and both sharded twins at every mesh width
x board_exchange mode — an honest cluster must compile and run the
SAME trajectory whether the bound is off or generously on).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sidecar_tpu import metrics
from sidecar_tpu import service as S
from sidecar_tpu.catalog import ServicesState
from sidecar_tpu.chaos import ChaosExactSim, ClockFault, FaultPlan
from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
from sidecar_tpu.models.exact import ExactSim, SimParams
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops import kernels as kernel_ops
from sidecar_tpu.ops import topology
from sidecar_tpu.ops.merge import (
    admit_gate,
    future_mask,
    merge_packed,
    staleness_mask,
)
from sidecar_tpu.ops.status import ALIVE, MAX_TICK, pack
from sidecar_tpu.parallel.mesh import make_mesh
from sidecar_tpu.runtime.looper import FreeLooper

from tests.test_sharded import DetShardedSim, det_sample_peers
from tests.test_sharded_compressed import (
    DET,
    DetShardedCompressedSim,
    assert_states_equal,
)

MODES = ("all_gather", "all_to_all", "ring")
DENSE_MODES = ("all_gather", "ring")
DS = (1, 2, 4, 8)

DET_DENSE = TimeConfig(refresh_interval_s=1000.0, push_pull_interval_s=1e6,
                       sweep_interval_s=1.0)


def key(ts, st=ALIVE):
    return int(pack(ts, st))


class TestFutureGateSemantics:
    """ops/merge.future_mask + admit_gate: strict ``>``, tie admitted,
    reject never clamps, ``None`` compiles no gate at all."""

    NOW = 50_000
    FT = 500

    def _merge(self, known, inc, ft):
        out = merge_packed(jnp.asarray([known], jnp.int32),
                           jnp.asarray([inc], jnp.int32),
                           self.NOW, stale_ticks=40_000, future_ticks=ft)
        return int(out[0])

    def test_boundary_tie_admitted(self):
        inc = key(self.NOW + self.FT)
        assert self._merge(key(10), inc, self.FT) == inc

    def test_one_tick_beyond_rejected_not_clamped(self):
        cur = key(10)
        inc = key(self.NOW + self.FT + 1)
        out = self._merge(cur, inc, self.FT)
        assert out == cur            # rejected outright — no clamped stamp

    def test_rejected_even_on_unknown_cell(self):
        assert self._merge(0, key(self.NOW + self.FT + 1), self.FT) == 0

    def test_none_disables_the_gate(self):
        inc = key(self.NOW + 10 * self.FT)
        assert self._merge(key(10), inc, None) == inc

    def test_future_mask_strictness(self):
        vals = jnp.asarray([key(self.NOW + self.FT),
                            key(self.NOW + self.FT + 1),
                            key(self.NOW - 1), 0], jnp.int32)
        m = np.asarray(future_mask(vals, self.NOW, self.FT))
        assert m.tolist() == [False, True, False, False]

    def test_admit_gate_zeroes_future_values(self):
        vals = jnp.asarray([key(self.NOW + self.FT + 1), key(100)],
                           jnp.int32)
        out = np.asarray(admit_gate(vals, self.NOW, 1_000_000, self.FT))
        assert out.tolist() == [0, key(100)]


class TestHorizonGuard:
    """int32 packed-key overflow guard: ``max_safe_rounds`` is the
    boundary, injected ClockFault skew counts against it, and the chaos
    driver refuses a run that would wrap the clock into the sign bit."""

    def test_max_safe_rounds_boundary(self):
        t = TimeConfig()
        assert t.max_safe_rounds == MAX_TICK // t.round_ticks
        t.validate_horizon(t.max_safe_rounds)           # exactly safe
        with pytest.raises(ValueError, match="overflows the int32"):
            t.validate_horizon(t.max_safe_rounds + 1)

    def test_skew_counts_against_horizon(self):
        t = TimeConfig()
        # Shift rounds into skew tick-for-tick: still exactly safe.
        t.validate_horizon(t.max_safe_rounds - 10,
                           skew_ticks=10 * t.round_ticks)
        with pytest.raises(ValueError, match="skew ticks"):
            t.validate_horizon(t.max_safe_rounds,
                               skew_ticks=t.round_ticks + 1)

    def test_plan_max_offset_folds_drift_and_step(self):
        f = ClockFault(nodes=(0,), start_round=10, end_round=20,
                       offset_ticks=100, drift_ticks_per_round=2.5,
                       step_ticks=1000, step_round=15)
        # Window peak: offset + floor(2.5 * 9) + step.
        assert f.max_offset == 100 + 22 + 1000
        plan = FaultPlan(seed=1, clocks=(
            f, ClockFault(nodes=(1,), offset_ticks=7)))
        assert plan.max_clock_offset == f.max_offset + 7

    def test_chaos_driver_refuses_overflowing_skew(self):
        plan = FaultPlan(seed=1, clocks=(
            ClockFault(nodes=(0,), start_round=0, end_round=10,
                       offset_ticks=MAX_TICK),))
        sim = ChaosExactSim(
            SimParams(n=4, services_per_node=1, fanout=2, budget=3),
            topology.complete(4), TimeConfig(), plan=plan)
        with pytest.raises(ValueError, match="overflows the int32"):
            sim.run(sim.init_state(), jax.random.PRNGKey(0), 1)


class TestStalenessCrossPin:
    """The host merge path (Service.is_stale, ns clocks) and the sim
    merge path (ops/merge.staleness_mask, tick clocks) must draw the
    SAME staleness line at the same logical instants — the cross-path
    equivalence the clock-skew work leans on."""

    def test_host_and_sim_agree_across_the_boundary(self):
        t = TimeConfig()
        # The two planes must start from the same wall-clock constants.
        assert t.tombstone_lifespan_s == S.TOMBSTONE_LIFESPAN
        assert t.staleness_fudge_s == S.STALENESS_FUDGE
        ns_per_tick = S.NS_PER_SECOND // t.ticks_per_second
        now_tick = 20_000_000
        now_ns = now_tick * ns_per_tick
        ages = (1, t.stale_ticks - 1, t.stale_ticks, t.stale_ticks + 1,
                now_tick - 1)
        for age in ages:
            ts = now_tick - age
            sim_stale = bool(np.asarray(staleness_mask(
                jnp.asarray([key(ts)], jnp.int32), now_tick,
                t.stale_ticks))[0])
            svc = S.Service(id="x", name="web", image="i:1",
                            hostname="h", updated=ts * ns_per_tick,
                            status=S.ALIVE, ports=[])
            host_stale = svc.is_stale(t.tombstone_lifespan_s, now=now_ns)
            assert sim_stale == host_stale, \
                f"paths disagree at age={age} ticks " \
                f"(sim={sim_stale}, host={host_stale})"


FIXED_NOW = 1_700_000_000 * S.NS_PER_SECOND


class TestLiveFutureGate:
    """catalog/state.py writer-path twin of the sim gate: reject (and
    count) beyond ``now + fudge``, admit the tie, pass everything when
    disabled."""

    def make_state(self, fudge):
        st = ServicesState(hostname="recv")
        st.future_fudge_s = fudge
        st.set_clock(lambda: FIXED_NOW)
        return st

    def svc(self, updated, sid="svc-1"):
        return S.Service(id=sid, name="web", image="i:1", hostname="src",
                         updated=updated, status=S.ALIVE,
                         ports=[S.Port("tcp", 1000, 80, "127.0.0.1")])

    def _admitted(self, st, svc):
        st.add_service_entry(svc)
        server = st.servers.get(svc.hostname)
        return server is not None and svc.id in server.services

    def test_future_record_rejected_and_counted(self):
        st = self.make_state(0.5)
        before = metrics.counter("clock.live.rejectedFuture")
        too_far = FIXED_NOW + int(0.5 * S.NS_PER_SECOND) + 1
        assert not self._admitted(st, self.svc(too_far))
        assert metrics.counter("clock.live.rejectedFuture") == before + 1

    def test_tie_admitted(self):
        st = self.make_state(0.5)
        at_bound = FIXED_NOW + int(0.5 * S.NS_PER_SECOND)
        assert self._admitted(st, self.svc(at_bound))

    def test_disabled_admits_any_future_stamp(self):
        st = self.make_state(-1.0)
        assert self._admitted(
            st, self.svc(FIXED_NOW + 3600 * S.NS_PER_SECOND))


class TestSendServicesBumpWithinBound:
    """Regression pin: the +50 ns/round re-broadcast bump
    (catalog/state.send_services, services_state.go:585-599) must stay
    FAR inside any practical future-admission bound over a full
    1-minute refresh window — the bound must never eat the protocol's
    own retransmit nudge."""

    REFRESH_ROUNDS = 60     # 1 Hz re-enqueue over the 1-min window

    def test_bump_is_nanoseconds_while_the_bound_is_milliseconds(self):
        sender = ServicesState(hostname="send")
        svc = S.Service(id="svc-1", name="web", image="i:1",
                        hostname="send", updated=FIXED_NOW,
                        status=S.ALIVE,
                        ports=[S.Port("tcp", 1000, 80, "127.0.0.1")])
        sender.send_services([svc], FreeLooper(self.REFRESH_ROUNDS),
                             background=False)
        stamps = []
        while not sender.broadcasts.empty():
            for payload in sender.broadcasts.get_nowait():
                stamps.append(S.decode(payload).updated)
        assert len(stamps) == self.REFRESH_ROUNDS
        worst = max(stamps) - FIXED_NOW
        assert worst == 50 * (self.REFRESH_ROUNDS - 1)
        # Tightest bound the skew bench ships (0.5 s): five orders of
        # magnitude of headroom over the worst in-window bump.
        assert worst < 0.5 * S.NS_PER_SECOND / 1e5

        # And end-to-end: the most-bumped copy clears a 0.5 s gate at a
        # receiver whose clock still reads the ORIGINAL stamp time.
        recv = ServicesState(hostname="recv")
        recv.future_fudge_s = 0.5
        recv.set_clock(lambda: FIXED_NOW)
        before = metrics.counter("clock.live.rejectedFuture")
        bumped = svc.copy()
        bumped.updated = FIXED_NOW + worst
        recv.add_service_entry(bumped)
        assert metrics.counter("clock.live.rejectedFuture") == before
        assert "send" in recv.servers


class TestBoundBitIdentity:
    """An honest (skew-free) cluster must run the SAME trajectory with
    the bound disabled (gate not compiled) and with it generously
    enabled (gate compiled, never firing) — pinned bit-for-bit on every
    model family.  Any off-by-one in the gate (e.g. rejecting the tie,
    or gating against the wrong clock) breaks equality at the first
    diverging round."""

    ON = 2.0                # seconds — generous vs honest stamps

    def test_exact_dense_and_sparse(self):
        params = SimParams(n=16, services_per_node=2, fanout=2,
                           budget=4, drop_prob=0.3)
        off_cfg = DET_DENSE
        on_cfg = dataclasses.replace(DET_DENSE, future_fudge_s=self.ON)
        off = ExactSim(params, topology.complete(16), off_cfg)
        on = ExactSim(params, topology.complete(16), on_cfg)
        on_sparse = ExactSim(params, topology.complete(16), on_cfg)
        so, sn, ss = (off.init_state(), on.init_state(),
                      on_sparse.init_state())
        for i in range(12):
            k = jax.random.PRNGKey(i)
            so = off.step(so, k)
            sn = on.step(sn, k)
            ss, _ = on_sparse.step_sparse(ss, k)
            for name, got in (("dense", sn), ("sparse", ss)):
                np.testing.assert_array_equal(
                    np.asarray(so.known), np.asarray(got.known),
                    err_msg=f"known {name} r{i + 1}")
                np.testing.assert_array_equal(
                    np.asarray(so.sent), np.asarray(got.sent),
                    err_msg=f"sent {name} r{i + 1}")

    def _compressed_run(self, sim, rounds=8):
        rng = np.random.default_rng(7)
        schedule = {i: np.sort(rng.choice(
            sim.p.m, size=5, replace=False)).astype(np.int32)
            for i in (0, 3)}
        st = sim.init_state()
        states = []
        for i in range(rounds):
            if i in schedule:
                tick = int(st.round_idx) * sim.t.round_ticks + 7
                st = sim.mint(st, schedule[i], tick)
            st = sim.step(st, jax.random.PRNGKey(100 + i))
            states.append(st)
        return states

    def test_compressed_single_chip(self, monkeypatch):
        monkeypatch.setattr(gossip_ops, "sample_peers", det_sample_peers)
        params = CompressedParams(n=16, services_per_node=2, fanout=2,
                                  budget=4, cache_lines=32)
        off = CompressedSim(params, topology.complete(16), DET)
        on = CompressedSim(params, topology.complete(16),
                           dataclasses.replace(DET,
                                               future_fudge_s=self.ON))
        ref = self._compressed_run(off)
        got = self._compressed_run(on)
        for i, (a, b) in enumerate(zip(ref, got)):
            assert_states_equal(a, b, f"compressed r{i + 1}")

    def test_sharded_dense_twin_modes_by_d(self, monkeypatch):
        monkeypatch.setattr(gossip_ops, "sample_peers", det_sample_peers)
        params = SimParams(n=16, services_per_node=2, fanout=2, budget=4)
        rounds = 8
        exact = ExactSim(params, topology.complete(16), DET_DENSE)
        se = exact.init_state()
        ref = []
        for i in range(rounds):
            se = exact.step(se, jax.random.PRNGKey(i))
            ref.append(se)
        on_cfg = dataclasses.replace(DET_DENSE, future_fudge_s=self.ON)
        for d in DS:
            for mode in DENSE_MODES:
                sharded = DetShardedSim(
                    params, topology.complete(16), on_cfg,
                    mesh=make_mesh(jax.devices()[:d]),
                    board_exchange=mode)
                ss = sharded.init_state()
                for i in range(rounds):
                    ss = sharded.step(ss, jax.random.PRNGKey(i))
                    np.testing.assert_array_equal(
                        np.asarray(ref[i].known), np.asarray(ss.known),
                        err_msg=f"known {mode}/d={d} r{i + 1}")
                    np.testing.assert_array_equal(
                        np.asarray(ref[i].sent), np.asarray(ss.sent),
                        err_msg=f"sent {mode}/d={d} r{i + 1}")

    @pytest.mark.pallas
    def test_sharded_compressed_twin_modes_by_d(self, monkeypatch):
        """Pallas kernels active: the post-kernel publish gate must be a
        no-op on honest stamps at every mode x d."""
        monkeypatch.setenv(kernel_ops.ENV_VAR, "pallas")
        monkeypatch.setattr(gossip_ops, "sample_peers", det_sample_peers)
        params = CompressedParams(n=16, services_per_node=2, fanout=2,
                                  budget=4, cache_lines=32)
        single = CompressedSim(params, topology.complete(16), DET)
        assert single._kernels == "pallas"
        ref = self._compressed_run(single)
        on_cfg = dataclasses.replace(DET, future_fudge_s=self.ON)
        for d in DS:
            for mode in MODES:
                sharded = DetShardedCompressedSim(
                    params, topology.complete(16), on_cfg,
                    mesh=make_mesh(jax.devices()[:d]),
                    board_exchange=mode)
                got = self._compressed_run(sharded)
                for i, (a, b) in enumerate(zip(ref, got)):
                    assert_states_equal(a, b, f"{mode}/d={d} r{i + 1}")
