"""Query-plane tests: versioned COW snapshots, the subscription hub's
backpressure/coalescing contract, and the acceptance concurrency run —
N parallel watchers must see identical, gap-free version sequences
while a config6-style chaos schedule mutates the catalog."""

import json
import threading

import pytest

from sidecar_tpu import metrics
from sidecar_tpu import service as S
from sidecar_tpu.catalog import ServicesState

NS = S.NS_PER_SECOND
T0 = 1_700_000_000 * NS


def make_state(services=3):
    state = ServicesState(hostname="h1", cluster_name="query-test")
    state.set_clock(lambda: T0)
    for i in range(services):
        state.add_service_entry(S.Service(
            id=f"svc{i}", name=f"app{i % 2}", image="i:1", hostname="h1",
            updated=T0, status=S.ALIVE,
            ports=[S.Port("tcp", 32768 + i, 8080, "10.0.0.1")]))
    return state


class TestSnapshot:
    def test_attach_builds_version_one(self):
        state = make_state()
        snap = state.query_hub().current()
        assert snap.version == 1
        assert set(snap.servers["h1"].services) == {"svc0", "svc1",
                                                    "svc2"}

    def test_versions_are_dense_and_monotonic(self):
        state = make_state()
        hub = state.query_hub()
        versions = [hub.current().version]
        for i in range(5):
            state.add_service_entry(S.Service(
                id=f"new{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
            versions.append(hub.current().version)
        assert versions == list(range(1, 7))

    def test_snapshots_are_immutable_and_share_structure(self):
        state = make_state()
        hub = state.query_hub()
        state.add_service_entry(S.Service(
            id="zzz", name="other", image="i:1", hostname="h2",
            updated=T0 + NS, status=S.ALIVE))
        before = hub.current()
        h1_view = before.servers["h1"]
        state.add_service_entry(S.Service(
            id="yyy", name="other", image="i:1", hostname="h2",
            updated=T0 + 2 * NS, status=S.ALIVE))
        after = hub.current()
        # The untouched host's view is the SAME object (copy-on-write
        # structural sharing); the old snapshot still shows the old h2.
        assert after.servers["h1"] is h1_view
        assert set(before.servers["h2"].services) == {"zzz"}
        assert set(after.servers["h2"].services) == {"zzz", "yyy"}

    def test_serialization_cached_per_version(self):
        state = make_state()
        snap = state.query_hub().current()
        assert snap.to_json() is snap.to_json()
        assert snap.encode() is snap.encode()
        assert snap.by_service() is snap.by_service()

    def test_by_service_matches_state(self):
        state = make_state()
        snap = state.query_hub().current()
        want = {name: [svc.to_json() for svc in instances]
                for name, instances in state.by_service().items()}
        assert snap.by_service_json() == want

    def test_state_json_parity_plus_version(self):
        state = make_state()
        snap = state.query_hub().current()
        with state._lock:
            want = state.to_json()
        got = dict(snap.to_json())
        assert got.pop("Version") == 1
        assert got == want

    def test_reader_never_takes_state_lock(self):
        """The point of the plane: with the writer wedged on its lock,
        every snapshot read still completes."""
        state = make_state()
        hub = state.query_hub()
        release = threading.Event()
        grabbed = threading.Event()

        def hold_lock():
            with state._lock:
                grabbed.set()
                release.wait(timeout=5)

        t = threading.Thread(target=hold_lock, daemon=True)
        t.start()
        assert grabbed.wait(timeout=5)
        try:
            snap = hub.current()          # must not block
            assert snap.version >= 1
            assert snap.encode()
            assert snap.by_service() is not None
        finally:
            release.set()
            t.join(timeout=5)


class TestHub:
    def test_prime_then_gap_free_deltas(self):
        state = make_state()
        hub = state.query_hub()
        sub = hub.subscribe("t", buffer=64)
        first = sub.get(timeout=1)
        assert first.kind == "snapshot" and first.version == 1
        for i in range(4):
            state.add_service_entry(S.Service(
                id=f"d{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
        versions = []
        while True:
            ev = sub.get(timeout=0.2)
            if ev is None:
                break
            assert ev.kind == "delta"
            assert ev.change.service.id == f"d{len(versions)}"
            versions.append(ev.version)
        assert versions == [2, 3, 4, 5]

    def test_backpressure_coalesces_to_snapshot(self):
        state = make_state()
        hub = state.query_hub()
        sub = hub.subscribe("slow", buffer=2, prime=False)
        dropped0 = metrics.counter("query.hub.dropped")
        coalesced0 = metrics.counter("query.hub.coalesced")
        for i in range(7):
            state.add_service_entry(S.Service(
                id=f"b{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
        events = []
        while True:
            ev = sub.get(timeout=0.2)
            if ev is None:
                break
            events.append(ev)
        # The overflow collapses EVERYTHING (the queued-but-unread
        # deltas included) into one snapshot marker at the LATEST
        # version — the snapshot subsumes them, and every discarded
        # delta is counted.
        assert [ev.kind for ev in events] == ["snapshot"]
        assert events[-1].version == hub.current().version
        assert "b6" in events[-1].snapshot.servers["h1"].services
        assert metrics.counter("query.hub.dropped") - dropped0 == 7
        assert metrics.counter("query.hub.coalesced") - coalesced0 == 1

    def test_delta_flow_resumes_after_resync(self):
        state = make_state()
        hub = state.query_hub()
        sub = hub.subscribe("slow", buffer=1, prime=False)
        for i in range(3):
            state.add_service_entry(S.Service(
                id=f"c{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
        ev = sub.get(timeout=1)
        assert ev.kind == "snapshot"
        resync_version = ev.version
        state.add_service_entry(S.Service(
            id="afterwards", name="app0", image="i:1", hostname="h1",
            updated=T0 + 10 * NS, status=S.ALIVE))
        ev = sub.get(timeout=1)
        assert ev.kind == "delta"
        assert ev.version == resync_version + 1

    def test_close_wakes_blocked_get_and_deregisters(self):
        state = make_state()
        hub = state.query_hub()
        sub = hub.subscribe("t", buffer=4)
        sub.get(timeout=1)  # the priming snapshot
        got = []

        def block():
            got.append(sub.get(timeout=5))

        t = threading.Thread(target=block, daemon=True)
        t.start()
        sub.close()
        t.join(timeout=5)
        assert got == [None]
        assert hub.subscriber_count() == 0

    def test_publish_never_blocks_writer(self):
        """A completely stuck subscriber must not slow the writer path:
        publishing 100 events with a dead 1-slot subscriber stays
        instant (bounded queue + collapse, no waiting)."""
        state = make_state()
        hub = state.query_hub()
        hub.subscribe("dead", buffer=1, prime=False)
        for i in range(100):
            state.add_service_entry(S.Service(
                id=f"w{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
        assert hub.current().version == 101


class TestConcurrencyUnderChaos:
    """The acceptance run: N parallel watchers, a config6-style chaos
    churn schedule mutating the catalog, every watcher sees the
    identical gap-free version sequence and converges on the same
    final snapshot."""

    N_WATCHERS = 8
    ROUNDS = 40       # churn window (config6 uses rounds 30-60)
    SIDE_A = 4        # churned hosts (config6 churns one side only)

    def test_parallel_watchers_gap_free(self):
        from sidecar_tpu.chaos.plan import FaultPlan, coin

        plan = FaultPlan(seed=6)  # the config6 seed
        state = ServicesState(hostname="n0", cluster_name="chaos")
        state.set_clock(lambda: T0)
        hosts = [f"n{i}" for i in range(8)]
        for hi, host in enumerate(hosts):
            for si in range(4):
                state.add_service_entry(S.Service(
                    id=f"{host}-s{si}", name=f"svc{si}", image="i:1",
                    hostname=host, updated=T0, status=S.ALIVE))
        hub = state.query_hub()
        start_version = hub.current().version

        stop = threading.Event()
        results = [None] * self.N_WATCHERS
        errors = []

        def watcher(idx):
            # Large buffer: this test pins the GAP-FREE delta contract;
            # the coalesce path has its own tests above.
            sub = hub.subscribe(f"w{idx}", buffer=8192, prime=True)
            try:
                first = sub.get(timeout=5)
                if first is None or first.kind != "snapshot":
                    errors.append(f"w{idx}: bad prime {first}")
                    return
                versions = []
                changes = []
                while True:
                    ev = sub.get(timeout=0.5)
                    if ev is None:
                        if stop.is_set():
                            break
                        continue
                    if ev.kind != "delta":
                        errors.append(f"w{idx}: unexpected coalesce")
                        return
                    versions.append(ev.version)
                    changes.append((ev.change.service.id,
                                    ev.change.service.status,
                                    ev.change.service.updated))
                results[idx] = (first.version, versions, changes,
                                sub.pending())
            finally:
                sub.close()

        threads = [threading.Thread(target=watcher, args=(i,),
                                    daemon=True)
                   for i in range(self.N_WATCHERS)]
        for t in threads:
            t.start()

        # The chaos writer: config6's one-sided Bernoulli churn recast
        # onto the live catalog — every flip decision is the plan's
        # deterministic coin, so the schedule replays from the seed.
        now = T0
        for rnd in range(self.ROUNDS):
            now += NS // 5  # one 200 ms gossip round
            for hi in range(self.SIDE_A):
                for si in range(4):
                    if coin(plan.seed, "churn", rnd, hi, si) < 0.1:
                        host = hosts[hi]
                        sid = f"{host}-s{si}"
                        cur = state.servers[host].services[sid]
                        new_status = (S.TOMBSTONE
                                      if cur.status == S.ALIVE
                                      else S.ALIVE)
                        state.add_service_entry(S.Service(
                            id=sid, name=f"svc{si}", image="i:1",
                            hostname=host, updated=now,
                            status=new_status))
        final_version = hub.current().version
        n_changes = final_version - start_version
        assert n_changes > 20, "chaos schedule produced too few changes"

        # Let every watcher drain, then stop them.
        deadline = threading.Event()
        deadline.wait(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors
        assert all(r is not None for r in results)

        expect_versions = list(range(start_version + 1,
                                     final_version + 1))
        first = results[0]
        for idx, (prime_v, versions, changes, pending) in \
                enumerate(results):
            assert pending == 0, f"w{idx} did not drain"
            assert prime_v == start_version
            # Gap-free: exactly the dense version range.
            assert versions == expect_versions, \
                f"w{idx} saw gaps: {len(versions)} vs {n_changes}"
            # Identical: byte-for-byte the same change sequence.
            assert changes == first[2], f"w{idx} diverged"

        # And the final snapshot equals the live catalog.
        snap = hub.current()
        with state._lock:
            for host, server in state.servers.items():
                got = snap.servers[host].services
                assert set(got) == set(server.services)
                for sid, svc in server.services.items():
                    assert got[sid].status == svc.status
                    assert got[sid].updated == svc.updated


@pytest.mark.slow
class TestConcurrencySoak:
    """Soak variant (slow marker): watchers with TINY buffers and
    random stalls, so the coalesce path fires constantly — every
    watcher must still reconstruct the exact final catalog from its
    mix of snapshots and deltas, with versions non-decreasing and
    delta runs contiguous after each resync."""

    def test_slow_watchers_converge_via_resync(self):
        import random

        state = ServicesState(hostname="n0", cluster_name="soak")
        state.set_clock(lambda: T0)
        hosts = [f"n{i}" for i in range(6)]
        for host in hosts:
            for si in range(3):
                state.add_service_entry(S.Service(
                    id=f"{host}-s{si}", name=f"svc{si}", image="i:1",
                    hostname=host, updated=T0, status=S.ALIVE))
        hub = state.query_hub()
        stop = threading.Event()
        errors = []
        views = [None] * 6

        def watcher(idx):
            rng = random.Random(idx)
            sub = hub.subscribe(f"soak{idx}", buffer=4, prime=True)
            view = {}
            last_version = 0
            expect_next = None  # None = just resynced, any version ok
            try:
                while True:
                    ev = sub.get(timeout=0.5)
                    if ev is None:
                        if stop.is_set() and sub.pending() == 0:
                            break
                        continue
                    if ev.version < last_version:
                        errors.append(f"w{idx}: version regressed")
                        return
                    if ev.kind == "snapshot":
                        view = {
                            (h, sid): (svc.updated, svc.status)
                            for h, srv in ev.snapshot.servers.items()
                            for sid, svc in srv.services.items()}
                        expect_next = ev.version + 1
                    else:
                        if expect_next is not None and \
                                ev.version != expect_next:
                            errors.append(
                                f"w{idx}: delta gap {expect_next} -> "
                                f"{ev.version} without resync")
                            return
                        expect_next = ev.version + 1
                        svc = ev.change.service
                        view[(svc.hostname, svc.id)] = (svc.updated,
                                                        svc.status)
                    last_version = ev.version
                    if rng.random() < 0.05:
                        stall = threading.Event()
                        stall.wait(rng.random() * 0.02)  # fall behind
                views[idx] = (view, last_version)
            finally:
                sub.close()

        threads = [threading.Thread(target=watcher, args=(i,),
                                    daemon=True) for i in range(6)]
        for t in threads:
            t.start()

        rng = random.Random(99)
        now = T0
        for _ in range(600):
            now += NS // 50
            host = hosts[rng.randrange(len(hosts))]
            si = rng.randrange(3)
            sid = f"{host}-s{si}"
            cur = state.servers[host].services[sid]
            state.add_service_entry(S.Service(
                id=sid, name=f"svc{si}", image="i:1", hostname=host,
                updated=now,
                status=S.TOMBSTONE if cur.status == S.ALIVE
                else S.ALIVE))
        final = hub.current()
        grace = threading.Event()
        grace.wait(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        assert not errors, errors

        want = {(h, sid): (svc.updated, svc.status)
                for h, srv in final.servers.items()
                for sid, svc in srv.services.items()}
        for idx, result in enumerate(views):
            assert result is not None, f"w{idx} died"
            view, last_version = result
            assert last_version == final.version, \
                f"w{idx} stopped at v{last_version} != v{final.version}"
            assert view == want, f"w{idx} diverged from final catalog"


class TestWatchHttpEndToEnd:
    """/watch over a real server: versioned snapshot + delta framing,
    contiguous version ranges, and the ?since cursor."""

    @pytest.fixture
    def server(self):
        from sidecar_tpu.web import SidecarApi, serve_http

        state = make_state()
        api = SidecarApi(state, cluster_name="query-test")
        srv = serve_http(api, bind="127.0.0.1", port=0)
        yield state, srv
        srv.shutdown()

    def read_docs(self, resp, want, timeout=5.0):
        """Read chunked /watch docs until ``want`` documents arrived."""
        import time as time_mod
        docs, buf = [], b""
        deadline = time_mod.monotonic() + timeout
        while len(docs) < want and time_mod.monotonic() < deadline:
            data = resp.read1(65536)
            if not data:
                break
            buf += data
            while True:
                brace = buf.find(b"{")
                if brace < 0:
                    break
                depth = 0
                end = -1
                for i in range(brace, len(buf)):
                    if buf[i:i + 1] == b"{":
                        depth += 1
                    elif buf[i:i + 1] == b"}":
                        depth -= 1
                        if depth == 0:
                            end = i + 1
                            break
                if end < 0:
                    break
                docs.append(json.loads(buf[brace:end]))
                buf = buf[end:]
        return docs

    def test_watch_versioned_stream(self, server):
        import urllib.request

        state, srv = server
        port = srv.server_address[1]
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/watch", timeout=10)
        docs = self.read_docs(resp, want=1)
        assert docs and "Snapshot" in docs[0]
        v0 = docs[0]["Version"]
        assert "app0" in docs[0]["Snapshot"]

        state.add_service_entry(S.Service(
            id="fresh", name="app9", image="i:1", hostname="h1",
            updated=T0 + NS, status=S.ALIVE))
        docs = self.read_docs(resp, want=1)
        assert docs, "no delta pushed"
        doc = docs[0]
        assert doc["From"] == v0 + 1
        assert doc["Version"] >= doc["From"]
        assert doc["Deltas"][0]["Service"]["ID"] == "fresh"
        resp.close()

    def test_watch_since_cursor_skips_snapshot(self, server):
        import urllib.request

        state, srv = server
        port = srv.server_address[1]
        current = state.query_hub().current().version
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/watch?since={current}", timeout=10)
        # Let the handler subscribe and evaluate the cursor before the
        # catalog moves — a change that lands first makes the cursor
        # stale, and a stale cursor correctly gets a snapshot instead.
        deadline = threading.Event()
        deadline.wait(0.3)
        state.add_service_entry(S.Service(
            id="only-delta", name="app9", image="i:1", hostname="h1",
            updated=T0 + NS, status=S.ALIVE))
        docs = self.read_docs(resp, want=1)
        assert docs
        # No snapshot document: the cursor was current, so the first
        # document is already the delta.
        assert "Deltas" in docs[0]
        assert docs[0]["From"] == current + 1
        resp.close()

    def test_watch_bad_since_400(self, server):
        import urllib.error
        import urllib.request

        state, srv = server
        port = srv.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/watch?since=banana",
                timeout=10)
        assert exc.value.code == 400


class TestHttpListenerDropOldest:
    def test_drop_oldest_counts_and_keeps_newest(self):
        from sidecar_tpu.catalog.state import ChangeEvent
        from sidecar_tpu.web.api import HttpListener

        listener = HttpListener()
        dropped0 = metrics.counter("web.watch.dropped")
        events = [ChangeEvent(
            service=S.Service(id=f"e{i}", name="w", hostname="h1",
                              updated=T0 + i, status=S.ALIVE),
            previous_status=S.UNKNOWN, time=T0 + i)
            for i in range(55)]
        for ev in events:
            listener.chan().put_nowait(ev)
        assert metrics.counter("web.watch.dropped") - dropped0 == 5
        held = []
        while not listener.chan().empty():
            held.append(listener.chan().get_nowait().service.id)
        # The OLDEST five were evicted; the newest 50 survive in order.
        assert held == [f"e{i}" for i in range(5, 55)]


class TestLagAccounting:
    def test_observe_lag_concurrent_hammer(self):
        """query.hub.lag.max is a high-water mark fed from every
        delivery thread; the old unlocked read-modify-write let racing
        observers regress it.  Hammer from 8 threads and require the
        gauge to equal the TRUE maximum."""
        import random

        state = make_state()
        hub = state.query_hub()
        seqs = []
        for t in range(8):
            rng = random.Random(1000 + t)
            seqs.append([rng.randrange(5000) for _ in range(3000)])
        true_max = max(max(s) for s in seqs)
        barrier = threading.Barrier(len(seqs))

        def run(seq):
            barrier.wait()
            for gap in seq:
                hub._observe_lag(gap)

        threads = [threading.Thread(target=run, args=(s,), daemon=True)
                   for s in seqs]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert hub._max_lag_versions == true_max
        assert metrics.snapshot()["gauges"]["query.hub.lag.max"] \
            == true_max


class TestSubscriberRegistry:
    def test_publish_order_stable_after_mid_close(self):
        """The id-keyed dict registry must keep publish-order iteration
        identical to the old list: insertion order, mid-close removes
        without reordering, re-subscribe appends at the tail."""
        state = make_state()
        hub = state.query_hub()
        subs = {n: hub.subscribe(n, buffer=8, prime=False)
                for n in ("a", "b", "c", "d", "e")}
        subs["c"].close()
        assert [s.name for s in hub._subs.values()] == \
            ["a", "b", "d", "e"]
        subs["f"] = hub.subscribe("f", buffer=8, prime=False)
        assert [s.name for s in hub._subs.values()] == \
            ["a", "b", "d", "e", "f"]
        state.add_service_entry(S.Service(
            id="reg0", name="app0", image="i:1", hostname="h1",
            updated=T0 + NS, status=S.ALIVE))
        for name in ("a", "b", "d", "e", "f"):
            ev = subs[name].get(timeout=1)
            assert ev is not None and ev.kind == "delta", name

    def test_close_is_idempotent(self):
        state = make_state()
        hub = state.query_hub()
        a = hub.subscribe("a", buffer=8, prime=False)
        b = hub.subscribe("b", buffer=8, prime=False)
        a.close()
        a.close()  # second close must be a no-op, not a miscount
        assert hub.subscriber_count() == 1
        assert metrics.snapshot()["gauges"]["query.hub.subscribers"] == 1
        b.close()
        assert hub.subscriber_count() == 0


class TestZeroCopyEncodings:
    def publish_one(self, state, sid="zc0"):
        state.add_service_entry(S.Service(
            id=sid, name="app0", image="i:1", hostname="h1",
            updated=T0 + NS, status=S.ALIVE))

    def test_watch_doc_cached_and_content_identical(self):
        state = make_state()
        snap = state.query_hub().current()
        raw = snap.watch_doc_bytes(False)
        assert raw is snap.watch_doc_bytes(False)
        doc = json.loads(raw)
        assert doc["Version"] == snap.version
        assert doc["Snapshot"] == snap.to_json()
        by = snap.watch_doc_bytes(True)
        assert by is snap.watch_doc_bytes(True)
        assert json.loads(by)["Snapshot"] == snap.by_service_json()

    def test_fanout_shares_one_event_and_one_buffer(self):
        """Publish once with two subscribers: both receive the SAME
        QueryEvent object, and its wire doc is one shared buffer —
        byte-identical to the legacy per-consumer builder."""
        from sidecar_tpu.catalog.url_listener import delta_event_json

        state = make_state()
        hub = state.query_hub()
        s1 = hub.subscribe("s1", buffer=8, prime=False)
        s2 = hub.subscribe("s2", buffer=8, prime=False)
        self.publish_one(state)
        e1, e2 = s1.get(timeout=1), s2.get(timeout=1)
        assert e1 is e2
        assert e1.delta_doc_bytes() is e2.delta_doc_bytes()
        assert e1.change_frag() is e2.change_frag()
        assert e1.delta_doc_bytes() == delta_event_json(e1.version,
                                                        e1.change)

    def test_resync_doc_byte_parity_with_legacy(self):
        from sidecar_tpu.catalog import url_listener as ul

        state = make_state()
        snap = state.query_hub().current()
        legacy = json.dumps({"Version": snap.version,
                             "State": snap.to_json()},
                            separators=(",", ":")).encode()
        assert snap.resync_doc_bytes() == legacy
        # The listener helper serves the cached object, not a copy.
        assert ul.resync_event_json(snap) is snap.resync_doc_bytes()

    def test_one_encode_fill_per_version_many_consumers(self):
        """The acceptance invariant behind the 100k-watcher climb:
        query.encode.count advances once per version no matter how many
        consumers read the buffers, including concurrently."""
        state = make_state()
        hub = state.query_hub()
        subs = [hub.subscribe(f"n{i}", buffer=8, prime=False)
                for i in range(16)]
        count0 = metrics.counter("query.encode.count")
        self.publish_one(state)
        events = [s.get(timeout=1) for s in subs]
        barrier = threading.Barrier(len(events))
        bufs = []

        def read(ev):
            barrier.wait()
            bufs.append(ev.delta_doc_bytes())

        threads = [threading.Thread(target=read, args=(ev,), daemon=True)
                   for ev in events]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert len(set(map(id, bufs))) == 1
        # Exactly ONE fill (the ChangeEvent fragment) for the version.
        assert metrics.counter("query.encode.count") - count0 == 1


def wait_until(cond, timeout=10.0, interval=0.01):
    import time as _time
    deadline = _time.monotonic() + timeout
    while _time.monotonic() < deadline:
        if cond():
            return True
        _time.sleep(interval)
    return cond()


class TestRelayHub:
    def test_relay_subscribers_see_root_versions(self):
        from sidecar_tpu.query import RelayHub

        state = make_state()
        hub = state.query_hub()
        relay = RelayHub(hub, name="r0", poll=0.05)
        try:
            sub = relay.subscribe("leaf")
            prime = sub.get(timeout=1)
            assert prime.kind == "snapshot" and prime.version == 1
            for i in range(3):
                state.add_service_entry(S.Service(
                    id=f"rl{i}", name="app0", image="i:1", hostname="h1",
                    updated=T0 + (i + 1) * NS, status=S.ALIVE))
            versions = []
            while len(versions) < 3:
                ev = sub.get(timeout=2)
                assert ev is not None and ev.kind == "delta"
                assert ev.change.service.id == f"rl{len(versions)}"
                versions.append(ev.version)
            assert versions == [2, 3, 4]
            assert versions[-1] == hub.current().version
        finally:
            relay.close()

    def test_relay_subscribe_primes_from_horizon(self):
        """A subscriber priming mid-stream starts at the relay's
        delivered horizon and the next delta is horizon+1 — contiguous
        by construction, never a gap against the relay-local stream."""
        from sidecar_tpu.query import RelayHub

        state = make_state()
        hub = state.query_hub()
        relay = RelayHub(hub, name="rh", poll=0.05)
        try:
            for i in range(2):
                state.add_service_entry(S.Service(
                    id=f"hz{i}", name="app0", image="i:1", hostname="h1",
                    updated=T0 + (i + 1) * NS, status=S.ALIVE))
            assert wait_until(
                lambda: relay._last.version == hub.current().version)
            sub = relay.subscribe("late")
            prime = sub.get(timeout=1)
            assert prime.kind == "snapshot"
            assert prime.version == hub.current().version
            state.add_service_entry(S.Service(
                id="hz2", name="app0", image="i:1", hostname="h1",
                updated=T0 + 9 * NS, status=S.ALIVE))
            nxt = sub.get(timeout=2)
            assert nxt.kind == "delta"
            assert nxt.version == prime.version + 1
        finally:
            relay.close()

    def test_slow_downstream_coalesces_then_resumes(self):
        from sidecar_tpu.query import RelayHub

        state = make_state()
        hub = state.query_hub()
        relay = RelayHub(hub, name="rc", poll=0.05)
        try:
            sub = relay.subscribe("slow", buffer=1, prime=False)
            for i in range(4):
                state.add_service_entry(S.Service(
                    id=f"sl{i}", name="app0", image="i:1", hostname="h1",
                    updated=T0 + (i + 1) * NS, status=S.ALIVE))
            target = hub.current().version
            assert wait_until(lambda: relay._last.version == target)
            ev = sub.get(timeout=2)
            assert ev.kind == "snapshot" and ev.version == target
            state.add_service_entry(S.Service(
                id="sl9", name="app0", image="i:1", hostname="h1",
                updated=T0 + 9 * NS, status=S.ALIVE))
            nxt = sub.get(timeout=2)
            assert nxt.kind == "delta" and nxt.version == target + 1
        finally:
            relay.close()

    def test_relay_close_semantics_and_gauge(self):
        from sidecar_tpu.query import RelayHub

        state = make_state()
        hub = state.query_hub()
        gauge = lambda: metrics.snapshot()["gauges"].get(  # noqa: E731
            "query.hub.tier.relays", 0)
        g0 = gauge()
        relay = RelayHub(hub, name="gx", poll=0.05)
        assert gauge() == g0 + 1
        sub = relay.subscribe("down")
        relay.close()
        assert gauge() == g0
        assert sub.get(timeout=1) is None and sub.closed
        with pytest.raises(RuntimeError):
            relay.subscribe("late")
        assert hub.subscriber_count() == 0  # parent sub detached

    def test_two_tier_tree_gap_free(self):
        from sidecar_tpu.query import relay_tree

        state = make_state()
        hub = state.query_hub()
        leaves, relays = relay_tree(hub, leaves=4, max_fanout=2,
                                    name="tt")
        try:
            assert len(leaves) == 4 and len(relays) == 6  # 2 mid + 4
            assert hub.subscriber_count() == 2  # only the mid tier
            subs = [leaf.subscribe(f"l{i}", buffer=16, prime=False)
                    for i, leaf in enumerate(leaves)]
            for i in range(5):
                state.add_service_entry(S.Service(
                    id=f"tt{i}", name="app0", image="i:1", hostname="h1",
                    updated=T0 + (i + 1) * NS, status=S.ALIVE))
            for sub in subs:
                versions = []
                while len(versions) < 5:
                    ev = sub.get(timeout=2)
                    assert ev is not None and ev.kind == "delta"
                    versions.append(ev.version)
                assert versions == [2, 3, 4, 5, 6]
        finally:
            for relay in relays:
                relay.close()


@pytest.mark.slow
class TestRelayTierSoak:
    """The ≥10k-subscriber acceptance soak: a two-tier relay tree fans
    one publish stream to 10 000 subscriptions.  Every healthy
    subscriber must see the identical gap-free version sequence; the
    deliberately tiny-buffered minority must coalesce with exact
    drop/coalesce counter accounting; and each version's wire buffer
    must be ONE shared object across all subscribers (zero aliasing
    between versions)."""

    N_SUBS = 10_000
    EVENTS = 12
    TINY_EVERY = 100  # every 100th subscriber gets a 2-slot buffer

    def test_ten_thousand_subscribers_gap_free(self):
        import hashlib

        from sidecar_tpu.query import relay_tree

        state = make_state()
        hub = state.query_hub()
        base = hub.current().version
        target = base + self.EVENTS
        dropped0 = metrics.counter("query.hub.dropped")
        coalesced0 = metrics.counter("query.hub.coalesced")
        leaves, relays = relay_tree(hub, leaves=8, max_fanout=4,
                                    name="soak")
        subs = []
        for i in range(self.N_SUBS):
            tiny = (i % self.TINY_EVERY) == 0
            subs.append(leaves[i % len(leaves)].subscribe(
                f"soak{i}", buffer=2 if tiny else self.EVENTS + 4,
                prime=False))
        for i in range(self.EVENTS):
            state.add_service_entry(S.Service(
                id=f"ev{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
        # All queues are fully populated once every leaf's horizon hits
        # the head; draining after that is non-blocking + deterministic.
        assert wait_until(
            lambda: all(leaf._last.version == target for leaf in leaves),
            timeout=60)
        delta_delivered = 0
        snapshot_delivered = 0
        buf_by_version: dict = {}
        digest_by_version: dict = {}
        try:
            for i, sub in enumerate(subs):
                events = sub.drain()
                tiny = (i % self.TINY_EVERY) == 0
                if tiny:
                    # Collapsed: exactly one marker at the head, every
                    # missed delta subsumed.
                    assert [ev.kind for ev in events] == ["snapshot"], i
                    assert events[0].version == target
                    snapshot_delivered += 1
                    continue
                versions = [ev.version for ev in events]
                assert versions == list(range(base + 1, target + 1)), i
                delta_delivered += len(events)
                for ev in events:
                    buf = ev.delta_doc_bytes()
                    seen = buf_by_version.setdefault(ev.version, buf)
                    # Zero-copy: every subscriber of a version holds
                    # THE SAME buffer object.
                    assert seen is buf, (i, ev.version)
            # No two versions alias one buffer.
            for version, buf in buf_by_version.items():
                digest_by_version[version] = hashlib.sha256(
                    buf).hexdigest()
            assert len(set(digest_by_version.values())) == self.EVENTS
            assert len(set(map(id, buf_by_version.values()))) \
                == self.EVENTS
            # Conservation: every offered event was either delivered as
            # a delta or counted into query.hub.dropped — and each
            # collapse transition produced exactly one marker delivery.
            n_tiny = len(range(0, self.N_SUBS, self.TINY_EVERY))
            dropped = metrics.counter("query.hub.dropped") - dropped0
            coalesced = metrics.counter("query.hub.coalesced") \
                - coalesced0
            assert delta_delivered + dropped \
                == self.EVENTS * self.N_SUBS
            assert delta_delivered \
                == self.EVENTS * (self.N_SUBS - n_tiny)
            assert coalesced == n_tiny
            assert snapshot_delivered == coalesced
        finally:
            for relay in relays:
                relay.close()
