"""Query-plane tests: versioned COW snapshots, the subscription hub's
backpressure/coalescing contract, and the acceptance concurrency run —
N parallel watchers must see identical, gap-free version sequences
while a config6-style chaos schedule mutates the catalog."""

import json
import threading

import pytest

from sidecar_tpu import metrics
from sidecar_tpu import service as S
from sidecar_tpu.catalog import ServicesState

NS = S.NS_PER_SECOND
T0 = 1_700_000_000 * NS


def make_state(services=3):
    state = ServicesState(hostname="h1", cluster_name="query-test")
    state.set_clock(lambda: T0)
    for i in range(services):
        state.add_service_entry(S.Service(
            id=f"svc{i}", name=f"app{i % 2}", image="i:1", hostname="h1",
            updated=T0, status=S.ALIVE,
            ports=[S.Port("tcp", 32768 + i, 8080, "10.0.0.1")]))
    return state


class TestSnapshot:
    def test_attach_builds_version_one(self):
        state = make_state()
        snap = state.query_hub().current()
        assert snap.version == 1
        assert set(snap.servers["h1"].services) == {"svc0", "svc1",
                                                    "svc2"}

    def test_versions_are_dense_and_monotonic(self):
        state = make_state()
        hub = state.query_hub()
        versions = [hub.current().version]
        for i in range(5):
            state.add_service_entry(S.Service(
                id=f"new{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
            versions.append(hub.current().version)
        assert versions == list(range(1, 7))

    def test_snapshots_are_immutable_and_share_structure(self):
        state = make_state()
        hub = state.query_hub()
        state.add_service_entry(S.Service(
            id="zzz", name="other", image="i:1", hostname="h2",
            updated=T0 + NS, status=S.ALIVE))
        before = hub.current()
        h1_view = before.servers["h1"]
        state.add_service_entry(S.Service(
            id="yyy", name="other", image="i:1", hostname="h2",
            updated=T0 + 2 * NS, status=S.ALIVE))
        after = hub.current()
        # The untouched host's view is the SAME object (copy-on-write
        # structural sharing); the old snapshot still shows the old h2.
        assert after.servers["h1"] is h1_view
        assert set(before.servers["h2"].services) == {"zzz"}
        assert set(after.servers["h2"].services) == {"zzz", "yyy"}

    def test_serialization_cached_per_version(self):
        state = make_state()
        snap = state.query_hub().current()
        assert snap.to_json() is snap.to_json()
        assert snap.encode() is snap.encode()
        assert snap.by_service() is snap.by_service()

    def test_by_service_matches_state(self):
        state = make_state()
        snap = state.query_hub().current()
        want = {name: [svc.to_json() for svc in instances]
                for name, instances in state.by_service().items()}
        assert snap.by_service_json() == want

    def test_state_json_parity_plus_version(self):
        state = make_state()
        snap = state.query_hub().current()
        with state._lock:
            want = state.to_json()
        got = dict(snap.to_json())
        assert got.pop("Version") == 1
        assert got == want

    def test_reader_never_takes_state_lock(self):
        """The point of the plane: with the writer wedged on its lock,
        every snapshot read still completes."""
        state = make_state()
        hub = state.query_hub()
        release = threading.Event()
        grabbed = threading.Event()

        def hold_lock():
            with state._lock:
                grabbed.set()
                release.wait(timeout=5)

        t = threading.Thread(target=hold_lock, daemon=True)
        t.start()
        assert grabbed.wait(timeout=5)
        try:
            snap = hub.current()          # must not block
            assert snap.version >= 1
            assert snap.encode()
            assert snap.by_service() is not None
        finally:
            release.set()
            t.join(timeout=5)


class TestHub:
    def test_prime_then_gap_free_deltas(self):
        state = make_state()
        hub = state.query_hub()
        sub = hub.subscribe("t", buffer=64)
        first = sub.get(timeout=1)
        assert first.kind == "snapshot" and first.version == 1
        for i in range(4):
            state.add_service_entry(S.Service(
                id=f"d{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
        versions = []
        while True:
            ev = sub.get(timeout=0.2)
            if ev is None:
                break
            assert ev.kind == "delta"
            assert ev.change.service.id == f"d{len(versions)}"
            versions.append(ev.version)
        assert versions == [2, 3, 4, 5]

    def test_backpressure_coalesces_to_snapshot(self):
        state = make_state()
        hub = state.query_hub()
        sub = hub.subscribe("slow", buffer=2, prime=False)
        dropped0 = metrics.counter("query.hub.dropped")
        coalesced0 = metrics.counter("query.hub.coalesced")
        for i in range(7):
            state.add_service_entry(S.Service(
                id=f"b{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
        events = []
        while True:
            ev = sub.get(timeout=0.2)
            if ev is None:
                break
            events.append(ev)
        # The overflow collapses EVERYTHING (the queued-but-unread
        # deltas included) into one snapshot marker at the LATEST
        # version — the snapshot subsumes them, and every discarded
        # delta is counted.
        assert [ev.kind for ev in events] == ["snapshot"]
        assert events[-1].version == hub.current().version
        assert "b6" in events[-1].snapshot.servers["h1"].services
        assert metrics.counter("query.hub.dropped") - dropped0 == 7
        assert metrics.counter("query.hub.coalesced") - coalesced0 == 1

    def test_delta_flow_resumes_after_resync(self):
        state = make_state()
        hub = state.query_hub()
        sub = hub.subscribe("slow", buffer=1, prime=False)
        for i in range(3):
            state.add_service_entry(S.Service(
                id=f"c{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
        ev = sub.get(timeout=1)
        assert ev.kind == "snapshot"
        resync_version = ev.version
        state.add_service_entry(S.Service(
            id="afterwards", name="app0", image="i:1", hostname="h1",
            updated=T0 + 10 * NS, status=S.ALIVE))
        ev = sub.get(timeout=1)
        assert ev.kind == "delta"
        assert ev.version == resync_version + 1

    def test_close_wakes_blocked_get_and_deregisters(self):
        state = make_state()
        hub = state.query_hub()
        sub = hub.subscribe("t", buffer=4)
        sub.get(timeout=1)  # the priming snapshot
        got = []

        def block():
            got.append(sub.get(timeout=5))

        t = threading.Thread(target=block, daemon=True)
        t.start()
        sub.close()
        t.join(timeout=5)
        assert got == [None]
        assert hub.subscriber_count() == 0

    def test_publish_never_blocks_writer(self):
        """A completely stuck subscriber must not slow the writer path:
        publishing 100 events with a dead 1-slot subscriber stays
        instant (bounded queue + collapse, no waiting)."""
        state = make_state()
        hub = state.query_hub()
        hub.subscribe("dead", buffer=1, prime=False)
        for i in range(100):
            state.add_service_entry(S.Service(
                id=f"w{i}", name="app0", image="i:1", hostname="h1",
                updated=T0 + (i + 1) * NS, status=S.ALIVE))
        assert hub.current().version == 101


class TestConcurrencyUnderChaos:
    """The acceptance run: N parallel watchers, a config6-style chaos
    churn schedule mutating the catalog, every watcher sees the
    identical gap-free version sequence and converges on the same
    final snapshot."""

    N_WATCHERS = 8
    ROUNDS = 40       # churn window (config6 uses rounds 30-60)
    SIDE_A = 4        # churned hosts (config6 churns one side only)

    def test_parallel_watchers_gap_free(self):
        from sidecar_tpu.chaos.plan import FaultPlan, coin

        plan = FaultPlan(seed=6)  # the config6 seed
        state = ServicesState(hostname="n0", cluster_name="chaos")
        state.set_clock(lambda: T0)
        hosts = [f"n{i}" for i in range(8)]
        for hi, host in enumerate(hosts):
            for si in range(4):
                state.add_service_entry(S.Service(
                    id=f"{host}-s{si}", name=f"svc{si}", image="i:1",
                    hostname=host, updated=T0, status=S.ALIVE))
        hub = state.query_hub()
        start_version = hub.current().version

        stop = threading.Event()
        results = [None] * self.N_WATCHERS
        errors = []

        def watcher(idx):
            # Large buffer: this test pins the GAP-FREE delta contract;
            # the coalesce path has its own tests above.
            sub = hub.subscribe(f"w{idx}", buffer=8192, prime=True)
            try:
                first = sub.get(timeout=5)
                if first is None or first.kind != "snapshot":
                    errors.append(f"w{idx}: bad prime {first}")
                    return
                versions = []
                changes = []
                while True:
                    ev = sub.get(timeout=0.5)
                    if ev is None:
                        if stop.is_set():
                            break
                        continue
                    if ev.kind != "delta":
                        errors.append(f"w{idx}: unexpected coalesce")
                        return
                    versions.append(ev.version)
                    changes.append((ev.change.service.id,
                                    ev.change.service.status,
                                    ev.change.service.updated))
                results[idx] = (first.version, versions, changes,
                                sub.pending())
            finally:
                sub.close()

        threads = [threading.Thread(target=watcher, args=(i,),
                                    daemon=True)
                   for i in range(self.N_WATCHERS)]
        for t in threads:
            t.start()

        # The chaos writer: config6's one-sided Bernoulli churn recast
        # onto the live catalog — every flip decision is the plan's
        # deterministic coin, so the schedule replays from the seed.
        now = T0
        for rnd in range(self.ROUNDS):
            now += NS // 5  # one 200 ms gossip round
            for hi in range(self.SIDE_A):
                for si in range(4):
                    if coin(plan.seed, "churn", rnd, hi, si) < 0.1:
                        host = hosts[hi]
                        sid = f"{host}-s{si}"
                        cur = state.servers[host].services[sid]
                        new_status = (S.TOMBSTONE
                                      if cur.status == S.ALIVE
                                      else S.ALIVE)
                        state.add_service_entry(S.Service(
                            id=sid, name=f"svc{si}", image="i:1",
                            hostname=host, updated=now,
                            status=new_status))
        final_version = hub.current().version
        n_changes = final_version - start_version
        assert n_changes > 20, "chaos schedule produced too few changes"

        # Let every watcher drain, then stop them.
        deadline = threading.Event()
        deadline.wait(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors
        assert all(r is not None for r in results)

        expect_versions = list(range(start_version + 1,
                                     final_version + 1))
        first = results[0]
        for idx, (prime_v, versions, changes, pending) in \
                enumerate(results):
            assert pending == 0, f"w{idx} did not drain"
            assert prime_v == start_version
            # Gap-free: exactly the dense version range.
            assert versions == expect_versions, \
                f"w{idx} saw gaps: {len(versions)} vs {n_changes}"
            # Identical: byte-for-byte the same change sequence.
            assert changes == first[2], f"w{idx} diverged"

        # And the final snapshot equals the live catalog.
        snap = hub.current()
        with state._lock:
            for host, server in state.servers.items():
                got = snap.servers[host].services
                assert set(got) == set(server.services)
                for sid, svc in server.services.items():
                    assert got[sid].status == svc.status
                    assert got[sid].updated == svc.updated


@pytest.mark.slow
class TestConcurrencySoak:
    """Soak variant (slow marker): watchers with TINY buffers and
    random stalls, so the coalesce path fires constantly — every
    watcher must still reconstruct the exact final catalog from its
    mix of snapshots and deltas, with versions non-decreasing and
    delta runs contiguous after each resync."""

    def test_slow_watchers_converge_via_resync(self):
        import random

        state = ServicesState(hostname="n0", cluster_name="soak")
        state.set_clock(lambda: T0)
        hosts = [f"n{i}" for i in range(6)]
        for host in hosts:
            for si in range(3):
                state.add_service_entry(S.Service(
                    id=f"{host}-s{si}", name=f"svc{si}", image="i:1",
                    hostname=host, updated=T0, status=S.ALIVE))
        hub = state.query_hub()
        stop = threading.Event()
        errors = []
        views = [None] * 6

        def watcher(idx):
            rng = random.Random(idx)
            sub = hub.subscribe(f"soak{idx}", buffer=4, prime=True)
            view = {}
            last_version = 0
            expect_next = None  # None = just resynced, any version ok
            try:
                while True:
                    ev = sub.get(timeout=0.5)
                    if ev is None:
                        if stop.is_set() and sub.pending() == 0:
                            break
                        continue
                    if ev.version < last_version:
                        errors.append(f"w{idx}: version regressed")
                        return
                    if ev.kind == "snapshot":
                        view = {
                            (h, sid): (svc.updated, svc.status)
                            for h, srv in ev.snapshot.servers.items()
                            for sid, svc in srv.services.items()}
                        expect_next = ev.version + 1
                    else:
                        if expect_next is not None and \
                                ev.version != expect_next:
                            errors.append(
                                f"w{idx}: delta gap {expect_next} -> "
                                f"{ev.version} without resync")
                            return
                        expect_next = ev.version + 1
                        svc = ev.change.service
                        view[(svc.hostname, svc.id)] = (svc.updated,
                                                        svc.status)
                    last_version = ev.version
                    if rng.random() < 0.05:
                        stall = threading.Event()
                        stall.wait(rng.random() * 0.02)  # fall behind
                views[idx] = (view, last_version)
            finally:
                sub.close()

        threads = [threading.Thread(target=watcher, args=(i,),
                                    daemon=True) for i in range(6)]
        for t in threads:
            t.start()

        rng = random.Random(99)
        now = T0
        for _ in range(600):
            now += NS // 50
            host = hosts[rng.randrange(len(hosts))]
            si = rng.randrange(3)
            sid = f"{host}-s{si}"
            cur = state.servers[host].services[sid]
            state.add_service_entry(S.Service(
                id=sid, name=f"svc{si}", image="i:1", hostname=host,
                updated=now,
                status=S.TOMBSTONE if cur.status == S.ALIVE
                else S.ALIVE))
        final = hub.current()
        grace = threading.Event()
        grace.wait(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        assert not errors, errors

        want = {(h, sid): (svc.updated, svc.status)
                for h, srv in final.servers.items()
                for sid, svc in srv.services.items()}
        for idx, result in enumerate(views):
            assert result is not None, f"w{idx} died"
            view, last_version = result
            assert last_version == final.version, \
                f"w{idx} stopped at v{last_version} != v{final.version}"
            assert view == want, f"w{idx} diverged from final catalog"


class TestWatchHttpEndToEnd:
    """/watch over a real server: versioned snapshot + delta framing,
    contiguous version ranges, and the ?since cursor."""

    @pytest.fixture
    def server(self):
        from sidecar_tpu.web import SidecarApi, serve_http

        state = make_state()
        api = SidecarApi(state, cluster_name="query-test")
        srv = serve_http(api, bind="127.0.0.1", port=0)
        yield state, srv
        srv.shutdown()

    def read_docs(self, resp, want, timeout=5.0):
        """Read chunked /watch docs until ``want`` documents arrived."""
        import time as time_mod
        docs, buf = [], b""
        deadline = time_mod.monotonic() + timeout
        while len(docs) < want and time_mod.monotonic() < deadline:
            data = resp.read1(65536)
            if not data:
                break
            buf += data
            while True:
                brace = buf.find(b"{")
                if brace < 0:
                    break
                depth = 0
                end = -1
                for i in range(brace, len(buf)):
                    if buf[i:i + 1] == b"{":
                        depth += 1
                    elif buf[i:i + 1] == b"}":
                        depth -= 1
                        if depth == 0:
                            end = i + 1
                            break
                if end < 0:
                    break
                docs.append(json.loads(buf[brace:end]))
                buf = buf[end:]
        return docs

    def test_watch_versioned_stream(self, server):
        import urllib.request

        state, srv = server
        port = srv.server_address[1]
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/watch", timeout=10)
        docs = self.read_docs(resp, want=1)
        assert docs and "Snapshot" in docs[0]
        v0 = docs[0]["Version"]
        assert "app0" in docs[0]["Snapshot"]

        state.add_service_entry(S.Service(
            id="fresh", name="app9", image="i:1", hostname="h1",
            updated=T0 + NS, status=S.ALIVE))
        docs = self.read_docs(resp, want=1)
        assert docs, "no delta pushed"
        doc = docs[0]
        assert doc["From"] == v0 + 1
        assert doc["Version"] >= doc["From"]
        assert doc["Deltas"][0]["Service"]["ID"] == "fresh"
        resp.close()

    def test_watch_since_cursor_skips_snapshot(self, server):
        import urllib.request

        state, srv = server
        port = srv.server_address[1]
        current = state.query_hub().current().version
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/watch?since={current}", timeout=10)
        # Let the handler subscribe and evaluate the cursor before the
        # catalog moves — a change that lands first makes the cursor
        # stale, and a stale cursor correctly gets a snapshot instead.
        deadline = threading.Event()
        deadline.wait(0.3)
        state.add_service_entry(S.Service(
            id="only-delta", name="app9", image="i:1", hostname="h1",
            updated=T0 + NS, status=S.ALIVE))
        docs = self.read_docs(resp, want=1)
        assert docs
        # No snapshot document: the cursor was current, so the first
        # document is already the delta.
        assert "Deltas" in docs[0]
        assert docs[0]["From"] == current + 1
        resp.close()

    def test_watch_bad_since_400(self, server):
        import urllib.error
        import urllib.request

        state, srv = server
        port = srv.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/watch?since=banana",
                timeout=10)
        assert exc.value.code == 400


class TestHttpListenerDropOldest:
    def test_drop_oldest_counts_and_keeps_newest(self):
        from sidecar_tpu.catalog.state import ChangeEvent
        from sidecar_tpu.web.api import HttpListener

        listener = HttpListener()
        dropped0 = metrics.counter("web.watch.dropped")
        events = [ChangeEvent(
            service=S.Service(id=f"e{i}", name="w", hostname="h1",
                              updated=T0 + i, status=S.ALIVE),
            previous_status=S.UNKNOWN, time=T0 + i)
            for i in range(55)]
        for ev in events:
            listener.chan().put_nowait(ev)
        assert metrics.counter("web.watch.dropped") - dropped0 == 5
        held = []
        while not listener.chan().empty():
            held.append(listener.chan().get_nowait().service.id)
        # The OLDEST five were evicted; the newest 50 survive in order.
        assert held == [f"e{i}" for i in range(5, 55)]
