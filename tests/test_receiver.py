"""UrlListener + receiver tests — outbound POSTs captured by a local
HTTP server (the reference's httpmock technique, url_listener_test.go)
and the ShouldNotify transition table (receiver_test.go)."""

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from sidecar_tpu import service as S
from sidecar_tpu.catalog import ServicesState
from sidecar_tpu.catalog.state import ChangeEvent
from sidecar_tpu.catalog.url_listener import (
    UrlListener,
    state_changed_event_json,
    with_retries,
)
from sidecar_tpu.receiver import (
    Receiver,
    should_notify,
    update_handler,
)
from sidecar_tpu.runtime.looper import FreeLooper

NS = S.NS_PER_SECOND
T0 = 1_700_000_000 * NS


def make_state():
    state = ServicesState(hostname="h1")
    state.set_clock(lambda: T0)
    state.add_service_entry(S.Service(
        id="aaa", name="web", image="i:1", hostname="h1", updated=T0,
        status=S.ALIVE))
    return state


def make_event(status=S.ALIVE, previous=S.UNKNOWN, updated=T0, name="web"):
    return ChangeEvent(
        service=S.Service(id="aaa", name=name, hostname="h1",
                          updated=updated, status=status),
        previous_status=previous, time=updated)


class CapturingServer:
    """Captures POST bodies; optionally fails the first N requests."""

    def __init__(self, fail_first=0, status=200):
        self.posts = queue.Queue()
        self.fail_remaining = fail_first
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length)
                if outer.fail_remaining > 0:
                    outer.fail_remaining -= 1
                    self.send_response(500)
                else:
                    outer.posts.put((self.path, dict(self.headers), body))
                    self.send_response(status)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server.server_address[1]}/update"

    def shutdown(self):
        self.server.shutdown()


class TestWithRetries:
    def test_succeeds_eventually(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("nope")

        assert with_retries(5, flaky) is None
        assert len(calls) == 3

    def test_gives_up(self):
        def always():
            raise OSError("nope")

        err = with_retries(2, always)
        assert isinstance(err, OSError)

    def test_linear_backoff_schedule(self):
        """The documented linear backoff, pinned against a fake clock:
        the FIRST retry must already back off (the old schedule slept
        0.1 * 0 = 0 s before it, hammering the failed endpoint
        immediately), and each later retry backs off one unit more.
        No sleep after the final failure."""
        slept = []

        def always():
            raise OSError("nope")

        err = with_retries(4, always, sleep=slept.append)
        assert isinstance(err, OSError)
        assert slept == pytest.approx([0.1, 0.2, 0.3, 0.4])

    def test_no_sleep_on_first_try_success(self):
        slept = []
        assert with_retries(3, lambda: None, sleep=slept.append) is None
        assert slept == []

    def test_backoff_stops_at_success(self):
        slept = []
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("nope")

        assert with_retries(5, flaky, sleep=slept.append) is None
        # Two failures → two backoffs (before retries 1 and 2), then
        # the third attempt succeeds with no further sleeping.
        assert slept == pytest.approx([0.1, 0.2])


class TestUrlListener:
    def test_posts_delta_event(self):
        """UrlListener is hub-driven (docs/query.md): one versioned
        delta document per change, no full-state dump."""
        server = CapturingServer()
        try:
            state = make_state()
            listener = UrlListener(server.url)
            listener.watch(state)
            state.notify_listeners(
                state.servers["h1"].services["aaa"], S.UNKNOWN, T0)
            path, headers, body = server.posts.get(timeout=5)
            doc = json.loads(body)
            assert set(doc) == {"Version", "ChangeEvent"}
            assert doc["Version"] == state.query_hub().current().version
            assert doc["ChangeEvent"]["Service"]["ID"] == "aaa"
            assert "sidecar-session-host=" in headers.get("Cookie", "")
            listener.stop()
        finally:
            server.shutdown()

    def test_retries_500s(self):
        server = CapturingServer(fail_first=2)
        try:
            state = make_state()
            listener = UrlListener(server.url)
            listener.watch(state)
            state.notify_listeners(
                state.servers["h1"].services["aaa"], S.UNKNOWN, T0)
            path, _, body = server.posts.get(timeout=10)
            assert json.loads(body)["ChangeEvent"]["PreviousStatus"] == \
                S.UNKNOWN
            listener.stop()
        finally:
            server.shutdown()

    def test_coalesces_to_resync_when_behind(self):
        """A stalled subscriber's backlog collapses to ONE full-state
        resync document at the latest version (the hub's backpressure
        rule) instead of a POST per missed event."""
        server = CapturingServer()
        try:
            state = make_state()
            listener = UrlListener(server.url)
            sub = state.query_hub().subscribe(listener.name(), buffer=2,
                                              prime=False)
            listener._sub = sub  # tiny buffer: overflow after 2 events
            # Burst past the buffer BEFORE any drain thread runs.
            for i in range(6):
                state.add_service_entry(S.Service(
                    id=f"b{i}", name="web", image="i:1", hostname="h1",
                    updated=T0 + (i + 1) * NS, status=S.ALIVE))
            events = []
            while True:
                ev = sub.get(timeout=0.2)
                if ev is None:
                    break
                events.append(ev)
            kinds = [ev.kind for ev in events]
            assert "snapshot" in kinds  # the collapse marker
            # The resync document is the full state at latest version.
            from sidecar_tpu.catalog.url_listener import resync_event_json
            snap_ev = [ev for ev in events if ev.kind == "snapshot"][-1]
            doc = json.loads(resync_event_json(snap_ev.snapshot))
            assert set(doc) == {"Version", "State"}
            assert doc["Version"] == snap_ev.version
            assert "b5" in doc["State"]["Servers"]["h1"]["Services"]
        finally:
            server.shutdown()

    def test_managed_lifecycle_registry(self):
        """Hub-driven listeners still register in the state's listener
        registry so track_local_listeners add/remove keeps working."""
        state = make_state()
        listener = UrlListener("http://127.0.0.1:1/x", managed=True)
        listener.watch(state)
        assert any(li.name() == listener.name()
                   for li in state.get_listeners())
        listener.stop()
        state.remove_listener(listener.name())
        assert not any(li.name() == listener.name()
                       for li in state.get_listeners())

    def test_wire_shapes(self):
        # Legacy full StateChangedEvent (kept for old consumers) —
        # served from the hub snapshot, no state lock.
        state = make_state()
        state.query_hub()
        data = state_changed_event_json(state, make_event())
        doc = json.loads(data)
        assert set(doc) == {"State", "ChangeEvent"}
        assert set(doc["ChangeEvent"]) == {"Service", "PreviousStatus",
                                           "Time"}
        # Delta shape.
        from sidecar_tpu.catalog.url_listener import delta_event_json
        doc = json.loads(delta_event_json(7, make_event()))
        assert set(doc) == {"Version", "ChangeEvent"}
        assert doc["Version"] == 7


class TestShouldNotify:
    @pytest.mark.parametrize("old,new,want", [
        (S.UNKNOWN, S.ALIVE, True),
        (S.ALIVE, S.TOMBSTONE, True),
        (S.ALIVE, S.DRAINING, True),
        (S.ALIVE, S.UNHEALTHY, True),
        (S.ALIVE, S.UNKNOWN, True),
        (S.UNHEALTHY, S.UNKNOWN, False),
        (S.TOMBSTONE, S.UNHEALTHY, False),
        (S.UNKNOWN, 99, False),
    ])
    def test_transition_table(self, old, new, want):
        assert should_notify(old, new) == want


class TestReceiver:
    def payload(self, state, event):
        return state_changed_event_json(state, event)

    def test_accepts_newer_state(self):
        rcvr = Receiver(on_update=lambda s: None)
        state = make_state()
        status, _ = update_handler(
            rcvr, self.payload(state, make_event()))
        assert status == 200
        assert rcvr.current_state is not None
        assert rcvr.current_state.servers["h1"].services["aaa"].name == "web"
        assert rcvr.reload_chan.qsize() == 1

    def test_rejects_older_state(self):
        rcvr = Receiver(on_update=lambda s: None)
        newer = make_state()
        newer.last_changed = T0 + NS
        update_handler(rcvr, self.payload(newer, make_event()))
        older = make_state()
        older.last_changed = T0
        update_handler(rcvr, self.payload(older, make_event()))
        assert rcvr.current_state.last_changed == T0 + NS
        assert rcvr.reload_chan.qsize() == 1  # only the first enqueued

    def test_subscription_filter(self):
        rcvr = Receiver(on_update=lambda s: None)
        rcvr.subscribe("other")
        state = make_state()
        state.last_changed = T0 + NS
        update_handler(rcvr, self.payload(state, make_event(name="web")))
        assert rcvr.reload_chan.qsize() == 0
        state.last_changed = T0 + 2 * NS
        update_handler(rcvr, self.payload(state, make_event(name="other")))
        assert rcvr.reload_chan.qsize() == 1

    def test_insignificant_transition_not_enqueued(self):
        rcvr = Receiver(on_update=lambda s: None)
        state = make_state()
        state.last_changed = T0 + NS
        update_handler(rcvr, self.payload(
            state, make_event(status=S.UNKNOWN, previous=S.UNHEALTHY)))
        assert rcvr.reload_chan.qsize() == 0
        assert rcvr.current_state is not None  # state still kept

    def test_bad_payload_500(self):
        status, body = update_handler(Receiver(), b"{not json")
        assert status == 500
        assert json.loads(body)["errors"]

    def test_process_updates_batches(self):
        seen = []
        rcvr = Receiver(on_update=lambda s: seen.append(s),
                        looper=FreeLooper(1))
        state = make_state()
        update_handler(rcvr, self.payload(state, make_event()))
        rcvr.enqueue_update()
        rcvr.enqueue_update()  # burst of 3 → one callback
        rcvr.process_updates()
        assert len(seen) == 1
        assert seen[0].servers["h1"].services["aaa"].name == "web"
        assert rcvr.reload_chan.qsize() == 0

    def test_fetch_initial_state(self):
        from sidecar_tpu.web import SidecarApi, serve_http
        state = make_state()
        api = SidecarApi(state)
        srv = serve_http(api, bind="127.0.0.1", port=0)
        try:
            port = srv.server_address[1]
            seen = []
            rcvr = Receiver(on_update=lambda s: seen.append(s))
            rcvr.fetch_initial_state(
                f"http://127.0.0.1:{port}/api/state.json")
            assert rcvr.current_state.servers["h1"].services["aaa"].id == \
                "aaa"
            assert len(seen) == 1
        finally:
            srv.shutdown()


class TestReceiverDeltaPath:
    """The query-plane wire (docs/query.md): versioned deltas merge into
    the local mirror; resync documents replace it."""

    def delta(self, version, **kw):
        return json.dumps({"Version": version,
                           "ChangeEvent": make_event(**kw).to_json()}
                          ).encode()

    def test_applies_delta(self):
        rcvr = Receiver(on_update=lambda s: None)
        status, _ = update_handler(rcvr, self.delta(2, updated=T0 + NS))
        assert status == 200
        assert rcvr.last_version == 2
        svc = rcvr.current_state.servers["h1"].services["aaa"]
        assert svc.name == "web" and svc.updated == T0 + NS
        assert rcvr.reload_chan.qsize() == 1

    def test_duplicate_replay_is_idempotent_no_reload(self):
        """The version cursor never gates: replays flow through the
        record-level LWW, which makes them no-ops — and a no-op must
        not enqueue a reload."""
        rcvr = Receiver(on_update=lambda s: None)
        update_handler(rcvr, self.delta(3, updated=T0 + NS))
        assert rcvr.reload_chan.qsize() == 1
        update_handler(rcvr, self.delta(3, updated=T0 + NS))  # replay
        assert rcvr.last_version == 3
        assert rcvr.reload_chan.qsize() == 1  # no duplicate reload
        assert rcvr.current_state.servers["h1"].services["aaa"].updated \
            == T0 + NS

    def test_sender_restart_resets_version_epoch(self):
        """A restarted sender's hub restarts at version 1; the receiver
        must keep applying (record LWW decides), not wedge on its old
        high-water cursor."""
        rcvr = Receiver(on_update=lambda s: None)
        update_handler(rcvr, self.delta(500, updated=T0 + NS))
        assert rcvr.last_version == 500
        # New epoch: version 2 but a genuinely newer record.
        update_handler(rcvr, self.delta(2, updated=T0 + 5 * NS,
                                        status=S.TOMBSTONE,
                                        previous=S.ALIVE))
        assert rcvr.current_state.servers["h1"].services["aaa"].status \
            == S.TOMBSTONE
        assert rcvr.reload_chan.qsize() == 2

    def test_gap_is_safe_lww(self):
        """A missed version is staleness, not corruption: each delta
        carries the full record, so merging across a gap keeps the
        mirror consistent."""
        rcvr = Receiver(on_update=lambda s: None)
        update_handler(rcvr, self.delta(2, updated=T0 + NS))
        update_handler(rcvr, self.delta(9, updated=T0 + 5 * NS,
                                        status=S.TOMBSTONE,
                                        previous=S.ALIVE))
        assert rcvr.last_version == 9
        assert rcvr.current_state.servers["h1"].services["aaa"].status \
            == S.TOMBSTONE

    def test_older_record_does_not_regress_mirror(self):
        rcvr = Receiver(on_update=lambda s: None)
        update_handler(rcvr, self.delta(2, updated=T0 + 5 * NS))
        update_handler(rcvr, self.delta(3, updated=T0 + NS))
        assert rcvr.current_state.servers["h1"].services["aaa"].updated \
            == T0 + 5 * NS

    def test_resync_document_replaces_mirror(self):
        seen = []
        rcvr = Receiver(on_update=lambda s: seen.append(s))
        update_handler(rcvr, self.delta(2, updated=T0 + NS))
        state = make_state()
        state.last_changed = T0 + 10 * NS
        snap = state.query_hub().current()
        from sidecar_tpu.catalog.url_listener import resync_event_json
        status, _ = update_handler(rcvr, resync_event_json(snap))
        assert status == 200
        assert rcvr.current_state.last_changed == T0 + 10 * NS
        assert rcvr.reload_chan.qsize() >= 1

    def test_empty_document_rejected_not_empty_resync(self):
        """A document with neither State nor ChangeEvent is malformed
        untrusted input — 500, never an 'empty resync' that would wipe
        the mirror and regenerate config from nothing."""
        rcvr = Receiver(on_update=lambda s: None)
        status, body = update_handler(rcvr, b"{}")
        assert status == 500
        assert rcvr.current_state is None
        assert rcvr.reload_chan.qsize() == 0

    def test_delta_without_version_rejected(self):
        rcvr = Receiver(on_update=lambda s: None)
        status, body = update_handler(
            rcvr, json.dumps({"ChangeEvent":
                              make_event().to_json()}).encode())
        assert status == 500
        assert json.loads(body)["errors"]

    def test_insignificant_delta_not_enqueued(self):
        rcvr = Receiver(on_update=lambda s: None)
        status, _ = update_handler(rcvr, self.delta(
            2, updated=T0 + NS, status=S.UNKNOWN, previous=S.UNHEALTHY))
        assert status == 200
        assert rcvr.reload_chan.qsize() == 0
        assert rcvr.current_state is not None  # still recorded


def test_update_handler_rejects_non_object_payloads():
    """POSTed bodies are untrusted; non-object JSON at any level must
    produce a clean 500 from the wrapper, not an uncaught
    AttributeError that kills the consumer's HTTP handler."""
    from sidecar_tpu.receiver.receiver import Receiver, update_handler

    rcvr = Receiver()
    for payload in (b"[1, 2]", b'"str"', b"5",
                    b'{"State": 5}', b'{"ChangeEvent": [1]}',
                    b'{"ChangeEvent": {"Service": 5}}',
                    b'{"ChangeEvent": {"Service": {"Ports": [5]}}}'):
        status, _doc = update_handler(rcvr, payload)
        assert status == 500, payload
