"""Scenario + checkpoint tests: small-scale versions of the BASELINE
configs, and exact chunked-resume equivalence."""

import dataclasses

import jax
import numpy as np
import pytest

from sidecar_tpu.models.exact import ExactSim, SimParams
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import topology
from sidecar_tpu.sim import scenarios
from sidecar_tpu.sim.checkpoint import load_state, save_state

FAST = TimeConfig(refresh_interval_s=1000.0, push_pull_interval_s=2.0)


class TestScenarios:
    def test_config1_trivially_converged(self):
        result = scenarios.config1_static_merge()
        assert result.convergence[-1] == 1.0
        assert result.eps_round == 1

    def test_config2_ring_converges(self):
        result = scenarios.config2_ring(rounds=120)
        assert result.convergence[-1] == 1.0
        assert result.eps_round is not None
        assert result.eps_seconds_simulated == pytest.approx(
            result.eps_round * 0.2)

    def test_config3_er_churn_small(self):
        result = scenarios.config3_er_churn(rounds=120, scale=0.02)
        assert result.n == 81 or result.n == 64  # max(64, 4096*0.02)
        assert result.scaled_from == 4096
        # Churn chases a moving target; should still be near-converged.
        assert result.convergence[-1] > 0.95

    def test_config4_ba_small(self):
        result = scenarios.config4_ba_antientropy(rounds=250, scale=0.002)
        assert result.scaled_from == 65_536
        # Compressed model: churn burst must fully drain; ε (0.1%) is
        # scaled to the 1% churn magnitude and must be genuinely reached
        # (not at round 1 — the burst starts ~1% behind).
        assert result.eps_round is not None and result.eps_round > 1
        assert result.convergence[-1] == 1.0

    def test_config4_ba_small_sharded(self):
        """config4 on the multi-device twin: same drain-to-convergence
        contract on the 8-device virtual mesh."""
        result = scenarios.config4_ba_antientropy(rounds=250, scale=0.002,
                                                  sharded=True)
        assert result.scaled_from == 65_536
        assert "sharded" in result.notes
        assert result.eps_round is not None and result.eps_round > 1
        assert result.convergence[-1] == 1.0

    def test_config5_split_heal_small_sharded(self):
        """config5 on the multi-device twin: split holds, heal drains;
        the mesh side is bumped so n divides the 8-device mesh."""
        result = scenarios.config5_split_heal(
            split_rounds=80, heal_rounds=320, scale=0.0001, sharded=True)
        assert result.scaled_from == 1_000_000
        assert result.n % 8 == 0
        assert result.convergence[:80].max() < 1.0
        assert result.convergence[-1] == 1.0

    def test_config5_split_heal_small(self):
        result = scenarios.config5_split_heal(
            split_rounds=80, heal_rounds=320, scale=0.0001)
        assert result.scaled_from == 1_000_000
        # While split, the one-side churn must NOT drain (cross-side
        # gossip and anti-entropy are severed); healing completes it.
        split_part = result.convergence[:80]
        assert split_part.max() < 1.0
        assert result.eps_round is not None
        assert result.eps_round > 80  # ε reached only after the heal
        assert result.convergence[-1] == 1.0


class TestCheckpoint:
    def make_sim(self):
        params = SimParams(n=8, services_per_node=3, fanout=2, budget=6)
        return ExactSim(params, topology.ring(8), FAST)

    def test_round_trip(self, tmp_path):
        sim = self.make_sim()
        state = sim.run_fast(sim.init_state(), jax.random.PRNGKey(0), 10)
        path = tmp_path / "ckpt.npz"
        save_state(path, state, sim.p)
        loaded, params = load_state(path)
        assert params == sim.p
        np.testing.assert_array_equal(np.asarray(loaded.known),
                                      np.asarray(state.known))
        assert int(loaded.round_idx) == 10

    def test_chunked_resume_equals_straight_run(self, tmp_path):
        sim = self.make_sim()
        key = jax.random.PRNGKey(7)

        straight = sim.run_fast(sim.init_state(), key, 30)

        half = sim.run_fast(sim.init_state(), key, 15)
        save_state(tmp_path / "mid.npz", half, sim.p)
        resumed_state, params = load_state(tmp_path / "mid.npz")
        sim2 = ExactSim(params, topology.ring(8), FAST)
        resumed = sim2.run_fast(resumed_state, key, 15)

        np.testing.assert_array_equal(np.asarray(straight.known),
                                      np.asarray(resumed.known))
        np.testing.assert_array_equal(np.asarray(straight.sent),
                                      np.asarray(resumed.sent))

    def test_shape_mismatch_rejected(self, tmp_path):
        sim = self.make_sim()
        state = sim.init_state()
        bad = dataclasses.replace(
            state, known=state.known[:, :4], sent=state.sent[:, :4])
        save_state(tmp_path / "bad.npz", bad, sim.p)
        with pytest.raises(ValueError, match="shape"):
            load_state(tmp_path / "bad.npz")

    def test_compressed_round_trip_and_resume(self, tmp_path):
        """The north-star model checkpoints too: round trip + exact
        chunked resume through a save/load boundary."""
        import jax.numpy as jnp

        from sidecar_tpu.models.compressed import (
            CompressedParams,
            CompressedSim,
        )

        p = CompressedParams(n=32, services_per_node=4, cache_lines=64)
        sim = CompressedSim(p, topology.complete(32), FAST)
        st = sim.mint(sim.init_state(),
                      jnp.arange(8, dtype=jnp.int32) * 3, 10)
        key = jax.random.PRNGKey(11)

        straight = sim.run_fast(st, key, 30, donate=False)

        half = sim.run_fast(st, key, 14)
        save_state(tmp_path / "c.npz", half, sim.p)
        loaded, params = load_state(tmp_path / "c.npz")
        assert params == sim.p
        sim2 = CompressedSim(params, topology.complete(32), FAST)
        resumed = sim2.run_fast(loaded, key, 16)
        for f in ("own", "cache_slot", "cache_val", "cache_sent",
                  "floor", "round_idx"):
            np.testing.assert_array_equal(
                np.asarray(getattr(straight, f)),
                np.asarray(getattr(resumed, f)), err_msg=f)

    def test_version1_file_loads(self, tmp_path):
        """The exact v1 on-disk format (pre-compressed-support) keeps
        loading: hand-write a file the way the old code did."""
        import json as json_mod

        sim = self.make_sim()
        state = sim.run_fast(sim.init_state(), jax.random.PRNGKey(2), 4)
        np.savez_compressed(
            tmp_path / "v1.npz",
            version=1,
            known=np.asarray(state.known),
            sent=np.asarray(state.sent),
            node_alive=np.asarray(state.node_alive),
            round_idx=np.asarray(state.round_idx),
            params=json_mod.dumps(dataclasses.asdict(sim.p)),
        )
        loaded, params = load_state(tmp_path / "v1.npz")
        assert params == sim.p
        np.testing.assert_array_equal(np.asarray(loaded.known),
                                      np.asarray(state.known))
        assert int(loaded.round_idx) == 4

    def test_mismatched_params_class_rejected(self, tmp_path):
        sim = self.make_sim()
        from sidecar_tpu.models.compressed import CompressedParams

        with pytest.raises(TypeError, match="must be saved with"):
            save_state(tmp_path / "x.npz", sim.init_state(),
                       CompressedParams(n=8))

    def test_stale_cache_layout_rejected(self, tmp_path):
        """A compressed checkpoint whose cache entries sit on lines the
        CURRENT hash_line does not assign them (the pre-owner-run-layout
        format) must fail LOUDLY on load: resuming it would plant
        duplicate records per slot and undercount the census (ADVICE.md
        r5 medium).  Valid checkpoints (previous test) load unchanged."""
        import dataclasses as dc

        import jax.numpy as jnp

        from sidecar_tpu.models.compressed import (
            CompressedParams,
            CompressedSim,
            hash_line,
        )

        p = CompressedParams(n=16, services_per_node=4, cache_lines=64)
        sim = CompressedSim(p, topology.complete(16), FAST)
        st = sim.mint(sim.init_state(),
                      jnp.arange(4, dtype=jnp.int32) * 5, 10)
        st = sim.run_fast(st, jax.random.PRNGKey(3), 4)
        occupied = np.argwhere(np.asarray(st.cache_slot) >= 0)
        assert occupied.size, "workload produced no cache entries"
        node, line = occupied[0]
        slot = int(np.asarray(st.cache_slot)[node, line])
        wrong = (int(hash_line(jnp.int32(slot), p.cache_lines,
                               p.services_per_node)) + 1) % p.cache_lines
        cs = np.asarray(st.cache_slot).copy()
        cv = np.asarray(st.cache_val).copy()
        cs[node, wrong], cv[node, wrong] = slot, cv[node, line]
        cs[node, line], cv[node, line] = -1, 0
        bad = dc.replace(st, cache_slot=jnp.asarray(cs),
                         cache_val=jnp.asarray(cv))
        save_state(tmp_path / "stale.npz", bad, p)
        with pytest.raises(ValueError, match="cache layout mismatch"):
            load_state(tmp_path / "stale.npz")


class TestRegistration:
    """Round-10 satellite: scenario configs are validated at
    REGISTRATION — duplicate names and out-of-range fanout/transmit
    values fail with a named error, not a mid-scan shape failure."""

    def test_builtin_scenarios_registered(self):
        for name in ("config1", "config2", "config3", "config4",
                     "config5", "config6"):
            assert name in scenarios.ALL_SCENARIOS

    def test_duplicate_name_rejected(self):
        with pytest.raises(ValueError, match="already registered"):
            scenarios.register_scenario("config1", lambda: None)

    def test_replace_is_explicit(self):
        original = scenarios.ALL_SCENARIOS["config1"]
        try:
            scenarios.register_scenario("config1", original,
                                        replace=True)
        finally:
            scenarios.ALL_SCENARIOS["config1"] = original

    def test_non_callable_rejected(self):
        with pytest.raises(TypeError, match="callable"):
            scenarios.register_scenario("bogus", 42)

    def test_fanout_out_of_range(self):
        with pytest.raises(ValueError, match="fanout=16 must be < n=16"):
            scenarios.validate_protocol_config(16, fanout=16, budget=5)
        with pytest.raises(ValueError, match="fanout=0"):
            scenarios.validate_protocol_config(16, fanout=0, budget=5)

    def test_transmit_limit_out_of_range(self):
        with pytest.raises(ValueError, match="int8 transmit"):
            scenarios.validate_protocol_config(
                16, fanout=3, budget=5, retransmit_limit=126)
        with pytest.raises(ValueError, match="retransmit_limit=-1"):
            scenarios.validate_protocol_config(
                16, fanout=3, budget=5, retransmit_limit=-1)

    def test_budget_and_sizes(self):
        with pytest.raises(ValueError, match="budget=0"):
            scenarios.validate_protocol_config(16, fanout=3, budget=0)
        with pytest.raises(ValueError, match="n=0"):
            scenarios.validate_protocol_config(0, fanout=1, budget=1)

    def test_valid_config_passes(self):
        scenarios.validate_protocol_config(
            16, fanout=3, budget=15, retransmit_limit=8,
            services_per_node=4, name="ok")
