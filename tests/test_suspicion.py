"""Suspicion & flap-damping subprotocol — the device-side contracts
(ops/suspicion.py, ops/ttl.py, docs/chaos.md).

Four surfaces:

* the sweep/announce kernels against straight-line numpy oracles —
  including a pin that ``suspicion_window=0`` compiles EXACTLY the
  pre-suspicion sweep rule (the disabled path must stay bit-identical
  to the pre-PR protocol);
* full-round lockstep of ExactSim against the sequential
  ``sim/oracle.py`` mirror WITH suspicion active, through the whole
  quarantine lifecycle (expiry → SUSPECT → gossiped → refuted, and an
  unrefutable dead owner → tombstone at original-ts+1 s);
* dense↔sparse and single-chip↔sharded lockstep (both models, both
  twins, d ∈ {1, 2, 4, 8} × every board_exchange mode) with the window
  BOTH disabled and enabled, plus trace/delta stream equality through
  chunked dispatch — the new status code must ride every execution
  path bit-identically;
* the flight recorder's robustness columns (suspects,
  fp_tombstones) against numpy recomputation, including under a
  config6-seeded chaos FaultPlan — the columns benchmarks/robustness.py
  and the bench `robustness` block report.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sidecar_tpu.models.compressed import CompressedParams, CompressedSim
from sidecar_tpu.models.exact import ExactSim, SimParams, clone_state
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import gossip as gossip_ops
from sidecar_tpu.ops import suspicion as suspicion_ops
from sidecar_tpu.ops import topology
from sidecar_tpu.ops import trace as trace_ops
from sidecar_tpu.ops.status import (
    ALIVE,
    DRAINING,
    SUSPECT,
    TOMBSTONE,
    UNHEALTHY,
    pack,
    unpack_status,
    unpack_ts,
)
from sidecar_tpu.ops.ttl import ttl_sweep
from sidecar_tpu.sim.oracle import OracleSim
from sidecar_tpu.parallel.mesh import make_mesh

from tests.test_sharded import DetShardedSim, det_sample_peers
from tests.test_sharded_compressed import (
    DetShardedCompressedSim,
    assert_states_equal,
)

DS = (1, 2, 4, 8)

# Expiry-scale clocks: refresh 10 rounds, lifespan 15 rounds, sweep
# every 2 rounds, push-pull every 5 — suspicion decisions happen INSIDE
# short runs.  Window 2 s = 10 rounds of quarantine.
TIGHT = TimeConfig(refresh_interval_s=2.0, alive_lifespan_s=3.0,
                   sweep_interval_s=0.4, push_pull_interval_s=1.0,
                   suspicion_window_s=2.0)
TIGHT_OFF = dataclasses.replace(TIGHT, suspicion_window_s=0.0)


def np_status(known):
    known = np.asarray(known)
    return np.where((known >> 3) > 0, known & 7, -1)


# -- sweep / announce kernels ------------------------------------------------

class TestTtlSweepSuspicion:
    L, D, T, SEC = 3000, 6000, 100_000, 1000
    KW = dict(alive_lifespan=L, draining_lifespan=D, tombstone_lifespan=T,
              one_second=SEC)

    def test_fresh_expiry_becomes_suspect_at_original_ts(self):
        known = jnp.asarray([pack(100, ALIVE), pack(100, UNHEALTHY)])
        swept, expired = ttl_sweep(known, 5000, suspicion_window=1000,
                                   **self.KW)
        np.testing.assert_array_equal(
            np.asarray(swept),
            [int(pack(100, SUSPECT)), int(pack(100, SUSPECT))])
        assert not np.asarray(expired).any()  # nothing tombstoned

    def test_unrefuted_suspect_tombstones_at_plus_one_second(self):
        known = jnp.asarray([pack(100, SUSPECT)])
        # Inside the window: held.
        swept, expired = ttl_sweep(known, self.L + 100 + 999,
                                   suspicion_window=1000, **self.KW)
        assert int(swept[0]) == int(pack(100, SUSPECT))
        assert not bool(expired[0])
        # Window lapsed: tombstone, stamped ORIGINAL ts + 1 s.
        swept, expired = ttl_sweep(known, self.L + 100 + 1001,
                                   suspicion_window=1000, **self.KW)
        assert int(swept[0]) == int(pack(100 + self.SEC, TOMBSTONE))
        assert bool(expired[0])

    def test_draining_never_enters_quarantine(self):
        known = jnp.asarray([pack(100, DRAINING)])
        swept, expired = ttl_sweep(known, self.D + 200,
                                   suspicion_window=1000, **self.KW)
        assert int(swept[0]) == int(pack(100 + self.SEC, TOMBSTONE))
        assert bool(expired[0])

    def test_fresh_records_and_gc_unchanged(self):
        now = 2 * self.T
        known = jnp.asarray([
            pack(now - 10, ALIVE),         # fresh: untouched
            pack(now - self.T - 1, TOMBSTONE),  # old tombstone: GC'd
            0,                             # unknown: untouched
        ])
        swept, _ = ttl_sweep(known, now, suspicion_window=1000, **self.KW)
        np.testing.assert_array_equal(
            np.asarray(swept), [int(pack(now - 10, ALIVE)), 0, 0])

    def test_packed_keys_never_regress_except_gc(self):
        rng = np.random.default_rng(0)
        ts = rng.integers(1, 50_000, size=512)
        st = rng.integers(0, 6, size=512)
        known = jnp.asarray((ts << 3 | st).astype(np.int32))
        swept, _ = ttl_sweep(known, 40_000, suspicion_window=1500,
                             **self.KW)
        swept = np.asarray(swept)
        kept = swept != 0
        assert (swept[kept] >= np.asarray(known)[kept]).all()

    def test_window_zero_is_the_pre_suspicion_rule(self):
        """The disabled path must implement EXACTLY the pre-PR sweep:
        pinned against an independent numpy replica of the old rule on
        randomized states."""
        rng = np.random.default_rng(1)
        ts = rng.integers(0, 220_000, size=2048)
        st = rng.integers(0, 5, size=2048)  # reference codes only
        known_np = (ts << 3 | st).astype(np.int32)
        for now in (5_000, 50_000, 150_000, 215_000):
            swept, expired = ttl_sweep(jnp.asarray(known_np), now,
                                       suspicion_window=0, **self.KW)
            present = (known_np >> 3) > 0
            is_tomb = present & (st == TOMBSTONE)
            gc = is_tomb & (ts < now - self.T)
            lifespan = np.where(st == DRAINING, self.D, self.L)
            exp = present & ~is_tomb & (ts < now - lifespan)
            want = np.where(exp, ((ts + self.SEC) << 3 | TOMBSTONE),
                            known_np)
            want = np.where(gc, 0, want).astype(np.int32)
            np.testing.assert_array_equal(np.asarray(swept), want)
            np.testing.assert_array_equal(np.asarray(expired), exp)


class TestAnnounceRefute:
    def test_disabled_is_identity(self):
        due = jnp.asarray([True, False])
        st = jnp.asarray([SUSPECT, SUSPECT])
        present = jnp.asarray([True, True])
        due2, st2 = suspicion_ops.announce_refute(due, st, present, False)
        assert due2 is due and st2 is st

    def test_suspect_own_record_refutes_immediately_as_alive(self):
        due = jnp.asarray([False, False, False, True])
        st = jnp.asarray([SUSPECT, SUSPECT, ALIVE, DRAINING])
        present = jnp.asarray([True, False, True, True])
        due2, st2 = suspicion_ops.announce_refute(due, st, present, True)
        # Present suspect: due now, announced ALIVE.  Absent suspect
        # (dead owner): untouched.  Others: untouched.
        np.testing.assert_array_equal(np.asarray(due2),
                                      [True, False, False, True])
        np.testing.assert_array_equal(
            np.asarray(st2), [ALIVE, SUSPECT, ALIVE, DRAINING])


# -- full-round oracle lockstep ----------------------------------------------

class TestOracleLockstep:
    def _run(self, cfg, rounds, dead_at=None, n=12, spn=2):
        params = SimParams(n=n, services_per_node=spn, fanout=2, budget=4)
        sim = ExactSim(params, topology.complete(n), cfg)
        state = sim.init_state()
        orc = OracleSim(sim, state)
        key = jax.random.PRNGKey(0)
        statuses = set()
        for r in range(rounds):
            if dead_at is not None and r == dead_at:
                alive = np.ones(n, bool)
                alive[0] = False
                state = dataclasses.replace(
                    state, node_alive=jnp.asarray(alive))
                orc.node_alive = alive.copy()
            k = jax.random.fold_in(key, r)
            state = sim.step(state, k)
            orc.step(k)
            np.testing.assert_array_equal(
                np.asarray(state.known), orc.known,
                err_msg=f"known diverged at round {r + 1}")
            np.testing.assert_array_equal(
                np.asarray(state.sent).astype(np.int32), orc.sent,
                err_msg=f"sent diverged at round {r + 1}")
            statuses.update(np_status(state.known)[
                np_status(state.known) >= 0].tolist())
        return statuses

    def test_suspicion_on_with_refutation(self):
        """All owners alive: expiries quarantine and every suspicion is
        refuted — SUSPECT appears, TOMBSTONE never does."""
        statuses = self._run(TIGHT, 70)
        assert SUSPECT in statuses
        assert TOMBSTONE not in statuses

    def test_suspicion_on_dead_owner_tombstones(self):
        """A dead owner cannot refute: its records walk the full
        quarantine lifecycle to tombstone."""
        statuses = self._run(TIGHT, 90, dead_at=10)
        assert SUSPECT in statuses and TOMBSTONE in statuses

    def test_window_zero_expiry_matches_pre_pr_oracle(self):
        """Disabled subprotocol, expiry-heavy run with a dead owner:
        the oracle's window-0 path is the untouched pre-PR sweep, so
        this lockstep pins the model to the pre-PR round."""
        statuses = self._run(TIGHT_OFF, 70, dead_at=10)
        assert TOMBSTONE in statuses
        assert SUSPECT not in statuses


# -- dense ↔ sparse ----------------------------------------------------------

class TestDenseSparseLockstep:
    @pytest.mark.sparse
    @pytest.mark.parametrize("cfg", [TIGHT, TIGHT_OFF],
                             ids=["window-on", "window-off"])
    def test_exact_dense_equals_sparse(self, cfg):
        params = SimParams(n=16, services_per_node=2, fanout=2, budget=4)
        key = jax.random.PRNGKey(3)

        def run(sparse):
            sim = ExactSim(params, topology.complete(16), cfg,
                           sparse="1" if sparse else "0")
            state = sim.init_state()
            alive = np.ones(16, bool)
            alive[1] = False     # dead owner: full lifecycle runs
            state = dataclasses.replace(state,
                                        node_alive=jnp.asarray(alive))
            return sim.run(state, key, 60, sparse=sparse)

        fd, cd = run(False)
        fs, cs = run(True)
        np.testing.assert_array_equal(np.asarray(fd.known),
                                      np.asarray(fs.known))
        np.testing.assert_array_equal(np.asarray(fd.sent),
                                      np.asarray(fs.sent))
        np.testing.assert_array_equal(np.asarray(cd), np.asarray(cs))
        if cfg.suspicion_window > 0:
            assert SUSPECT in set(np_status(fd.known).ravel().tolist()) \
                or TOMBSTONE in set(np_status(fd.known).ravel().tolist())

    @pytest.mark.sparse
    @pytest.mark.parametrize("cfg", [TIGHT, TIGHT_OFF],
                             ids=["window-on", "window-off"])
    def test_compressed_dense_equals_sparse(self, cfg):
        params = CompressedParams(n=16, services_per_node=2, fanout=2,
                                  budget=4, cache_lines=32)
        key = jax.random.PRNGKey(4)

        def run(sparse):
            sim = CompressedSim(params, topology.complete(16), cfg,
                                sparse="1" if sparse else "0")
            state = sim.init_state()
            alive = np.ones(16, bool)
            alive[1] = False
            state = dataclasses.replace(state,
                                        node_alive=jnp.asarray(alive))
            return sim.run(state, key, 60, sparse=sparse)

        fd, cd = run(False)
        fs, cs = run(True)
        assert_states_equal(fd, fs, 60)
        np.testing.assert_array_equal(np.asarray(cd), np.asarray(cs))
        if cfg.suspicion_window > 0:
            # The dead owner's records have walked the quarantine
            # lifecycle by round 60: SUSPECT if still quarantined,
            # TOMBSTONE once the window lapsed unrefuted.
            seen = set(np_status(fd.floor).tolist() +
                       np_status(fd.own).ravel().tolist())
            assert SUSPECT in seen or TOMBSTONE in seen


# -- single-chip ↔ sharded twins --------------------------------------------

# Exact↔sharded lockstep requires the shared deterministic peer rule
# and push-pull pinned out (the sharded twin's stride anti-entropy is a
# DOCUMENTED divergence from partner sampling).  Refresh and the sweep
# stay live — the suspicion lifecycle rides announce + sweep.
SHARD_CFG = dataclasses.replace(TIGHT, push_pull_interval_s=1e6)


class TestShardedExactLockstep:
    @pytest.mark.parametrize("mode", ("all_gather", "ring"))
    @pytest.mark.parametrize("d", DS)
    def test_lockstep_with_suspicion(self, monkeypatch, d, mode):
        monkeypatch.setattr(gossip_ops, "sample_peers", det_sample_peers)
        n = 16
        params = SimParams(n=n, services_per_node=2, fanout=2, budget=4)
        exact = ExactSim(params, topology.complete(n), SHARD_CFG)
        sharded = DetShardedSim(params, topology.complete(n), SHARD_CFG,
                                mesh=make_mesh(jax.devices()[:d]),
                                board_exchange=mode)
        se, ss = exact.init_state(), sharded.init_state()
        alive = np.ones(n, bool)
        alive[0] = False
        se = dataclasses.replace(se, node_alive=jnp.asarray(alive))
        ss = dataclasses.replace(ss, node_alive=jnp.asarray(alive))
        saw = set()
        for r in range(40):
            key = jax.random.PRNGKey(r)  # ignored by det samplers
            se = exact.step(se, key)
            ss = sharded.step(ss, key)
            np.testing.assert_array_equal(
                np.asarray(se.known), np.asarray(ss.known),
                err_msg=f"known diverged at round {r + 1} "
                        f"(d={d}, {mode})")
            np.testing.assert_array_equal(
                np.asarray(se.sent), np.asarray(ss.sent),
                err_msg=f"sent diverged at round {r + 1}")
            saw.update(np_status(se.known)[
                np_status(se.known) >= 0].tolist())
        # The run must actually exercise the quarantine lifecycle.
        assert SUSPECT in saw and TOMBSTONE in saw


class TestShardedCompressedLockstep:
    @pytest.mark.parametrize("mode", ("all_gather", "all_to_all", "ring"))
    @pytest.mark.parametrize("d", DS)
    def test_lockstep_with_suspicion(self, monkeypatch, d, mode):
        monkeypatch.setattr(gossip_ops, "sample_peers", det_sample_peers)
        n = 16
        params = CompressedParams(n=n, services_per_node=2, fanout=2,
                                  budget=4, cache_lines=32)
        single = CompressedSim(params, topology.complete(n), TIGHT)
        sharded = DetShardedCompressedSim(
            params, topology.complete(n), TIGHT,
            mesh=make_mesh(jax.devices()[:d]), board_exchange=mode)
        ss, sh = single.init_state(), sharded.init_state()
        alive = np.ones(n, bool)
        alive[1] = False
        ss = dataclasses.replace(ss, node_alive=jnp.asarray(alive))
        sh = dataclasses.replace(sh, node_alive=jnp.asarray(alive))
        for r in range(40):
            key = jax.random.PRNGKey(r)  # stride draw shared via key
            ss = single.step(ss, key)
            sh = sharded.step(sh, key)
            assert_states_equal(ss, sh, r + 1)
        assert SUSPECT in set(np_status(ss.floor).tolist()) \
            or TOMBSTONE in set(np_status(ss.floor).tolist())


# -- trace / delta streams through chunked dispatch --------------------------

class TestStreamsWithSuspicion:
    @pytest.mark.parametrize("cfg", [TIGHT, TIGHT_OFF],
                             ids=["window-on", "window-off"])
    def test_exact_chunked_trace_and_deltas_equal_straight(self, cfg):
        params = SimParams(n=12, services_per_node=2, fanout=2, budget=4)
        sim = ExactSim(params, topology.complete(12), cfg)
        key = jax.random.PRNGKey(5)

        def dead_start(state):
            alive = np.ones(12, bool)
            alive[0] = False
            return dataclasses.replace(state,
                                       node_alive=jnp.asarray(alive))

        base = dead_start(sim.init_state())

        f1, tr1, c1 = sim.run_with_trace(clone_state(base), key, 40,
                                         cap=40)
        mid, tra, ca = sim.run_with_trace(clone_state(base), key, 20,
                                          cap=40)
        f2, trb, cb = sim.run_with_trace(mid, key, 20, cap=40,
                                         start_round=20)
        np.testing.assert_array_equal(np.asarray(f1.known),
                                      np.asarray(f2.known))
        np.testing.assert_array_equal(np.asarray(c1),
                                      np.concatenate([ca, cb]))
        recs = np.concatenate([np.asarray(tra.rec)[:20],
                               np.asarray(trb.rec)[:20]])
        np.testing.assert_array_equal(np.asarray(tr1.rec)[:40], recs)

        f3, d1, c3 = sim.run_with_deltas(clone_state(base), key, 40,
                                         cap=64)
        np.testing.assert_array_equal(np.asarray(f1.known),
                                      np.asarray(f3.known))
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c3))

    def test_compressed_trace_rides_suspicion(self):
        params = CompressedParams(n=12, services_per_node=2, fanout=2,
                                  budget=4, cache_lines=32)
        sim = CompressedSim(params, topology.complete(12), TIGHT)
        state = sim.init_state()
        alive = np.ones(12, bool)
        alive[1] = False
        state = dataclasses.replace(state, node_alive=jnp.asarray(alive))
        final, tr = sim.run_with_trace(state, jax.random.PRNGKey(6), 40)
        recs = np.asarray(tr.rec)
        assert recs[:, trace_ops.TRACE_SUSPECTS].max() > 0
        summary = trace_ops.summarize(tr)
        assert summary["suspects_max"] > 0
        assert "fp_tombstones_total" in summary


# -- the robustness columns --------------------------------------------------

class TestRobustnessColumns:
    def _oracle_columns(self, prev, nxt):
        """Numpy recomputation of suspects + fp_tombstones from a
        consecutive state pair (exact family)."""
        p_st = np_status(prev.known)
        n_st = np_status(nxt.known)
        alive = np.asarray(nxt.node_alive)
        n, m = np.asarray(nxt.known).shape
        owner = np.arange(m) // (m // n)
        suspects = int((n_st == SUSPECT).sum())
        entered = (n_st == TOMBSTONE) & (p_st != TOMBSTONE)
        fp = int((entered & alive[owner][None, :]).sum())
        return suspects, fp

    def test_exact_trace_columns_match_numpy(self):
        params = SimParams(n=12, services_per_node=2, fanout=2, budget=4)
        sim = ExactSim(params, topology.complete(12), TIGHT)
        state = sim.init_state()
        alive = np.ones(12, bool)
        alive[0] = False
        state = dataclasses.replace(state, node_alive=jnp.asarray(alive))
        key = jax.random.PRNGKey(7)
        saw_fp = saw_suspect = False
        for r in range(80):
            prev = state
            state = sim.step(state, jax.random.fold_in(key, r))
            rec = np.asarray(trace_ops.exact_record(
                prev, state, budget=4, fanout=2,
                limit=params.resolved_retransmit_limit()))
            suspects, fp = self._oracle_columns(prev, state)
            assert rec[trace_ops.TRACE_SUSPECTS] == suspects
            assert rec[trace_ops.TRACE_FP_TOMBSTONES] == fp
            saw_suspect |= suspects > 0
            saw_fp |= fp > 0
        assert saw_suspect
        # Node 0 is dead, so ITS records' tombstones are true positives;
        # under loss-free all-alive-otherwise conditions no false
        # positives occur — exactly the column's contract.
        assert not saw_fp

    def test_chaos_pause_produces_false_positives_and_suspicion_stops_them(
            self):
        """The headline mechanism end to end, tied to the flight
        recorder: a config6-seeded FaultPlan pause (node healthy but
        silent) makes bare TTL mint false-positive tombstones; the same
        run with the window on quarantines instead (the
        benchmarks/robustness.py measurement in miniature)."""
        from sidecar_tpu.chaos import ChaosExactSim, FaultPlan, NodeFault

        n = 12
        params = SimParams(n=n, services_per_node=2, fanout=2, budget=4)
        plan = FaultPlan(seed=6, nodes=(
            NodeFault(nodes=(2, 3), start_round=10, end_round=35,
                      kind="pause"),))

        def fp_total(cfg):
            sim = ChaosExactSim(params, topology.complete(n), cfg,
                                plan=plan)
            final, tr, _ = sim.run_with_trace(
                sim.init_state(), jax.random.PRNGKey(8), 80, cap=80)
            recs = np.asarray(tr.rec)
            return (int(recs[:, trace_ops.TRACE_FP_TOMBSTONES].sum()),
                    int(recs[:, trace_ops.TRACE_SUSPECTS].max()))

        fp_off, sus_off = fp_total(TIGHT_OFF)
        fp_on, sus_on = fp_total(
            dataclasses.replace(TIGHT, suspicion_window_s=6.0))
        assert sus_off == 0 and sus_on > 0
        assert fp_off > 0, "pause must mint false positives with TTL only"
        assert fp_on * 5 <= fp_off, (
            f"suspicion must cut false positives >= 5x: "
            f"off={fp_off}, on={fp_on}")
