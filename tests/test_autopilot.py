"""Digital-twin autopilot (sidecar_tpu/autopilot/, docs/autopilot.md).

The ISSUE's two named test contracts plus the layer units:

* a ``FaultPlan``/knob estimate FITTED from a ``ChaosExactSim`` trace
  reproduces the injected loss / churn / pause within tolerance
  (TestFit);
* a full fitted-then-swept recommendation is deterministic under a
  fixed seed, its winner meets the SLO the status-quo baseline fails,
  and its unbatched replay is bit-identical to the fleet lane
  (TestController);
* the auto-apply master gate: a request may ask, only
  ``SIDECAR_TPU_AUTOPILOT_APPLY=1`` arms, and a blocked apply is
  counted, never silent (TestApplyGate).
"""

import dataclasses

import jax
import pytest

from sidecar_tpu import metrics
from sidecar_tpu.autopilot import (
    AutopilotController,
    AxisSpec,
    ConditionEstimate,
    FleetEvaluator,
    Objective,
    es_search,
    fit_from_trace,
    fit_live,
    replay_check,
)
from sidecar_tpu.autopilot.controller import ENV_APPLY
from sidecar_tpu.models.exact import ExactSim, SimParams
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import topology as topo_mod
from sidecar_tpu.ops.trace import trace_to_dicts

N, SPN, ROUNDS = 16, 4, 60
PARAMS = SimParams(n=N, services_per_node=SPN, fanout=3, budget=15)
CFG = TimeConfig(refresh_interval_s=10_000.0)


def _trace(sim, rounds=ROUNDS, seed=0):
    final, tr, _conv = sim.run_with_trace(
        sim.init_state(), jax.random.PRNGKey(seed), rounds, cap=rounds)
    return final, trace_to_dicts(tr)


# -- fit: telemetry inverts back to the injected conditions ----------------


class TestFit:
    def test_loss_fit_recovers_injected_drop(self):
        from sidecar_tpu.chaos import ChaosExactSim, EdgeFault, FaultPlan
        everyone = tuple(range(N))
        plan = FaultPlan(seed=2, edges=(EdgeFault(
            src=everyone, dst=everyone, drop_prob=0.2),))
        sim = ChaosExactSim(PARAMS, topo_mod.complete(N), CFG, plan=plan)
        final, rows = _trace(sim)
        est = fit_from_trace(rows, params=PARAMS,
                             injections=sim.injection_counts(final),
                             timecfg=CFG)
        # The frontier-census inversion: within ±50% of the injected
        # rate (the offered-packet denominator is exact; the sampled
        # drops carry the variance).
        assert 0.1 <= est.loss_rate <= 0.3
        assert est.signals["dropped_packets"] > 0
        assert est.seconds_per_round == pytest.approx(
            CFG.round_ticks / CFG.ticks_per_second)

    def test_churn_fit_within_tolerance(self):
        from sidecar_tpu.fleet import restart_churn_perturb
        p = 0.002
        sim = ExactSim(PARAMS, topo_mod.complete(N), CFG,
                       perturb=restart_churn_perturb(PARAMS, prob=p))
        _final, rows = _trace(sim)
        est = fit_from_trace(rows, params=PARAMS, timecfg=CFG)
        # The fp-tombstone inversion is calibrated for flip churn
        # (half the flips tombstone); restart churn tombstones every
        # flip, so the fit lands within a factor ~2 — order-of-
        # magnitude fidelity is the contract, not exactness.
        assert 0.5 * p <= est.churn_rate <= 3.0 * p
        assert est.loss_rate == 0.0

    def test_pause_fit_recovers_paused_fraction(self):
        from sidecar_tpu.chaos import ChaosExactSim, FaultPlan, NodeFault
        from sidecar_tpu.chaos.plan import FOREVER
        plan = FaultPlan(seed=1, nodes=(NodeFault(
            nodes=tuple(range(N - 4, N)), start_round=1,
            end_round=FOREVER, kind="pause"),))
        sim = ChaosExactSim(PARAMS, topo_mod.complete(N), CFG, plan=plan)
        final, rows = _trace(sim)
        est = fit_from_trace(rows, params=PARAMS,
                             injections=sim.injection_counts(final),
                             timecfg=CFG)
        assert est.paused_frac == pytest.approx(0.25, abs=0.1)

    def test_quiet_trace_fits_zero(self):
        sim = ExactSim(PARAMS, topo_mod.complete(N), CFG)
        _final, rows = _trace(sim, rounds=30)
        est = fit_from_trace(rows, params=PARAMS, timecfg=CFG)
        assert est.loss_rate == 0.0
        assert est.churn_rate == 0.0
        assert est.paused_frac == 0.0
        assert est.base_fields() == {}
        assert est.fault_plan() is None

    def test_base_fields_and_fault_plan_round_trip(self):
        est = ConditionEstimate(n=16, services_per_node=4,
                                loss_rate=0.3, churn_rate=0.001,
                                paused_frac=0.25)
        assert est.base_fields() == {"drop_prob": 0.3,
                                     "churn_prob": 0.001}
        plan = est.fault_plan(seed=7)
        assert plan.seed == 7
        assert sum(len(nf.nodes) for nf in plan.nodes) == 4
        doc = est.to_json()
        assert doc["loss_rate"] == 0.3 and doc["n"] == 16

    def test_fit_live_from_snapshot(self):
        snap = {"gauges": {"engine.udpOut": 1000.0,
                           "engine.udpSendDrops": 50.0,
                           "coherence.agreement": 0.9},
                "counters": {"damping.flaps": 64.0}}
        est = fit_live(snap, n=16, services_per_node=4,
                       window_rounds=100)
        assert est.loss_rate == pytest.approx(0.05)
        assert est.churn_rate == pytest.approx(64 / (64 * 100))
        assert est.paused_frac == pytest.approx(0.1)
        assert est.source == "live"
        # no round base -> churn must stay 0, never be invented
        est2 = fit_live(snap, n=16, services_per_node=4)
        assert est2.churn_rate == 0.0


# -- objective: the SLO scalar ---------------------------------------------


class TestObjective:
    ROW_GOOD = {"rounds_to_eps": 6, "seconds_to_eps": 1.2,
                "rounds_run": 40, "exchange_bytes": 1e6,
                "digest_agreement": 1.0}
    ROW_BAD = {"rounds_to_eps": None, "seconds_to_eps": None,
               "rounds_run": 40, "exchange_bytes": 1e6,
               "digest_agreement": 0.5}

    def test_pass_scores_below_one(self):
        obj = Objective(["converge <= 10 rounds", "agreement >= 0.99"])
        score, block = obj.score_row(self.ROW_GOOD, horizon=40)
        assert block["pass"] is True
        assert 0.0 <= score < 1.0

    def test_fail_dominates_any_tiebreak(self):
        obj = Objective(["converge <= 10 rounds", "agreement >= 0.99"])
        score, block = obj.score_row(self.ROW_BAD, horizon=40)
        assert block["pass"] is False
        good, _ = obj.score_row(self.ROW_GOOD, horizon=40)
        assert score > good + 1000.0

    def test_cheaper_passing_config_wins_tiebreak(self):
        obj = Objective(["converge <= 20 rounds"])
        slow, _ = obj.score_row(dict(self.ROW_GOOD, rounds_to_eps=15,
                                     exchange_bytes=5e7), horizon=40)
        fast, _ = obj.score_row(self.ROW_GOOD, horizon=40)
        assert fast < slow

    def test_bad_rule_raises(self):
        with pytest.raises(ValueError):
            Objective(["converge <= banana"])


# -- search: axes, determinism, counted evaluations ------------------------


class TestSearch:
    def test_axis_validation(self):
        with pytest.raises(ValueError):
            AxisSpec("not_a_knob", 0.0, 1.0)
        with pytest.raises(ValueError):
            AxisSpec("drop_prob", 0.5, 0.5)     # empty range
        with pytest.raises(ValueError):
            AxisSpec("push_pull_interval_s", 0.0, 10.0, log=True)

    def test_integer_axes_auto_coerce(self):
        ax = AxisSpec("retransmit_limit", 2, 12)
        assert ax.integer
        assert all(isinstance(v, int) for v in ax.grid(4))
        assert ax.clip(3.7) == 4

    def test_log_grid_spans_orders_of_magnitude(self):
        ax = AxisSpec("push_pull_interval_s", 0.5, 32.0, log=True)
        g = ax.grid(3)
        assert g[0] == 0.5 and g[-1] == 32.0
        assert g[1] == pytest.approx(4.0, rel=0.01)   # geometric mid

    def test_es_search_deterministic_and_counted(self):
        obj = Objective(["converge <= 30 rounds"])

        def run():
            ev = FleetEvaluator(PARAMS, CFG, obj, rounds=20,
                                base={"seed": 5})
            return es_search(
                ev, (AxisSpec("drop_prob", 0.0, 0.4),),
                seed_grid=2, generations=1, population=2, seed=9)

        a, b = run(), run()
        assert a.best.candidate == b.best.candidate
        assert a.best.score == b.best.score
        assert a.evaluations == b.evaluations
        assert a.evaluations == len(a.history)
        assert a.grid_points == 4
        assert a.baseline is not None \
            and a.baseline.candidate == {}


# -- controller: the closed loop -------------------------------------------


AXES = [{"name": "push_pull_interval_s", "lo": 0.5, "hi": 30.0,
         "log": True, "base": 20.0}]
RULES = ["converge <= 10 rounds"]


@pytest.fixture(scope="module")
def report():
    """One full fitted-then-swept recommendation, shared across the
    assertions (the pass is the expensive part)."""
    ctl = AutopilotController(timecfg=TimeConfig())
    return ctl.recommend(rules=RULES, estimate={"loss_rate": 0.45},
                         n=16, rounds=40, seed=3, seed_grid=2,
                         generations=1, population=3, axes=AXES)


class TestController:
    def test_baseline_fails_winner_passes(self, report):
        # The closed-loop claim at test scale: under the fitted 45%
        # loss the status-quo 20 s cadence misses the convergence SLO;
        # the recommended cadence meets it.
        assert report["baseline"]["candidate"] == {}
        assert report["baseline"]["slo"]["pass"] is False
        assert report["recommended"]["slo"]["pass"] is True
        assert "push_pull_interval_s" in report["recommended"]["candidate"]

    def test_replay_bit_identical(self, report):
        assert report["replay"]["checked"] is True
        assert report["replay"]["identical"] is True
        assert set(report["replay"]["fields"]) == {
            "known", "sent", "node_alive", "round_idx"}

    def test_deterministic_under_fixed_seed(self, report):
        rep2 = AutopilotController(timecfg=TimeConfig()).recommend(
            rules=RULES, estimate={"loss_rate": 0.45}, n=16, rounds=40,
            seed=3, seed_grid=2, generations=1, population=3, axes=AXES)
        assert rep2["recommended"]["candidate"] == \
            report["recommended"]["candidate"]
        assert rep2["recommended"]["score"] == \
            report["recommended"]["score"]
        assert rep2["evaluations"] == report["evaluations"]

    def test_report_carries_the_fit_and_the_counts(self, report):
        assert report["estimate"]["loss_rate"] == 0.45
        assert report["estimate"]["source"] == "request"
        assert report["evaluations"] == report["candidates"] > 0
        assert report["grid_points"] > 0
        assert report["rules"] == ["converge <= 10 rounds"]

    def test_malformed_inputs_raise_value_error(self):
        ctl = AutopilotController(timecfg=TimeConfig())
        with pytest.raises(ValueError):
            ctl.recommend(rules=[], estimate={}, n=16)
        with pytest.raises(ValueError):
            ctl.recommend(rules=RULES, estimate={"loss_rate": 1.5},
                          n=16)
        with pytest.raises(ValueError):
            ctl.recommend(rules=RULES, estimate={"typo_rate": 0.1},
                          n=16)
        with pytest.raises(ValueError):
            ctl.recommend(rules=RULES, estimate={}, n=16,
                          axes=[{"name": "push_pull_interval_s",
                                 "lo": 1, "hi": 2, "bogus": 3}])
        with pytest.raises(ValueError):
            ctl.recommend(rules=RULES, estimate={})   # no n anywhere

    def test_requires_n_or_catalog_or_estimate(self):
        est = ConditionEstimate(n=12, services_per_node=4)
        ctl = AutopilotController(timecfg=TimeConfig())
        # n resolvable from the estimate: allowed for library use.
        rep = ctl.recommend(rules=RULES, estimate=est, rounds=10,
                            seed_grid=1, generations=0, axes=AXES)
        assert rep["n"] == 12


class TestApplyGate:
    class _Bridge:
        def __init__(self):
            self.state = None
            self.t = TimeConfig()

    def _recommend(self, bridge, apply):
        return AutopilotController(bridge=bridge).recommend(
            rules=RULES, estimate={"loss_rate": 0.45}, n=16, rounds=40,
            seed=3, seed_grid=2, generations=1, population=3,
            axes=AXES, apply=apply)

    def test_apply_blocked_without_master_gate(self, monkeypatch):
        monkeypatch.delenv(ENV_APPLY, raising=False)
        bridge = self._Bridge()
        before = dataclasses.replace(bridge.t)
        blocked0 = metrics.snapshot()["counters"].get(
            "autopilot.apply_blocked", 0)
        rep = self._recommend(bridge, apply=True)
        assert rep["apply"] == {"requested": True, "armed": False,
                                "applied": False, "fields": {}}
        assert bridge.t == before     # the live clock is untouched
        assert metrics.snapshot()["counters"][
            "autopilot.apply_blocked"] == blocked0 + 1

    def test_apply_lands_when_armed_and_replay_identical(
            self, monkeypatch):
        monkeypatch.setenv(ENV_APPLY, "1")
        bridge = self._Bridge()
        rep = self._recommend(bridge, apply=True)
        assert rep["replay"]["identical"] is True
        assert rep["apply"]["armed"] is True
        assert rep["apply"]["applied"] is True
        knob = rep["apply"]["fields"]["push_pull_interval_s"]
        assert bridge.t.push_pull_interval_s == knob
        assert bridge.t.push_pull_interval_s != 20.0

    def test_armed_but_not_requested_stays_advisory(self, monkeypatch):
        monkeypatch.setenv(ENV_APPLY, "1")
        bridge = self._Bridge()
        before = dataclasses.replace(bridge.t)
        rep = self._recommend(bridge, apply=False)
        assert rep["apply"]["applied"] is False
        assert bridge.t == before


class TestReplayCheck:
    def test_replay_check_on_plain_evaluator(self):
        obj = Objective(["converge <= 30 rounds"])
        ev = FleetEvaluator(PARAMS, CFG, obj, rounds=20,
                            base={"seed": 1})
        res = ev.evaluate([{"drop_prob": 0.1}], "t")[0]
        verdict = replay_check(res)
        assert verdict["identical"] is True
        assert verdict["rounds"] == 20
