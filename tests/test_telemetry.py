"""Telemetry-plane tests (PR 6, docs/telemetry.md).

Four surfaces:

* the in-scan flight recorder (ops/trace.py): record streams validated
  round-for-round against a pure-Python oracle on both single-chip
  models, the dense↔sparse lockstep, both sharded twins at
  d ∈ {1, 2, 4, 8} (the trace must equal the untraced run's post-hoc
  census — and must not perturb the run), and the static-cap
  truncation contract;
* the bridge plumbing: ``simulate(trace=N)`` / ``POST /simulate``
  round-trip, chunked-pipeline equality, the deltas exclusivity rule;
* the host instruments: histogram percentile math, the reservoir
  bound, the timers-block back-compat mirror, statsd ``|ms`` emission,
  and the ``configure_statsd`` reconfiguration fix (old socket closed,
  pair swapped atomically);
* exposition: span nesting / thread isolation, Prometheus text
  rendering, and the ``GET /metrics`` + ``GET /api/trace`` endpoints.
"""

import dataclasses
import json
import socket
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sidecar_tpu import service as S
from sidecar_tpu.bridge import SimBridge, serve_bridge
from sidecar_tpu.catalog import ServicesState
from sidecar_tpu.metrics import Metrics
from sidecar_tpu.models.compressed import (
    CompressedParams,
    CompressedSim,
    hash_line,
)
from sidecar_tpu.models.exact import ExactSim, SimParams
from sidecar_tpu.models.timecfg import TimeConfig
from sidecar_tpu.ops import topology
from sidecar_tpu.ops import trace as trace_ops
from sidecar_tpu.ops.status import ALIVE, TOMBSTONE, pack, unpack_status
from sidecar_tpu.parallel.mesh import make_mesh
from sidecar_tpu.parallel.sharded import ShardedSim
from sidecar_tpu.parallel.sharded_compressed import ShardedCompressedSim
from sidecar_tpu.telemetry import render_prometheus, reset_spans, span, spans
from sidecar_tpu.web import SidecarApi

NS = S.NS_PER_SECOND
T0 = 1_700_000_000 * NS

CFG = TimeConfig(refresh_interval_s=1000.0, push_pull_interval_s=2.0)


# -- pure-Python oracle ------------------------------------------------------
# Independent numpy recomputation of every record field from consecutive
# state pairs — the jitted extractor must reproduce it cell-for-cell.

def np_tombstones(*arrays) -> int:
    """is_known & status==TOMBSTONE across packed-key tensors."""
    total = 0
    for a in arrays:
        a = np.asarray(a)
        total += int((((a >> 3) > 0) & ((a & 7) == TOMBSTONE)).sum())
    return total


def oracle_exact_record(prev, nxt, p: SimParams) -> dict:
    limit = p.resolved_retransmit_limit()
    known_p = np.asarray(prev.known)
    sent_p = np.asarray(prev.sent)
    elig = (known_p > 0) & (sent_p.astype(np.int32) < limit)
    per_row = elig.sum(axis=1)
    budget = min(p.budget, p.m)
    known_n = np.asarray(nxt.known)
    alive = np.asarray(nxt.node_alive)
    truth = np.max(np.where(alive[:, None], known_n, 0), axis=0)
    return {
        "round": int(nxt.round_idx),
        "frontier": int((per_row > 0).sum()),
        "behind": int((alive[:, None] & (known_n < truth[None, :])).sum()),
        "admitted": int((known_n != known_p).sum()),
        "exchange_bytes": int(np.minimum(per_row, budget).sum())
        * p.fanout * trace_ops.RECORD_WIRE_BYTES,
        "tombstones": np_tombstones(known_n),
    }


def np_belief(state, params: CompressedParams) -> np.ndarray:
    """Numpy materialization of the compressed belief view (the
    test_delta oracle): max(floor, cache hit, own at owner rows)."""
    n, s, m = params.n, params.services_per_node, params.m
    own = np.asarray(state.own)
    cache_slot = np.asarray(state.cache_slot)
    cache_val = np.asarray(state.cache_val)
    floor = np.asarray(state.floor)
    out = np.tile(floor, (n, 1))
    lines = np.asarray(hash_line(jnp.arange(m, dtype=jnp.int32),
                                 params.cache_lines, s))
    for i in range(n):
        for slot in range(m):
            li = lines[slot]
            if cache_slot[i, li] == slot:
                out[i, slot] = max(out[i, slot], cache_val[i, li])
            if slot // s == i:
                out[i, slot] = max(out[i, slot], own[i, slot % s])
    return out


def oracle_compressed_record(prev, nxt, p: CompressedParams) -> dict:
    """All nodes alive, no DRAINING (the test regimes below) — the
    behind census is #(node, slot) beliefs below the per-slot max."""
    limit = p.resolved_retransmit_limit()
    elig = (np.asarray(prev.cache_slot) >= 0) \
        & (np.asarray(prev.cache_sent).astype(np.int32) < limit)
    per_row = elig.sum(axis=1)
    budget = min(p.budget, p.cache_lines)
    belief = np_belief(nxt, p)
    truth = belief.max(axis=0)
    admitted = (
        int((np.asarray(nxt.own) != np.asarray(prev.own)).sum())
        + int((np.asarray(nxt.cache_val)
               != np.asarray(prev.cache_val)).sum())
        + int((np.asarray(nxt.cache_slot)
               != np.asarray(prev.cache_slot)).sum())
        + int((np.asarray(nxt.floor) != np.asarray(prev.floor)).sum()))
    return {
        "round": int(nxt.round_idx),
        "frontier": int((per_row > 0).sum()),
        "behind": int((belief < truth[None, :]).sum()),
        "admitted": admitted,
        "exchange_bytes": int(np.minimum(per_row, budget).sum())
        * p.fanout * trace_ops.RECORD_WIRE_BYTES,
        "tombstones": np_tombstones(nxt.own, nxt.floor, nxt.cache_val),
    }


def assert_trace_matches(rec: np.ndarray, r: int, want: dict,
                         label: str) -> None:
    got = {name: int(rec[r, i])
           for i, name in enumerate(trace_ops.TRACE_FIELDS)}
    for field, value in want.items():
        assert got[field] == value, \
            f"{label} round {r}: {field} = {got[field]}, want {value}"


def churn_perturb(params: SimParams, spn: int, flip_prob: float = 0.05):
    """config3-style churn (the test_delta hook): a Bernoulli subset of
    owners re-stamps each round, flipping ALIVE ↔ TOMBSTONE so the
    trace's tombstone census actually moves."""
    owner = jnp.arange(params.m, dtype=jnp.int32) // spn
    cols = jnp.arange(params.m, dtype=jnp.int32)

    def perturb(state, key, now):
        churn = jax.random.bernoulli(key, flip_prob, (params.m,))
        own = state.known[owner, cols]
        flip = churn & (own > 0) & state.node_alive[owner]
        st = unpack_status(own)
        new_status = jnp.where(st == ALIVE, TOMBSTONE, ALIVE)
        new_val = jnp.where(flip, pack(now, new_status), own)
        known = state.known.at[owner, cols].set(new_val)
        reset = jnp.where(flip, owner, params.n)
        sent = state.sent.at[reset, cols].set(jnp.int8(0), mode="drop")
        return dataclasses.replace(state, known=known, sent=sent)

    return perturb


# -- the flight recorder vs the oracle --------------------------------------

@pytest.mark.parametrize("seed", [0, 1])
class TestExactTraceVsOracle:
    def make(self):
        params = SimParams(n=8, services_per_node=3, fanout=2, budget=6)
        sim = ExactSim(params, topology.complete(8),
                       perturb=churn_perturb(params, 3))
        return params, sim

    def test_stream_matches_stepwise_census(self, seed):
        params, sim = self.make()
        state = sim.init_state()
        key = jax.random.PRNGKey(seed)
        rounds = 10
        final, tr, conv = sim.run_with_trace(state, key, rounds,
                                             donate=False)
        assert int(tr.count) == rounds and not bool(tr.overflow)
        rec = np.asarray(tr.rec)

        st, saw_tombstone = state, False
        for r in range(rounds):
            prev = st
            st = sim.step(st, jax.random.fold_in(key, st.round_idx))
            want = oracle_exact_record(prev, st, params)
            assert_trace_matches(rec, r, want, "exact")
            # Dense run: mode flags stay zero.
            assert rec[r, trace_ops.TRACE_SPARSE] == 0
            assert rec[r, trace_ops.TRACE_OVERFLOW] == 0
            saw_tombstone = saw_tombstone or want["tombstones"] > 0
        assert saw_tombstone, "churn never produced a traced tombstone"
        np.testing.assert_array_equal(np.asarray(final.known),
                                      np.asarray(st.known))

    def test_trace_does_not_perturb_the_run(self, seed):
        """trace=N and trace=0 dispatches produce bit-identical states
        and convergence curves (the trace extractor sits OUTSIDE the
        step)."""
        params, sim = self.make()
        state = sim.init_state()
        key = jax.random.PRNGKey(seed)
        plain_final, plain_conv = sim.run(state, key, 8, donate=False)
        traced_final, _, traced_conv = sim.run_with_trace(
            state, key, 8, donate=False)
        np.testing.assert_array_equal(np.asarray(plain_final.known),
                                      np.asarray(traced_final.known))
        np.testing.assert_array_equal(np.asarray(plain_conv),
                                      np.asarray(traced_conv))


@pytest.mark.parametrize("seed", [0, 3])
class TestCompressedTraceVsOracle:
    def make(self):
        params = CompressedParams(n=8, services_per_node=4,
                                  cache_lines=16, fanout=2, budget=6)
        sim = CompressedSim(params, topology.complete(8))
        return params, sim

    def seeded_state(self, sim, params, seed):
        """Minted churn (tombstones included) so the traced rounds
        carry real in-flight records."""
        state = sim.init_state()
        rng = np.random.default_rng(seed)
        for burst in range(2):
            slots = rng.choice(params.m, size=5, replace=False)
            state = sim.mint(state, jnp.asarray(slots, jnp.int32),
                             now_tick=burst * 50 + 10,
                             status=TOMBSTONE if burst else ALIVE)
        return state

    def test_stream_matches_stepwise_census(self, seed):
        params, sim = self.make()
        state = self.seeded_state(sim, params, seed)
        key = jax.random.PRNGKey(seed)
        rounds = 6
        final, tr = sim.run_with_trace(state, key, rounds, donate=False)
        assert int(tr.count) == rounds and not bool(tr.overflow)
        rec = np.asarray(tr.rec)

        st = state
        for r in range(rounds):
            prev = st
            st = sim.step(st, jax.random.fold_in(key, st.round_idx))
            want = oracle_compressed_record(prev, st, params)
            assert_trace_matches(rec, r, want, "compressed")
        np.testing.assert_array_equal(np.asarray(final.cache_val),
                                      np.asarray(st.cache_val))

    def test_trace_does_not_perturb_the_run(self, seed):
        params, sim = self.make()
        state = self.seeded_state(sim, params, seed)
        key = jax.random.PRNGKey(seed)
        plain_final, plain_conv = sim.run(state, key, 6, donate=False)
        traced_final, _ = sim.run_with_trace(state, key, 6,
                                             donate=False)
        np.testing.assert_array_equal(np.asarray(plain_final.cache_val),
                                      np.asarray(traced_final.cache_val))
        np.testing.assert_array_equal(np.asarray(plain_final.floor),
                                      np.asarray(traced_final.floor))


# Every trace column EXCEPT the execution-mode pair — dense and sparse
# runs must agree on all of these (the PR-5 bit-identity contract,
# observed through the flight recorder).
CENSUS_COLS = [trace_ops.TRACE_ROUND, trace_ops.TRACE_FRONTIER,
               trace_ops.TRACE_BEHIND, trace_ops.TRACE_ADMITTED,
               trace_ops.TRACE_EXCHANGE_BYTES, trace_ops.TRACE_TOMBSTONES]


class TestDenseSparseLockstep:
    def test_exact_traces_agree(self):
        params = SimParams(n=16, services_per_node=2, fanout=2,
                           budget=4, sparse_cap=16)
        sim = ExactSim(params, topology.complete(16))
        state = sim.init_state()
        key = jax.random.PRNGKey(7)
        fd, td, cd = sim.run_with_trace(state, key, 8, donate=False,
                                        sparse=False)
        fs, ts, cs = sim.run_with_trace(state, key, 8, donate=False,
                                        sparse=True)
        rd, rs = np.asarray(td.rec), np.asarray(ts.rec)
        np.testing.assert_array_equal(rd[:, CENSUS_COLS],
                                      rs[:, CENSUS_COLS])
        assert not rd[:, trace_ops.TRACE_SPARSE].any()
        # cap == n: no overflow, every sparse round takes the
        # compacted path and the trace says so.
        assert rs[:, trace_ops.TRACE_SPARSE].all()
        assert not rs[:, trace_ops.TRACE_OVERFLOW].any()
        np.testing.assert_array_equal(np.asarray(fd.known),
                                      np.asarray(fs.known))
        np.testing.assert_array_equal(np.asarray(cd), np.asarray(cs))

    def test_compressed_traces_agree(self):
        params = CompressedParams(n=16, services_per_node=2,
                                  cache_lines=32, fanout=2, budget=4,
                                  sparse_cap=16)
        sim = CompressedSim(params, topology.complete(16))
        state = sim.mint(sim.init_state(),
                         jnp.arange(0, params.m, 2, dtype=jnp.int32),
                         now_tick=10)
        key = jax.random.PRNGKey(9)
        fd, td = sim.run_with_trace(state, key, 8, donate=False,
                                    sparse=False)
        fs, ts = sim.run_with_trace(state, key, 8, donate=False,
                                    sparse=True)
        rd, rs = np.asarray(td.rec), np.asarray(ts.rec)
        np.testing.assert_array_equal(rd[:, CENSUS_COLS],
                                      rs[:, CENSUS_COLS])
        assert rs[:, trace_ops.TRACE_SPARSE].all()
        assert not rd[:, trace_ops.TRACE_SPARSE].any()
        np.testing.assert_array_equal(np.asarray(fd.cache_val),
                                      np.asarray(fs.cache_val))


DS = (1, 2, 4, 8)


class TestShardedTrace:
    """Both sharded twins, every device count: the jit-level trace
    (GSPMD-sharded reductions over the global tensors) must equal the
    untraced run's post-hoc census."""

    def test_exact_twin_matches_census_by_d(self):
        params = SimParams(n=16, services_per_node=2, fanout=2,
                           budget=4)
        for d in DS:
            sim = ShardedSim(params, topology.complete(16),
                             mesh=make_mesh(jax.devices()[:d]))
            state = sim.init_state()
            key = jax.random.PRNGKey(d)
            rounds = 6
            final, tr, conv = sim.run_with_trace(state, key, rounds,
                                                 donate=False)
            assert int(tr.count) == rounds and not bool(tr.overflow)
            rec = np.asarray(tr.rec)
            st = state
            for r in range(rounds):
                prev = st
                st = sim.step(st, jax.random.fold_in(key,
                                                     st.round_idx))
                want = oracle_exact_record(prev, st, params)
                assert_trace_matches(rec, r, want, f"sharded d={d}")
            np.testing.assert_array_equal(np.asarray(final.known),
                                          np.asarray(st.known))

    def test_compressed_twin_matches_census_by_d(self):
        params = CompressedParams(n=16, services_per_node=2,
                                  cache_lines=32, fanout=2, budget=4)
        for d in DS:
            sim = ShardedCompressedSim(params, topology.complete(16),
                                       mesh=make_mesh(jax.devices()[:d]))
            state = sim.mint(
                sim.init_state(),
                jnp.arange(0, params.m, 2, dtype=jnp.int32),
                now_tick=10)
            key = jax.random.PRNGKey(d)
            rounds = 6
            final, tr = sim.run_with_trace(state, key, rounds,
                                           donate=False)
            assert int(tr.count) == rounds and not bool(tr.overflow)
            rec = np.asarray(tr.rec)
            st = state
            for r in range(rounds):
                prev = st
                st = sim.step(st, jax.random.fold_in(key,
                                                     st.round_idx))
                want = oracle_compressed_record(prev, st, params)
                assert_trace_matches(rec, r, want,
                                     f"sharded-compressed d={d}")
            np.testing.assert_array_equal(np.asarray(final.cache_val),
                                          np.asarray(st.cache_val))


class TestTruncationContract:
    """The DeltaBatch contract: count stays exact, rows past the cap
    truncate, overflow reports it — never silent."""

    def make_run(self, cap):
        params = SimParams(n=8, services_per_node=3, fanout=2, budget=6)
        sim = ExactSim(params, topology.complete(8),
                       perturb=churn_perturb(params, 3))
        state = sim.init_state()
        _, tr, _ = sim.run_with_trace(state, jax.random.PRNGKey(0), 10,
                                      cap=cap, donate=False)
        return tr

    def test_truncates_with_exact_count(self):
        full = self.make_run(cap=10)
        capped = self.make_run(cap=4)
        assert int(capped.count) == 10 and bool(capped.overflow)
        assert capped.rec.shape == (4, trace_ops.TRACE_WIDTH)
        # The records it DID keep are the first 4 of the full stream.
        np.testing.assert_array_equal(np.asarray(capped.rec),
                                      np.asarray(full.rec)[:4])
        dicts = trace_ops.trace_to_dicts(capped)
        assert len(dicts) == 4
        assert [d["round"] for d in dicts] == [1, 2, 3, 4]
        assert set(dicts[0]) == set(trace_ops.TRACE_FIELDS)

    def test_default_cap_traces_every_round(self):
        full = self.make_run(cap=0)   # 0 → cap = num_rounds
        assert int(full.count) == 10 and not bool(full.overflow)
        assert full.rec.shape[0] == 10
        summary = trace_ops.summarize(full)
        assert summary["rounds"] == 10 and not summary["truncated"]
        rec = np.asarray(full.rec)
        assert summary["exchange_bytes_total"] == int(
            rec[:, trace_ops.TRACE_EXCHANGE_BYTES].sum())
        assert summary["frontier_max"] == int(
            rec[:, trace_ops.TRACE_FRONTIER].max())

    def test_summarize_reports_truncation(self):
        capped = self.make_run(cap=4)
        summary = trace_ops.summarize(capped)
        assert summary["truncated"] and summary["rounds"] == 10


# -- bridge plumbing ---------------------------------------------------------

def make_bridge_state(hosts=("h1", "h2", "h3"), spn=2):
    state = ServicesState(hostname=hosts[0])
    state.set_clock(lambda: T0)
    for hi, host in enumerate(hosts):
        for si in range(spn):
            state.add_service_entry(S.Service(
                id=f"{host}-svc{si}", name=f"app{si}", image="i:1",
                hostname=host, updated=T0 + hi * NS + si,
                status=S.ALIVE))
    return state


class TestBridgeTrace:
    def test_trace_block_shape(self):
        bridge = SimBridge(make_bridge_state(), CFG)
        report = bridge.simulate(rounds=8, seed=1, trace=5,
                                 cold_nodes=["h3"])
        assert report.trace is not None
        assert report.trace["requested"] == 5
        rounds = report.trace["rounds"]
        assert len(rounds) == 5
        for i, rd in enumerate(rounds):
            assert set(rd) == set(trace_ops.TRACE_FIELDS)
            assert rd["exchange_bytes"] >= 0
        # Absolute, consecutive round numbering.
        assert [rd["round"] for rd in rounds] == \
            [rounds[0]["round"] + i for i in range(5)]
        # The cold joiner forces re-teaching → a live sender frontier.
        assert max(rd["frontier"] for rd in rounds) > 0
        # Untraced requests carry no block.
        assert SimBridge(make_bridge_state(), CFG).simulate(
            rounds=4, seed=1).trace is None
        json.dumps(report.to_json())

    def test_chunked_pipeline_stream_identical(self):
        """Trace records crossing CHUNK_ROUNDS boundaries equal the
        single-dispatch stream (absolute rounds, fold-in PRNG)."""
        single = SimBridge(make_bridge_state(), CFG).simulate(
            rounds=12, seed=3, trace=9, cold_nodes=["h2"])
        chunked_bridge = SimBridge(make_bridge_state(), CFG)
        chunked_bridge.CHUNK_ROUNDS = 5     # 5+5+2 chunks, trace=9
        chunked = chunked_bridge.simulate(
            rounds=12, seed=3, trace=9, cold_nodes=["h2"])
        assert chunked.trace["rounds"] == single.trace["rounds"]
        assert chunked.convergence == single.convergence

    def test_trace_and_deltas_mutually_exclusive(self):
        bridge = SimBridge(make_bridge_state(), CFG)
        with pytest.raises(ValueError, match="mutually exclusive"):
            bridge.simulate(rounds=4, trace=3, deltas_cap=8)

    def test_sharded_trace(self):
        hosts = tuple(f"h{i}" for i in range(8))
        bridge = SimBridge(make_bridge_state(hosts=hosts), CFG)
        report = bridge.simulate(rounds=6, sharded=True, trace=3)
        assert len(report.trace["rounds"]) == 3
        assert report.devices == 8

    def test_http_round_trip(self):
        bridge = SimBridge(make_bridge_state(), CFG)
        server = serve_bridge(bridge, port=0)
        try:
            port = server.server_address[1]
            body = json.dumps({"rounds": 6, "seed": 2, "trace": 4,
                               "cold_nodes": ["h3"]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/simulate", data=body,
                method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                doc = json.loads(resp.read())
            assert doc["trace"]["requested"] == 4
            assert len(doc["trace"]["rounds"]) == 4
            assert set(doc["trace"]["rounds"][0]) == \
                set(trace_ops.TRACE_FIELDS)
        finally:
            server.shutdown()


# -- host instruments --------------------------------------------------------

class TestHistogram:
    def test_percentile_math(self):
        m = Metrics(prefix="t")
        for v in range(1, 101):
            m.histogram("h", float(v))
        h = m.snapshot()["histograms"]["h"]
        assert h["count"] == 100
        assert h["total_ms"] == 5050.0
        assert h["min_ms"] == 1.0 and h["max_ms"] == 100.0
        assert h["last_ms"] == 100.0
        # Nearest-rank over the full (sub-reservoir) sample set.
        assert h["p50_ms"] == 50.0
        assert h["p95_ms"] == 95.0
        assert h["p99_ms"] == 99.0

    def test_single_sample(self):
        m = Metrics(prefix="t")
        m.histogram("h", 7.5)
        h = m.snapshot()["histograms"]["h"]
        assert h["p50_ms"] == h["p95_ms"] == h["p99_ms"] == 7.5
        assert h["count"] == 1

    def test_reservoir_bound_with_exact_aggregates(self):
        m = Metrics(prefix="t")
        total = 3 * Metrics.HIST_RESERVOIR
        for v in range(total):
            m.histogram("h", float(v))
        with m._lock:
            assert len(m._hists["h"][5]) == Metrics.HIST_RESERVOIR
        h = m.snapshot()["histograms"]["h"]
        # Aggregates stay exact past the reservoir; percentiles stay
        # inside the observed range.
        assert h["count"] == total
        assert h["total_ms"] == float(sum(range(total)))
        assert h["min_ms"] == 0.0 and h["max_ms"] == total - 1
        assert 0.0 <= h["p50_ms"] <= h["p95_ms"] <= h["p99_ms"] \
            <= total - 1

    def test_timers_backcompat_mirror(self):
        """The migration contract (docs/metrics.md): every histogram
        mirrors count/total/last into the legacy ``timers`` block so
        pre-histogram dashboards keep reading; pure timers gain no
        histograms entry."""
        m = Metrics(prefix="t")
        m.histogram("site.hist", 10.0)
        m.histogram("site.hist", 30.0)
        m.measure_since("site.legacy", time.perf_counter())
        snap = m.snapshot()
        assert set(snap) == {"counters", "gauges", "timers",
                             "histograms"}
        mirror = snap["timers"]["site.hist"]
        hist = snap["histograms"]["site.hist"]
        assert mirror == {"count": 2, "total_ms": 40.0,
                          "last_ms": 30.0}
        assert hist["count"] == 2 and hist["total_ms"] == 40.0
        assert "site.legacy" in snap["timers"]
        assert "site.legacy" not in snap["histograms"]

    def test_statsd_ms_datagram(self):
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        sock.settimeout(2.0)
        port = sock.getsockname()[1]
        m = Metrics(prefix="t")
        m.configure_statsd(f"127.0.0.1:{port}")
        try:
            m.histogram("h", 12.5)
            data, _ = sock.recvfrom(4096)
            assert data == b"t.h:12.5|ms"
        finally:
            m.configure_statsd(None)
            sock.close()

    def test_histogram_since(self):
        m = Metrics(prefix="t")
        m.histogram_since("h", time.perf_counter())
        h = m.snapshot()["histograms"]["h"]
        assert h["count"] == 1 and h["last_ms"] >= 0.0


class TestStatsdReconfigure:
    """The PR-6 satellite fix: reconfiguration must close the previous
    socket (no fd leak) and swap the (addr, sock) pair atomically."""

    def test_old_socket_closed_on_reconfigure(self):
        m = Metrics(prefix="t")
        m.configure_statsd("127.0.0.1:9125")
        first = m._sink[1]
        assert first.fileno() != -1
        m.configure_statsd("127.0.0.1:9126")
        assert first.fileno() == -1, "previous statsd socket leaked"
        second = m._sink[1]
        assert second.fileno() != -1
        m.configure_statsd(None)
        assert second.fileno() == -1 and m._sink is None

    def test_disable_when_never_configured_is_noop(self):
        m = Metrics(prefix="t")
        m.configure_statsd(None)
        assert m._sink is None

    def test_concurrent_emit_never_sees_torn_pair(self):
        """Emitters load ONE reference: while reconfiguration churns,
        every emit sees either a complete sink or none — no
        half-configured (addr, sock) crash."""
        m = Metrics(prefix="t")
        errors = []
        stop = threading.Event()

        def hammer():
            try:
                while not stop.is_set():
                    m.incr("x")
            except Exception as exc:  # pragma: no cover — the bug
                errors.append(exc)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for i in range(200):
                m.configure_statsd(f"127.0.0.1:{9200 + i % 2}")
                m.configure_statsd(None)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
        assert errors == []
        assert m._sink is None


# -- spans -------------------------------------------------------------------

class TestSpans:
    def setup_method(self):
        reset_spans()

    def test_nesting_links_parent_and_trace(self):
        with span("outer"):
            with span("inner"):
                pass
            with span("sibling"):
                pass
        inner, sibling, outer = spans()[-3:]
        assert [s["name"] for s in (inner, sibling, outer)] == \
            ["inner", "sibling", "outer"]
        assert outer["parent_id"] is None
        assert inner["parent_id"] == outer["span_id"]
        assert sibling["parent_id"] == outer["span_id"]
        assert inner["trace_id"] == sibling["trace_id"] \
            == outer["trace_id"] == outer["span_id"]
        assert inner["duration_ms"] >= 0.0
        assert not outer["error"]

    def test_threads_get_independent_traces(self):
        done = threading.Barrier(3)

        def worker():
            with span("w"):
                done.wait(timeout=5)

        threads = [threading.Thread(target=worker) for _ in range(2)]
        for t in threads:
            t.start()
        done.wait(timeout=5)
        for t in threads:
            t.join(timeout=5)
        ws = [s for s in spans() if s["name"] == "w"]
        assert len(ws) == 2
        assert ws[0]["trace_id"] != ws[1]["trace_id"]
        assert all(s["parent_id"] is None for s in ws)

    def test_error_flag_and_unwind(self):
        with pytest.raises(RuntimeError):
            with span("boom"):
                raise RuntimeError("x")
        rec = spans()[-1]
        assert rec["name"] == "boom" and rec["error"]
        # The stack unwound: a new span is a fresh root.
        with span("after"):
            pass
        assert spans()[-1]["parent_id"] is None

    def test_limit_and_reset(self):
        for i in range(5):
            with span(f"s{i}"):
                pass
        newest = spans(limit=2)
        assert [s["name"] for s in newest] == ["s3", "s4"]
        reset_spans()
        assert spans() == []


# -- exposition --------------------------------------------------------------

class TestPrometheus:
    SNAP = {
        "counters": {"query.hub.published": 3},
        "gauges": {"kernels.pallas_active": 1.0},
        "timers": {
            "notifyMsg": {"count": 2, "total_ms": 4.0, "last_ms": 1.5},
            # The back-compat mirror of the histogram below — must NOT
            # render a second family under the same name.
            "bridge.chunk": {"count": 4, "total_ms": 100.0,
                             "last_ms": 30.0},
        },
        "histograms": {
            "bridge.chunk": {"count": 4, "total_ms": 100.0,
                             "last_ms": 30.0, "min_ms": 10.0,
                             "max_ms": 40.0, "p50_ms": 20.0,
                             "p95_ms": 40.0, "p99_ms": 40.0},
        },
    }

    def test_render_families(self):
        text = render_prometheus(self.SNAP)
        assert "# TYPE sidecar_query_hub_published_total counter\n" \
            "sidecar_query_hub_published_total 3\n" in text
        assert "# TYPE sidecar_kernels_pallas_active gauge\n" \
            "sidecar_kernels_pallas_active 1\n" in text
        assert 'sidecar_bridge_chunk_ms{quantile="0.5"} 20' in text
        assert 'sidecar_bridge_chunk_ms{quantile="0.99"} 40' in text
        assert "sidecar_bridge_chunk_ms_sum 100" in text
        assert "sidecar_bridge_chunk_ms_count 4" in text
        # Legacy timer: summary with sum/count only.
        assert "# TYPE sidecar_notifyMsg_ms summary" in text
        assert "sidecar_notifyMsg_ms_sum 4" in text
        # The mirrored timer is skipped — exactly one family.
        assert text.count("# TYPE sidecar_bridge_chunk_ms summary") == 1

    def test_renders_live_registry(self):
        # Seed the process-global registry so this test is
        # order-independent (any -k selection must pass).
        from sidecar_tpu import metrics as global_metrics
        global_metrics.incr("telemetry.render.probe")
        text = render_prometheus()
        assert text.endswith("\n")
        assert "# TYPE sidecar_telemetry_render_probe_total counter" \
            in text


def make_api():
    state = ServicesState(hostname="h1")
    state.set_clock(lambda: T0)
    state.add_service_entry(S.Service(
        id="aaa111", name="web", image="img:1", hostname="h1",
        updated=T0, status=S.ALIVE))
    return SidecarApi(state, members_fn=lambda: ["h1"],
                      cluster_name="test-cluster")


class TestEndpoints:
    def test_metrics_prometheus(self):
        for path in ("/metrics", "/api/metrics"):
            status, ctype, body, _ = make_api().dispatch("GET", path)
            assert status == 200
            assert ctype.startswith("text/plain")
            text = body.decode()
            assert "sidecar_" in text
            # The make_api add_service_entry above records a timer.
            assert "sidecar_addServiceEntry_ms_count" in text

    def test_trace_endpoint(self):
        reset_spans()
        api = make_api()   # add_service_entry → a catalog.merge span
        status, ctype, body, _ = api.dispatch("GET", "/api/trace")
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert any(s["name"] == "catalog.merge" for s in doc["spans"])

    def test_trace_endpoint_limit(self):
        reset_spans()
        api = make_api()
        with span("extra"):
            pass
        status, _, body, _ = api.dispatch("GET", "/trace",
                                          {"limit": ["1"]})
        doc = json.loads(body)
        assert [s["name"] for s in doc["spans"]] == ["extra"]
        status, _, body, _ = api.dispatch("GET", "/api/trace",
                                          {"limit": ["nope"]})
        assert status == 400
